"""Numpy-side metrics (reference ``python/hetu/metrics.py``: AUC:120,
accuracy:154, precision/recall/F1:220-315) + host-side performance
counters on the unified observability registry (ISSUE 10).

Every counter family, latency histogram and gauge here registers
against :data:`hetu_tpu.obs.registry` — the thin ``record_*`` wrappers
below are the ONE recording API the rest of the package calls, and
``obs.metrics_dump()`` / ``tools/metricsd.py`` read the same registry
back out (one source of truth; the per-family accessors are kept as
thin views over it).  The wrappers keep the exact hot-path cost of the
pre-registry module-level families: ``record_run_plan`` runs once per
training step on the dispatch path, so its counter branch is one lock +
one dict add, nothing more.  ``reset_all()`` zeroes everything;
the per-family ``reset_*`` functions remain as thin delegates.

When span tracing is on (``HETU_TRACE=1``), every fault-counter
recording also lands as an instant event on the active thread's trace
track — retries, failovers, promotions, epoch refusals and chaos
injections appear INSIDE the step/RPC span that absorbed them.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from .obs.registry import REGISTRY
from .obs.trace import TRACER as _TR

# ------------------------------------------------------- counter suppression
# The static analyzer (``hetu_tpu.analysis``) abstractly evaluates op
# lowering rules with ``jax.eval_shape``; dispatch-time counters (flash
# fallbacks) must not record those fake traces as real dispatches.

# thread-LOCAL: an abstract trace on one thread must not silence real
# dispatch recording (or the HETU_REQUIRE_FLASH hard-fail) on another
_suppress = threading.local()


@contextlib.contextmanager
def suppress_perf_counters():
    """Scope in which dispatch-time perf counters do not record (used by
    abstract shape evaluation, which traces lowering rules without running
    them).  Per-thread: only the analyzing thread is suppressed."""
    _suppress.depth = getattr(_suppress, "depth", 0) + 1
    try:
        yield
    finally:
        _suppress.depth -= 1


def counters_suppressed():
    """True inside a :func:`suppress_perf_counters` scope (this thread)."""
    return getattr(_suppress, "depth", 0) > 0

# --------------------------------------------------- flash fallback counters
# The attention dispatchers record WHY a call left the Pallas fast path
# (backend, gate, shape, mask layout, ring chunking).  Counts are per
# TRACE, not per step — dispatch happens when jax traces the program, so
# a counter that keeps climbing across steps means the jit cache is
# thrashing, and a single nonzero entry means that workload compiled onto
# the slow path.  Surfaced by ``HetuProfiler.flash_fallbacks()`` and the
# bench.py attention microbench; ``HETU_REQUIRE_FLASH=1`` turns any
# recording into a hard failure (ops/attention.py).

_flash = REGISTRY.counter_family(
    "flash_fallbacks",
    "attention dispatches that left the Pallas flash fast path, by "
    "reason (per jax trace, not per step)")


def record_flash_fallback(reason):
    """Count one attention dispatch that fell back off the flash path."""
    if counters_suppressed():
        return  # abstract (eval_shape) trace, not a real dispatch
    _flash.inc(str(reason))


def flash_fallback_counts():
    """{reason: count} snapshot of recorded fallbacks."""
    return _flash.counts()


def reset_flash_fallbacks():
    _flash.reset()


# ---------------------------------------------- embedding Pallas fallbacks
# The device-resident embedding-cache dispatchers
# (``ops/pallas/emb_cache.py``) record WHY a gather / grad scatter-add
# left the Pallas kernel path (backend, forced interpret policy).  Flash
# semantics: counts are per jax TRACE, not per step — one nonzero entry
# means that workload compiled onto the fallback (``jnp.take`` /
# ``jax.ops.segment_sum``) path, and a count climbing across steps means
# the jit cache is thrashing.  Surfaced by
# ``HetuProfiler.emb_pallas_fallbacks()`` and ``bench.py --config wdl
# --emb-device device``; ``HETU_REQUIRE_PALLAS_EMB=1`` turns any
# recording into a hard failure (emb_cache._note_fallback).

_emb_pallas = REGISTRY.counter_family(
    "emb_pallas_fallbacks",
    "embedding-cache dispatches that left the Pallas device-kernel "
    "path, by reason (per jax trace, not per step)")


def record_emb_pallas_fallback(reason):
    """Count one embedding-cache dispatch that fell back off Pallas."""
    if counters_suppressed():
        return  # abstract (eval_shape) trace, not a real dispatch
    _emb_pallas.inc(str(reason))


def emb_pallas_fallback_counts():
    """{reason: count} snapshot of recorded embedding-kernel fallbacks."""
    return _emb_pallas.counts()


def reset_emb_pallas_fallbacks():
    _emb_pallas.reset()


# ------------------------------------------------------ fault-event counters
# The fault-tolerance layer records every detection/recovery event here so
# a run can PROVE what happened: transport retries (``ps_rpc_retry``),
# exhausted peers (``ps_peer_unreachable``), injected chaos
# (``chaos_drop``/``chaos_kill_ps``/``chaos_kill_primary``/...), dead
# ranks excluded from a partial-reduce group
# (``preduce_dead_rank_excluded``), checkpoints written/skipped
# (``auto_save``, ``emergency_save``, ``ckpt_incomplete_skipped``),
# resumes (``resume``), supervisor restarts (``supervisor_restart``),
# standby respawns (``standby_spawn``), and the PS replication plane:
# client-side failovers (``ps_failover`` detected, ``ps_failover_promoted``
# rerouted, ``ps_failover_failed`` both copies gone,
# ``ps_failover_primary_reported_alive`` possible partition), server-side
# promotions (``ps_promoted``), op-log forward breakage
# (``repl_forward_failed``), redundancy repair (``ps_re_replicated``
# / ``ps_re_replicate_deferred`` / ``ps_re_replicate_failed``), and the
# partition-tolerance plane: frames the chaos DSL's partition window
# dropped (``partition_frames_dropped``), fencing-epoch advances at
# promotion (``ps_epoch_bumps``), frames refused for carrying a stale or
# deposed lineage's epoch (``ps_epoch_refused``), stale ex-primaries that
# stopped serving on learning of a newer lineage (``ps_demotions``), and
# heartbeat-silent ranks that still answered a direct probe
# (``ps_unreachable`` — partition, not crash).
# Invariant (asserted by the chaos + replication tests): every counter
# EXCEPT the ``auto_save`` bookkeeping records a detected fault or a
# recovery action, so a clean run — replicated or not — reports none of
# those, and a clean run without auto-checkpointing records nothing at
# all.  Surfaced by ``HetuProfiler.fault_counters()`` and ``bench.py
# --config chaos`` / ``--config failover``.

_faults = REGISTRY.counter_family(
    "faults",
    "fault-tolerance events: detections, injections, recoveries "
    "(a clean run records none but auto_save bookkeeping)")


def record_fault(kind, n=1):
    """Count one fault-tolerance event (detection, injection, recovery).
    With tracing on, the event also lands as an instant on the calling
    thread's trace track — a failover/retry/epoch-refusal is visible
    INSIDE the step or RPC span that absorbed it."""
    kind = str(kind)
    _faults.inc(kind, n)
    if _TR.on:
        _TR.instant("fault:" + kind, cat="fault")


def fault_counts():
    """{kind: count} snapshot of recorded fault events."""
    return _faults.counts()


def reset_faults():
    _faults.reset()


# ------------------------------------------------------ elastic-resize counters
# The elastic data-parallel layer (``parallel/elastic.py``) records every
# world-resize event here: dead ranks detected (``elastic_dead_rank``),
# shrinks executed (``elastic_shrink``) and shrinks refused at the
# ``min_dp`` floor (``elastic_shrink_refused``), rejoins detected
# (``elastic_rejoin``) and grows executed (``elastic_grow``),
# heartbeat-silent-but-probe-answering ranks HELD instead of resized
# over (``elastic_unreachable_held`` — partition evidence, the fencing
# epochs' problem), and the cumulative resize wall time
# (``elastic_resize_ms`` — detection poll to resized executor, summed
# over resizes; per-event recovery_ms lives on the controller's
# timeline).  Whether a resize recompiled or reused an executable is
# the step-cache family's story (``step_cache_hit`` on a grow-back).
# Invariant (asserted by the elastic tests): a fixed-world run records
# nothing here.  Surfaced by ``HetuProfiler.elastic_counters()`` and
# ``bench.py --config elastic``.

_elastic = REGISTRY.counter_family(
    "elastic",
    "elastic data-parallel resize events: dead-rank detections, "
    "shrinks/grows, held partitions (a fixed-world run records none)")


def record_elastic(kind, n=1):
    """Count ``n`` elastic-resize events of ``kind``.  With tracing on,
    the event also lands as an instant on the calling thread's track —
    a shrink/grow is visible next to the step spans it sits between."""
    kind = str(kind)
    if n:
        _elastic.inc(kind, int(n))
    if _TR.on:
        _TR.instant("elastic:" + kind, cat="elastic")


def elastic_counts():
    """{kind: count} snapshot of elastic-resize events."""
    return _elastic.counts()


def reset_elastic_counts():
    _elastic.reset()


# ----------------------------------------------------- selective-remat counters
# The remat policy layer (``parallel/remat.py``) records each plan build
# here: segments found (``remat_layers_total``) and chosen for remat
# (``remat_layers_rematted``), the activation bytes the chosen plan
# frees (``remat_bytes_saved``) vs the matmul FLOPs a backward replay
# re-pays (``remat_recompute_flops``), and activation-offload requests
# served by the counted on-device fallback because the backend cannot
# host-offload (``remat_offload_fallback`` — flash-dispatcher style,
# ``HETU_REQUIRE_OFFLOAD=1`` hard-fails instead).  Counts are per plan
# BUILD, not per step (flash-counter semantics: a count climbing across
# steps means executors are being rebuilt).  Surfaced by
# ``HetuProfiler.remat_counters()`` and ``bench.py --config remat``; a
# run without ``Executor(remat=...)`` records nothing.

_remat = REGISTRY.counter_family(
    "remat",
    "selective-remat plan builds: segments rematted, bytes freed vs "
    "recompute flops, offload fallbacks (empty without remat=)")


def record_remat(kind, n=1):
    """Count ``n`` selective-remat events of ``kind`` (plan builds,
    offload fallbacks)."""
    if counters_suppressed():
        return  # abstract (eval_shape) trace, not a real build
    if n:
        _remat.inc(str(kind), int(n))


def remat_counts():
    """{kind: count} snapshot of selective-remat plan counters."""
    return _remat.counts()


def reset_remat_counts():
    _remat.reset()


# ------------------------------------------------- concurrency-verifier counters
# The concurrency verifier (ISSUE 14) records its runtime evidence here:
# the lock-witness (``obs/lock_witness.py``, ``HETU_LOCK_WITNESS=1``)
# publishes distinct lock classes seen (``concurrency_witness_locks``),
# acquisition-graph edges observed (``concurrency_witness_edges``) and
# cycles detected (``concurrency_witness_cycles`` — any nonzero count is
# a deadlock-able order, the tier-1 witness smoke asserts ZERO) at each
# ``WITNESS.check()`` as deltas since the previous check; the
# deterministic race harness (``hetu_tpu.race``) counts forced
# preemptions actually fired (``concurrency_preemptions`` — a loser
# thread held at its site until the winner's region completed) and
# rendezvous that timed out because the peer site never arrived
# (``concurrency_race_timeouts`` — the harness's no-deadlock escape
# hatch; a deterministic repro should count zero).  Invariant: a run
# with the witness off and no race schedule installed records nothing.
# Surfaced by ``HetuProfiler.concurrency_counters()``.

_concurrency = REGISTRY.counter_family(
    "concurrency",
    "concurrency-verifier runtime events: witness locks/edges/cycles, "
    "race-harness preemptions (empty without HETU_LOCK_WITNESS/"
    "HETU_RACE)")


def record_concurrency(kind, n=1):
    """Count ``n`` concurrency-verifier events of ``kind`` (witness
    graph deltas, race-harness preemptions/timeouts)."""
    if n:
        _concurrency.inc(str(kind), int(n))


def concurrency_counts():
    """{kind: count} snapshot of concurrency-verifier counters."""
    return _concurrency.counts()


def reset_concurrency_counts():
    _concurrency.reset()


# ------------------------------------------------- autoparallel-loop counters
# The auto-parallel search/execute/measure loop (``autoparallel/``)
# records its lifecycle here: searches that produced a plan
# (``autoparallel_plans_searched`` — one per :func:`search`/
# :func:`search_graph` call), candidate executables built fresh by the
# measurement pass (``autoparallel_plans_compiled`` — a compiled-step
# cache miss while measuring) vs candidates whose executable was REUSED
# (``autoparallel_candidate_cache_hits`` — the one-compile-per-candidate
# claim: re-measuring a plan must hit, not rebuild), candidates actually
# run for measured step times (``autoparallel_plans_measured``), and
# re-ranks where the MEASURED ordering overturned the predicted best
# (``autoparallel_rerank_flips`` — each flip is a mispricing the
# feedback loop corrected).  Invariant (asserted by the tests): a run
# that never searches or measures plans records nothing.  Surfaced by
# ``HetuProfiler.autoparallel_counters()`` and ``tools/plan_diff.py``.

_autoparallel = REGISTRY.counter_family(
    "autoparallel",
    "auto-parallel loop events: plans searched/compiled/measured, "
    "candidate executable reuse, measured re-rank flips (empty without "
    "autoparallel use)")


def record_autoparallel(kind, n=1):
    """Count ``n`` auto-parallel loop events of ``kind`` (searches,
    candidate compiles/cache hits, measurements, rerank flips)."""
    if n:
        _autoparallel.inc(str(kind), int(n))


def autoparallel_counts():
    """{kind: count} snapshot of auto-parallel loop counters."""
    return _autoparallel.counts()


def reset_autoparallel_counts():
    _autoparallel.reset()


# ------------------------------------------------- cache / sparse-RPC counters
# The HET embedding cache (``ps/dist_store.py:DistCacheTable``) and the
# sparse transport (``DistributedStore.pull/push/push_pull``) record their
# batching wins here: rows served from cache vs refreshed
# (``emb_cache_hit_rows`` / ``emb_cache_miss_rows``), rows evicted
# (``emb_cache_evict_rows``), rows pushed and the number of BATCHED push
# round trips that carried them (``emb_cache_push_rows`` /
# ``emb_cache_push_rpcs`` — the pre-PR per-key path paid one RPC per row),
# redundant rows/bytes that client-side ``np.unique`` dedup eliminated
# BEFORE the shard fanout (``ps_dedup_{pull,push}_{rows,bytes}_saved`` —
# the saving covers the local shard's share too, so on a w-rank store
# (w-1)/w of it is wire traffic), and round trips where a fused
# ``OP_PUSH_PULL`` frame carried both a push and a pull shard
# (``ps_push_pull_fused_rpcs``), and grad segment-sums that ran on the
# scipy-absent ``np.add.at`` host fallback (``emb_grad_host_fallback``
# — scipy ships with jax, so any count here means an exotic build lost
# the CSR fast path; device-resident tables skip the host pass
# entirely).  Invariant (asserted by the tests):
# only sparse-PS traffic records here, so a clean dense run reports an
# empty dict.  Surfaced by ``HetuProfiler.cache_counters()`` and
# ``bench.py --config emb``.

_cache = REGISTRY.counter_family(
    "cache",
    "HET embedding-cache / sparse-transport batching events (a clean "
    "dense run records nothing)")


def record_cache(kind, n=1):
    """Count ``n`` cache/sparse-transport events of ``kind``."""
    if n:
        _cache.inc(str(kind), int(n))


def cache_counts():
    """{kind: count} snapshot of cache/dedup/batching counters."""
    return _cache.counts()


def reset_cache_counts():
    _cache.reset()


# ------------------------------------------------- ZeRO weight-update counters
# The ZeRO sharded-update layer (``parallel/zero.py``) records its
# collective traffic and padding waste here: grad-slab bytes pinned to the
# sharded layout (``zero_reduce_scatter_bytes`` — what the partitioner may
# lower as a reduce-scatter), updated-param bytes gathered back
# (``zero_all_gather_bytes``), and zero-fill bytes added so ragged shapes
# shard evenly (``zero_pad_bytes``).  Counts are per TRACE, not per step
# (the slabs are built when jax traces the program — flash-counter
# semantics): a count that keeps climbing across steps means the jit cache
# is thrashing.  Surfaced by ``HetuProfiler.zero_counters()`` and
# ``bench.py --config zero``; a run without ``zero=`` records nothing.

_zero = REGISTRY.counter_family(
    "zero",
    "ZeRO sharded-update collective/padding bytes (per jax trace; "
    "empty without Executor(zero=...))")


def record_zero(kind, n=1):
    """Count ``n`` bytes/events of ZeRO sharded-update traffic."""
    if counters_suppressed():
        return  # abstract (eval_shape) trace, not a real build
    if n:
        _zero.inc(str(kind), int(n))


def zero_counts():
    """{kind: bytes} snapshot of ZeRO collective/padding counters."""
    return _zero.counts()


def reset_zero_counts():
    _zero.reset()


# -------------------------------------------------- compiled-step cache counters
# The executor's compiled-executable cache (``graph/step_cache.py``) keys a
# jitted step on (graph signature, mesh, compute_dtype, zero stage) and
# reuses it across Executor instances; hits skip a full XLA retrace.
# ``step_cache_hit`` / ``step_cache_miss`` count lookups;
# ``step_cache_uncachable`` counts graphs whose signature could not be
# computed (caching skipped, never wrong-cached).  Surfaced by
# ``HetuProfiler.step_cache_counters()``.

_step_cache = REGISTRY.counter_family(
    "step_cache",
    "compiled-step cache lookups: hit / miss / uncachable")


def record_step_cache(kind, n=1):
    """Count one compiled-step cache event (hit/miss/uncachable)."""
    _step_cache.inc(str(kind), n)


def step_cache_counts():
    """{kind: count} snapshot of compiled-step cache events."""
    return _step_cache.counts()


def reset_step_cache_counts():
    _step_cache.reset()


# ------------------------------------------------------ run-plan counters
# The executor's cached run plans (``graph/run_plan.py``) record the
# dispatch-path behaviour here: ``plan_cache_hit`` / ``plan_cache_miss``
# count per-step plan lookups (a steady feed schema hits every step after
# the first — the per-step Python work of resolving feeds, placement
# closures and validation is amortized to zero; misses climbing across
# steps mean the feed schema is churning, see the ``feed-schema-churn``
# warning), ``feeds_pipelined`` counts feed arrays whose host→device
# transfer was issued ahead of the step that consumed them (the
# double-buffered feed pipeline: dataloader prefetch + the
# ``Executor.run_steps`` driver), ``feed_pipeline_depth_hw`` is the
# high-water count of dataloader feed NODES with an outstanding
# prefetched transfer — the double-buffer is one step deep per node, so
# a 3-loader graph tops out at 3 (gauge semantics: the stored value is
# the MAX ever seen), and ``async_sync_points``
# counts the places where non-blocking stepping (``run(..., sync=False)``)
# was FORCED to materialize — a numpy conversion, a PS push boundary, a
# checkpoint save, or the bounded in-flight window filling up.  Surfaced
# by ``HetuProfiler.run_plan_counters()`` and ``bench.py --config
# overhead``.

_run_plan = REGISTRY.counter_family(
    "run_plan",
    "cached-run-plan / async-dispatch events: plan cache hits/misses, "
    "pipelined feeds, forced async sync points")


def record_run_plan(kind, n=1):
    """Count ``n`` run-plan/dispatch events of ``kind``; kinds ending in
    ``_hw`` are high-water gauges (the stored value is the max seen).
    This recorder runs once per training step on the dispatch hot path
    — the plain-counter branch is kept deliberately lean."""
    if kind.__class__ is not str:
        kind = str(kind)
    if not kind.endswith("_hw"):
        if n:
            _run_plan.inc(kind, int(n))
            if kind == "async_sync_points" and _TR.on:
                # trace view of the forced materialization (numpy
                # convert, PS push boundary, save drain, window full)
                _TR.instant("async_sync_point", cat="async")
    else:
        _run_plan.max_gauge(kind, int(n))


def run_plan_counts():
    """{kind: count} snapshot of run-plan / async-dispatch counters."""
    return _run_plan.counts()


def reset_run_plan_counts():
    _run_plan.reset()


# ------------------------------------------------------- serving counters
# The online-serving layer (``hetu_tpu.serving``) records its request /
# batching behaviour here: requests admitted (``serve_requests``) and
# answered (``serve_responses``), batches dispatched (``serve_batches``)
# with the TOTAL bucket rows they ran at (``serve_batch_rows`` — real
# plus padding), of which ``serve_pad_rows`` were padding added to reach
# a legal bucket (the micro-batcher's waste: real rows =
# ``serve_batch_rows - serve_pad_rows``), queue-full rejections
# (``serve_rejections`` — the backpressure path), PS failovers absorbed
# MID-SERVE (``serve_failovers``), dispatched batches re-run ONCE after
# a transient device-call failure before their futures fail
# (``serve_batch_retries``, ISSUE 19), per-bucket executable builds
# (``serve_bucket_compiles`` — the compile-once claim is exactly "this
# equals the number of distinct buckets used"), read-only embedding
# refreshes (``serve_emb_refresh_rows``), and the queue-depth high-water
# mark (``serve_queue_depth_hw`` — gauge semantics: the recorded value is
# the MAX ever seen, not a sum).  Surfaced by
# ``HetuProfiler.serve_counters()`` and ``bench.py --config serve``; a
# process that never serves reports an empty dict.

_serve = REGISTRY.counter_family(
    "serve",
    "online-serving request/batching events (empty in a process that "
    "never serves)")


def record_serve(kind, n=1):
    """Count ``n`` serving events of ``kind``; kinds ending in ``_hw``
    are high-water gauges (the stored value is the max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _serve.max_gauge(kind, int(n))
    elif n:
        _serve.inc(kind, int(n))


def serve_counts():
    """{kind: count} snapshot of serving counters."""
    return _serve.counts()


def reset_serve_counts():
    """Reset the serving counters AND the serving latency histograms —
    one serving run's telemetry, one reset."""
    _serve.reset()
    _serve_latency.reset()


# ------------------------------------------------------- decode counters
# The autoregressive-decode serving plane (``hetu_tpu.serving.decode``)
# records its token/batch behaviour here: tokens emitted to streams
# (``decode_tokens``), sequences joining (``decode_joins``) and leaving
# (``decode_leaves``) the in-flight continuous batch, KV-cache slots
# recycled to a later sequence (``decode_slot_recycles``), engine steps
# (``decode_steps`` — one jitted decode call per token batch) split into
# the per-row prefill/generate accounting (``decode_prefill_rows``: rows
# that consumed a PROMPT token, building KV cache without emitting;
# ``decode_generate_rows``: rows that consumed a generated token), bucket
# ladder growths (``decode_batch_grows`` / ``decode_len_grows`` — each one
# is at most one fresh compile, the compile-once-per-(batch, len) bucket
# claim), queue-full rejections (``decode_rejections``), and the
# device-resident KV-cache footprint high-water mark
# (``decode_kv_bytes_hw`` — gauge semantics: the recorded value is the MAX
# ever seen).  Chunked prefill (ISSUE 18) adds the prompt-ingestion
# accounting: ``decode_prefill_steps`` (steps that ran the q_len=C
# chunked entry), ``decode_prefill_steps_saved`` (dispatches a chunked
# step avoided vs the token-by-token path: the widest row's chunk minus
# one, per chunked step), and ``decode_logits_skipped`` (steps that
# skipped the (batch, vocab) logits D2H because no row was past its
# prompt).  Surfaced by ``HetuProfiler.decode_counters()`` and
# ``bench.py --config decode``; a process that never decodes reports an
# empty dict.

_decode = REGISTRY.counter_family(
    "decode",
    "continuous-batching autoregressive decode events (empty in a "
    "process that never decodes)")


def record_decode(kind, n=1):
    """Count ``n`` decode events of ``kind``; kinds ending in ``_hw``
    are high-water gauges (the stored value is the max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _decode.max_gauge(kind, int(n))
    elif n:
        _decode.inc(kind, int(n))


def decode_counts():
    """{kind: count} snapshot of decode counters."""
    return _decode.counts()


def reset_decode_counts():
    """Reset the decode counters AND the per-token latency histogram —
    one decode run's telemetry, one reset."""
    _decode.reset()
    _decode_latency.reset()


# ------------------------------------------------- prefix-cache counters
# The shared-prefix KV store (``hetu_tpu.serving.prefix_cache``, ISSUE
# 18) records its reuse economics here: lookups that found a usable
# stored prefix (``prefix_cache_hits``) vs not (``prefix_cache_misses``),
# the total KV-cache ROWS those hits seated pre-filled — i.e. prompt
# tokens whose prefill was skipped outright (``prefix_cache_hit_rows``),
# snapshots inserted (``prefix_cache_inserts``) and deduplicated against
# an existing key (``prefix_cache_dup_inserts``), entries LRU-evicted to
# stay under the capacity bound (``prefix_cache_evictions``) with the
# bytes they freed (``prefix_cache_evicted_bytes``), and the store's
# resident-bytes high-water mark (``prefix_cache_bytes_hw`` — gauge
# semantics: the recorded value is the MAX ever seen).  Surfaced by
# ``HetuProfiler.prefix_cache_counters()`` and the decode bench; a
# process with no prefix store reports an empty dict.

_prefix_cache = REGISTRY.counter_family(
    "prefix_cache",
    "shared-prefix KV snapshot reuse events (empty in a process with "
    "no PrefixKVStore)")


def record_prefix_cache(kind, n=1):
    """Count ``n`` prefix-cache events of ``kind``; kinds ending in
    ``_hw`` are high-water gauges (the stored value is the max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _prefix_cache.max_gauge(kind, int(n))
    elif n:
        _prefix_cache.inc(kind, int(n))


def prefix_cache_counts():
    """{kind: count} snapshot of prefix-cache counters."""
    return _prefix_cache.counts()


def reset_prefix_cache_counts():
    _prefix_cache.reset()


# --------------------------------------------- decode recovery counters
# Exactly-once stream migration (ISSUE 19): when the fleet sweep ejects
# a dead/wedged decode replica, every SEATED in-flight generation is
# detached as a continuation request (``decode_recovery_detached`` —
# the host-side emitted-token journal becomes the replay prompt suffix
# and the stream's replay epoch is bumped, fencing the old engine) and
# re-seated on a survivor through the chunked-prefill entry
# (``decode_recovery_reseated``).  ``decode_recovery_replayed_rows``
# counts the KV rows the survivor actually re-prefilled,
# ``decode_recovery_prefix_assisted`` the rows a PrefixKVStore hit
# seated for free (the two partition the continuation prompt).
# ``decode_recovery_exhausted`` counts streams the door failed FAST
# instead of resurrecting (retry budget, deadline estimator, or zero
# survivors — the failure carries ``DecodeStream.partial()``),
# ``decode_recovery_retries`` second-and-later recoveries of the same
# stream, and ``decode_recovery_fenced`` stale emissions a migrated-away
# replica attempted that the epoch fence dropped (each one a token that
# would have been delivered TWICE without the fence).  Surfaced by
# ``HetuProfiler.decode_recovery_counters()`` and the decode bench's
# recovery leg; a process that never migrates a stream reports an empty
# dict.

_decode_recovery = REGISTRY.counter_family(
    "decode_recovery",
    "exactly-once in-flight decode stream migration events (empty in a "
    "process that never recovers a stream)")


def record_decode_recovery(kind, n=1):
    """Count ``n`` stream-recovery events of ``kind``; kinds ending in
    ``_hw`` are high-water gauges (the stored value is the max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _decode_recovery.max_gauge(kind, int(n))
    elif n:
        _decode_recovery.inc(kind, int(n))


def decode_recovery_counts():
    """{kind: count} snapshot of decode stream-recovery counters."""
    return _decode_recovery.counts()


def reset_decode_recovery_counts():
    _decode_recovery.reset()


# --------------------------------------------- serving rejection reasons
# ISSUE 17: every :class:`ServeRejected` now carries a structured
# ``reason`` from the closed taxonomy ``queue_full | over_max_len |
# deadline | shed:<class> | draining``, and every raise site counts it
# here keyed BY that reason — bench artifacts and tests read this family
# instead of string-matching exception text.  The legacy ``serve`` /
# ``decode`` families keep their coarse ``*_rejections`` totals; this is
# the per-cause breakdown.

_serve_reject = REGISTRY.counter_family(
    "serve_rejection_reason",
    "serving rejections keyed by structured ServeRejected reason "
    "(queue_full | over_max_len | deadline | shed:<class> | draining)")


def record_serve_rejection(reason, n=1):
    """Count ``n`` rejections with structured ``reason`` (one of the
    ``ServeRejected.REASONS`` taxonomy, e.g. ``shed:best_effort``)."""
    if n:
        _serve_reject.inc(str(reason), int(n))


def serve_rejection_counts():
    """{reason: count} snapshot of structured serving rejections."""
    return _serve_reject.counts()


def reset_serve_rejection_counts():
    _serve_reject.reset()


# --------------------------------------------------------- fleet counters
# The replica-set serving tier (``hetu_tpu.serving.fleet``) records its
# lifecycle here: requests admitted at the front door
# (``fleet_admitted``) and dispatched to a replica (``fleet_dispatch``),
# replicas added (``fleet_scale_out``) / retired (``fleet_scale_in``),
# dead-or-wedged replicas ejected from dispatch
# (``fleet_replica_ejected``) and re-admitted after recovery
# (``fleet_replica_readmitted``), queued requests rescued off a dead or
# draining replica onto a survivor (``fleet_rescued`` — the graceful-
# degradation path: admitted work is handed over, not failed), admitted
# requests whose future ultimately failed (``fleet_request_failures`` —
# the bench gates this at zero), SLO-autoscaler polls
# (``fleet_autoscaler_polls``) and resizes refused at the min/max bound
# (``fleet_scale_refused``), and the live-replica high-water mark
# (``fleet_replicas_hw`` — gauge semantics: the recorded value is the
# MAX ever seen).  Surfaced by ``HetuProfiler.fleet_counters()`` and
# ``bench.py --config fleet``; a process with no fleet reports an empty
# dict.

_fleet = REGISTRY.counter_family(
    "fleet",
    "replica-set serving-tier events (empty in a process that never "
    "runs a FrontDoor)")


def record_fleet(kind, n=1):
    """Count ``n`` fleet events of ``kind``; kinds ending in ``_hw``
    are high-water gauges (the stored value is the max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _fleet.max_gauge(kind, int(n))
    elif n:
        _fleet.inc(kind, int(n))


def fleet_counts():
    """{kind: count} snapshot of fleet serving-tier counters."""
    return _fleet.counts()


def reset_fleet_counts():
    _fleet.reset()


# ------------------------------------------------ protocol verification
# The ISSUE 20 model checker and its trace-conformance layer
# (``analysis/protocol.py``) record their activity here:
# ``protocol_events`` counts transition events the :data:`PROTO`
# recorder captured at the real protocol sites (dist_store / decode /
# fleet / elastic — zero unless ``HETU_PROTO_TRACE`` or a chaos bench
# flips the recorder on) and ``protocol_events_dropped`` events the
# buffer cap discarded; ``protocol_conformance_checks`` counts events
# replayed against the models' transition relations and
# ``protocol_divergences`` the replays a monitor rejected (the chaos
# benches gate on ZERO of these — an allowlisted divergence counts
# under ``protocol_divergences_allowlisted`` instead);
# ``protocol_states_explored`` counts canonical states the BFS checker
# visited and ``protocol_violations`` the invariant violations it found
# (nonzero only under a seeded mutation — HEAD models verify clean).
# Surfaced by ``HetuProfiler.protocol_counters()`` and
# ``tools/verify_protocols.py``; a process that never checks or records
# a protocol reports an empty dict.

_protocol = REGISTRY.counter_family(
    "protocol",
    "protocol model-checking and trace-conformance events (empty in a "
    "process that never verifies a protocol)")


def record_protocol(kind, n=1):
    """Count ``n`` protocol-verification events of ``kind``; kinds
    ending in ``_hw`` are high-water gauges (the stored value is the
    max seen)."""
    kind = str(kind)
    if kind.endswith("_hw"):
        _protocol.max_gauge(kind, int(n))
    elif n:
        _protocol.inc(kind, int(n))


def protocol_counts():
    """{kind: count} snapshot of protocol-verification counters."""
    return _protocol.counts()


def reset_protocol_counts():
    _protocol.reset()


# --------------------------------------------------- latency histograms
# Log-bucketed distributions (``obs.registry.Histogram``: 8 buckets per
# octave, p50/p90/p99 accessors) — the mean-only counters above cannot
# distinguish a p99 spike from a shifted mean; these can.

# Per-opcode PS RPC latency (one observation per CLIENT round trip,
# labeled ``OP_PULL``/``OP_PUSH``/... — ``opcodes.op_name``) plus the
# request payload bytes it carried (keys + payload, header excluded), as
# a counter family keyed the same way.  Recording rides ``_rpc``'s
# success path; counter-silent probes (``record=False``) stay silent
# here too.
_rpc_lat = REGISTRY.histogram(
    "ps_rpc_us",
    "PS client RPC round-trip latency per opcode, microseconds")
_rpc_bytes = REGISTRY.counter_family(
    "ps_rpc_bytes",
    "PS client RPC request payload bytes per opcode (keys + payload)")


def record_rpc(op, us, nbytes):
    """One successful PS client RPC: latency (us) into the per-opcode
    histogram, request bytes into the per-opcode byte counter."""
    _rpc_lat.observe(us, label=op)
    if nbytes:
        _rpc_bytes.inc(op, int(nbytes))


def rpc_stats():
    """{"latency_us": {op: histogram snapshot}, "bytes": {op: total}}."""
    return {"latency_us": _rpc_lat.snapshot(),
            "bytes": _rpc_bytes.counts()}


def reset_rpc_stats():
    _rpc_lat.reset()
    _rpc_bytes.reset()


# Serving latency: per-request queue wait (submit -> batch claim) and
# per-batch device-call time, labeled ``queue_wait`` / ``batch``.
_serve_latency = REGISTRY.histogram(
    "serve_latency_us",
    "serving latency: per-request queue wait and per-batch device "
    "call, microseconds")


def record_serve_latency(kind, us):
    """Observe one serving latency sample (``kind``: ``queue_wait`` per
    request, ``batch`` per dispatched micro-batch)."""
    _serve_latency.observe(us, label=kind)


def serve_latency_stats():
    """{kind: histogram snapshot} for the serving latency families."""
    return _serve_latency.snapshot()


# Decode latency: per-token inter-emission latency (``token`` — one
# observation per token STREAMED to a caller, the number a serving SLO is
# written against), per-request join wait (``join_wait`` — submit ->
# joined the in-flight batch), per-request time-to-first-token (``ttft``
# — submit -> FIRST generated token, the prompt-ingestion latency
# chunked prefill attacks; distinct from the steady-state ``token``
# gap), per-engine-step device call (``step``), and detach->reseat
# migration latency for recovered in-flight streams (``recovery`` — one
# observation per continuation seated on a survivor, ISSUE 19).
_decode_latency = REGISTRY.histogram(
    "decode_latency_us",
    "decode latency: per-token emission, per-request join wait, "
    "time-to-first-token, per-step device call, and detach->reseat "
    "stream recovery, microseconds")


def record_decode_latency(kind, us):
    """Observe one decode latency sample (``kind``: ``token`` per emitted
    token, ``join_wait`` per joined request, ``ttft`` once per stream at
    its first generated token, ``step`` per engine step, ``recovery``
    per migrated continuation at reseat)."""
    _decode_latency.observe(us, label=kind)


def decode_latency_stats():
    """{kind: histogram snapshot} for the decode latency families."""
    return _decode_latency.snapshot()


# Executor step wall time, labeled by subexecutor name.  OFF by default:
# the observation costs ~0.5us (two clock reads + one bucketed insert),
# which the dispatch-gap work (PR 9) fought to excise — benches and
# traced runs enable it (``enable_step_timing`` / ``HETU_STEP_TIMING=1``
# / any ``HETU_TRACE=1`` session records spans anyway).
_step_time = REGISTRY.histogram(
    "step_time_us",
    "executor step wall time per subexecutor, microseconds (enable "
    "with metrics.enable_step_timing or HETU_STEP_TIMING=1)")

#: read directly by ``SubExecutor.run`` — a module attribute load, not
#: a function call, keeps the disabled path at ~one global read
step_timing = False


def _init_step_timing():
    global step_timing
    import os
    step_timing = os.environ.get("HETU_STEP_TIMING", "0").lower() \
        not in ("", "0", "false", "off")


_init_step_timing()


def enable_step_timing(on=True):
    """Turn the per-step wall-time histogram on/off (see
    ``step_time_us``'s registration note for why it is opt-in)."""
    global step_timing
    step_timing = bool(on)


def record_step_time(us, label="default"):
    """Observe one executor step's wall time (called by
    ``SubExecutor.run`` when step timing is enabled)."""
    _step_time.observe(us, label=label)


def step_time_stats():
    """{subexecutor: histogram snapshot} of recorded step wall times."""
    return _step_time.snapshot()


def reset_step_times():
    _step_time.reset()


# ------------------------------------------------------------- run gauges
# Per-run step-time/MFU gauges: ``obs.record_mfu`` computes MFU from the
# PR 5 inferred-shape FLOP model (``obs.graph_flops``) over measured
# step time and publishes both here, labeled by run/config name — the
# measured half of the BENCH trajectory (ROADMAP item 2).

_mfu_gauge = REGISTRY.gauge(
    "mfu",
    "model FLOP/s utilization per run: inferred-shape FLOPs / step "
    "time / hardware peak")
_step_gauge = REGISTRY.gauge(
    "step_time_ms",
    "measured step wall time per run, milliseconds")


def record_run_gauges(label, step_time_ms, mfu):
    """Publish one run's measured step time + MFU gauges."""
    _step_gauge.set(step_time_ms, label=label)
    _mfu_gauge.set(mfu, label=label)


def run_gauges():
    """{"mfu": {label: v}, "step_time_ms": {label: v}}."""
    return {"mfu": _mfu_gauge.values(),
            "step_time_ms": _step_gauge.values()}


# ------------------------------------------------------------ one-registry view

#: the counter families in registration order — ``all_counts`` and the
#: profiler's ``all_counters`` read this instead of seven accessors
_FAMILIES = {
    "flash_fallbacks": _flash,
    "emb_pallas_fallbacks": _emb_pallas,
    "faults": _faults,
    "elastic": _elastic,
    "concurrency": _concurrency,
    "remat": _remat,
    "autoparallel": _autoparallel,
    "cache": _cache,
    "zero": _zero,
    "step_cache": _step_cache,
    "run_plan": _run_plan,
    "serve": _serve,
    "decode": _decode,
    "prefix_cache": _prefix_cache,
    "decode_recovery": _decode_recovery,
    "serve_rejection_reason": _serve_reject,
    "fleet": _fleet,
    "protocol": _protocol,
    "ps_rpc_bytes": _rpc_bytes,
}


def all_counts():
    """{family: {kind: count}} over EVERY counter family — the one-call
    view behind ``HetuProfiler.all_counters()`` (the per-family
    accessors are thin slices of this)."""
    return {name: fam.counts() for name, fam in _FAMILIES.items()}


def reset_all():
    """Zero every registered instrument — counters, histograms and
    gauges — in one call (replaces the per-family ``reset_*`` bodies,
    which remain as thin delegates)."""
    REGISTRY.reset_all()


def _np(x):
    return np.asarray(x)


def accuracy(y_pred, y_true):
    """Row-wise argmax accuracy; accepts one-hot or class-index y_true."""
    y_pred = _np(y_pred)
    y_true = _np(y_true)
    pred = np.argmax(y_pred, axis=-1)
    true = np.argmax(y_true, axis=-1) if y_true.ndim == y_pred.ndim else y_true
    return float((pred == true).mean())


def auc(y_pred, y_true):
    """Binary ROC-AUC via rank statistic (ties averaged)."""
    score = _np(y_pred).reshape(-1)
    label = _np(y_true).reshape(-1)
    # average ranks with ties, vectorized: rank of a tied group = mean of its
    # positions = start + (count-1)/2
    uniq, inv, counts = np.unique(score, return_inverse=True,
                                  return_counts=True)
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    ranks = (starts + (counts - 1) / 2.0 + 1.0)[inv]
    pos = label > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def confusion_matrix(y_pred, y_true, num_classes=None):
    pred = np.argmax(_np(y_pred), axis=-1) if _np(y_pred).ndim > 1 else _np(y_pred)
    true = np.argmax(_np(y_true), axis=-1) if _np(y_true).ndim > 1 else _np(y_true)
    n = num_classes or int(max(pred.max(), true.max())) + 1
    cm = np.zeros((n, n), np.int64)
    np.add.at(cm, (true.astype(int), pred.astype(int)), 1)
    return cm


def precision(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[:, cls].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def recall(y_pred, y_true, cls=1):
    cm = confusion_matrix(y_pred, y_true)
    denom = cm[cls, :].sum()
    return float(cm[cls, cls] / denom) if denom else 0.0


def f1_score(y_pred, y_true, cls=1):
    p = precision(y_pred, y_true, cls)
    r = recall(y_pred, y_true, cls)
    return 2 * p * r / (p + r) if (p + r) else 0.0
