"""Flagship model zoo built on the graph API (reference keeps these in
``examples/transformers/*``; they live in-package here so benchmarks, the
graft entry and examples share one implementation)."""
from .bert import BertConfig, bert_model, bert_pretrain_graph
