"""Flagship model zoo built on the graph API (reference keeps these in
``examples/transformers/*``; they live in-package here so benchmarks, the
graft entry and examples share one implementation)."""
from .bert import BertConfig, bert_model, bert_pretrain_graph
from .gpt2 import GPT2Config, gpt2_model, gpt2_lm_graph, synthetic_lm_batch
from .t5 import (T5Config, t5_encoder, t5_decoder, t5_seq2seq_graph,
                 synthetic_seq2seq_batch)
from .vit import (ViTConfig, vit_model, vit_classify_graph,
                  synthetic_image_batch)
from .transformer import (TransformerConfig, transformer_graph,
                          synthetic_copy_batch)
