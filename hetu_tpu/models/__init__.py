"""Flagship model zoo built on the graph API (reference keeps these in
``examples/transformers/*``; they live in-package here so benchmarks, the
graft entry and examples share one implementation)."""
from .bert import (BertConfig, bert_model, bert_pretrain_graph,
                   bert_pooler, bert_classify_graph)
from .gpt2 import (GPT2Config, gpt2_model, gpt2_lm_graph,
                   gpt2_decode_graph, gpt2_decode_chunked_graph,
                   synthetic_lm_batch)
from .t5 import (T5Config, t5_encoder, t5_decoder, t5_seq2seq_graph,
                 synthetic_seq2seq_batch)
from .vit import (ViTConfig, vit_model, vit_classify_graph,
                  synthetic_image_batch)
from .swin import SwinConfig, swin_model, swin_classify_graph
from .transformer import (TransformerConfig, transformer_graph,
                          synthetic_copy_batch)
from .bart import BartConfig, bart_seq2seq_graph
from .longformer import (LongformerConfig, longformer_model,
                         longformer_mlm_graph, longformer_attention_mask)
from .reformer import (ReformerConfig, reformer_model, reformer_lm_graph,
                       lsh_attention)
from .transfoxl import TransfoXLConfig, transfoxl_model, transfoxl_lm_graph
from .clip import CLIPConfig, clip_graph, clip_vision_tower, clip_text_tower
from .mae import MAEConfig, mae_pretrain_graph, synthetic_mae_batch
from .bigbird import (BigBirdConfig, bigbird_model, bigbird_mlm_graph,
                      bigbird_attention_mask)
from .xlnet import (XLNetConfig, xlnet_model, xlnet_plm_graph,
                    perm_masks_from_order, synthetic_plm_batch)
