"""BART (reference ``examples/transformers/bart/hetu_bart.py`` — HF-style
BART built from hetu ops).  TPU-native rewrite: post-LN encoder-decoder with
learned position embeddings (offset 2, BART quirk), fused ``sdpa_op``
attention (causal in the decoder, cross-attention to encoder memory),
activations flattened to (batch*seq, d_model) so every projection is one
MXU matmul; the LM head ties the shared token embedding.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class BartConfig:
    def __init__(self, vocab_size=50265, d_model=768, encoder_layers=6,
                 decoder_layers=6, encoder_attention_heads=12,
                 decoder_attention_heads=12, encoder_ffn_dim=3072,
                 decoder_ffn_dim=3072, max_position_embeddings=1024,
                 dropout=0.1, layer_norm_eps=1e-5, batch_size=8,
                 src_len=128, tgt_len=128):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.encoder_layers = encoder_layers
        self.decoder_layers = decoder_layers
        self.encoder_attention_heads = encoder_attention_heads
        self.decoder_attention_heads = decoder_attention_heads
        self.encoder_ffn_dim = encoder_ffn_dim
        self.decoder_ffn_dim = decoder_ffn_dim
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.src_len = src_len
        self.tgt_len = tgt_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("d_model", 128)
        kw.setdefault("encoder_layers", 2)
        kw.setdefault("decoder_layers", 2)
        kw.setdefault("encoder_attention_heads", 2)
        kw.setdefault("decoder_attention_heads", 2)
        kw.setdefault("encoder_ffn_dim", 256)
        kw.setdefault("decoder_ffn_dim", 256)
        kw.setdefault("vocab_size", 512)
        return cls(**kw)


def _learned_positions(cfg, seq, name):
    """BART's learned positions start at offset 2 (pad/bos reserved)."""
    table = init.truncated_normal(
        (cfg.max_position_embeddings + 2, cfg.d_model), 0.0, 0.02, name=name)
    pos = Variable(name + ".ids",
                   value=(np.arange(seq) + 2).astype(np.float32),
                   trainable=False)
    return ops.embedding_lookup_op(table, pos)          # (seq, d_model)


def _embed(cfg, shared, ids, seq, name):
    e = ops.embedding_lookup_op(shared, ids)            # (B, seq, d)
    pe = _learned_positions(cfg, seq, name + ".pos")
    pe = ops.array_reshape_op(pe, output_shape=(1, seq, cfg.d_model))
    e = e + ops.broadcastto_op(pe, e)
    e = ops.array_reshape_op(
        e, output_shape=(cfg.batch_size * seq, cfg.d_model))
    e = LayerNorm(cfg.d_model, cfg.layer_norm_eps, name + ".ln")(e)
    return ops.dropout_op(e, 1.0 - cfg.dropout)


def _post_ln_block(cfg, x, sub, residual_name):
    return LayerNorm(cfg.d_model, cfg.layer_norm_eps, residual_name)(x + sub)


def bart_encoder(cfg, x, name="bart.encoder"):
    for i in range(cfg.encoder_layers):
        ln = f"{name}.layer{i}"
        mha = MultiHeadAttention(cfg.d_model, cfg.encoder_attention_heads,
                                 dropout=cfg.dropout, name=ln + ".attn")
        x = _post_ln_block(cfg, x, mha(x, cfg.batch_size, cfg.src_len),
                           ln + ".ln1")
        h = Linear(cfg.d_model, cfg.encoder_ffn_dim, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".fc1")(x)
        h = Linear(cfg.encoder_ffn_dim, cfg.d_model,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".fc2")(h)
        x = _post_ln_block(cfg, x, ops.dropout_op(h, 1.0 - cfg.dropout),
                           ln + ".ln2")
    return x


def bart_decoder(cfg, y, memory, name="bart.decoder"):
    for i in range(cfg.decoder_layers):
        ln = f"{name}.layer{i}"
        self_attn = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dropout=cfg.dropout,
            causal=True, name=ln + ".self")
        y = _post_ln_block(cfg, y,
                           self_attn(y, cfg.batch_size, cfg.tgt_len),
                           ln + ".ln1")
        cross = MultiHeadAttention(
            cfg.d_model, cfg.decoder_attention_heads, dropout=cfg.dropout,
            name=ln + ".cross")
        y = _post_ln_block(
            cfg, y, cross(y, cfg.batch_size, cfg.tgt_len, kv=memory,
                          kv_seq=cfg.src_len), ln + ".ln2")
        h = Linear(cfg.d_model, cfg.decoder_ffn_dim, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".fc1")(y)
        h = Linear(cfg.decoder_ffn_dim, cfg.d_model,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".fc2")(h)
        y = _post_ln_block(cfg, y, ops.dropout_op(h, 1.0 - cfg.dropout),
                           ln + ".ln3")
    return y


def bart_seq2seq_graph(cfg, name="bart"):
    """Denoising seq2seq training graph (teacher forcing).

    Returns (feeds dict, loss node, logits node); the LM head is tied to
    the shared embedding (logits = h @ E^T, BART semantics).
    """
    src = placeholder_op("input_ids", shape=(cfg.batch_size, cfg.src_len),
                         dtype=np.int32)
    tgt_in = placeholder_op("decoder_input_ids",
                            shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    labels = placeholder_op("labels", shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    shared = init.truncated_normal((cfg.vocab_size, cfg.d_model), 0.0, 0.02,
                                   name=name + ".shared_embed")
    enc_in = _embed(cfg, shared, src, cfg.src_len, name + ".enc_embed")
    dec_in = _embed(cfg, shared, tgt_in, cfg.tgt_len, name + ".dec_embed")
    memory = bart_encoder(cfg, enc_in, name + ".encoder")
    hidden = bart_decoder(cfg, dec_in, memory, name + ".decoder")
    logits = ops.matmul_op(hidden, shared, trans_B=True)  # tied head
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.tgt_len)
    feeds = {"input_ids": src, "decoder_input_ids": tgt_in, "labels": labels}
    return feeds, loss, logits
