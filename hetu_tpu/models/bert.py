"""BERT (reference ``examples/transformers/bert/hetu_bert.py`` — an HF-style
BERT built from hetu ops).  TPU-native rewrite: same graph-API surface, but
attention is the fused ``sdpa_op`` (Pallas flash kernel on TPU) instead of
composed batch_matmul+softmax, and activations flow as (batch*seq, hidden)
2-D tensors so every projection is one MXU matmul.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm, Embedding


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, layer_norm_eps=1e-12,
                 batch_size=8, seq_len=128):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.seq_len = seq_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        kw.setdefault("hidden_size", 1024)
        kw.setdefault("num_hidden_layers", 24)
        kw.setdefault("num_attention_heads", 16)
        kw.setdefault("intermediate_size", 4096)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 512)
        kw.setdefault("vocab_size", 1024)
        return cls(**kw)


def _embeddings(cfg, input_ids, token_type_ids, name="embeddings"):
    word = Embedding(cfg.vocab_size, cfg.hidden_size,
                     init.GenTruncatedNormal(0.0, 0.02), name + ".word")
    pos_table = init.truncated_normal(
        (cfg.max_position_embeddings, cfg.hidden_size), 0.0, 0.02,
        name=name + ".position")
    ttype = Embedding(cfg.type_vocab_size, cfg.hidden_size,
                      init.GenTruncatedNormal(0.0, 0.02), name + ".token_type")
    positions = Variable(
        name + ".pos_ids",
        value=np.arange(cfg.seq_len, dtype=np.float32), trainable=False)
    e = word(input_ids) + ops.embedding_lookup_op(pos_table, positions) \
        + ttype(token_type_ids)
    e = ops.array_reshape_op(
        e, output_shape=(cfg.batch_size * cfg.seq_len, cfg.hidden_size))
    e = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".ln")(e)
    return ops.dropout_op(e, 1.0 - cfg.hidden_dropout_prob)


def _encoder_layer(cfg, x, name, mask=None):
    # attention_probs_dropout_prob applies to the attention OUTPUT, not
    # the probabilities (flash-incompatible) — see layers/attention.py
    mha = MultiHeadAttention(cfg.hidden_size, cfg.num_attention_heads,
                             dropout=cfg.attention_probs_dropout_prob,
                             name=name + ".attn")
    attn = mha(x, cfg.batch_size, cfg.seq_len, mask=mask)
    x = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps,
                  name + ".ln1")(x + attn)
    h = Linear(cfg.hidden_size, cfg.intermediate_size, activation="gelu",
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".ffn1")(x)
    h = Linear(cfg.intermediate_size, cfg.hidden_size,
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".ffn2")(h)
    h = ops.dropout_op(h, 1.0 - cfg.hidden_dropout_prob)
    return LayerNorm(cfg.hidden_size, cfg.layer_norm_eps,
                     name + ".ln2")(x + h)


def bert_model(cfg, input_ids, token_type_ids, attention_mask=None,
               name="bert"):
    """Returns sequence_output node of shape (batch*seq, hidden).

    ``attention_mask``: optional (batch, seq) node of 1/0 key-validity flags
    (reference hetu_bert.py's extended_attention_mask input) — reshaped once
    to the (B, 1, 1, S) key-padding form that ``sdpa_masked_op`` routes to
    the flash kernel's O(S) key-mask strip path.
    """
    x = _embeddings(cfg, input_ids, token_type_ids, name + ".embeddings")
    mask = None
    if attention_mask is not None:
        mask = ops.array_reshape_op(
            attention_mask, output_shape=(cfg.batch_size, 1, 1, cfg.seq_len))
    for i in range(cfg.num_hidden_layers):
        x = _encoder_layer(cfg, x, f"{name}.layer{i}", mask=mask)
    return x


def bert_pretrain_graph(cfg, name="bert", use_mask=True, use_nsp=False):
    """Full MLM pretraining graph (reference train_hetu_bert_dp.py flow).

    Returns (placeholders dict, loss node, logits node).
    masked_lm_labels: (batch, seq) with -1 for unmasked positions.
    ``use_mask=True`` (the flagship default) adds an ``attention_mask``
    (batch, seq) int32 input so padded pretraining attends only to real
    tokens (reference hetu_bert.py attention_mask input).
    ``use_nsp=True`` adds the next-sentence-prediction objective of the
    reference's full pretrain loss (train_hetu_bert.py:59 — mlm + nsp):
    pooler over [CLS] → 2-way head, a ``next_sentence_label`` (batch,)
    feed, and loss = mlm_mean + nsp_mean.  Opt-in so the flagship bench
    workload (MLM-only, BASELINE.md) is unchanged.
    """
    from ..graph.node import placeholder_op
    shape = (cfg.batch_size, cfg.seq_len)
    # int32 placeholders: token ids/labels must never ride the fp32→bf16
    # compute_dtype cast (bf16 only represents integers exactly up to 256)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    token_type_ids = placeholder_op("token_type_ids", shape=shape,
                                    dtype=np.int32)
    labels = placeholder_op("masked_lm_labels", shape=shape, dtype=np.int32)
    attention_mask = placeholder_op("attention_mask", shape=shape,
                                    dtype=np.int32) if use_mask else None

    seq = bert_model(cfg, input_ids, token_type_ids,
                     attention_mask=attention_mask, name=name)
    # MLM head: transform + tied-ish decoder (fresh decoder weights, like the
    # reference which also keeps an independent decoder matrix)
    h = Linear(cfg.hidden_size, cfg.hidden_size, activation="gelu",
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".mlm_transform")(seq)
    h = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".mlm_ln")(h)
    logits = Linear(cfg.hidden_size, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".mlm_decoder")(h)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.seq_len)
    feeds = {"input_ids": input_ids, "token_type_ids": token_type_ids,
             "masked_lm_labels": labels}
    if use_nsp:
        nsp_label = placeholder_op("next_sentence_label",
                                   shape=(cfg.batch_size,), dtype=np.int32)
        pooled = bert_pooler(cfg, seq, name + ".pooler")
        nsp_logits = Linear(cfg.hidden_size, 2,
                            initializer=init.GenTruncatedNormal(0.0, 0.02),
                            name=name + ".seq_relationship")(pooled)
        loss = loss + ops.reduce_mean_op(
            ops.softmaxcrossentropy_sparse_op(nsp_logits, nsp_label), [0])
        feeds["next_sentence_label"] = nsp_label
    if attention_mask is not None:
        feeds["attention_mask"] = attention_mask
    return feeds, loss, logits


def bert_pooler(cfg, seq, name="bert.pooler"):
    """HF-style pooler: dense+tanh over the [CLS] (first) token
    (reference hetu_bert.py BertPooler).  ``seq``: (batch*seq_len,
    hidden) → (batch, hidden)."""
    x = ops.array_reshape_op(
        seq, output_shape=(cfg.batch_size, cfg.seq_len, cfg.hidden_size))
    cls = ops.slice_op(x, begin=(0, 0, 0),
                       size=(cfg.batch_size, 1, cfg.hidden_size))
    cls = ops.array_reshape_op(
        cls, output_shape=(cfg.batch_size, cfg.hidden_size))
    return Linear(cfg.hidden_size, cfg.hidden_size, activation="tanh",
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".dense")(cls)


def bert_classify_graph(cfg, num_labels, name="bert", use_mask=True):
    """Sequence-classification fine-tuning graph (the reference's GLUE
    flow: ``examples/transformers/bert/test_glue_hetu_bert.py`` —
    pooler + classifier head over the pretrained encoder).

    Returns (placeholders dict, loss node, logits node).  ``labels``:
    (batch,) int class ids.  Warm-start: encoder/embedding variable
    names match ``bert_pretrain_graph``'s exactly, so
    ``Executor.load(pretrain_ckpt, params_only=True)`` restores the
    shared trunk by name and leaves the fresh pooler/classifier at
    their init — the pretrain → fine-tune flow needs no remapping.
    (``params_only`` matters: a full ``load`` would also resume the
    pretrain LR-schedule step and Adam moments into the new task.)
    """
    from ..graph.node import placeholder_op
    shape = (cfg.batch_size, cfg.seq_len)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    token_type_ids = placeholder_op("token_type_ids", shape=shape,
                                    dtype=np.int32)
    labels = placeholder_op("labels", shape=(cfg.batch_size,),
                            dtype=np.int32)
    attention_mask = placeholder_op("attention_mask", shape=shape,
                                    dtype=np.int32) if use_mask else None

    seq = bert_model(cfg, input_ids, token_type_ids,
                     attention_mask=attention_mask, name=name)
    pooled = bert_pooler(cfg, seq, name + ".pooler")
    pooled = ops.dropout_op(pooled, 1.0 - cfg.hidden_dropout_prob)
    logits = Linear(cfg.hidden_size, num_labels,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".classifier")(pooled)
    loss = ops.reduce_mean_op(
        ops.softmaxcrossentropy_sparse_op(logits, labels), [0])
    feeds = {"input_ids": input_ids, "token_type_ids": token_type_ids,
             "labels": labels}
    if attention_mask is not None:
        feeds["attention_mask"] = attention_mask
    return feeds, loss, logits


def synthetic_mlm_batch(cfg, seed=0, mask_frac=0.15, full_frac=0.35):
    """Deterministic synthetic MLM batch (hermetic benches/tests).

    Returns (ids, token_type_ids, labels, attention_mask).  Sequence lengths
    follow a padded-pretraining distribution: ``full_frac`` of the batch is
    packed full-length, the rest is uniform over [seq/4, seq] (real MLM
    corpora mix packed segments with short documents).  Positions beyond a
    row's length are PAD: id 0, label -1, attention_mask 0.
    """
    rng = np.random.RandomState(seed)
    b, s = cfg.batch_size, cfg.seq_len
    ids = rng.randint(0, cfg.vocab_size, (b, s))
    tt = np.zeros((b, s), np.int32)
    lengths = np.full((b,), s, np.int32)
    short = rng.rand(b) >= full_frac
    lengths[short] = rng.randint(max(1, s // 4), s + 1, short.sum())
    attn = (np.arange(s)[None, :] < lengths[:, None])
    ids[~attn] = 0
    labels = np.full((b, s), -1, np.int64)
    mask = (rng.rand(b, s) < mask_frac) & attn
    labels[mask] = ids[mask]
    return (ids.astype(np.int32), tt, labels.astype(np.int32),
            attn.astype(np.int32))
