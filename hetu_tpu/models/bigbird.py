"""BigBird (reference ``examples/transformers/bigbird/``).

TPU-native rewrite: the window + global + random block-sparse pattern is a
STATIC 0/1 mask built at graph-construction time (random blocks drawn once
from a seed, as in the reference's static ``bigbird_block_rand_mask``) and
applied through the fused ``sdpa_masked_op`` — no gather kernels; XLA sees
one fixed mask tensor.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm


class BigBirdConfig:
    def __init__(self, vocab_size=50358, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, block_size=64, num_random_blocks=3,
                 num_global_blocks=1, max_position_embeddings=4096,
                 hidden_dropout_prob=0.1, layer_norm_eps=1e-12,
                 batch_size=2, seq_len=1024, mask_seed=0):
        assert seq_len % block_size == 0
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.block_size = block_size
        self.num_random_blocks = num_random_blocks
        self.num_global_blocks = num_global_blocks
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.mask_seed = mask_seed

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 256)
        kw.setdefault("block_size", 8)
        kw.setdefault("num_random_blocks", 2)
        kw.setdefault("vocab_size", 512)
        kw.setdefault("seq_len", 64)
        return cls(**kw)


def bigbird_attention_mask(seq_len, block_size, num_random_blocks,
                           num_global_blocks=1, seed=0):
    """Static block-sparse mask (S, S): sliding window of 3 blocks, global
    first block(s), plus ``num_random_blocks`` random key blocks per query
    block (the ITC pattern of the paper)."""
    nb = seq_len // block_size
    rng = np.random.RandomState(seed)
    blk = np.zeros((nb, nb), bool)
    for i in range(nb):
        for j in (i - 1, i, i + 1):                   # window
            if 0 <= j < nb:
                blk[i, j] = True
        cand = [j for j in range(nb)
                if abs(j - i) > 1 and j >= num_global_blocks]
        if cand:
            pick = rng.choice(cand, size=min(num_random_blocks, len(cand)),
                              replace=False)
            blk[i, pick] = True                        # random
    blk[:num_global_blocks, :] = True                  # global rows
    blk[:, :num_global_blocks] = True                  # global cols
    return np.kron(blk, np.ones((block_size, block_size))).astype(np.float32)


class _BigBirdLayer:
    def __init__(self, cfg, name, mask=None):
        h = cfg.hidden_size
        self.cfg = cfg
        self.heads = cfg.num_attention_heads
        self.dk = h // self.heads
        self.q = Linear(h, h, name=name + ".q")
        self.k = Linear(h, h, name=name + ".k")
        self.v = Linear(h, h, name=name + ".v")
        self.o = Linear(h, h, name=name + ".o")
        if mask is None:  # standalone use; the model shares one per stack
            m = bigbird_attention_mask(
                cfg.seq_len, cfg.block_size, cfg.num_random_blocks,
                cfg.num_global_blocks, cfg.mask_seed)
            mask = Variable(name + ".sparse_mask",
                            value=m.reshape(1, 1, cfg.seq_len, cfg.seq_len),
                            trainable=False)
        self.mask = mask

    def _split(self, x):
        from .common import split_heads
        cfg = self.cfg
        return split_heads(x, cfg.batch_size, cfg.seq_len, self.heads,
                           self.dk)

    def __call__(self, x):
        from .common import merge_heads
        cfg = self.cfg
        o = ops.sdpa_masked_op(self._split(self.q(x)), self._split(self.k(x)),
                               self._split(self.v(x)), self.mask)
        o = merge_heads(o, cfg.batch_size, cfg.seq_len, cfg.hidden_size)
        return ops.dropout_op(self.o(o), 1.0 - cfg.hidden_dropout_prob)


def bigbird_model(cfg, input_ids, name="bigbird"):
    tokens = cfg.batch_size * cfg.seq_len
    word = init.truncated_normal((cfg.vocab_size, cfg.hidden_size), 0.0, 0.02,
                                 name=name + ".word")
    pos = init.truncated_normal(
        (cfg.max_position_embeddings, cfg.hidden_size), 0.0, 0.02,
        name=name + ".pos")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(cfg.seq_len, dtype=np.float32),
                       trainable=False)
    x = ops.embedding_lookup_op(word, input_ids) \
        + ops.embedding_lookup_op(pos, pos_ids)
    x = ops.array_reshape_op(x, output_shape=(tokens, cfg.hidden_size))
    x = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".emb_ln")(x)
    x = ops.dropout_op(x, 1.0 - cfg.hidden_dropout_prob)
    m = bigbird_attention_mask(
        cfg.seq_len, cfg.block_size, cfg.num_random_blocks,
        cfg.num_global_blocks, cfg.mask_seed)
    shared_mask = Variable(name + ".sparse_mask",
                           value=m.reshape(1, 1, cfg.seq_len, cfg.seq_len),
                           trainable=False)
    from .common import post_ln_encoder_stack
    return post_ln_encoder_stack(
        x, cfg, lambda nm: _BigBirdLayer(cfg, nm, mask=shared_mask), name)


def bigbird_mlm_graph(cfg, name="bigbird"):
    """MLM pretraining graph. Returns (feeds dict, loss, logits)."""
    shape = (cfg.batch_size, cfg.seq_len)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    labels = placeholder_op("labels", shape=shape, dtype=np.int32)
    x = bigbird_model(cfg, input_ids, name)
    logits = Linear(cfg.hidden_size, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".mlm_head")(x)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.seq_len)
    return {"input_ids": input_ids, "labels": labels}, loss, logits
