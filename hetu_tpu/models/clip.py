"""CLIP (reference ``examples/transformers/clip/``).

TPU-native rewrite: ViT-style image tower (patchify = one MXU GEMM) and a
causal text tower, projected to a shared space; the symmetric InfoNCE loss
is one (B, B) logits matmul with a learnable temperature — entirely
matmul-shaped for the MXU.  On a 'dp' mesh the logits matrix shards over
batch and XLA inserts the gather of the other shard's embeddings.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm


class CLIPConfig:
    def __init__(self, vocab_size=49408, text_hidden=512, text_layers=12,
                 text_heads=8, text_len=77, image_size=224, patch_size=32,
                 vision_hidden=768, vision_layers=12, vision_heads=12,
                 projection_dim=512, logit_scale_init=2.6592,
                 layer_norm_eps=1e-5, batch_size=8):
        self.vocab_size = vocab_size
        self.text_hidden = text_hidden
        self.text_layers = text_layers
        self.text_heads = text_heads
        self.text_len = text_len
        self.image_size = image_size
        self.patch_size = patch_size
        self.vision_hidden = vision_hidden
        self.vision_layers = vision_layers
        self.vision_heads = vision_heads
        self.projection_dim = projection_dim
        self.logit_scale_init = logit_scale_init
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.num_patches = (image_size // patch_size) ** 2

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 512)
        kw.setdefault("text_hidden", 64)
        kw.setdefault("text_layers", 2)
        kw.setdefault("text_heads", 2)
        kw.setdefault("text_len", 16)
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("vision_hidden", 64)
        kw.setdefault("vision_layers", 2)
        kw.setdefault("vision_heads", 2)
        kw.setdefault("projection_dim", 32)
        return cls(**kw)


def clip_vision_tower(cfg, images, name="clip.vision"):
    """(B, C, H, W) → pooled (B, vision_hidden)."""
    from .common import patchify
    x = patchify(images, cfg.batch_size, 3, cfg.image_size, cfg.patch_size,
                 cfg.vision_hidden, name + ".patch", bias=False)
    pos = init.truncated_normal((cfg.num_patches, cfg.vision_hidden),
                                0.0, 0.02, name=name + ".pos")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(cfg.num_patches, dtype=np.float32),
                       trainable=False)
    pe = ops.embedding_lookup_op(pos, pos_ids)
    pe = ops.array_reshape_op(
        pe, output_shape=(1, cfg.num_patches, cfg.vision_hidden))
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, cfg.num_patches, cfg.vision_hidden))
    x = x + ops.broadcastto_op(pe, x)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size * cfg.num_patches, cfg.vision_hidden))
    x = LayerNorm(cfg.vision_hidden, cfg.layer_norm_eps, name + ".pre_ln")(x)
    from .common import pre_ln_block
    for i in range(cfg.vision_layers):
        x = pre_ln_block(cfg.vision_hidden, cfg.vision_heads,
                         cfg.num_patches, cfg.batch_size,
                         cfg.layer_norm_eps, f"{name}.layer{i}")(x)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, cfg.num_patches, cfg.vision_hidden))
    pooled = ops.reduce_mean_op(x, [1])
    return LayerNorm(cfg.vision_hidden, cfg.layer_norm_eps,
                     name + ".post_ln")(pooled)


def clip_text_tower(cfg, input_ids, name="clip.text"):
    """(B, L) ids → pooled (B, text_hidden) (last-token pooling ≈ EOS)."""
    word = init.truncated_normal((cfg.vocab_size, cfg.text_hidden), 0.0, 0.02,
                                 name=name + ".word")
    pos = init.truncated_normal((cfg.text_len, cfg.text_hidden), 0.0, 0.01,
                                name=name + ".pos")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(cfg.text_len, dtype=np.float32),
                       trainable=False)
    x = ops.embedding_lookup_op(word, input_ids) \
        + ops.embedding_lookup_op(pos, pos_ids)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size * cfg.text_len, cfg.text_hidden))
    from .common import pre_ln_block
    for i in range(cfg.text_layers):
        x = pre_ln_block(cfg.text_hidden, cfg.text_heads, cfg.text_len,
                         cfg.batch_size, cfg.layer_norm_eps,
                         f"{name}.layer{i}", causal=True)(x)
    x = LayerNorm(cfg.text_hidden, cfg.layer_norm_eps, name + ".ln_f")(x)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, cfg.text_len, cfg.text_hidden))
    # last-position pooling (fixed-length inputs; EOS sits at the end)
    last = ops.slice_op(x, begin=(0, cfg.text_len - 1, 0),
                        size=(cfg.batch_size, 1, cfg.text_hidden))
    return ops.array_reshape_op(last, output_shape=(cfg.batch_size,
                                                    cfg.text_hidden))


def _l2_normalize(x):
    sq = ops.reduce_sum_op(ops.mul_op(x, x), [1], keepdims=True)
    return x / ops.broadcastto_op(ops.sqrt_op(sq + 1e-12), x)


def clip_graph(cfg, name="clip"):
    """Contrastive pretraining graph.

    Returns (feeds dict, loss node, (img_emb, txt_emb) nodes).
    """
    images = placeholder_op("images",
                            shape=(cfg.batch_size, 3, cfg.image_size,
                                   cfg.image_size))
    input_ids = placeholder_op("input_ids",
                               shape=(cfg.batch_size, cfg.text_len),
                               dtype=np.int32)
    iv = clip_vision_tower(cfg, images, name + ".vision")
    tv = clip_text_tower(cfg, input_ids, name + ".text")
    img = Linear(cfg.vision_hidden, cfg.projection_dim, bias=False,
                 name=name + ".visual_projection")(iv)
    txt = Linear(cfg.text_hidden, cfg.projection_dim, bias=False,
                 name=name + ".text_projection")(tv)
    img = _l2_normalize(img)
    txt = _l2_normalize(txt)
    scale = Variable(name + ".logit_scale",
                     value=np.asarray([cfg.logit_scale_init], np.float32))
    logits = ops.matmul_op(img, txt, trans_B=True)        # (B, B)
    logits = logits * ops.broadcastto_op(ops.exp_op(scale), logits)
    targets = Variable(name + ".targets",
                       value=np.arange(cfg.batch_size, dtype=np.float32),
                       trainable=False)
    li = ops.reduce_mean_op(
        ops.softmaxcrossentropy_sparse_op(logits, targets), [0])
    lt = ops.reduce_mean_op(
        ops.softmaxcrossentropy_sparse_op(
            ops.transpose_op(logits, perm=(1, 0)), targets), [0])
    loss = (li + lt) * 0.5
    return {"images": images, "input_ids": input_ids}, loss, (img, txt)
