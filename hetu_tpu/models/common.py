"""Shared model-graph helpers."""
from .. import ops


def masked_lm_loss(logits, labels, n_tokens, ignored_index=-1):
    """Token-masked cross-entropy: mean over positions whose label !=
    ``ignored_index``.  ``logits``: (n_tokens, vocab); ``labels``: any shape
    flattening to (n_tokens,).  Used by every LM head (BERT MLM, GPT-2
    causal LM, T5/transformer seq2seq)."""
    flat = ops.array_reshape_op(labels, output_shape=(n_tokens,))
    per_tok = ops.softmaxcrossentropy_sparse_op(logits, flat,
                                                ignored_index=ignored_index)
    valid = ops.ne_op(flat, flat * 0.0 + float(ignored_index))
    return ops.reduce_sum_op(per_tok, [0]) \
        / (ops.reduce_sum_op(valid, [0]) + 1e-6)


def patchify(images, batch, channels, image_size, patch_size, hidden,
             name, bias=True):
    """(B, C, H, W) → (B*P, hidden) with one MXU GEMM (shared by ViT/CLIP/
    MAE — reshape (B,C,g,p,g,p) → transpose → (B*g*g, C*p*p) @ W)."""
    from .. import initializers as init
    from ..layers.core import Linear
    p_ = patch_size
    g = image_size // p_
    x = ops.array_reshape_op(
        images, output_shape=(batch, channels, g, p_, g, p_))
    x = ops.transpose_op(x, perm=(0, 2, 4, 1, 3, 5))
    x = ops.array_reshape_op(
        x, output_shape=(batch * g * g, channels * p_ * p_))
    return Linear(channels * p_ * p_, hidden, bias=bias,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name)(x)


def pre_ln_block(hidden, heads, seq, batch, eps, name, causal=False,
                 dropout=0.0):
    """Standard pre-LN transformer encoder block builder (shared by
    ViT/CLIP/MAE towers): x + attn(ln1(x)); x + mlp(ln2(x))."""
    from .. import initializers as init
    from ..layers.attention import MultiHeadAttention
    from ..layers.core import Linear, LayerNorm

    def block(x):
        h = LayerNorm(hidden, eps, name + ".ln1")(x)
        mha = MultiHeadAttention(hidden, heads, causal=causal,
                                 dropout=dropout, name=name + ".attn")
        x = x + mha(h, batch, seq)
        h = LayerNorm(hidden, eps, name + ".ln2")(x)
        h = Linear(hidden, 4 * hidden, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=name + ".mlp1")(h)
        h = Linear(4 * hidden, hidden,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=name + ".mlp2")(h)
        if dropout:
            h = ops.dropout_op(h, 1.0 - dropout)
        return x + h
    return block
