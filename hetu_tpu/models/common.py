"""Shared model-graph helpers."""
from .. import ops


def masked_lm_loss(logits, labels, n_tokens, ignored_index=-1):
    """Token-masked cross-entropy: mean over positions whose label !=
    ``ignored_index``.  ``logits``: (n_tokens, vocab); ``labels``: any shape
    flattening to (n_tokens,).  Used by every LM head (BERT MLM, GPT-2
    causal LM, T5/transformer seq2seq)."""
    flat = ops.array_reshape_op(labels, output_shape=(n_tokens,))
    per_tok = ops.softmaxcrossentropy_sparse_op(logits, flat,
                                                ignored_index=ignored_index)
    valid = ops.ne_op(flat, flat * 0.0 + float(ignored_index))
    return ops.reduce_sum_op(per_tok, [0]) \
        / (ops.reduce_sum_op(valid, [0]) + 1e-6)
