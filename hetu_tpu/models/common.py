"""Shared model-graph helpers."""
from .. import ops


def masked_lm_loss(logits, labels, n_tokens, ignored_index=-1):
    """Token-masked cross-entropy: mean over positions whose label !=
    ``ignored_index``.  ``logits``: (n_tokens, vocab); ``labels``: any shape
    flattening to (n_tokens,).  Used by every LM head (BERT MLM, GPT-2
    causal LM, T5/transformer seq2seq)."""
    flat = ops.array_reshape_op(labels, output_shape=(n_tokens,))
    per_tok = ops.softmaxcrossentropy_sparse_op(logits, flat,
                                                ignored_index=ignored_index)
    valid = ops.ne_op(flat, flat * 0.0 + float(ignored_index))
    return ops.reduce_sum_op(per_tok, [0]) \
        / (ops.reduce_sum_op(valid, [0]) + 1e-6)


def patchify(images, batch, channels, image_size, patch_size, hidden,
             name, bias=True):
    """(B, C, H, W) → (B*P, hidden) with one MXU GEMM (shared by ViT/CLIP/
    MAE — reshape (B,C,g,p,g,p) → transpose → (B*g*g, C*p*p) @ W)."""
    from .. import initializers as init
    from ..layers.core import Linear
    p_ = patch_size
    g = image_size // p_
    x = ops.array_reshape_op(
        images, output_shape=(batch, channels, g, p_, g, p_))
    x = ops.transpose_op(x, perm=(0, 2, 4, 1, 3, 5))
    x = ops.array_reshape_op(
        x, output_shape=(batch * g * g, channels * p_ * p_))
    return Linear(channels * p_ * p_, hidden, bias=bias,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name)(x)


def pre_ln_block(hidden, heads, seq, batch, eps, name, causal=False,
                 dropout=0.0):
    """Standard pre-LN transformer encoder block builder (shared by
    ViT/CLIP/MAE towers): x + attn(ln1(x)); x + mlp(ln2(x))."""
    from .. import initializers as init
    from ..layers.attention import MultiHeadAttention
    from ..layers.core import Linear, LayerNorm

    def block(x):
        h = LayerNorm(hidden, eps, name + ".ln1")(x)
        mha = MultiHeadAttention(hidden, heads, causal=causal,
                                 dropout=dropout, name=name + ".attn")
        x = x + mha(h, batch, seq)
        h = LayerNorm(hidden, eps, name + ".ln2")(x)
        h = Linear(hidden, 4 * hidden, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=name + ".mlp1")(h)
        h = Linear(4 * hidden, hidden,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=name + ".mlp2")(h)
        if dropout:
            h = ops.dropout_op(h, 1.0 - dropout)
        return x + h
    return block


def split_heads(x, batch, seq, heads, head_dim):
    """(batch*seq, hidden) → (batch, heads, seq, head_dim)."""
    x = ops.array_reshape_op(x, output_shape=(batch, seq, heads, head_dim))
    return ops.transpose_op(x, perm=(0, 2, 1, 3))


def merge_heads(x, batch, seq, hidden):
    """(batch, heads, seq, head_dim) → (batch*seq, hidden)."""
    x = ops.transpose_op(x, perm=(0, 2, 1, 3))
    return ops.array_reshape_op(x, output_shape=(batch * seq, hidden))


def post_ln_encoder_stack(x, cfg, attn_factory, name):
    """BERT-style post-LN encoder stack shared by the static-sparse-mask
    models (Longformer/BigBird): per layer, x = LN(x + attn(x));
    x = LN(x + dropout(FFN(x))).  ``attn_factory(layer_name) -> callable``.
    Reads hidden_size / num_hidden_layers / intermediate_size /
    hidden_dropout_prob / layer_norm_eps off ``cfg``."""
    from .. import initializers as init
    from ..layers.core import Linear, LayerNorm
    for i in range(cfg.num_hidden_layers):
        ln = f"{name}.layer{i}"
        attn = attn_factory(ln + ".attn")
        x = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps,
                      ln + ".ln1")(x + attn(x))
        h = Linear(cfg.hidden_size, cfg.intermediate_size, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ffn1")(x)
        h = Linear(cfg.intermediate_size, cfg.hidden_size,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ffn2")(h)
        h = ops.dropout_op(h, 1.0 - cfg.hidden_dropout_prob)
        x = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps,
                      ln + ".ln2")(x + h)
    return x
