"""GPT-2 (reference ``examples/transformers/gpt2/hetu_gpt2.py`` — HF-style
GPT-2 composed from hetu ops).  TPU-native rewrite: pre-LN blocks, fused
causal ``sdpa_op`` (Pallas flash kernel on TPU) instead of composed
batch_matmul+softmax+mask, activations as (batch*seq, hidden) MXU matmuls.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class GPT2Config:
    def __init__(self, vocab_size=50257, n_positions=1024, n_embd=768,
                 n_layer=12, n_head=12, resid_pdrop=0.1, embd_pdrop=0.1,
                 attn_pdrop=0.1, layer_norm_epsilon=1e-5,
                 batch_size=8, seq_len=128):
        self.vocab_size = vocab_size
        self.n_positions = n_positions
        self.n_embd = n_embd
        self.n_layer = n_layer
        self.n_head = n_head
        self.resid_pdrop = resid_pdrop
        self.embd_pdrop = embd_pdrop
        self.attn_pdrop = attn_pdrop
        self.layer_norm_epsilon = layer_norm_epsilon
        self.batch_size = batch_size
        self.seq_len = seq_len

    @classmethod
    def small(cls, **kw):
        return cls(**kw)

    @classmethod
    def medium(cls, **kw):
        kw.setdefault("n_embd", 1024)
        kw.setdefault("n_layer", 24)
        kw.setdefault("n_head", 16)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("n_embd", 128)
        kw.setdefault("n_layer", 2)
        kw.setdefault("n_head", 2)
        kw.setdefault("vocab_size", 512)
        return cls(**kw)


def _block(cfg, x, name):
    """Pre-LN transformer block: x + attn(ln1(x)); x + mlp(ln2(x))."""
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln1")(x)
    # attn_pdrop applies to the attention OUTPUT, not the probabilities
    # (flash-incompatible) — see the design note in layers/attention.py
    mha = MultiHeadAttention(cfg.n_embd, cfg.n_head, dropout=cfg.attn_pdrop,
                             causal=True, name=name + ".attn")
    x = x + mha(h, cfg.batch_size, cfg.seq_len)
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln2")(x)
    h = Linear(cfg.n_embd, 4 * cfg.n_embd, activation="gelu",
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".mlp_fc")(h)
    h = Linear(4 * cfg.n_embd, cfg.n_embd,
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".mlp_proj")(h)
    h = ops.dropout_op(h, 1.0 - cfg.resid_pdrop)
    return x + h


def gpt2_model(cfg, input_ids, name="gpt2"):
    """Returns hidden states node of shape (batch*seq, n_embd)."""
    wte = init.truncated_normal((cfg.vocab_size, cfg.n_embd), 0.0, 0.02,
                                name=name + ".wte")
    wpe = init.truncated_normal((cfg.n_positions, cfg.n_embd), 0.0, 0.01,
                                name=name + ".wpe")
    positions = Variable(name + ".pos_ids",
                         value=np.arange(cfg.seq_len, dtype=np.float32),
                         trainable=False)
    x = ops.embedding_lookup_op(wte, input_ids) \
        + ops.embedding_lookup_op(wpe, positions)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size * cfg.seq_len, cfg.n_embd))
    x = ops.dropout_op(x, 1.0 - cfg.embd_pdrop)
    for i in range(cfg.n_layer):
        x = _block(cfg, x, f"{name}.h{i}")
    return LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln_f")(x)


def gpt2_lm_graph(cfg, name="gpt2"):
    """Causal LM training graph: next-token prediction.

    Returns (feeds dict, loss node, logits node).  ``labels``: (batch, seq)
    with -1 at padded positions (ignored).
    """
    shape = (cfg.batch_size, cfg.seq_len)
    # int32: fp32 id feeds would ride the bf16 compute_dtype cast (exact
    # only up to 256 — silent corruption for any real vocab)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    labels = placeholder_op("labels", shape=shape, dtype=np.int32)
    hidden = gpt2_model(cfg, input_ids, name)
    logits = Linear(cfg.n_embd, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(hidden)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.seq_len)
    return {"input_ids": input_ids, "labels": labels}, loss, logits


class _DecodeBlockLayer:
    """Per-block kernel handles for ``ParallelPlan.bind``/``apply``:
    column-parallel q/k/v + mlp_fc, row-parallel o + mlp_proj (the
    canonical Megatron pair) — lets a searched tp plan annotate the
    decode graph exactly like the training model's layers."""

    def __init__(self, in_kernels, out_kernels):
        self.in_kernels = in_kernels
        self.out_kernels = out_kernels


def _block_decode(cfg, x, k_cache, v_cache, positions, name):
    """One-token decode of :func:`_block`: identical weights BY NAME
    (``.ln1``/``.attn.{q,k,v,o}``/``.ln2``/``.mlp_fc``/``.mlp_proj``),
    attention against the bucketed KV cache through the flash kernel's
    q_len=1 entry instead of the full sequence.  No dropout: decode is a
    serving graph.  Returns (x, new_k_cache, new_v_cache, layer)."""
    dk = cfg.n_embd // cfg.n_head
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln1")(x)

    def heads(t):
        # (B, n_embd) -> (B, H, 1, dk); -1 keeps the graph batch-agnostic
        # (decode buckets the batch dim at runtime)
        t = ops.array_reshape_op(t, output_shape=(-1, 1, cfg.n_head, dk))
        return ops.transpose_op(t, perm=(0, 2, 1, 3))

    lq = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.q")
    lk = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.k")
    lv = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.v")
    lo = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.o")
    q = heads(lq(h))
    kc = ops.kv_cache_append_op(k_cache, heads(lk(h)), positions)
    vc = ops.kv_cache_append_op(v_cache, heads(lv(h)), positions)
    att = ops.sdpa_decode_op(q, kc, vc, positions)       # (B, H, 1, dk)
    att = ops.transpose_op(att, perm=(0, 2, 1, 3))
    att = ops.array_reshape_op(att, output_shape=(-1, cfg.n_embd))
    x = x + lo(att)
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln2")(x)
    fc = Linear(cfg.n_embd, 4 * cfg.n_embd, activation="gelu",
                initializer=init.GenTruncatedNormal(0.0, 0.02),
                name=name + ".mlp_fc")
    proj = Linear(4 * cfg.n_embd, cfg.n_embd,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".mlp_proj")
    x = x + proj(fc(h))
    layer = _DecodeBlockLayer(
        [lq.weight_var, lk.weight_var, lv.weight_var, fc.weight_var],
        [lo.weight_var, proj.weight_var])
    return x, kc, vc, layer


def gpt2_decode_graph(cfg, max_len=None, name="gpt2"):
    """One-token autoregressive decode graph over per-layer KV caches.

    Weight names match :func:`gpt2_lm_graph` exactly, so a trained
    checkpoint (or a live Executor) loads into the decode executor BY
    NAME with zero conversion.  Feeds (all batch-leading, bucketed by the
    decode engine at runtime):

    * ``input_ids`` (B, 1) int32 — the one token each sequence consumes
      this step (a prompt token during prefill, the previous sample
      during generation)
    * ``positions`` (B,) int32 — the cache row that token writes; keys
      beyond it stay invisible to the q_len=1 attention
    * ``k_cache_i`` / ``v_cache_i`` (B, n_head, L, head_dim) per layer —
      the device-resident caches, fed back from the previous step's
      fetches (donated: XLA updates them in place)

    Returns ``(feeds, logits, cache_fetches, layers)``: ``feeds`` maps
    the names above to placeholder nodes, ``logits`` is (B, vocab) for
    the fed token, ``cache_fetches`` is [k0', v0', k1', v1', ...] (the
    appended caches, in feed order), and ``layers`` are per-block kernel
    handles for ``ParallelPlan.bind`` (tp-sharded decode)."""
    max_len = int(max_len or cfg.n_positions)
    dk = cfg.n_embd // cfg.n_head
    shape = (cfg.batch_size, 1)
    ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    positions = placeholder_op("positions", shape=(cfg.batch_size,),
                               dtype=np.int32)
    wte = init.truncated_normal((cfg.vocab_size, cfg.n_embd), 0.0, 0.02,
                                name=name + ".wte")
    wpe = init.truncated_normal((cfg.n_positions, cfg.n_embd), 0.0, 0.01,
                                name=name + ".wpe")
    x = ops.embedding_lookup_op(wte, ids)                # (B, 1, n_embd)
    x = ops.array_reshape_op(x, output_shape=(-1, cfg.n_embd))
    x = x + ops.embedding_lookup_op(wpe, positions)      # (B, n_embd)
    feeds = {"input_ids": ids, "positions": positions}
    cache_fetches, layers = [], []
    for i in range(cfg.n_layer):
        kc = placeholder_op(
            f"k_cache_{i}", dtype=np.float32,
            shape=(cfg.batch_size, cfg.n_head, max_len, dk))
        vc = placeholder_op(
            f"v_cache_{i}", dtype=np.float32,
            shape=(cfg.batch_size, cfg.n_head, max_len, dk))
        feeds[f"k_cache_{i}"] = kc
        feeds[f"v_cache_{i}"] = vc
        x, kc2, vc2, layer = _block_decode(cfg, x, kc, vc, positions,
                                           f"{name}.h{i}")
        cache_fetches += [kc2, vc2]
        layers.append(layer)
    x = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln_f")(x)
    logits = Linear(cfg.n_embd, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(x)
    return feeds, logits, cache_fetches, layers


def _block_decode_chunked(cfg, x, ids, k_cache, v_cache, positions, valid,
                          name):
    """Chunked-prefill twin of :func:`_block_decode` (ISSUE 18): the
    residual stream is (B*C, n_embd) for a (B, C) token chunk, weights
    identical BY NAME, the cache write masked by ``valid`` (rows past a
    sequence's real consumption keep the old cache bytes) and attention
    through the q_len=C entry with causal-within-chunk masking.
    Returns (x, new_k_cache, new_v_cache, layer)."""
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln1")(x)

    def heads(t):
        # (B*C, n_embd) -> (B, H, C, dk), (B, C) recovered from ids
        return ops.split_heads_chunk_op(t, ids, n_head=cfg.n_head)

    lq = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.q")
    lk = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.k")
    lv = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.v")
    lo = Linear(cfg.n_embd, cfg.n_embd, name=name + ".attn.o")
    q = heads(lq(h))
    kc = ops.kv_cache_append_op(k_cache, heads(lk(h)), positions, valid)
    vc = ops.kv_cache_append_op(v_cache, heads(lv(h)), positions, valid)
    att = ops.sdpa_prefill_op(q, kc, vc, positions)      # (B, H, C, dk)
    att = ops.merge_heads_chunk_op(att)                  # (B*C, n_embd)
    x = x + lo(att)
    h = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln2")(x)
    fc = Linear(cfg.n_embd, 4 * cfg.n_embd, activation="gelu",
                initializer=init.GenTruncatedNormal(0.0, 0.02),
                name=name + ".mlp_fc")
    proj = Linear(4 * cfg.n_embd, cfg.n_embd,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".mlp_proj")
    x = x + proj(fc(h))
    layer = _DecodeBlockLayer(
        [lq.weight_var, lk.weight_var, lv.weight_var, fc.weight_var],
        [lo.weight_var, proj.weight_var])
    return x, kc, vc, layer


def gpt2_decode_chunked_graph(cfg, max_len=None, chunk=4, name="gpt2"):
    """Chunked-prefill autoregressive decode graph (ISSUE 18): each step
    consumes a (B, C) token CHUNK instead of one token per sequence, so
    a P-token prompt ingests in ceil(P/C) dispatches instead of P.

    Weight names match :func:`gpt2_decode_graph` / :func:`gpt2_lm_graph`
    exactly — the decode engine loads this graph's executor FROM the
    primary executor's params so both entries serve the same bytes.
    Feeds (batch AND chunk dim bucketed by the engine at runtime —
    ``chunk`` here only sizes the nominal placeholders):

    * ``input_ids`` (B, C) int32 — up to C prompt tokens per sequence
      this step (generating rows ride along with their one token at
      column 0)
    * ``positions`` (B,) int32 — the cache row of each sequence's FIRST
      chunk token
    * ``valid`` (B,) int32 — how many chunk columns each sequence
      actually consumes (0 for idle slots); rows ``>= valid`` neither
      write the cache nor reach the logits
    * ``k_cache_i`` / ``v_cache_i`` (B, n_head, L, head_dim) per layer —
      donated, fed back from the previous step's fetches

    Returns ``(feeds, logits, cache_fetches, layers)`` like the
    one-token graph; ``logits`` is (B, vocab) for each sequence's LAST
    consumed chunk token (gathered before ln_f/lm_head so the vocab
    projection stays B-row)."""
    max_len = int(max_len or cfg.n_positions)
    chunk = int(chunk)
    dk = cfg.n_embd // cfg.n_head
    ids = placeholder_op("input_ids", shape=(cfg.batch_size, chunk),
                         dtype=np.int32)
    positions = placeholder_op("positions", shape=(cfg.batch_size,),
                               dtype=np.int32)
    valid = placeholder_op("valid", shape=(cfg.batch_size,),
                           dtype=np.int32)
    wte = init.truncated_normal((cfg.vocab_size, cfg.n_embd), 0.0, 0.02,
                                name=name + ".wte")
    wpe = init.truncated_normal((cfg.n_positions, cfg.n_embd), 0.0, 0.01,
                                name=name + ".wpe")
    pos2d = ops.chunk_positions_op(positions, ids,
                                   limit=cfg.n_positions)   # (B, C)
    x = ops.embedding_lookup_op(wte, ids)             # (B, C, n_embd)
    x = ops.array_reshape_op(x, output_shape=(-1, cfg.n_embd))
    pe = ops.embedding_lookup_op(wpe, pos2d)          # (B, C, n_embd)
    pe = ops.array_reshape_op(pe, output_shape=(-1, cfg.n_embd))
    x = x + pe
    feeds = {"input_ids": ids, "positions": positions, "valid": valid}
    cache_fetches, layers = [], []
    for i in range(cfg.n_layer):
        kc = placeholder_op(
            f"k_cache_{i}", dtype=np.float32,
            shape=(cfg.batch_size, cfg.n_head, max_len, dk))
        vc = placeholder_op(
            f"v_cache_{i}", dtype=np.float32,
            shape=(cfg.batch_size, cfg.n_head, max_len, dk))
        feeds[f"k_cache_{i}"] = kc
        feeds[f"v_cache_{i}"] = vc
        x, kc2, vc2, layer = _block_decode_chunked(
            cfg, x, ids, kc, vc, positions, valid, f"{name}.h{i}")
        cache_fetches += [kc2, vc2]
        layers.append(layer)
    # each sequence's last consumed row, BEFORE ln_f/lm_head: LayerNorm
    # is row-wise so the gather commutes, and the vocab matmul shrinks C×
    x = ops.chunk_emit_gather_op(x, ids, valid)       # (B, n_embd)
    x = LayerNorm(cfg.n_embd, cfg.layer_norm_epsilon, name + ".ln_f")(x)
    logits = Linear(cfg.n_embd, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(x)
    return feeds, logits, cache_fetches, layers


def synthetic_lm_batch(cfg, seed=0):
    """Next-token synthetic batch: ids shifted left for labels."""
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (cfg.batch_size, cfg.seq_len + 1))
    return (ids[:, :-1].astype(np.float32), ids[:, 1:].astype(np.float32))
