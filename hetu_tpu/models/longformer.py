"""Longformer (reference ``examples/transformers/longformer/``).

TPU-native rewrite: the sliding-window + global attention pattern is a
STATIC (1, 1, S, S) 0/1 mask fed to the fused ``sdpa_masked_op`` — windows
and global positions are compile-time constants, so XLA sees a fixed mask
tensor instead of the reference's chunked gather kernels.  For long
sequences the same mask composes with the Pallas flash kernel's blockwise
iteration (fully-masked blocks are multiplies by zero that XLA folds);
ring-attention ('cp') covers the beyond-HBM regime (SURVEY.md §5.7).
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm


class LongformerConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, attention_window=512,
                 num_global_tokens=1, max_position_embeddings=4098,
                 hidden_dropout_prob=0.1, layer_norm_eps=1e-5,
                 batch_size=2, seq_len=1024):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.attention_window = attention_window
        self.num_global_tokens = num_global_tokens
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.seq_len = seq_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 256)
        kw.setdefault("attention_window", 8)
        kw.setdefault("vocab_size", 512)
        kw.setdefault("seq_len", 64)
        return cls(**kw)


def longformer_attention_mask(seq_len, window, num_global=1):
    """Static sliding-window + global mask, (S, S) float 0/1.

    Position i attends to |i - j| <= window/2; the first ``num_global``
    tokens attend everywhere and are attended by everyone (the reference's
    global-attention ids — CLS by convention).
    """
    half = max(1, window // 2)
    i = np.arange(seq_len)[:, None]
    j = np.arange(seq_len)[None, :]
    local = np.abs(i - j) <= half
    glob = (i < num_global) | (j < num_global)
    return (local | glob).astype(np.float32)


class LongformerSelfAttention:
    def __init__(self, cfg, name, mask=None):
        self.cfg = cfg
        h = cfg.hidden_size
        self.h = cfg.num_attention_heads
        self.dk = h // self.h
        self.q = Linear(h, h, name=name + ".q")
        self.k = Linear(h, h, name=name + ".k")
        self.v = Linear(h, h, name=name + ".v")
        # separate global query projection (Longformer's q_global) blended
        # in at the global token positions via a static 0/1 selector
        self.qg = Linear(h, h, name=name + ".q_global")
        self.o = Linear(h, h, name=name + ".o")
        if mask is None:  # standalone use; models share one across layers
            m = longformer_attention_mask(cfg.seq_len, cfg.attention_window,
                                          cfg.num_global_tokens)
            mask = Variable(
                name + ".window_mask",
                value=m.reshape(1, 1, cfg.seq_len, cfg.seq_len),
                trainable=False)
        self.mask = mask
        gsel = (np.arange(cfg.seq_len) < cfg.num_global_tokens)
        gsel = np.tile(gsel.astype(np.float32), cfg.batch_size)[:, None]
        self.gsel = Variable(name + ".global_sel", value=gsel,
                             trainable=False)

    def _split(self, x):
        from .common import split_heads
        cfg = self.cfg
        return split_heads(x, cfg.batch_size, cfg.seq_len, self.h, self.dk)

    def __call__(self, x):
        from .common import merge_heads
        cfg = self.cfg
        qmix = self.q(x) * (1.0 - self.gsel) + self.qg(x) * self.gsel
        o = ops.sdpa_masked_op(self._split(qmix), self._split(self.k(x)),
                               self._split(self.v(x)), self.mask)
        o = merge_heads(o, cfg.batch_size, cfg.seq_len, cfg.hidden_size)
        return ops.dropout_op(self.o(o), 1.0 - cfg.hidden_dropout_prob)


def longformer_model(cfg, input_ids, name="longformer"):
    tokens = cfg.batch_size * cfg.seq_len
    word = init.truncated_normal((cfg.vocab_size, cfg.hidden_size), 0.0, 0.02,
                                 name=name + ".word")
    pos = init.truncated_normal(
        (cfg.max_position_embeddings, cfg.hidden_size), 0.0, 0.02,
        name=name + ".pos")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(cfg.seq_len, dtype=np.float32),
                       trainable=False)
    x = ops.embedding_lookup_op(word, input_ids) \
        + ops.embedding_lookup_op(pos, pos_ids)
    x = ops.array_reshape_op(x, output_shape=(tokens, cfg.hidden_size))
    x = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".emb_ln")(x)
    x = ops.dropout_op(x, 1.0 - cfg.hidden_dropout_prob)
    m = longformer_attention_mask(cfg.seq_len, cfg.attention_window,
                                  cfg.num_global_tokens)
    shared_mask = Variable(
        name + ".window_mask",
        value=m.reshape(1, 1, cfg.seq_len, cfg.seq_len), trainable=False)
    from .common import post_ln_encoder_stack
    return post_ln_encoder_stack(
        x, cfg,
        lambda nm: LongformerSelfAttention(cfg, nm, mask=shared_mask), name)


def longformer_mlm_graph(cfg, name="longformer"):
    """MLM pretraining graph. Returns (feeds dict, loss, logits)."""
    shape = (cfg.batch_size, cfg.seq_len)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    labels = placeholder_op("labels", shape=shape, dtype=np.int32)
    x = longformer_model(cfg, input_ids, name)
    logits = Linear(cfg.hidden_size, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".mlm_head")(x)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.seq_len)
    return {"input_ids": input_ids, "labels": labels}, loss, logits
