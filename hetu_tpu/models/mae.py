"""MAE — masked autoencoder ViT (reference ``examples/transformers/mae/``).

TPU-native rewrite: the random patch masking is a host-side permutation fed
as an int32 placeholder (static shapes under jit — the reference shuffles
on device per batch); the encoder sees only the visible patches via
``indexing_op`` gather, the decoder re-inserts learned mask tokens with the
inverse permutation and reconstructs pixels; loss is MSE on masked patches
only.  Patchify is one MXU GEMM, as in :mod:`hetu_tpu.models.vit`.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm


class MAEConfig:
    def __init__(self, image_size=224, patch_size=16, encoder_hidden=768,
                 encoder_layers=12, encoder_heads=12, decoder_hidden=512,
                 decoder_layers=8, decoder_heads=16, mask_ratio=0.75,
                 layer_norm_eps=1e-6, batch_size=8):
        assert image_size % patch_size == 0
        self.image_size = image_size
        self.patch_size = patch_size
        self.encoder_hidden = encoder_hidden
        self.encoder_layers = encoder_layers
        self.encoder_heads = encoder_heads
        self.decoder_hidden = decoder_hidden
        self.decoder_layers = decoder_layers
        self.decoder_heads = decoder_heads
        self.mask_ratio = mask_ratio
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.num_patches = (image_size // patch_size) ** 2
        self.num_visible = max(1, int(round(
            self.num_patches * (1 - mask_ratio))))
        self.patch_dim = 3 * patch_size * patch_size

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("encoder_hidden", 64)
        kw.setdefault("encoder_layers", 2)
        kw.setdefault("encoder_heads", 2)
        kw.setdefault("decoder_hidden", 32)
        kw.setdefault("decoder_layers", 1)
        kw.setdefault("decoder_heads", 2)
        return cls(**kw)


def _blocks(hidden, heads, seq, batch, eps, n_layers, name):
    from .common import pre_ln_block

    def run(x):
        for i in range(n_layers):
            x = pre_ln_block(hidden, heads, seq, batch, eps,
                             f"{name}.layer{i}")(x)
        return x
    return run


def _pos_embed_flat(n, batch, hidden, name):
    """Learned (n, hidden) position table, gathered as (batch*n, hidden) —
    per-sample tiling is one embedding lookup with tiled static ids."""
    pos = init.truncated_normal((n, hidden), 0.0, 0.02, name=name)
    ids = Variable(name + ".ids",
                   value=np.tile(np.arange(n), batch).astype(np.float32),
                   trainable=False)
    return ops.embedding_lookup_op(pos, ids)   # (batch*n, hidden)


def mae_pretrain_graph(cfg, name="mae"):
    """Masked-autoencoding pretraining graph.

    Feeds: ``images`` (B, 3, H, W) and ``shuffle`` (B, num_patches) int32 —
    a per-sample permutation of patch indices; the first ``num_visible``
    entries are the kept patches.  Returns (feeds, loss, recon_patches).
    """
    B, P, V = cfg.batch_size, cfg.num_patches, cfg.num_visible
    p, g = cfg.patch_size, cfg.image_size // cfg.patch_size
    images = placeholder_op(
        "images", shape=(B, 3, cfg.image_size, cfg.image_size))
    shuffle = placeholder_op("shuffle", shape=(B, P), dtype=np.int32)

    # patchify → (B*P, patch_dim) raw pixel targets
    x = ops.array_reshape_op(images, output_shape=(B, 3, g, p, g, p))
    x = ops.transpose_op(x, perm=(0, 2, 4, 1, 3, 5))
    patches = ops.array_reshape_op(x, output_shape=(B * P, cfg.patch_dim))

    # flat gather indices: row b of shuffle indexes into b's patches
    base = Variable(name + ".rowbase",
                    value=(np.arange(B)[:, None] * P
                           * np.ones((1, P))).astype(np.float32),
                    trainable=False)
    shuf2 = shuffle + base                                  # (B, P) flat ids
    vis_idx = ops.array_reshape_op(
        ops.slice_op(shuf2, begin=(0, 0), size=(B, V)),
        output_shape=(B * V,))
    mask_idx = ops.array_reshape_op(
        ops.slice_op(shuf2, begin=(0, V), size=(B, P - V)),
        output_shape=(B * (P - V),))

    # ---- encoder on visible patches only
    enc_in = Linear(cfg.patch_dim, cfg.encoder_hidden, name=name + ".proj")(
        ops.indexing_op(patches, vis_idx))            # (B*V, enc_hidden)
    pe_flat = _pos_embed_flat(P, B, cfg.encoder_hidden, name + ".enc_pos")
    enc_in = enc_in + ops.indexing_op(pe_flat, vis_idx)
    enc = _blocks(cfg.encoder_hidden, cfg.encoder_heads, V, B,
                  cfg.layer_norm_eps, cfg.encoder_layers, name + ".enc")(
        enc_in)
    enc = LayerNorm(cfg.encoder_hidden, cfg.layer_norm_eps,
                    name + ".enc_ln")(enc)

    # ---- decoder: visible tokens + learned mask tokens, un-shuffled
    dec_vis = Linear(cfg.encoder_hidden, cfg.decoder_hidden,
                     name=name + ".dec_embed")(enc)        # (B*V, dec_h)
    mask_tok = init.truncated_normal((1, cfg.decoder_hidden), 0.0, 0.02,
                                     name=name + ".mask_token")
    zeros_ids = Variable(name + ".mask_tok_ids",
                         value=np.zeros(B * (P - V), np.float32),
                         trainable=False)
    mask_rows = ops.embedding_lookup_op(mask_tok, zeros_ids)  # (B*(P-V), h)
    shuffled_all = ops.concatenate_op([dec_vis, mask_rows], axis=0)
    # un-shuffle scatter: shuffled_all row order is [all visible rows, then
    # all mask rows], so the destination index vector must follow the SAME
    # order: dest[concat(vis_idx, mask_idx)[i]] = shuffled_all[i]
    scatter_idx = ops.concatenate_op([vis_idx, mask_idx], axis=0)
    dec_seq = ops.scatter1d_grad_op(shuffled_all, scatter_idx, size=B * P)
    dec_seq = dec_seq + _pos_embed_flat(P, B, cfg.decoder_hidden,
                                        name + ".dec_pos")
    dec = _blocks(cfg.decoder_hidden, cfg.decoder_heads, P, B,
                  cfg.layer_norm_eps, cfg.decoder_layers, name + ".dec")(
        dec_seq)
    dec = LayerNorm(cfg.decoder_hidden, cfg.layer_norm_eps,
                    name + ".dec_ln")(dec)
    recon = Linear(cfg.decoder_hidden, cfg.patch_dim,
                   name=name + ".pred")(dec)               # (B*P, patch_dim)

    # ---- MSE on masked patches only (indices V..P of the shuffle)
    diff = ops.indexing_op(recon, mask_idx) \
        - ops.indexing_op(patches, mask_idx)
    loss = ops.reduce_mean_op(ops.mul_op(diff, diff), [0, 1])
    return {"images": images, "shuffle": shuffle}, loss, recon


def synthetic_mae_batch(cfg, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.rand(cfg.batch_size, 3, cfg.image_size,
                    cfg.image_size).astype(np.float32)
    shuffle = np.stack([rng.permutation(cfg.num_patches)
                        for _ in range(cfg.batch_size)]).astype(np.int32)
    return imgs, shuffle
