"""Reformer (reference ``examples/transformers/reformer/``).

TPU-native rewrite of LSH attention: random-rotation bucketing, a *sort* by
bucket (XLA's bitonic sort — static shapes, no data-dependent control
flow), chunked attention over the sorted order with one chunk of lookback,
then un-sort.  The reference's reversible-residual trick exists to avoid
storing activations; here ``jax.checkpoint``/1F1B recompute serves that
role (SURVEY.md §7), so blocks keep plain residuals.  Shared-QK projection
and per-layer fixed random rotations follow the paper.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm
from ..ops.base import def_op


class ReformerConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, num_buckets=32, chunk_length=64,
                 max_position_embeddings=4096, hidden_dropout_prob=0.1,
                 layer_norm_eps=1e-12, batch_size=2, seq_len=1024):
        assert seq_len % chunk_length == 0
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.num_buckets = num_buckets
        self.chunk_length = chunk_length
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.seq_len = seq_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 256)
        kw.setdefault("num_buckets", 4)
        kw.setdefault("chunk_length", 16)
        kw.setdefault("vocab_size", 512)
        kw.setdefault("seq_len", 64)
        return cls(**kw)


def lsh_attention(qk, v, rotations, chunk_length, causal=True):
    """Single-round LSH attention, (B, H, S, D) → (B, H, S, D).

    ``rotations``: (D, n_buckets // 2) fixed random projections.
    Sorted-bucket chunking with one chunk of lookback; self-attention is
    down-weighted (-1e5) per the paper; causal masks future *original*
    positions.
    """
    b, h, s, d = qk.shape
    c = chunk_length
    nc = s // c
    # --- bucket by random rotation sign pattern
    rot = jnp.einsum("bhsd,df->bhsf", qk, rotations)
    buckets = jnp.argmax(jnp.concatenate([rot, -rot], -1), -1)  # (B,H,S)
    pos = jnp.arange(s)[None, None, :]
    # stable sort: bucket-major, position-minor
    order = jnp.argsort(buckets * (s + 1) + pos, axis=-1)       # (B,H,S)
    inv = jnp.argsort(order, axis=-1)

    def take(x, idx):
        return jnp.take_along_axis(x, idx[..., None], axis=2)

    sq = take(qk, order)
    sv = take(v, order)
    spos = jnp.take_along_axis(pos * jnp.ones_like(buckets), order, axis=-1)
    # chunk and attach one lookback chunk of keys/values
    sq_c = sq.reshape(b, h, nc, c, d)
    sk_c = sq_c / jnp.maximum(
        jnp.linalg.norm(sq_c, axis=-1, keepdims=True), 1e-6)  # shared-QK norm
    sv_c = sv.reshape(b, h, nc, c, d)
    spos_c = spos.reshape(b, h, nc, c)

    def with_prev(x):
        prev = jnp.roll(x, 1, axis=2)
        return jnp.concatenate([prev, x], axis=3)

    keys = with_prev(sk_c)                                  # (B,H,nc,2c,D)
    vals = with_prev(sv_c)
    kpos = with_prev(spos_c[..., None])[..., 0]             # (B,H,nc,2c)

    logits = jnp.einsum("bhncd,bhnkd->bhnck", sq_c, keys,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    qpos = spos_c[..., :, None]
    if causal:
        logits = jnp.where(kpos[..., None, :] > qpos, -1e30, logits)
    # self-attention only as a last resort (paper: -1e5, not -inf)
    logits = jnp.where(kpos[..., None, :] == qpos, -1e5, logits)
    # chunk 0 has no real predecessor (roll wraps): mask its lookback half
    first = jnp.arange(nc)[None, None, :, None, None] == 0
    look = jnp.arange(2 * c)[None, None, None, None, :] < c
    logits = jnp.where(first & look, -1e30, logits)
    probs = jax.nn.softmax(logits, axis=-1)
    # operand-dtype result (no f32 forcing): keeps the backward dots in
    # bf16 — the matmul.py dtype-discipline note
    out = jnp.einsum("bhnck,bhnkd->bhncd", probs.astype(vals.dtype), vals)
    out = out.reshape(b, h, s, d).astype(qk.dtype)
    return take(out, inv)                                   # un-sort


lsh_attention_op = def_op(
    "LSHAttention",
    lambda ctx, qk, v, rotations, chunk_length=64, causal=True:
        lsh_attention(qk, v, rotations, chunk_length, causal))


class ReformerSelfAttention:
    def __init__(self, cfg, name, seed=0):
        h = cfg.hidden_size
        self.cfg = cfg
        self.heads = cfg.num_attention_heads
        self.dk = h // self.heads
        self.qk = Linear(h, h, bias=False, name=name + ".qk")  # shared QK
        self.v = Linear(h, h, bias=False, name=name + ".v")
        self.o = Linear(h, h, name=name + ".o")
        rng = np.random.RandomState(seed)
        self.rot = Variable(
            name + ".rotations",
            value=rng.randn(self.dk, cfg.num_buckets // 2).astype(np.float32),
            trainable=False)

    def _split(self, x):
        cfg = self.cfg
        x = ops.array_reshape_op(
            x, output_shape=(cfg.batch_size, cfg.seq_len, self.heads,
                             self.dk))
        return ops.transpose_op(x, perm=(0, 2, 1, 3))

    def __call__(self, x):
        cfg = self.cfg
        qk = self._split(self.qk(x))
        v = self._split(self.v(x))
        o = lsh_attention_op(qk, v, self.rot,
                             chunk_length=cfg.chunk_length, causal=True)
        o = ops.transpose_op(o, perm=(0, 2, 1, 3))
        o = ops.array_reshape_op(
            o, output_shape=(cfg.batch_size * cfg.seq_len, cfg.hidden_size))
        return self.o(o)


def reformer_model(cfg, input_ids, name="reformer"):
    tokens = cfg.batch_size * cfg.seq_len
    word = init.truncated_normal((cfg.vocab_size, cfg.hidden_size), 0.0, 0.02,
                                 name=name + ".word")
    pos = init.truncated_normal(
        (cfg.max_position_embeddings, cfg.hidden_size), 0.0, 0.02,
        name=name + ".pos")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(cfg.seq_len, dtype=np.float32),
                       trainable=False)
    x = ops.embedding_lookup_op(word, input_ids) \
        + ops.embedding_lookup_op(pos, pos_ids)
    x = ops.array_reshape_op(x, output_shape=(tokens, cfg.hidden_size))
    for i in range(cfg.num_hidden_layers):
        ln = f"{name}.layer{i}"
        h = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, ln + ".ln1")(x)
        attn = ReformerSelfAttention(cfg, ln + ".attn", seed=i)
        x = x + attn(h)
        h = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, ln + ".ln2")(x)
        h = Linear(cfg.hidden_size, cfg.intermediate_size, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ffn1")(h)
        h = Linear(cfg.intermediate_size, cfg.hidden_size,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ffn2")(h)
        x = x + ops.dropout_op(h, 1.0 - cfg.hidden_dropout_prob)
    return LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".ln_f")(x)


def reformer_lm_graph(cfg, name="reformer"):
    """Causal LM graph. Returns (feeds dict, loss, logits)."""
    shape = (cfg.batch_size, cfg.seq_len)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    labels = placeholder_op("labels", shape=shape, dtype=np.int32)
    x = reformer_model(cfg, input_ids, name)
    logits = Linear(cfg.hidden_size, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(x)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.seq_len)
    return {"input_ids": input_ids, "labels": labels}, loss, logits
