"""Swin Transformer (reference ``tools/Galvatron/swin/`` — the fourth
model family of the reference's auto-parallel runtime, alongside
bert/t5/vit).

TPU-native rewrite, not a port of the reference's torch/Megatron layers:

- **Window partition is pure reshape+transpose** — static shapes end to
  end, so XLA lays every window batch out for the MXU with no dynamic
  gather.  Resolutions must be divisible by the window size (asserted at
  build time); when a stage's resolution is smaller than the window the
  window clamps to the full resolution and the shift is skipped — the
  same degenerate-window rule the reference inherits from HF swin, minus
  its dynamic padding (padding would force dynamic shapes into every
  jitted step).
- **The cyclic shift is ``jnp.roll``** (one XLA collective-permute-style
  slice+concat on device) and its cross-window attention mask is
  precomputed on the host as a constant (B·nW, 1, w², w²) validity mask
  — the mask never depends on data, so it compiles into the program.
- **Relative position bias is an embedding lookup**: a trainable
  ((2w-1)², heads) table indexed by a constant flattened coordinate
  grid, reshaped/transposed into the (1, heads, w², w²) logit bias the
  fused attention op takes.  Shifted and unshifted blocks share the
  per-block table layout of the original paper.
- **Patch merging is reshape→transpose→concat→LayerNorm→Linear** (one
  GEMM).  The 2×2 neighbourhood concatenation order is
  (row-major within the 2×2 cell); it differs from torch-swin's
  column-interleaved order but is internally consistent — this is a
  fresh framework, not a weight-compatible clone.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class SwinConfig:
    def __init__(self, image_size=224, patch_size=4, num_channels=3,
                 embed_dim=96, depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24),
                 window_size=7, mlp_ratio=4.0, hidden_dropout_prob=0.0,
                 layer_norm_eps=1e-5, num_classes=1000, batch_size=8):
        assert len(depths) == len(num_heads)
        assert image_size % patch_size == 0
        self.image_size = image_size
        self.patch_size = patch_size
        self.num_channels = num_channels
        self.embed_dim = embed_dim
        self.depths = tuple(depths)
        self.num_heads = tuple(num_heads)
        self.window_size = window_size
        self.mlp_ratio = mlp_ratio
        self.hidden_dropout_prob = hidden_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.num_classes = num_classes
        self.batch_size = batch_size
        res = image_size // patch_size
        for i in range(len(depths)):
            ws = min(window_size, res)
            assert res % ws == 0, (
                f"stage {i}: resolution {res} not divisible by window {ws}"
                " — pick image/patch/window sizes that tile exactly"
                " (static shapes are the TPU contract)")
            res //= 2 if i + 1 < len(depths) else 1

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 4)     # 8x8 grid
        kw.setdefault("embed_dim", 32)
        kw.setdefault("depths", (2, 2))    # stage 2 at 4x4
        kw.setdefault("num_heads", (2, 4))
        kw.setdefault("window_size", 4)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


def _rel_bias_index(w):
    """Flattened (w², w²) index into the (2w-1)² relative-coord table."""
    coords = np.stack(np.meshgrid(np.arange(w), np.arange(w),
                                  indexing="ij")).reshape(2, -1)  # (2, w²)
    rel = coords[:, :, None] - coords[:, None, :]                 # (2,w²,w²)
    rel = rel + (w - 1)
    return (rel[0] * (2 * w - 1) + rel[1]).reshape(-1)            # (w⁴,)


def _shift_mask(H, W, w, s):
    """(nW, w², w²) validity mask (1=attend) for shifted-window attention:
    pairs that came from different pre-roll regions must not attend.  The
    house mask convention is boolean validity, not additive logits
    (ops/attention.py sdpa_reference)."""
    img = np.zeros((H, W), dtype=np.float32)
    cnt = 0
    for hs in (slice(0, -w), slice(-w, -s), slice(-s, None)):
        for ws_ in (slice(0, -w), slice(-w, -s), slice(-s, None)):
            img[hs, ws_] = cnt
            cnt += 1
    win = img.reshape(H // w, w, W // w, w).transpose(0, 2, 1, 3)
    win = win.reshape(-1, w * w)                                  # (nW, w²)
    diff = win[:, None, :] - win[:, :, None]
    return (diff == 0).astype(np.float32)


class _WindowBlock:
    """One swin block: (shifted-)window MSA + MLP, pre-LN residuals."""

    def __init__(self, cfg, dim, heads, res, shift, name, consts=None):
        self.cfg, self.dim, self.heads, self.res = cfg, dim, heads, res
        self.w = min(cfg.window_size, res)
        self.shift = shift if self.w < res else 0
        self.name = name
        consts = consts if consts is not None else {}
        self.ln1 = LayerNorm(dim, cfg.layer_norm_eps, name + ".ln1")
        self.mha = MultiHeadAttention(dim, heads, name=name + ".attn")
        self.ln2 = LayerNorm(dim, cfg.layer_norm_eps, name + ".ln2")
        hid = int(dim * cfg.mlp_ratio)
        self.fc1 = Linear(dim, hid, activation="gelu",
                          initializer=init.GenTruncatedNormal(0.0, 0.02),
                          name=name + ".mlp1")
        self.fc2 = Linear(hid, dim,
                          initializer=init.GenTruncatedNormal(0.0, 0.02),
                          name=name + ".mlp2")
        w = self.w
        self.rel_table = init.truncated_normal(
            ((2 * w - 1) ** 2, heads), 0.0, 0.02, name=name + ".rel_table")
        # the index and shift-mask constants depend only on (res, w,
        # shift): share ONE non-trainable Variable per distinct geometry
        # across blocks/stages instead of re-materialising ~MB of
        # byte-identical program constants per shifted block
        ikey = ("idx", w)
        if ikey not in consts:
            consts[ikey] = Variable(
                f"swin.rel_idx.w{w}",
                value=_rel_bias_index(w).astype(np.float32),
                trainable=False)
        self.rel_idx = consts[ikey]
        if self.shift:
            mkey = ("mask", res, w, self.shift)
            if mkey not in consts:
                m = _shift_mask(res, res, w, self.shift)    # (nW, w², w²)
                # stored at (nW, 1, w², w²): __call__ tiles it over the
                # window batch B·nW with an on-graph Repeat (XLA keeps
                # the repeat lazy), instead of baking a B×-larger
                # byte-identical constant into the compiled program
                consts[mkey] = Variable(
                    f"swin.shift_mask.r{res}w{w}s{self.shift}",
                    value=np.ascontiguousarray(m[:, None]),
                    trainable=False)
            self.mask = consts[mkey]
        else:
            self.mask = None

    def _windows(self, x):
        """(B*res², C) → (B*nW*w², C) by reshape/transpose only."""
        B, r, w, C = self.cfg.batch_size, self.res, self.w, self.dim
        x = ops.array_reshape_op(
            x, output_shape=(B, r // w, w, r // w, w, C))
        x = ops.transpose_op(x, perm=(0, 1, 3, 2, 4, 5))
        return ops.array_reshape_op(
            x, output_shape=(B * (r // w) ** 2 * w * w, C))

    def _unwindows(self, x):
        B, r, w, C = self.cfg.batch_size, self.res, self.w, self.dim
        x = ops.array_reshape_op(
            x, output_shape=(B, r // w, r // w, w, w, C))
        x = ops.transpose_op(x, perm=(0, 1, 3, 2, 4, 5))
        return ops.array_reshape_op(x, output_shape=(B * r * r, C))

    def _bias(self):
        """Relative-position logit bias (1, heads, w², w²) — broadcast
        across the window batch by the fused attention op."""
        w2 = self.w * self.w
        b = ops.embedding_lookup_op(self.rel_table, self.rel_idx)
        b = ops.array_reshape_op(b, output_shape=(w2, w2, self.heads))
        b = ops.transpose_op(b, perm=(2, 0, 1))
        return ops.array_reshape_op(b, output_shape=(1, self.heads, w2, w2))

    def __call__(self, x):
        B, r, w, C = self.cfg.batch_size, self.res, self.w, self.dim
        nwin = B * (r // w) ** 2
        h = self.ln1(x)
        if self.shift:
            h = ops.array_reshape_op(h, output_shape=(B, r, r, C))
            h = ops.roll_op(h, shift=(-self.shift, -self.shift), axis=(1, 2))
            h = ops.array_reshape_op(h, output_shape=(B * r * r, C))
        h = self._windows(h)
        mask = None
        if self.mask is not None:
            # (nW, 1, w², w²) → (B·nW, 1, w², w²): tile maps flat window
            # index t = b·nW + w to mask[t % nW] = mask[w], matching
            # _windows' batch-major (B, nW) flattening
            mask = ops.repeat_op(self.mask, reps=(B, 1, 1, 1))
        h = self.mha(h, nwin, w * w, mask=mask, bias=self._bias())
        h = self._unwindows(h)
        if self.shift:
            h = ops.array_reshape_op(h, output_shape=(B, r, r, C))
            h = ops.roll_op(h, shift=(self.shift, self.shift), axis=(1, 2))
            h = ops.array_reshape_op(h, output_shape=(B * r * r, C))
        x = x + ops.dropout_op(h, 1.0 - self.cfg.hidden_dropout_prob) \
            if self.cfg.hidden_dropout_prob else x + h
        m = self.fc2(self.fc1(self.ln2(x)))
        return (x + ops.dropout_op(m, 1.0 - self.cfg.hidden_dropout_prob)
                if self.cfg.hidden_dropout_prob else x + m)


def _patch_merge(cfg, x, res, dim, name):
    """(B*res², C) → (B*(res/2)², 2C): 2×2 cell concat → LN → Linear."""
    B = cfg.batch_size
    x = ops.array_reshape_op(
        x, output_shape=(B, res // 2, 2, res // 2, 2, dim))
    x = ops.transpose_op(x, perm=(0, 1, 3, 2, 4, 5))
    x = ops.array_reshape_op(
        x, output_shape=(B * (res // 2) ** 2, 4 * dim))
    x = LayerNorm(4 * dim, cfg.layer_norm_eps, name + ".ln")(x)
    return Linear(4 * dim, 2 * dim, bias=False,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".reduce")(x)


def swin_model(cfg, images, name="swin"):
    """Hierarchical swin encoder.

    Returns ``(hidden, res, dim)``: final-stage hidden states flattened to
    (B*res², dim) plus the final grid resolution and channel width — the
    caller needs both to un-flatten (unlike the fixed-width siblings,
    swin's output geometry depends on the stage schedule).
    """
    from .common import patchify
    B = cfg.batch_size
    g = cfg.image_size // cfg.patch_size
    x = patchify(images, B, cfg.num_channels, cfg.image_size,
                 cfg.patch_size, cfg.embed_dim, name + ".patch")
    x = LayerNorm(cfg.embed_dim, cfg.layer_norm_eps, name + ".patch_ln")(x)

    res, dim = g, cfg.embed_dim
    consts = {}   # (res, w, shift) → shared mask/rel_idx constants
    for si, (depth, heads) in enumerate(zip(cfg.depths, cfg.num_heads)):
        for bi in range(depth):
            blk = _WindowBlock(
                cfg, dim, heads, res,
                shift=(min(cfg.window_size, res) // 2) if bi % 2 else 0,
                name=f"{name}.s{si}.b{bi}", consts=consts)
            x = blk(x)
        if si + 1 < len(cfg.depths):
            x = _patch_merge(cfg, x, res, dim, f"{name}.s{si}.merge")
            res, dim = res // 2, dim * 2
    return LayerNorm(dim, cfg.layer_norm_eps, name + ".ln_f")(x), res, dim


def swin_classify_graph(cfg, name="swin"):
    """Image classification graph: mean-pooled tokens → linear head.

    Returns (feeds dict, loss node, logits node) — the house model-zoo
    contract (models/vit.py:108).
    """
    images = placeholder_op(
        "images", shape=(cfg.batch_size, cfg.num_channels,
                         cfg.image_size, cfg.image_size))
    labels = placeholder_op(
        "labels", shape=(cfg.batch_size, cfg.num_classes))
    x, res, dim = swin_model(cfg, images, name)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, res * res, dim))
    pooled = ops.reduce_mean_op(x, [1])
    logits = Linear(dim, cfg.num_classes,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".head")(pooled)
    loss = ops.reduce_mean_op(
        ops.softmaxcrossentropy_op(logits, labels), [0])
    return {"images": images, "labels": labels}, loss, logits
