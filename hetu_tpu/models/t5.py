"""T5 encoder-decoder (reference ``examples/transformers/t5/``).

TPU-native rewrite: RMSNorm (T5LayerNorm), bucketed relative-position bias
realized as a trainable embedding gathered with *static* bucket indices
(static shapes — XLA-friendly; the reference recomputes buckets on device),
fused attention with additive bias via ``sdpa_bias_op``, cross-attention
through the shared :class:`MultiHeadAttention` layer.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, RMSNorm


class T5Config:
    def __init__(self, vocab_size=32128, d_model=512, d_ff=2048,
                 num_layers=6, num_heads=8, relative_attention_num_buckets=32,
                 relative_attention_max_distance=128, dropout_rate=0.1,
                 layer_norm_epsilon=1e-6, batch_size=8, src_len=128,
                 tgt_len=128, context_parallel=None):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_ff = d_ff
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.relative_attention_num_buckets = relative_attention_num_buckets
        self.relative_attention_max_distance = relative_attention_max_distance
        self.dropout_rate = dropout_rate
        self.layer_norm_epsilon = layer_norm_epsilon
        self.batch_size = batch_size
        self.src_len = src_len
        self.tgt_len = tgt_len
        # 'ring' | 'ulysses' | None: shard SELF-attention over the 'cp'
        # mesh axis (the relative-position bias rides the schedule);
        # cross-attention stays local (unequal q/kv lengths)
        self.context_parallel = context_parallel

    @classmethod
    def small(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("d_model", 128)
        kw.setdefault("d_ff", 256)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("vocab_size", 512)
        return cls(**kw)


def _relative_bucket(rel_pos, bidirectional, num_buckets, max_distance):
    """T5's log-spaced relative position bucketing (numpy, host-side —
    indices are static under jit).  ``rel_pos`` = memory_pos - context_pos;
    causal mode buckets the *past* distance max(-rel, 0), so visible keys
    get distinct buckets and masked future keys collapse to 0."""
    ret = np.zeros_like(rel_pos)
    if bidirectional:
        num_buckets //= 2
        ret += (rel_pos > 0).astype(np.int64) * num_buckets
        n = np.abs(rel_pos)
    else:
        n = np.maximum(-rel_pos, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        np.log(np.maximum(n, 1) / max_exact) / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(np.int64)
    large = np.minimum(large, num_buckets - 1)
    return ret + np.where(is_small, n, large)


def _relpos_bias(cfg, q_len, k_len, bidirectional, name):
    """Trainable (num_buckets, heads) embedding gathered with static bucket
    indices → bias node broadcastable to (1, H, q_len, k_len)."""
    ctx = np.arange(q_len)[:, None]
    mem = np.arange(k_len)[None, :]
    buckets = _relative_bucket(mem - ctx, bidirectional,
                               cfg.relative_attention_num_buckets,
                               cfg.relative_attention_max_distance)
    table = init.truncated_normal(
        (cfg.relative_attention_num_buckets, cfg.num_heads), 0.0, 0.02,
        name=name)
    idx = Variable(name + ".buckets",
                   value=buckets.reshape(-1).astype(np.float32),
                   trainable=False)
    bias = ops.embedding_lookup_op(table, idx)          # (q*k, H)
    bias = ops.array_reshape_op(bias, output_shape=(q_len, k_len,
                                                    cfg.num_heads))
    bias = ops.transpose_op(bias, perm=(2, 0, 1))       # (H, q, k)
    return ops.array_reshape_op(bias,
                                output_shape=(1, cfg.num_heads, q_len, k_len))


def _ffn(cfg, x, name):
    h = Linear(cfg.d_model, cfg.d_ff, activation="relu", bias=False,
               initializer=init.GenTruncatedNormal(0.0, 0.02),
               name=name + ".wi")(x)
    h = ops.dropout_op(h, 1.0 - cfg.dropout_rate)
    return Linear(cfg.d_ff, cfg.d_model, bias=False,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".wo")(h)


def t5_encoder(cfg, x_embed, mask=None, name="t5.encoder"):
    """x_embed: (batch*src_len, d_model); returns same shape.
    ``mask``: optional (B, 1, 1, src_len) key-padding mask node — composes
    with the relative-position bias (and with context parallelism)."""
    bias = _relpos_bias(cfg, cfg.src_len, cfg.src_len, True,
                        name + ".relpos")
    x = x_embed
    for i in range(cfg.num_layers):
        ln = name + f".block{i}"
        h = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, ln + ".ln1")(x)
        mha = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                 context_parallel=cfg.context_parallel,
                                 name=ln + ".attn")
        x = x + mha(h, cfg.batch_size, cfg.src_len, mask=mask, bias=bias,
                    scale=1.0)
        h = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, ln + ".ln2")(x)
        x = x + ops.dropout_op(_ffn(cfg, h, ln + ".ffn"),
                               1.0 - cfg.dropout_rate)
    return RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, name + ".ln_f")(x)


def t5_decoder(cfg, y_embed, memory, mem_mask=None, name="t5.decoder"):
    """y_embed: (batch*tgt_len, d_model); memory: encoder output.
    ``mem_mask``: optional (B, 1, 1, src_len) padding mask over the
    encoder memory keys (cross-attention must not attend to PAD)."""
    self_bias = _relpos_bias(cfg, cfg.tgt_len, cfg.tgt_len, False,
                             name + ".relpos")
    x = y_embed
    for i in range(cfg.num_layers):
        ln = name + f".block{i}"
        h = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, ln + ".ln1")(x)
        self_attn = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                       causal=True,
                                       context_parallel=cfg.context_parallel,
                                       name=ln + ".self")
        x = x + self_attn(h, cfg.batch_size, cfg.tgt_len, bias=self_bias,
                          scale=1.0)
        h = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, ln + ".ln2")(x)
        cross = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                   name=ln + ".cross")
        x = x + cross(h, cfg.batch_size, cfg.tgt_len, kv=memory,
                      kv_seq=cfg.src_len, mask=mem_mask, scale=1.0)
        h = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, ln + ".ln3")(x)
        x = x + ops.dropout_op(_ffn(cfg, h, ln + ".ffn"),
                               1.0 - cfg.dropout_rate)
    return RMSNorm(cfg.d_model, cfg.layer_norm_epsilon, name + ".ln_f")(x)


def t5_seq2seq_graph(cfg, name="t5", use_mask=False):
    """Teacher-forced seq2seq training graph.

    Returns (feeds dict, loss node, logits node).
    ``use_mask=True`` adds an ``attention_mask`` (B, src_len) input
    (reference T5 takes attention_mask) threaded through encoder
    self-attention AND decoder cross-attention — padded sources stop
    leaking into the memory the decoder reads.  Opt-in: the dense default
    keeps existing callers/benches unchanged.
    """
    # int32 ids/labels: fp32 feeds would ride the compute_dtype bf16 cast,
    # which corrupts token ids > 256 (bert.py precedent)
    src = placeholder_op("input_ids", shape=(cfg.batch_size, cfg.src_len),
                         dtype=np.int32)
    tgt_in = placeholder_op("decoder_input_ids",
                            shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    labels = placeholder_op("labels", shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    mask = None
    if use_mask:
        attention_mask = placeholder_op(
            "attention_mask", shape=(cfg.batch_size, cfg.src_len),
            dtype=np.int32)
        mask = ops.array_reshape_op(
            attention_mask, output_shape=(cfg.batch_size, 1, 1, cfg.src_len))

    shared = init.truncated_normal((cfg.vocab_size, cfg.d_model), 0.0, 0.02,
                                   name=name + ".shared_embed")
    src_e = ops.array_reshape_op(
        ops.embedding_lookup_op(shared, src),
        output_shape=(cfg.batch_size * cfg.src_len, cfg.d_model))
    tgt_e = ops.array_reshape_op(
        ops.embedding_lookup_op(shared, tgt_in),
        output_shape=(cfg.batch_size * cfg.tgt_len, cfg.d_model))
    mem = t5_encoder(cfg, src_e, mask=mask, name=name + ".encoder")
    dec = t5_decoder(cfg, tgt_e, mem, mem_mask=mask,
                     name=name + ".decoder")
    # T5 scales decoder output by d_model^-0.5 before the (untied) lm head
    dec = dec * float(cfg.d_model) ** -0.5
    logits = Linear(cfg.d_model, cfg.vocab_size, bias=False,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(dec)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.tgt_len)
    feeds = {"input_ids": src, "decoder_input_ids": tgt_in, "labels": labels}
    if use_mask:
        feeds["attention_mask"] = attention_mask
    return feeds, loss, logits


def synthetic_seq2seq_batch(cfg, seed=0, padded=False):
    """``padded=True`` additionally returns an attention_mask with a
    padded source-length distribution (PAD id 0 beyond each length)."""
    rng = np.random.RandomState(seed)
    src = rng.randint(0, cfg.vocab_size, (cfg.batch_size, cfg.src_len))
    tgt = rng.randint(0, cfg.vocab_size, (cfg.batch_size, cfg.tgt_len + 1))
    if not padded:
        return (src.astype(np.int32), tgt[:, :-1].astype(np.int32),
                tgt[:, 1:].astype(np.int32))
    lengths = rng.randint(max(1, cfg.src_len // 4), cfg.src_len + 1,
                          cfg.batch_size)
    attn = (np.arange(cfg.src_len)[None, :] < lengths[:, None])
    src[~attn] = 0
    return (src.astype(np.int32), tgt[:, :-1].astype(np.int32),
            tgt[:, 1:].astype(np.int32), attn.astype(np.int32))
