"""Vanilla encoder-decoder transformer, "Attention Is All You Need" layout
(reference ``examples/transformers/transformer/``): sinusoidal positions,
post-LN blocks, causal decoder self-attention + cross-attention.
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class TransformerConfig:
    def __init__(self, vocab_size=32000, d_model=512, d_ff=2048,
                 num_layers=6, num_heads=8, dropout=0.1, batch_size=8,
                 src_len=64, tgt_len=64):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_ff = d_ff
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.dropout = dropout
        self.batch_size = batch_size
        self.src_len = src_len
        self.tgt_len = tgt_len

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("d_model", 64)
        kw.setdefault("d_ff", 128)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 2)
        kw.setdefault("vocab_size", 256)
        return cls(**kw)


def _sinusoid(seq_len, d_model):
    pos = np.arange(seq_len)[:, None]
    i = np.arange(d_model)[None, :]
    angle = pos / np.power(10000.0, (2 * (i // 2)) / d_model)
    enc = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return enc.astype(np.float32)


def _embed(cfg, ids, table, seq_len, name):
    e = ops.embedding_lookup_op(table, ids) * float(cfg.d_model) ** 0.5
    pe = Variable(name + ".sinusoid", value=_sinusoid(seq_len, cfg.d_model),
                  trainable=False)
    pe3 = ops.array_reshape_op(pe, output_shape=(1, seq_len, cfg.d_model))
    e = e + ops.broadcastto_op(pe3, e)
    e = ops.array_reshape_op(
        e, output_shape=(cfg.batch_size * seq_len, cfg.d_model))
    return ops.dropout_op(e, 1.0 - cfg.dropout)


def _ffn(cfg, x, name):
    h = Linear(cfg.d_model, cfg.d_ff, activation="relu", name=name + ".w1")(x)
    return Linear(cfg.d_ff, cfg.d_model, name=name + ".w2")(h)


def transformer_graph(cfg, name="transformer"):
    """Seq2seq training graph. Returns (feeds, loss, logits)."""
    # int32 ids/labels (see bert.py: fp32 feeds ride the bf16 cast)
    src = placeholder_op("src_ids", shape=(cfg.batch_size, cfg.src_len),
                         dtype=np.int32)
    tgt_in = placeholder_op("tgt_ids", shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    labels = placeholder_op("labels", shape=(cfg.batch_size, cfg.tgt_len),
                            dtype=np.int32)
    table = init.truncated_normal((cfg.vocab_size, cfg.d_model), 0.0, 0.02,
                                  name=name + ".embed")

    # encoder (post-LN)
    x = _embed(cfg, src, table, cfg.src_len, name + ".src")
    for i in range(cfg.num_layers):
        ln = f"{name}.enc{i}"
        mha = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                 dropout=cfg.dropout, name=ln + ".attn")
        x = LayerNorm(cfg.d_model, name=ln + ".ln1")(
            x + mha(x, cfg.batch_size, cfg.src_len))
        x = LayerNorm(cfg.d_model, name=ln + ".ln2")(
            x + ops.dropout_op(_ffn(cfg, x, ln + ".ffn"), 1.0 - cfg.dropout))
    memory = x

    # decoder
    y = _embed(cfg, tgt_in, table, cfg.tgt_len, name + ".tgt")
    for i in range(cfg.num_layers):
        ln = f"{name}.dec{i}"
        self_attn = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                       dropout=cfg.dropout, causal=True,
                                       name=ln + ".self")
        y = LayerNorm(cfg.d_model, name=ln + ".ln1")(
            y + self_attn(y, cfg.batch_size, cfg.tgt_len))
        cross = MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                   dropout=cfg.dropout, name=ln + ".cross")
        y = LayerNorm(cfg.d_model, name=ln + ".ln2")(
            y + cross(y, cfg.batch_size, cfg.tgt_len, kv=memory,
                      kv_seq=cfg.src_len))
        y = LayerNorm(cfg.d_model, name=ln + ".ln3")(
            y + ops.dropout_op(_ffn(cfg, y, ln + ".ffn"), 1.0 - cfg.dropout))

    logits = Linear(cfg.d_model, cfg.vocab_size, name=name + ".out")(y)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.tgt_len)
    feeds = {"src_ids": src, "tgt_ids": tgt_in, "labels": labels}
    return feeds, loss, logits


def synthetic_copy_batch(cfg, seed=0):
    """Copy task: target = source (learnable quickly; loss should fall)."""
    rng = np.random.RandomState(seed)
    assert cfg.src_len == cfg.tgt_len
    src = rng.randint(2, cfg.vocab_size, (cfg.batch_size, cfg.src_len))
    tgt_in = np.concatenate([np.ones((cfg.batch_size, 1)), src[:, :-1]], 1)
    return (src.astype(np.float32), tgt_in.astype(np.float32),
            src.astype(np.float32))
