"""Transformer-XL (reference ``examples/transformers/transfoxl/``).

TPU-native rewrite: segment-level recurrence rides the executor's
functional-state side-channel (the same mechanism as BatchNorm running
stats) — per-layer memories are non-trainable (B, mem_len, d) variables
consumed by the step and rewritten with the segment's (stop-gradient)
hidden states, so the jitted step stays pure while ``executor.run`` carries
state across segments.  Attention over [mems ‖ segment] uses the fused
``sdpa_bias_op`` whose causal mask is bottom-right aligned (query i sees
keys j ≤ i + mem_len — exactly Transformer-XL's visibility), plus a
learned relative-distance bias table gathered with static indices
(the reference recomputes R·Wk sinusoids per step on device).
"""
from __future__ import annotations

import numpy as np

import jax

from .. import ops
from .. import initializers as init
from ..graph.node import Op, Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class TransfoXLConfig:
    def __init__(self, vocab_size=267735, d_model=410, n_head=10,
                 d_inner=2100, n_layer=16, mem_len=160, clamp_len=400,
                 dropout=0.1, layer_norm_eps=1e-5, batch_size=4,
                 tgt_len=128):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_head = n_head
        self.d_inner = d_inner
        self.n_layer = n_layer
        self.mem_len = mem_len
        self.clamp_len = clamp_len
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.tgt_len = tgt_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("d_model", 128)
        kw.setdefault("n_head", 2)
        kw.setdefault("d_inner", 256)
        kw.setdefault("n_layer", 2)
        kw.setdefault("mem_len", 16)
        kw.setdefault("vocab_size", 512)
        kw.setdefault("tgt_len", 32)
        return cls(**kw)


class _StateWriteOp(Op):
    """Route a computed value into the executor's state side-channel for a
    non-trainable variable (the BatchNorm running-stat mechanism, exposed
    as a graph op for segment recurrence)."""

    op_type = "StateWrite"

    def __init__(self, value_node, var, name=None):
        super().__init__([value_node, var], name=name)
        self.var = var

    def lower(self, ctx, value, var_val):
        del var_val
        new = jax.lax.stop_gradient(value)
        ctx.state_updates[self.var] = new
        return new

    def infer_shape(self, input_shapes):
        return input_shapes[0]


def _rel_bias(cfg, name):
    """Learned per-head bias over clamped relative distance q−k ∈
    [0, clamp_len], static-gathered → (1, H, S, M+S)."""
    S, M = cfg.tgt_len, cfg.mem_len
    q = np.arange(S)[:, None]
    k = np.arange(M + S)[None, :] - M
    dist = np.clip(q - k, 0, cfg.clamp_len)       # causal distances ≥ 0
    table = init.truncated_normal((cfg.clamp_len + 1, cfg.n_head), 0.0, 0.02,
                                  name=name)
    idx = Variable(name + ".idx", value=dist.reshape(-1).astype(np.float32),
                   trainable=False)
    bias = ops.embedding_lookup_op(table, idx)     # (S*(M+S), H)
    bias = ops.array_reshape_op(bias, output_shape=(S, M + S, cfg.n_head))
    bias = ops.transpose_op(bias, perm=(2, 0, 1))
    return ops.array_reshape_op(bias,
                                output_shape=(1, cfg.n_head, S, M + S))


def transfoxl_model(cfg, input_ids, name="transfoxl"):
    """Returns (hidden (B*S, d), list of new-mem nodes).

    The new-mem nodes are :class:`_StateWriteOp`s — fetch-independent
    consumers are unnecessary; they sit on the layer dataflow so the
    executor commits them every step.
    """
    B, S, M, d = cfg.batch_size, cfg.tgt_len, cfg.mem_len, cfg.d_model
    word = init.truncated_normal((cfg.vocab_size, d), 0.0, 0.02,
                                 name=name + ".word")
    x = ops.embedding_lookup_op(word, input_ids)          # (B, S, d)
    x = ops.dropout_op(x, 1.0 - cfg.dropout)
    mem_writes = []
    for i in range(cfg.n_layer):
        ln = f"{name}.layer{i}"
        mem = Variable(ln + ".mems", value=np.zeros((B, M, d), np.float32),
                       trainable=False)
        # new memory = last M positions of [mem ‖ x], detached
        cat = ops.concatenate_op([mem, x], axis=1)        # (B, M+S, d)
        new_mem = ops.slice_op(cat, begin=(0, S, 0), size=(B, M, d))
        mem_writes.append(_StateWriteOp(new_mem, mem, name=ln + ".memwrite"))

        flat_x = ops.array_reshape_op(x, output_shape=(B * S, d))
        flat_kv = ops.array_reshape_op(cat, output_shape=(B * (M + S), d))
        bias = _rel_bias(cfg, ln + ".rel_bias")
        mha = MultiHeadAttention(d, cfg.n_head, dropout=cfg.dropout,
                                 causal=True, name=ln + ".attn")
        a = mha(flat_x, B, S, kv=flat_kv, kv_seq=M + S, bias=bias)
        h = LayerNorm(d, cfg.layer_norm_eps, ln + ".ln1")(flat_x + a)
        f = Linear(d, cfg.d_inner, activation="relu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ff1")(h)
        f = Linear(cfg.d_inner, d,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".ff2")(f)
        f = ops.dropout_op(f, 1.0 - cfg.dropout)
        h = LayerNorm(d, cfg.layer_norm_eps, ln + ".ln2")(h + f)
        x = ops.array_reshape_op(h, output_shape=(B, S, d))
    hidden = ops.array_reshape_op(x, output_shape=(B * S, d))
    return hidden, mem_writes


def transfoxl_lm_graph(cfg, name="transfoxl"):
    """Segment-recurrent causal LM graph.

    Returns (feeds dict, loss, logits).  Feeding consecutive segments to
    ``executor.run`` carries memory across calls (reference
    ``hetu_transfoxl.py`` mems plumbing).
    """
    shape = (cfg.batch_size, cfg.tgt_len)
    input_ids = placeholder_op("input_ids", shape=shape, dtype=np.int32)
    labels = placeholder_op("labels", shape=shape, dtype=np.int32)
    hidden, mem_writes = transfoxl_model(cfg, input_ids, name)
    logits = Linear(cfg.d_model, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(hidden)
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, cfg.batch_size * cfg.tgt_len)
    # anchor the mem writes on the loss so they are always in the topo
    for w in mem_writes:
        loss = loss + ops.reduce_mean_op(w, [0, 1, 2]) * 0.0
    return {"input_ids": input_ids, "labels": labels}, loss, logits
