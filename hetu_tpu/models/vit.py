"""ViT (reference ``examples/transformers/vit/``).

TPU-native rewrite: patchify is a reshape+transpose+matmul (one MXU GEMM —
equivalent to the reference's strided conv but lays out directly for the
systolic array), pre-LN encoder blocks with fused ``sdpa_op``, learned
position embeddings, mean-pool head (static-shape-friendly alternative to
the class token, selectable).
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.attention import MultiHeadAttention
from ..layers.core import Linear, LayerNorm


class ViTConfig:
    def __init__(self, image_size=224, patch_size=16, num_channels=3,
                 hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072,
                 hidden_dropout_prob=0.0, layer_norm_eps=1e-6,
                 num_classes=1000, batch_size=8, pool="mean"):
        assert image_size % patch_size == 0
        assert pool in ("mean", "cls")
        self.image_size = image_size
        self.patch_size = patch_size
        self.num_channels = num_channels
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.layer_norm_eps = layer_norm_eps
        self.num_classes = num_classes
        self.batch_size = batch_size
        self.pool = pool
        self.num_patches = (image_size // patch_size) ** 2
        #: sequence length through the encoder (CLS prepends a token)
        self.seq_len = self.num_patches + (1 if pool == "cls" else 0)

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("image_size", 32)
        kw.setdefault("patch_size", 8)
        kw.setdefault("hidden_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 256)
        kw.setdefault("num_classes", 10)
        return cls(**kw)


def _patchify(cfg, images, name):
    """(B, C, H, W) → (B*P, hidden) with one matmul.

    reshape (B,C,gh,p,gw,p) → transpose → (B*gh*gw, C*p*p) @ W.
    """
    p = cfg.patch_size
    g = cfg.image_size // p
    x = ops.array_reshape_op(
        images, output_shape=(cfg.batch_size, cfg.num_channels, g, p, g, p))
    x = ops.transpose_op(x, perm=(0, 2, 4, 1, 3, 5))  # B,gh,gw,C,p,p
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size * g * g,
                         cfg.num_channels * p * p))
    return Linear(cfg.num_channels * p * p, cfg.hidden_size,
                  initializer=init.GenTruncatedNormal(0.0, 0.02),
                  name=name + ".proj")(x)


def vit_model(cfg, images, name="vit"):
    """Returns token-sequence hidden states (batch*seq_len, hidden);
    ``cfg.pool == "cls"`` prepends a learned class token (the HF/original
    layout — tests/test_hf_parity.py pins it against transformers),
    ``"mean"`` (default) keeps the token-free mean-pool head."""
    S = cfg.seq_len
    x = _patchify(cfg, images, name + ".patch")
    pos = init.truncated_normal((S, cfg.hidden_size), 0.0, 0.02,
                                name=name + ".pos_embed")
    pos_ids = Variable(name + ".pos_ids",
                       value=np.arange(S, dtype=np.float32),
                       trainable=False)
    pe = ops.embedding_lookup_op(pos, pos_ids)        # (S, hidden)
    pe = ops.array_reshape_op(pe, output_shape=(1, S, cfg.hidden_size))
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, cfg.num_patches, cfg.hidden_size))
    if cfg.pool == "cls":
        cls = init.truncated_normal((1, 1, cfg.hidden_size), 0.0, 0.02,
                                    name=name + ".cls_token")
        x = ops.concatenate_op(
            [ops.broadcast_shape_op(
                cls, shape=(cfg.batch_size, 1, cfg.hidden_size)), x],
            axis=1)
    x = x + ops.broadcastto_op(pe, x)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size * S, cfg.hidden_size))
    x = ops.dropout_op(x, 1.0 - cfg.hidden_dropout_prob)
    for i in range(cfg.num_hidden_layers):
        ln = f"{name}.layer{i}"
        h = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, ln + ".ln1")(x)
        mha = MultiHeadAttention(cfg.hidden_size, cfg.num_attention_heads,
                                 name=ln + ".attn")
        x = x + mha(h, cfg.batch_size, S)
        h = LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, ln + ".ln2")(x)
        h = Linear(cfg.hidden_size, cfg.intermediate_size, activation="gelu",
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".mlp1")(h)
        h = Linear(cfg.intermediate_size, cfg.hidden_size,
                   initializer=init.GenTruncatedNormal(0.0, 0.02),
                   name=ln + ".mlp2")(h)
        x = x + ops.dropout_op(h, 1.0 - cfg.hidden_dropout_prob)
    return LayerNorm(cfg.hidden_size, cfg.layer_norm_eps, name + ".ln_f")(x)


def vit_classify_graph(cfg, name="vit"):
    """Image classification graph: pooled tokens → linear head
    (``cfg.pool``: mean over patches, or the CLS token).

    Returns (feeds dict, loss node, logits node).
    """
    images = placeholder_op("images", shape=(cfg.batch_size, cfg.num_channels,
                                             cfg.image_size, cfg.image_size))
    labels = placeholder_op("labels", shape=(cfg.batch_size,
                                             cfg.num_classes))
    x = vit_model(cfg, images, name)
    x = ops.array_reshape_op(
        x, output_shape=(cfg.batch_size, cfg.seq_len, cfg.hidden_size))
    if cfg.pool == "cls":
        pooled = ops.array_reshape_op(
            ops.slice_op(x, begin=(0, 0, 0),
                         size=(cfg.batch_size, 1, cfg.hidden_size)),
            output_shape=(cfg.batch_size, cfg.hidden_size))
    else:
        pooled = ops.reduce_mean_op(x, [1])
    logits = Linear(cfg.hidden_size, cfg.num_classes,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".head")(pooled)
    loss = ops.reduce_mean_op(
        ops.softmaxcrossentropy_op(logits, labels), [0])
    return {"images": images, "labels": labels}, loss, logits


def synthetic_image_batch(cfg, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.rand(cfg.batch_size, cfg.num_channels, cfg.image_size,
                    cfg.image_size).astype(np.float32)
    y = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.randint(0, cfg.num_classes, cfg.batch_size)]
    return imgs, y
