"""XLNet (reference ``examples/transformers/xlnet/``).

TPU-native rewrite of two-stream permutation-LM attention:

* the factorization-order visibility masks are built HOST-SIDE per batch
  from the sampled permutation and fed as (B, 1, S, S) placeholders
  (static shapes; the reference computes them on device per step);
* the content stream h attends with the inclusive mask (j visible if
  perm_pos[j] ≤ perm_pos[i], self included), the query stream g queries
  the SAME content keys/values with the exclusive mask (strictly earlier
  in the permutation — g never sees its own token), sharing projection
  weights between streams exactly as in the paper;
* predictions come from the query stream; relative position information
  enters as a learned clamped-distance bias (cf. transfoxl).
"""
from __future__ import annotations

import numpy as np

from .. import ops
from .. import initializers as init
from ..graph.node import Variable, placeholder_op
from ..layers.core import Linear, LayerNorm


class XLNetConfig:
    def __init__(self, vocab_size=32000, d_model=768, n_head=12,
                 d_inner=3072, n_layer=12, clamp_len=256, dropout=0.1,
                 layer_norm_eps=1e-12, batch_size=4, seq_len=128):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_head = n_head
        self.d_inner = d_inner
        self.n_layer = n_layer
        self.clamp_len = clamp_len
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.batch_size = batch_size
        self.seq_len = seq_len

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("d_model", 128)
        kw.setdefault("n_head", 2)
        kw.setdefault("d_inner", 256)
        kw.setdefault("n_layer", 2)
        kw.setdefault("vocab_size", 512)
        kw.setdefault("seq_len", 32)
        return cls(**kw)


def perm_masks_from_order(perm):
    """Build (content_mask, query_mask) from a permutation.

    ``perm``: (B, S) int — perm[b, k] is the position processed k-th.
    content_mask[b, i, j] = 1 iff perm_pos[j] <= perm_pos[i] (self incl.)
    query_mask[b, i, j]   = 1 iff perm_pos[j] <  perm_pos[i]
    """
    B, S = perm.shape
    rank = np.empty_like(perm)
    for b in range(B):
        rank[b, perm[b]] = np.arange(S)
    r_i = rank[:, :, None]
    r_j = rank[:, None, :]
    content = (r_j <= r_i).astype(np.float32)
    query = (r_j < r_i).astype(np.float32)
    return content.reshape(B, 1, S, S), query.reshape(B, 1, S, S)


def _rel_bias(cfg, name):
    S = cfg.seq_len
    dist = np.clip(np.abs(np.arange(S)[:, None] - np.arange(S)[None, :]),
                   0, cfg.clamp_len)
    table = init.truncated_normal((cfg.clamp_len + 1, cfg.n_head), 0.0, 0.02,
                                  name=name)
    idx = Variable(name + ".idx", value=dist.reshape(-1).astype(np.float32),
                   trainable=False)
    bias = ops.embedding_lookup_op(table, idx)
    bias = ops.array_reshape_op(bias, output_shape=(S, S, cfg.n_head))
    bias = ops.transpose_op(bias, perm=(2, 0, 1))
    return ops.array_reshape_op(bias, output_shape=(1, cfg.n_head, S, S))


class _TwoStreamLayer:
    """One XLNet layer: shared QKV weights, two masked attention streams."""

    def __init__(self, cfg, name):
        d = cfg.d_model
        self.cfg = cfg
        self.heads = cfg.n_head
        self.dk = d // self.heads
        self.q = Linear(d, d, bias=False, name=name + ".q")
        self.k = Linear(d, d, bias=False, name=name + ".k")
        self.v = Linear(d, d, bias=False, name=name + ".v")
        self.o = Linear(d, d, name=name + ".o")
        self.ln1 = LayerNorm(d, cfg.layer_norm_eps, name + ".ln1")
        self.f1 = Linear(d, cfg.d_inner, activation="gelu",
                         initializer=init.GenTruncatedNormal(0.0, 0.02),
                         name=name + ".ff1")
        self.f2 = Linear(cfg.d_inner, d,
                         initializer=init.GenTruncatedNormal(0.0, 0.02),
                         name=name + ".ff2")
        self.ln2 = LayerNorm(d, cfg.layer_norm_eps, name + ".ln2")
        self.bias = _rel_bias(cfg, name + ".rel_bias")

    def _split(self, x):
        cfg = self.cfg
        x = ops.array_reshape_op(
            x, output_shape=(cfg.batch_size, cfg.seq_len, self.heads,
                             self.dk))
        return ops.transpose_op(x, perm=(0, 2, 1, 3))

    def _attend(self, q_src, k_heads, v_heads, mask):
        cfg = self.cfg
        q = self._split(self.q(q_src))
        o = ops.sdpa_masked_bias_op(q, k_heads, v_heads, mask, self.bias)
        o = ops.transpose_op(o, perm=(0, 2, 1, 3))
        o = ops.array_reshape_op(
            o, output_shape=(cfg.batch_size * cfg.seq_len, cfg.d_model))
        return self.o(o)

    def _ffn(self, x):
        return self.ln2(x + self.f2(self.f1(x)))

    def __call__(self, h, g, content_mask, query_mask):
        k = self._split(self.k(h))
        v = self._split(self.v(h))
        h2 = self.ln1(h + self._attend(h, k, v, content_mask))
        g2 = self.ln1(g + self._attend(g, k, v, query_mask))
        return self._ffn(h2), self._ffn(g2)


def xlnet_model(cfg, input_ids, content_mask, query_mask, name="xlnet"):
    """Returns (content stream, query stream), each (B*S, d)."""
    B, S, d = cfg.batch_size, cfg.seq_len, cfg.d_model
    word = init.truncated_normal((cfg.vocab_size, d), 0.0, 0.02,
                                 name=name + ".word")
    h = ops.embedding_lookup_op(word, input_ids)
    h = ops.array_reshape_op(h, output_shape=(B * S, d))
    h = ops.dropout_op(h, 1.0 - cfg.dropout)
    # query stream starts from a single learned vector w (paper init);
    # tiling = one embedding lookup with constant zero ids
    g0 = init.truncated_normal((1, d), 0.0, 0.02, name=name + ".mask_emb")
    g_ids = Variable(name + ".g_ids", value=np.zeros(B * S, np.float32),
                     trainable=False)
    g = ops.embedding_lookup_op(g0, g_ids)
    for i in range(cfg.n_layer):
        layer = _TwoStreamLayer(cfg, f"{name}.layer{i}")
        h, g = layer(h, g, content_mask, query_mask)
    return h, g


def xlnet_plm_graph(cfg, name="xlnet"):
    """Permutation-LM pretraining graph.

    Feeds: input_ids (B,S) int32; content_mask/query_mask (B,1,S,S) from
    :func:`perm_masks_from_order`; labels (B,S) with -1 outside the
    predicted target positions.  Returns (feeds, loss, logits).
    """
    B, S = cfg.batch_size, cfg.seq_len
    input_ids = placeholder_op("input_ids", shape=(B, S), dtype=np.int32)
    labels = placeholder_op("labels", shape=(B, S), dtype=np.int32)
    content_mask = placeholder_op("content_mask", shape=(B, 1, S, S))
    query_mask = placeholder_op("query_mask", shape=(B, 1, S, S))
    h, g = xlnet_model(cfg, input_ids, content_mask, query_mask, name)
    logits = Linear(cfg.d_model, cfg.vocab_size,
                    initializer=init.GenTruncatedNormal(0.0, 0.02),
                    name=name + ".lm_head")(g)          # predictions from g
    from .common import masked_lm_loss
    loss = masked_lm_loss(logits, labels, B * S)
    feeds = {"input_ids": input_ids, "labels": labels,
             "content_mask": content_mask, "query_mask": query_mask}
    return feeds, loss, logits


def synthetic_plm_batch(cfg, seed=0, target_frac=0.25):
    """ids + permutation masks + labels on the last-k permutation targets."""
    rng = np.random.RandomState(seed)
    B, S = cfg.batch_size, cfg.seq_len
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    perm = np.stack([rng.permutation(S) for _ in range(B)])
    cmask, qmask = perm_masks_from_order(perm)
    labels = np.full((B, S), -1, np.int64)
    k = max(1, int(S * target_frac))
    for b in range(B):
        targets = perm[b, -k:]                    # last-k in factorization
        labels[b, targets] = ids[b, targets]
    return ids, cmask.astype(np.float32), qmask.astype(np.float32), \
        labels.astype(np.int32)
