"""NDArray façade over ``jax.Array`` (reference ``python/hetu/ndarray.py``:
NDArray:140, ND_Sparse_Array:460, IndexedSlices:507, ``array``:405).

The reference NDArray owns raw device memory via the ctypes DLArray ABI; here
it is a thin veneer: jax.Array already provides device residence, async
transfer and buffer lifetime.  Kept so model/example code using
``ht.array(...)``, ``.asnumpy()``, ``ht.empty`` ports unchanged.  Streams and
events (``stream.py``) have no TPU analogue under XLA's async runtime —
``wait()`` maps to ``block_until_ready``.
"""
from __future__ import annotations

import numpy as np

from .context import DLContext, gpu

#: shared default context: NDArray is constructed per fetch per step on
#: the executor's dispatch path — a fresh DLContext each wrap is pure
#: allocation churn (the ctx is descriptive metadata, never mutated)
_DEFAULT_CTX = gpu(0)


def wrap_device(arr):
    """Fetch-handle constructor for the executor's dispatch path:
    ``arr`` is ALREADY a device array (a jitted step output), so the
    ``NDArray.__init__`` isinstance/conversion ladder is pure per-step
    overhead — this skips straight to the wrapped form."""
    nd = NDArray.__new__(NDArray)
    nd._arr = arr
    nd.ctx = _DEFAULT_CTX
    return nd


class NDArray:
    __slots__ = ("_arr", "ctx")

    def __init__(self, arr, ctx=None):
        import jax.numpy as jnp
        if isinstance(arr, NDArray):
            arr = arr._arr
        if not hasattr(arr, "devices"):  # numpy / list → device array
            arr = jnp.asarray(np.asarray(arr))
        self._arr = arr
        self.ctx = ctx or _DEFAULT_CTX

    @property
    def shape(self):
        return tuple(self._arr.shape)

    @property
    def dtype(self):
        return self._arr.dtype

    def asnumpy(self):
        return np.asarray(self._arr)

    def numpy(self):
        return self.asnumpy()

    def jax(self):
        return self._arr

    def wait(self):
        self._arr.block_until_ready()
        return self

    def copyto(self, target):
        if isinstance(target, NDArray):
            target._arr = self._arr
            return target
        raise TypeError(target)

    def __getitem__(self, idx):
        return NDArray(self._arr[idx], self.ctx)

    def __array__(self, dtype=None):
        out = self.asnumpy()
        return out.astype(dtype) if dtype is not None else out

    def __float__(self):
        return float(self._arr)

    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return f"NDArray(shape={self.shape}, dtype={self.dtype}, ctx={self.ctx})"


def array(arr, ctx=None, data_type=np.float32):
    """``ht.array(np_arr, ctx=ht.gpu(0))`` parity (reference ndarray.py:405)."""
    return NDArray(np.asarray(arr, dtype=data_type), ctx)


def empty(shape, ctx=None, dtype=np.float32):
    import jax.numpy as jnp
    return NDArray(jnp.zeros(shape, dtype), ctx)


def is_gpu_ctx(ctx):
    return isinstance(ctx, DLContext) and not ctx.is_host


class IndexedSlices:
    """Sparse gradient rows (reference ndarray.py:507).  Under jit, XLA's
    scatter-add covers the dense path; this host-side type serves the
    host-embedding store (:mod:`hetu_tpu.embedding`)."""

    def __init__(self, indices=None, values=None, dense_shape=None):
        self.indices = indices
        self.values = values
        self.dense_shape = dense_shape

    def to_dense(self):
        out = np.zeros(self.dense_shape, np.float32)
        np.add.at(out, np.asarray(self.indices).astype(np.int64).reshape(-1),
                  np.asarray(self.values).reshape(-1, self.dense_shape[-1]))
        return out

    def cpu_deduplicate(self):
        idx = np.asarray(self.indices).reshape(-1)
        vals = np.asarray(self.values).reshape(-1, np.asarray(self.values).shape[-1])
        uniq, inv = np.unique(idx, return_inverse=True)
        out = np.zeros((len(uniq), vals.shape[-1]), vals.dtype)
        np.add.at(out, inv, vals)
        return uniq, out


class NDSparseArray:
    """COO sparse matrix (reference ``ND_Sparse_Array``, ndarray.py:460):
    values + (row, col) indices + dense shape.  On TPU the compute path
    uses dense/segment-sum lowerings (:mod:`hetu_tpu.ops.gnn`); this host
    type keeps the construction API portable."""

    __slots__ = ("values", "row", "col", "shape", "ctx")

    def __init__(self, values, row, col, shape, ctx=None):
        self.values = np.asarray(values)
        self.row = np.asarray(row).astype(np.int64)
        self.col = np.asarray(col).astype(np.int64)
        self.shape = tuple(shape)
        self.ctx = ctx

    def asnumpy(self):
        out = np.zeros(self.shape, self.values.dtype)
        np.add.at(out, (self.row, self.col), self.values)
        return out


def sparse_array(values, indices, shape, ctx=None):
    """Reference ``ndarray.py:477``: COO construction from
    ``(values, (rows, cols), shape)``."""
    row, col = indices
    return NDSparseArray(values, row, col, shape, ctx=ctx)
