"""hetu_tpu.obs — unified telemetry: step-span tracing, a metrics
registry with latency histograms and MFU gauges, Chrome/Perfetto export
(ISSUE 10).

The framework's behaviours worth reproducing — overlapped
communication, PS failover, pipelined execution — are exactly the ones
invisible to per-op timers and disconnected counters.  This subsystem
makes them visible from one place:

* **Spans/events** (:mod:`~hetu_tpu.obs.trace`): ``obs.span("x")`` /
  ``obs.event("x")`` write into lock-free per-thread ring buffers.
  Compiled to a no-op when ``HETU_TRACE=0`` (the default — guarded
  sites pay one flag read); ``HETU_TRACE=1`` (or ``obs.enable(True)``)
  records the executor's step phases (run-plan lookup, feed placement,
  jit dispatch, the PS push boundary), every PS client RPC per opcode
  (latency + payload bytes, with retry/failover/promotion/epoch-refusal
  as point events via the fault counters), the serving router
  lifecycle, chaos injections, the background feed-pipeline /
  replication / read-only-refresh threads as named tracks, and
  ``run(sync=False)`` in-flight windows as flow arrows.
  ``HETU_TRACE_BUF`` sizes the per-thread rings (default 65536; the
  ring keeps the newest events when it wraps).

* **Export** (:mod:`~hetu_tpu.obs.export`):
  ``obs.export_chrome_trace(path)`` writes Chrome trace JSON — load it
  at https://ui.perfetto.dev.  For host-span <-> device-trace
  correlation, ``HetuProfiler.trace()`` wraps each captured step in
  ``jax.profiler.StepTraceAnnotation`` so XProf aligns device slices
  with the host step index.

* **Metrics registry** (:mod:`~hetu_tpu.obs.registry`): every counter
  family, latency histogram and gauge registers against
  ``obs.registry``; :func:`metrics_dump` snapshots all of it as one
  JSON-able dict and ``tools/metricsd.py`` exposes the same registry as
  Prometheus text (file export or a tiny HTTP endpoint).  The
  histograms are log-bucketed (8 buckets/octave) with p50/p90/p99
  accessors; the ``mfu``/``step_time_ms`` gauges are computed per run
  from the PR 5 inferred-shape FLOP model over measured step time
  (:func:`graph_flops` / :func:`record_mfu`).

Diagnostic-style conventions follow PR 5/PR 8: every exported name
says WHERE the number comes from and what a surprising value means.
"""
from __future__ import annotations

from .trace import TRACER, span, event
from .export import trace_events, export_chrome_trace
from .registry import REGISTRY as registry
from . import lock_witness  # noqa: F401  (ISSUE 14 runtime lock-witness)


def enabled():
    """True iff span/event tracing is currently recording."""
    return TRACER.on


def enable(on=True, buf=None):
    """Turn tracing on/off at runtime; ``buf`` resizes the per-thread
    rings first (dropping prior records)."""
    if buf is not None:
        TRACER.set_capacity(buf)
    TRACER.enable(on)


def set_track_name(name):
    """Name the calling thread's track in the exported trace."""
    TRACER.set_track_name(name)


def clear_trace():
    """Drop every recorded span/event (ring capacity unchanged)."""
    TRACER.clear()


def flow_begin(name, cat="async"):
    """Open a flow arrow; returns the id ``flow_end`` closes it with
    (no-op returning None when tracing is off)."""
    if not TRACER.on:
        return None
    return TRACER.flow_begin(name, cat)


def flow_end(name, fid, cat="async"):
    """Close a flow arrow opened by :func:`flow_begin` (from any
    thread); a ``None`` id (tracing was off at begin) is ignored."""
    if fid is not None and TRACER.on:
        TRACER.flow_end(name, fid, cat)


def metrics_dump():
    """One JSON-able snapshot of EVERY registered instrument:
    ``{"counters": {family: {kind: n}}, "histograms": {name: {label:
    {count/sum/min/max/mean/p50/p90/p99}}}, "gauges": {name: {label:
    value}}}``.  The counter values are the same numbers the legacy
    per-family accessors (``HetuProfiler.fault_counters()`` & co)
    report — one registry, two views."""
    return registry.dump()


def prometheus_text():
    """The registry as Prometheus text exposition (see
    ``tools/metricsd.py`` for the file/HTTP wrappers)."""
    return registry.prometheus_text()


def reset_all_metrics():
    """Zero every registered counter family, histogram and gauge
    (alias of ``hetu_tpu.metrics.reset_all``)."""
    registry.reset_all()


# -- MFU / step-time gauges --------------------------------------------------

def graph_flops(fetches, feeds=None, train=True):
    """Per-step FLOPs of a fetch subgraph from the PR 5 inferred-shape
    cost model (``autoparallel.graph_layer_spec``: every matmul-family
    and attention contraction priced off the abstract-interpreter
    shapes — no hand-derived approximation).  ``train=True`` applies
    the standard 3x forward multiplier (forward + ~2x backward matmul
    work); pass ``train=False`` for inference-only graphs."""
    from ..autoparallel.cost_model import graph_layer_spec
    spec = graph_layer_spec(fetches, feeds=feeds)
    return (3.0 if train else 1.0) * float(spec.fwd_flops)


#: bf16 peak FLOP/s per chip by device_kind prefix (public TPU spec
#: sheets), most-specific prefix first.  THE one table — ``bench.py``
#: and ``autoparallel.measure`` both resolve through
#: :func:`device_peak_flops`, so a new device kind lands here once.
TPU_PEAK_BY_KIND = (
    ("TPU v6 lite", 918e12), ("TPU v6", 918e12),     # Trillium
    ("TPU v5 lite", 197e12), ("TPU v5p", 459e12), ("TPU v5", 459e12),
    ("TPU v4", 275e12), ("TPU v3", 123e12), ("TPU v2", 46e12),
)


def device_peak_flops():
    """(peak_flops_per_chip, device_kind).  Unknown TPU kinds get the
    most conservative (smallest) table entry so MFU cannot be inflated
    by a lookup miss; non-TPU backends get a nominal 50 TF placeholder
    (their MFU is a relative gauge, never the headline number)."""
    import jax
    kind = jax.devices()[0].device_kind
    if jax.default_backend() != "tpu":
        return 50e12, kind
    for prefix, peak in TPU_PEAK_BY_KIND:
        if str(kind).startswith(prefix):
            return peak, kind
    return min(p for _, p in TPU_PEAK_BY_KIND), kind


def record_mfu(label, flops_per_step, step_time_s, peak_flops):
    """Compute and publish the per-run ``mfu`` + ``step_time_ms``
    gauges: ``flops_per_step`` (see :func:`graph_flops`) over measured
    ``step_time_s``, against the hardware peak (``bench.py``'s
    per-device-kind table).  Returns the MFU value; ``metrics_dump()``
    exposes both gauges under ``label``."""
    from .. import metrics
    mfu = float(flops_per_step) / max(float(step_time_s), 1e-12) \
        / max(float(peak_flops), 1e-12)
    metrics.record_run_gauges(label, step_time_s * 1e3, mfu)
    return mfu


__all__ = ["TRACER", "span", "event", "enabled", "enable",
           "set_track_name", "clear_trace", "flow_begin", "flow_end",
           "trace_events", "export_chrome_trace", "registry",
           "metrics_dump", "prometheus_text", "reset_all_metrics",
           "graph_flops", "record_mfu", "device_peak_flops",
           "TPU_PEAK_BY_KIND"]
