"""Chrome/Perfetto trace export (ISSUE 10 tentpole, part 2).

Serializes the :data:`~hetu_tpu.obs.trace.TRACER` rings into the Chrome
trace-event JSON format (the ``{"traceEvents": [...]}`` object both
``chrome://tracing`` and https://ui.perfetto.dev load directly):

* complete spans   -> ``ph="X"`` with microsecond ``ts``/``dur``
* instant events   -> ``ph="i"`` (thread scope)
* flow begin/end   -> ``ph="s"``/``ph="f"`` (``bp="e"``) — the arrows
  tying a ``run(sync=False)`` dispatch to the sync point that
  materialized it
* track names      -> ``ph="M"`` ``thread_name`` metadata per thread
  (the feed-pipeline / serve-router / PS-serve threads appear as named
  tracks), plus a ``process_name`` record.

Timestamps are ``perf_counter_ns / 1000`` — one shared monotonic base,
so cross-track ordering in the viewer is real.
"""
from __future__ import annotations

import json
import os

from .trace import TRACER


def trace_events(tracer=None):
    """The recorded rings as a list of Chrome trace-event dicts
    (metadata first, then events sorted by timestamp)."""
    tr = tracer or TRACER
    pid = os.getpid()
    events = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": "hetu_tpu"}}]
    for tid, name in tr.tracks():
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": name}})
    rows = []
    for tid, rec in tr.records():
        ph = rec[0]
        if ph == "P":
            # packed executor phase set -> three spans (trace.py)
            _, t_pl, t0, t1, t2 = rec
            for name, a, b in (("run_plan.lookup", t_pl, t0),
                               ("feeds.place", t0, t1),
                               ("jit.dispatch", t1, t2)):
                if a:       # t_pl may be 0 (no lookup window captured)
                    rows.append({"ph": "X", "name": name,
                                 "cat": "executor", "pid": pid,
                                 "tid": tid, "ts": a / 1e3,
                                 "dur": (b - a) / 1e3})
            continue
        if ph == "S":
            _, sub, t0, t1, step = rec
            rows.append({"ph": "X", "name": "step", "cat": "executor",
                         "pid": pid, "tid": tid, "ts": t0 / 1e3,
                         "dur": (t1 - t0) / 1e3,
                         "args": {"sub": sub, "step": step}})
            continue
        if ph == "X":
            _, name, cat, t0, dur, args = rec
            ev = {"ph": "X", "name": name, "cat": cat, "pid": pid,
                  "tid": tid, "ts": t0 / 1e3, "dur": dur / 1e3}
            if args:
                ev["args"] = dict(args)
        elif ph == "i":
            _, name, cat, t, args = rec
            ev = {"ph": "i", "name": name, "cat": cat, "pid": pid,
                  "tid": tid, "ts": t / 1e3, "s": "t"}
            if args:
                ev["args"] = dict(args)
        else:       # "s" / "f" flow pair
            _, name, cat, t, fid = rec
            ev = {"ph": ph, "name": name, "cat": cat, "pid": pid,
                  "tid": tid, "ts": t / 1e3, "id": int(fid)}
            if ph == "f":
                ev["bp"] = "e"
        rows.append(ev)
    rows.sort(key=lambda e: e["ts"])
    return events + rows


def export_chrome_trace(path, tracer=None):
    """Write the recorded trace as Chrome/Perfetto JSON to ``path``
    (atomic rename).  Returns the event count.  Load it at
    https://ui.perfetto.dev or ``chrome://tracing``."""
    evs = trace_events(tracer)
    blob = {"traceEvents": evs, "displayTimeUnit": "ms"}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(blob, fh)
    os.replace(tmp, path)
    return len(evs)


__all__ = ["trace_events", "export_chrome_trace"]
