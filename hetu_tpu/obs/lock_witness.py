"""Runtime lock-witness — the dynamic twin of the static concurrency
verifier (ISSUE 14 tentpole, runtime pass).

The AST pass (:mod:`hetu_tpu.analysis.concurrency`) cannot see through
``ctypes``, sockets, callbacks or dynamically-wired transports (the
server's ``rpc_fn`` rides the client's connection locks, a relationship
no static attr resolution reaches).  This module records what ACTUALLY
happens: with ``HETU_LOCK_WITNESS=1`` every lock created through the
factories below is wrapped, each thread keeps its held-stack, and every
acquisition adds ``held -> acquired`` edges to one process-wide
acquisition graph — CheckMate/lockdep-style witnessing at Python scale.
At teardown (or on :func:`check`) the merged graph is cycle-checked: an
observed cycle means two threads CAN deadlock given the right timing,
even if this run got lucky.

Cost discipline (the PR 10 flag-read rule): with the witness off — the
default — the factories return PLAIN ``threading`` primitives, so
instrumented call sites pay nothing at all, not even a wrapper
attribute hop.  The flag is read once at import (and by
:func:`enable` for tests); locks created while the witness is off stay
plain even if it is enabled later, so tests enable FIRST, then build
the stack under test.

Lock identity is the CLASS-LEVEL name passed to the factory
(``"StoreServer._repl_lock"``) — lockdep's "lock class", not the
instance: a thousand per-connection locks are one node, and the
hierarchy stays readable.  Per-name acquisition counts, re-entries and
max held-depth ride along.

``export(path)`` writes the observed hierarchy as JSON
(``artifacts/lock_hierarchy.json`` is a committed witness run over the
training, serving and elastic planes): nodes with topological LEVELS
when the graph is acyclic (level 0 = roots, acquired first; leaves
last), the edge list with counts, any cycles, and the threads that
participated.  The README's documented lock hierarchy is generated
from exactly this artifact (``tools/gen_lock_hierarchy.py``).

Witness counters land in the ``concurrency_*`` metrics family at
:func:`check` time (``concurrency_witness_locks`` / ``_edges`` /
``_cycles``), surfaced by ``HetuProfiler.concurrency_counters()`` —
never from inside the witness's own critical section (the registry's
lock is deliberately NOT witnessed: instrument-of-the-instrument
recursion).
"""
from __future__ import annotations

import json
import os
import threading


def _env_on():
    return os.environ.get("HETU_LOCK_WITNESS", "0").lower() not in (
        "", "0", "false", "off")


class _WitnessLock:
    """One instrumented lock: delegates to the wrapped primitive and
    reports acquire/release to the process-wide witness.  Exposes the
    ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` trio so a
    ``threading.Condition`` built over it keeps exact RLock semantics
    (a ``cond.wait`` pops the held-stack, the wakeup pushes it back)."""

    __slots__ = ("_inner", "name", "kind")

    def __init__(self, inner, name, kind):
        self._inner = inner
        self.name = name
        self.kind = kind

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            WITNESS._note_acquire(self)
        return got

    def release(self):
        WITNESS._note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        f = getattr(self._inner, "locked", None)
        return f() if f else False

    # -- threading.Condition integration ----------------------------------
    def _release_save(self):
        # the witness depth rides the saved state: a wait under NESTED
        # acquisition must restore the held-stack entry at its true
        # recursion count, or the post-wait releases delete it early and
        # later orderings go unrecorded (review finding)
        depth = WITNESS._note_release(self, full=True)
        f = getattr(self._inner, "_release_save", None)
        inner_state = f() if f is not None else self._inner.release()
        return (inner_state, depth)

    def _acquire_restore(self, state):
        inner_state, depth = state
        f = getattr(self._inner, "_acquire_restore", None)
        if f is not None:
            f(inner_state)
        else:
            self._inner.acquire()
        WITNESS._note_acquire(self, depth=depth)

    def _is_owned(self):
        f = getattr(self._inner, "_is_owned", None)
        if f is not None:
            return f()
        # plain-Lock fallback (threading.Condition's own trick)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<witnessed {self.kind} {self.name}>"


class LockWitness:
    """Process-wide acquisition-graph recorder (singleton
    :data:`WITNESS`).  ``on`` is the one hot flag; everything else hides
    behind the factories."""

    def __init__(self):
        self.on = _env_on()
        self._lock = threading.Lock()   # guards the merged graph (plain
        self._tl = threading.local()    # by design: never witnessed)
        self._edges = {}        # (held name, acquired name) -> count
        self._locks = {}        # name -> {"kind", "acquires", "reentries"}
        self._threads = set()
        self._max_depth = 0
        self._reported = {"locks": 0, "edges": 0, "cycles": 0}

    # -- per-thread held stack ---------------------------------------------
    def _held(self):
        h = getattr(self._tl, "held", None)
        if h is None:
            h = self._tl.held = []
        return h

    def _note_acquire(self, wl, depth=1):
        held = self._held()
        for ent in held:
            if ent[0] is wl:
                ent[1] += 1     # re-entry: no new edge, bump the count
                with self._lock:
                    self._locks[wl.name]["reentries"] += 1
                return
        with self._lock:
            rec = self._locks.get(wl.name)
            if rec is None:
                rec = self._locks[wl.name] = {
                    "kind": wl.kind, "acquires": 0, "reentries": 0}
            rec["acquires"] += 1
            self._threads.add(threading.current_thread().name)
            for ent in held:
                if ent[0].name != wl.name:
                    k = (ent[0].name, wl.name)
                    self._edges[k] = self._edges.get(k, 0) + 1
            if len(held) + 1 > self._max_depth:
                self._max_depth = len(held) + 1
        held.append([wl, depth])

    def _note_release(self, wl, full=False):
        """Pop one recursion level (or, ``full``, the whole entry — the
        Condition.wait path); returns the depth removed so
        ``_acquire_restore`` can put it back exactly."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is wl:
                prior = held[i][1]
                if full:
                    held[i][1] = 0      # Condition.wait: drop ALL depth
                else:
                    held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return prior if full else 1
        # release of a lock this thread never witnessed acquiring (e.g.
        # enabled mid-hold): ignore rather than corrupt the stack
        return 1

    # -- control -----------------------------------------------------------
    def enable(self, on=True):
        """Turn witnessing on/off for locks created FROM NOW ON (the
        factories consult this flag at creation; already-plain locks
        stay plain — enable first, then build the stack under test)."""
        self.on = bool(on)

    def reset(self):
        """Drop the recorded graph (the on/off flag is unchanged)."""
        with self._lock:
            self._edges = {}
            self._locks = {}
            self._threads = set()
            self._max_depth = 0
            self._reported = {"locks": 0, "edges": 0, "cycles": 0}

    # -- readout -----------------------------------------------------------
    def cycles(self):
        """Distinct cycles in the merged acquisition graph, each as the
        node list ``[a, b, ..., a]`` — a non-empty answer means two
        threads can deadlock with the observed orders."""
        with self._lock:
            graph = {}
            for (a, b) in self._edges:
                graph.setdefault(a, set()).add(b)
        out, seen, color, stack = [], set(), {}, []

        def dfs(n):
            color[n] = 1
            stack.append(n)
            for nxt in sorted(graph.get(n, ())):
                if color.get(nxt, 0) == 1:
                    cyc = stack[stack.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        out.append(cyc)
                elif color.get(nxt, 0) == 0:
                    dfs(nxt)
            stack.pop()
            color[n] = 2

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                dfs(n)
        return out

    def _levels(self):
        """{name: topological level} when acyclic (roots = level 0 —
        acquired first, i.e. outermost), else None."""
        with self._lock:
            names = set(self._locks)
            succ = {}
            pred_count = {n: 0 for n in names}
            for (a, b) in self._edges:
                names.add(a)
                names.add(b)
                pred_count.setdefault(a, 0)
                pred_count.setdefault(b, 0)
                if b not in succ.setdefault(a, set()):
                    succ[a].add(b)
                    pred_count[b] += 1
        level = {}
        frontier = sorted(n for n, c in pred_count.items() if c == 0)
        depth = 0
        while frontier:
            nxt = []
            for n in frontier:
                level[n] = depth
                for m in sorted(succ.get(n, ())):
                    pred_count[m] -= 1
                    if pred_count[m] == 0:
                        nxt.append(m)
            frontier = sorted(set(nxt))
            depth += 1
        if len(level) != len(pred_count):
            return None     # a cycle kept some nodes un-leveled
        return level

    def report(self):
        """The merged graph as one JSON-able dict: per-lock stats, edge
        list with counts, cycles, topological levels (when acyclic),
        participating threads."""
        cycles = self.cycles()
        with self._lock:
            locks = {n: dict(rec) for n, rec in sorted(self._locks.items())}
            edges = [{"from": a, "to": b, "count": c}
                     for (a, b), c in sorted(self._edges.items())]
            threads = sorted(self._threads)
            depth = self._max_depth
        levels = self._levels() if not cycles else None
        return {"locks": locks, "edges": edges, "cycles": cycles,
                "levels": levels, "threads": threads,
                "max_held_depth": depth, "acyclic": not cycles}

    def check(self):
        """Cycle-check the merged graph, publish the witness counters
        (``concurrency_witness_locks/edges/cycles`` — deltas since the
        last check, so repeated checks don't double-count), and return
        the cycle list.  Called at teardown by the atexit hook and by
        the tier-1 witness smoke."""
        cycles = self.cycles()
        from ..metrics import record_concurrency
        with self._lock:
            n_locks, n_edges = len(self._locks), len(self._edges)
        for kind, now in (("concurrency_witness_locks", n_locks),
                          ("concurrency_witness_edges", n_edges),
                          ("concurrency_witness_cycles", len(cycles))):
            delta = now - self._reported[kind.rsplit("_", 1)[-1]]
            if delta > 0:
                record_concurrency(kind, delta)
            self._reported[kind.rsplit("_", 1)[-1]] = now
        return cycles

    def export(self, path):
        """Write :meth:`report` to ``path`` (the committed
        ``artifacts/lock_hierarchy.json`` shape); returns the report."""
        rep = self.report()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return rep


#: the process-wide witness — the factories below consult ``WITNESS.on``
WITNESS = LockWitness()


def make_lock(name):
    """A ``threading.Lock`` — plain when the witness is off (zero cost),
    wrapped and graph-recorded when on.  ``name`` is the lock CLASS
    identity (``"Cls._attr"``), shared by every instance."""
    if not WITNESS.on:
        return threading.Lock()
    return _WitnessLock(threading.Lock(), name, "Lock")


def make_rlock(name):
    """A ``threading.RLock`` (witnessed when the witness is on;
    re-entries are counted, never edges)."""
    if not WITNESS.on:
        return threading.RLock()
    return _WitnessLock(threading.RLock(), name, "RLock")


def make_condition(name):
    """A ``threading.Condition`` over a (witnessed) RLock — ``with
    cond:`` acquisitions and the release/re-acquire inside ``wait``
    both land on the held-stack correctly."""
    if not WITNESS.on:
        return threading.Condition()
    return threading.Condition(
        _WitnessLock(threading.RLock(), name, "Condition"))


_atexit_armed = False


def _arm_atexit():
    """Warn (and count) at interpreter exit if the witnessed run
    observed a deadlock-able cycle — the 'detects cycles at teardown'
    half of the witness contract."""
    global _atexit_armed
    if _atexit_armed:
        return
    _atexit_armed = True
    import atexit

    def _teardown_check():
        if not WITNESS.on:
            return
        try:
            cycles = WITNESS.check()
        except Exception:
            return      # metrics may already be torn down
        if cycles:
            import warnings
            warnings.warn(
                f"lock witness observed {len(cycles)} acquisition-order "
                f"cycle(s) this run: {cycles} — two threads can deadlock "
                f"with these orders", RuntimeWarning)

    atexit.register(_teardown_check)


if WITNESS.on:
    _arm_atexit()


__all__ = ["WITNESS", "LockWitness", "make_lock", "make_rlock",
           "make_condition"]
