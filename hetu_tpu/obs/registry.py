"""Metrics registry: counters, log-bucketed latency histograms, gauges
(ISSUE 10 tentpole, part 3).

One process-wide :class:`Registry` (module singleton :data:`REGISTRY`)
owns every instrument, so ``metrics_dump()`` and the Prometheus
exposition (``tools/metricsd.py``) read ONE source of truth instead of
eight disconnected counter-family modules.  ``hetu_tpu.metrics``
registers every instrument at import — the thin ``record_*`` wrappers
there are the recording API; this module is the storage + readout.

* :class:`CounterFamily` — the pre-existing ``{kind: count}`` family
  shape (plain adds plus ``*_hw`` high-water max-gauges), with the same
  Counter-under-a-Lock hot path the old module-level families had: the
  migration must not tax ``record_run_plan`` (called once per training
  step on the dispatch path).
* :class:`Histogram` — log-bucketed latency distributions.  Buckets are
  8 per octave via ``math.frexp`` (no ``log`` call on the observe
  path): relative bucket width <= 12.5%, so p50/p90/p99 estimates (log-
  linear interpolation inside the hit bucket, clamped to the observed
  min/max) land within a few percent of a numpy reference — a p99
  PS-RPC spike or serving queue-wait is now distinguishable from its
  mean.  Optionally labeled (one sub-histogram per label, e.g. per
  opcode).
* :class:`Gauge` — last-written values per label (the per-run
  step-time/MFU gauges: ``flops_per_step`` from PR 5's inferred-shape
  cost model over measured step time).
"""
from __future__ import annotations

import math
import threading
from collections import Counter


class CounterFamily:
    """One named ``{kind: count}`` family (see module docstring)."""

    kind = "counter"
    __slots__ = ("name", "doc", "_c", "_lock")

    def __init__(self, name, doc):
        self.name = name
        self.doc = doc
        self._c = Counter()
        self._lock = threading.Lock()

    def inc(self, key, n=1):
        with self._lock:
            self._c[key] += n

    def max_gauge(self, key, v):
        """High-water semantics (``*_hw`` kinds): keep the max seen."""
        with self._lock:
            if v > self._c[key]:
                self._c[key] = v

    def counts(self):
        with self._lock:
            return dict(self._c)

    def reset(self):
        with self._lock:
            self._c.clear()

    def snapshot(self):
        return {k: int(v) for k, v in self.counts().items()}


def _bucket_of(v):
    """Log bucket index of a positive value: 8 sub-buckets per octave
    (``frexp``-based — no transcendental call on the observe path)."""
    m, e = math.frexp(v)        # v = m * 2**e, m in [0.5, 1)
    return (e << 3) | int((m - 0.5) * 16.0)


def _bucket_bounds(idx):
    """(lo, hi) value bounds of bucket ``idx`` (inverse of _bucket_of)."""
    e, sub = idx >> 3, idx & 7
    lo = math.ldexp(0.5 + sub / 16.0, e)
    hi = math.ldexp(0.5 + (sub + 1) / 16.0, e)
    return lo, hi


class _Hist:
    """One label's histogram state (caller holds the family lock)."""

    __slots__ = ("buckets", "n", "sum", "min", "max", "zeros")

    def __init__(self):
        self.buckets = Counter()    # bucket idx -> count
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.zeros = 0              # v <= 0 observations (kept exact)

    def observe(self, v):
        self.n += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v > 0.0:
            self.buckets[_bucket_of(v)] += 1
        else:
            self.zeros += 1

    def percentile(self, q):
        """Estimate the q-th percentile (log-linear interpolation inside
        the hit bucket, clamped to the exact observed min/max)."""
        if self.n == 0:
            return None
        rank = q / 100.0 * self.n
        cum = self.zeros
        if rank <= cum:     # non-positive observations sort first
            return min(self.min, 0.0)
        est = self.max
        for idx in sorted(self.buckets):
            cnt = self.buckets[idx]
            if cum + cnt >= rank:
                lo, hi = _bucket_bounds(idx)
                frac = (rank - cum) / cnt
                est = lo * (hi / lo) ** frac
                break
            cum += cnt
        return float(min(max(est, self.min), self.max))

    def snapshot(self):
        out = {"count": int(self.n),
               "sum": float(self.sum),
               "min": None if self.n == 0 else float(self.min),
               "max": None if self.n == 0 else float(self.max),
               "mean": float(self.sum / self.n) if self.n else None}
        for q in (50, 90, 99):
            out[f"p{q}"] = self.percentile(q)
        return out


class Histogram:
    """A (possibly labeled) log-bucketed distribution (module docstring)."""

    kind = "histogram"
    __slots__ = ("name", "doc", "unit", "_h", "_lock")

    def __init__(self, name, doc, unit="us"):
        self.name = name
        self.doc = doc
        self.unit = unit
        self._h = {}            # label -> _Hist
        self._lock = threading.Lock()

    def observe(self, v, label=""):
        with self._lock:
            h = self._h.get(label)
            if h is None:
                h = self._h[label] = _Hist()
            h.observe(float(v))

    def percentile(self, q, label=""):
        with self._lock:
            h = self._h.get(label)
            return h.percentile(q) if h is not None else None

    def labels(self):
        with self._lock:
            return list(self._h)

    def snapshot(self):
        with self._lock:
            return {label: h.snapshot() for label, h in self._h.items()}

    def reset(self):
        with self._lock:
            self._h.clear()


class Gauge:
    """Last-written values per label (``mfu``, ``step_time_ms``, ...)."""

    kind = "gauge"
    __slots__ = ("name", "doc", "_v", "_lock")

    def __init__(self, name, doc):
        self.name = name
        self.doc = doc
        self._v = {}
        self._lock = threading.Lock()

    def set(self, v, label=""):
        with self._lock:
            self._v[label] = float(v)

    def values(self):
        with self._lock:
            return dict(self._v)

    def reset(self):
        with self._lock:
            self._v.clear()

    def snapshot(self):
        return self.values()


class Registry:
    """Name -> instrument map with one dump/reset/exposition surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._insts = {}

    def _register(self, cls, name, *args):
        with self._lock:
            inst = self._insts.get(name)
            if inst is not None:
                if type(inst) is not cls:
                    raise ValueError(
                        f"instrument {name!r} already registered as "
                        f"{inst.kind}, cannot re-register as a "
                        f"different kind")
                return inst     # idempotent re-registration (reimports)
            inst = cls(name, *args)
            self._insts[name] = inst
            return inst

    def counter_family(self, name, doc):
        return self._register(CounterFamily, name, doc)

    def histogram(self, name, doc, unit="us"):
        return self._register(Histogram, name, doc, unit)

    def gauge(self, name, doc):
        return self._register(Gauge, name, doc)

    def instruments(self):
        with self._lock:
            return dict(self._insts)

    def get(self, name):
        with self._lock:
            return self._insts.get(name)

    def dump(self):
        """One JSON-able snapshot of every instrument, grouped by kind:
        ``{"counters": {family: {kind: n}}, "histograms": {name: {label:
        {count/sum/min/max/mean/p50/p90/p99}}}, "gauges": {name: {label:
        value}}}`` — the single source of truth ``metrics_dump()``,
        ``bench.py`` artifacts and ``tools/metricsd.py`` all read."""
        out = {"counters": {}, "histograms": {}, "gauges": {}}
        for name, inst in sorted(self.instruments().items()):
            out[inst.kind + "s"][name] = inst.snapshot()
        return out

    def reset_all(self):
        """Zero every registered instrument (replaces the per-family
        copy-pasted ``reset_*`` bodies)."""
        for inst in self.instruments().values():
            inst.reset()

    # -- Prometheus text exposition ---------------------------------------

    @staticmethod
    def _san(s):
        return "".join(c if c.isalnum() or c == "_" else "_" for c in s)

    def prometheus_text(self, prefix="hetu"):
        """Prometheus text-format exposition: counter families as
        ``<name>_total{kind=...}``, histograms as summaries (quantile
        series + ``_sum``/``_count``), gauges as plain gauges."""
        lines = []
        for name, inst in sorted(self.instruments().items()):
            mname = f"{prefix}_{self._san(name)}"
            doc = " ".join((inst.doc or "").split()) or name
            if inst.kind == "counter":
                lines.append(f"# HELP {mname}_total {doc}")
                lines.append(f"# TYPE {mname}_total counter")
                for k, v in sorted(inst.counts().items()):
                    lines.append(
                        f'{mname}_total{{kind="{self._san(str(k))}"}} '
                        f'{int(v)}')
            elif inst.kind == "histogram":
                lines.append(f"# HELP {mname} {doc}")
                lines.append(f"# TYPE {mname} summary")
                for label, snap in sorted(inst.snapshot().items()):
                    sel = f'label="{self._san(label)}",' if label else ""
                    for q in (50, 90, 99):
                        p = snap[f"p{q}"]
                        if p is not None:
                            lines.append(
                                f'{mname}{{{sel}quantile='
                                f'"{q / 100}"}} {p}')
                    lab = f'{{label="{self._san(label)}"}}' if label else ""
                    lines.append(f'{mname}_sum{lab} {snap["sum"]}')
                    lines.append(f'{mname}_count{lab} {snap["count"]}')
            else:
                lines.append(f"# HELP {mname} {doc}")
                lines.append(f"# TYPE {mname} gauge")
                for label, v in sorted(inst.values().items()):
                    lab = f'{{label="{self._san(label)}"}}' if label else ""
                    lines.append(f"{mname}{lab} {v}")
        return "\n".join(lines) + "\n"


#: the process-wide registry every instrument registers against
REGISTRY = Registry()


__all__ = ["CounterFamily", "Histogram", "Gauge", "Registry", "REGISTRY"]
