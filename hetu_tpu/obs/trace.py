"""Lock-cheap thread-aware span/event tracer (ISSUE 10 tentpole, part 1).

One process-wide :class:`Tracer` collects timing records into
PER-THREAD ring buffers: the hot path touches only thread-local state
(no lock, no allocation beyond the record tuple), so a span costs two
``perf_counter_ns`` reads plus one ring store (~0.3us) — cheap enough
to leave compiled into the executor's dispatch path behind a single
``TRACER.on`` flag read (the ``HETU_TRACE=0`` default pays one
attribute load per guarded site, nothing else; the host-overhead gate
in ``tools/host_overhead_bench.py`` holds that claim to <= 2.0x a raw
jit dispatch, and the traced path to <= 25% over the untraced one).

Record shapes (plain tuples — a ring slot assignment, never a dict):

* complete span  ``("X", name, cat, t0_ns, dur_ns, args)``
* instant event  ``("i", name, cat, t_ns, args)``
* flow begin/end ``("s"/"f", name, cat, t_ns, flow_id)`` — ties a
  ``run(sync=False)`` dispatch to the sync point that materialized it
  across arbitrary span nesting (rendered as arrows in Perfetto).
* packed hot-path records, expanded by the exporter: ``("P", t_pl, t0,
  t1, t2)`` is the executor fast lane's whole phase set (run-plan
  lookup / feed placement / jit dispatch) in ONE tuple, and ``("S",
  sub, t0, t1, step)`` one step span — per-step telemetry allocates
  two GC-tracked objects instead of five (generation-0 collections
  were a measurable slice of the tracing tax at microsecond step
  rates).

Thread buffers register themselves on first emit, named after their
thread (``threading.current_thread().name`` — the feed-pipeline /
serve-router / PS-serve pools pass ``thread_name_prefix``, so the
background planes show up as named tracks for free);
:meth:`Tracer.set_track_name` overrides.  Each buffer is a ring of
``HETU_TRACE_BUF`` slots (default 65536): a long run keeps the newest
events per thread instead of growing without bound, and
:func:`hetu_tpu.obs.export_chrome_trace` merges whatever survived.

Timestamps are ``time.perf_counter_ns()`` everywhere — one monotonic
base shared by every thread, so cross-track ordering is meaningful.
"""
from __future__ import annotations

import itertools
import os
import threading
import time


def _env_on():
    return os.environ.get("HETU_TRACE", "0").lower() not in (
        "", "0", "false", "off")


def _env_cap():
    try:
        return max(16, int(os.environ.get("HETU_TRACE_BUF", "65536")))
    except ValueError:
        return 65536


class _Buf:
    """One thread's ring: ``items[i % cap]`` with a monotonically growing
    write index ``i`` (``i > cap`` means the ring wrapped and the oldest
    ``i - cap`` records were overwritten)."""

    __slots__ = ("items", "i", "cap", "tid", "name", "gen")

    def __init__(self, cap, tid, name, gen):
        self.items = [None] * cap
        self.i = 0
        self.cap = cap
        self.tid = tid
        self.name = name
        self.gen = gen


class Tracer:
    """Process-wide trace collector (module singleton :data:`TRACER`).

    ``on`` is the ONE hot flag: instrumentation sites read it directly
    (``if TRACER.on: ...``) so a disabled tracer costs an attribute
    load per site.  Everything else — buffers, capacity, the flow-id
    counter — hides behind it.
    """

    def __init__(self):
        self.on = _env_on()
        self.cap = _env_cap()
        self._lock = threading.Lock()
        self._bufs = []
        self._tl = threading.local()
        self._gen = 0           # bumped by clear()/set_capacity()
        self._flow_ids = itertools.count(1)     # thread-safe in CPython

    # -- buffer management -------------------------------------------------

    def _buf(self):
        b = getattr(self._tl, "buf", None)
        if b is None or b.gen != self._gen:
            t = threading.current_thread()
            with self._lock:
                b = _Buf(self.cap, threading.get_ident(), t.name,
                         self._gen)
                self._bufs.append(b)
            self._tl.buf = b
        return b

    def set_track_name(self, name):
        """Name this thread's track in the exported trace (defaults to
        the thread's own name)."""
        self._buf().name = str(name)

    # -- hot emitters ------------------------------------------------------

    def complete(self, name, t0_ns, t1_ns, cat="hetu", args=None):
        """One finished span: explicit timestamps, for hot paths that
        stamp ``perf_counter_ns`` inline instead of entering a context
        manager."""
        b = self._buf()
        i = b.i
        b.items[i % b.cap] = ("X", name, cat, t0_ns, t1_ns - t0_ns, args)
        b.i = i + 1

    def instant(self, name, cat="hetu", args=None):
        """One point event (a fault, a sync point, an injection)."""
        b = self._buf()
        i = b.i
        b.items[i % b.cap] = ("i", name, cat,
                              time.perf_counter_ns(), args)
        b.i = i + 1

    def flow_begin(self, name, cat="async"):
        """Open a flow arrow (returns the flow id to close it with)."""
        fid = next(self._flow_ids)
        b = self._buf()
        i = b.i
        b.items[i % b.cap] = ("s", name, cat, time.perf_counter_ns(), fid)
        b.i = i + 1
        return fid

    def flow_end(self, name, fid, cat="async"):
        """Close a flow arrow opened by :meth:`flow_begin` (any thread)."""
        b = self._buf()
        i = b.i
        b.items[i % b.cap] = ("f", name, cat, time.perf_counter_ns(), fid)
        b.i = i + 1

    # -- control -----------------------------------------------------------

    def enable(self, on=True):
        """Turn tracing on/off at runtime (the env knob sets the initial
        state; tests and ``bench.py --config trace`` flip it live)."""
        self.on = bool(on)

    def set_capacity(self, cap):
        """Resize the per-thread rings.  Drops everything recorded so
        far (each thread re-registers a fresh ring on its next emit)."""
        with self._lock:
            self.cap = max(16, int(cap))
            self._gen += 1
            self._bufs = []

    def clear(self):
        """Drop all recorded events (capacity unchanged)."""
        with self._lock:
            self._gen += 1
            self._bufs = []

    # -- readout -----------------------------------------------------------

    def tracks(self):
        """[(tid, track name)] for every thread that recorded events."""
        with self._lock:
            bufs = list(self._bufs)
        return [(b.tid, b.name) for b in bufs if b.i]

    def records(self):
        """Merged [(tid, record)] over all live rings, oldest-first per
        ring (the export sorts globally by timestamp)."""
        with self._lock:
            bufs = list(self._bufs)
        out = []
        for b in bufs:
            i, cap = b.i, b.cap
            if i <= cap:
                ring = b.items[:i]
            else:       # wrapped: oldest surviving record first
                k = i % cap
                ring = b.items[k:] + b.items[:k]
            for rec in ring:
                if rec is not None:
                    out.append((b.tid, rec))
        return out

    def dropped(self):
        """{tid: overwritten-record count} for rings that wrapped."""
        with self._lock:
            bufs = list(self._bufs)
        return {b.tid: b.i - b.cap for b in bufs if b.i > b.cap}


#: the process-wide tracer — instrumentation sites read ``TRACER.on``
TRACER = Tracer()


class _SpanCtx:
    """Context-manager span for non-hot call sites (``obs.span(...)``)."""

    __slots__ = ("name", "cat", "args", "t0")

    def __init__(self, name, cat, args):
        self.name = name
        self.cat = cat
        self.args = args or None

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        TRACER.complete(self.name, self.t0, time.perf_counter_ns(),
                        self.cat, self.args)
        return False


class _NullSpan:
    """Tracing-off singleton: enter/exit are no-ops."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def span(name, cat="hetu", **args):
    """``with obs.span("step", step=3): ...`` — a no-op singleton when
    tracing is off, a recorded complete event when on."""
    if not TRACER.on:
        return _NULL_SPAN
    return _SpanCtx(name, cat, args)


def event(name, cat="hetu", **args):
    """Record one instant event (no-op when tracing is off)."""
    if TRACER.on:
        TRACER.instant(name, cat, args or None)


__all__ = ["Tracer", "TRACER", "span", "event"]
