"""hetu_tpu.onnx — ONNX export/import without external deps.

Reference parity: ``python/hetu/onnx/`` (hetu2onnx, onnx2hetu, 24 opset
handlers). The protobuf wire format is hand-coded in :mod:`.proto`, so the
files interoperate with onnxruntime/Netron even though the environment has
no ``onnx`` package.
"""
from .hetu2onnx import export, register_exporter
from .onnx2hetu import load, register_importer, ImportedModel
from .proto import Model, Graph, Node, Tensor, ValueInfo

__all__ = ["export", "load", "register_exporter", "register_importer",
           "ImportedModel", "Model", "Graph", "Node", "Tensor", "ValueInfo"]
