"""Export a hetu_tpu graph to ONNX (reference ``onnx/hetu2onnx.py:27``).

``export(executor_or_fetches, path)`` walks the topo from the fetches and
emits one ONNX node per graph op through per-op-type handlers (the
reference's ``onnx_opset/`` table). Variables become initializers (values
taken from the executor when given, else the node's init value).
"""
from __future__ import annotations

import numpy as np

from ..graph.node import PlaceholderOp
from ..graph.executor import Executor, topo_sort
from . import proto
from .proto import Graph, Model, Node, Tensor, ValueInfo

_EXPORTERS = {}


def register_exporter(op_type):
    def deco(fn):
        _EXPORTERS[op_type] = fn
        return fn
    return deco


class _Ctx:
    """Export context: names, extra nodes, extra initializers."""

    def __init__(self):
        self.counter = 0
        self.extra_inits = []

    def const(self, name, arr):
        self.extra_inits.append(Tensor(name, np.asarray(arr)))
        return name

    def fresh(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"


def _n(node):
    return f"n{node.id}_{node.op_type}"


# -- handlers ---------------------------------------------------------------

_UNARY = {"Relu": "Relu", "Sigmoid": "Sigmoid", "Tanh": "Tanh",
          "Exp": "Exp", "Log": "Log", "Sqrt": "Sqrt", "Abs": "Abs",
          "Floor": "Floor", "Sin": "Sin", "Cos": "Cos",
          "Softmax": "Softmax", "LogSoftmax": "LogSoftmax",
          "Opposite": "Neg", "Gelu": "Gelu", "Flatten": "Flatten"}

_BINARY = {"AddElewise": "Add", "MinusElewise": "Sub",
           "MultiplyElewise": "Mul", "Division": "Div", "Pow": "Pow",
           "MatrixDot": None}


def _simple(onnx_op, **attrs):
    def fn(node, ins, out, ctx):
        return [Node(onnx_op, ins, [out], name=out, **attrs)]
    return fn


for ht_op, ox in _UNARY.items():
    _EXPORTERS[ht_op] = _simple(ox)
for ht_op, ox in _BINARY.items():
    if ox:
        _EXPORTERS[ht_op] = _simple(ox)


@register_exporter("MatrixMult")
def _mm(node, ins, out, ctx):
    a, b = ins
    nodes = []
    if node.attrs.get("trans_A"):
        t = ctx.fresh(out + "_tA")
        nodes.append(Node("Transpose", [a], [t], name=t, perm=[1, 0]))
        a = t
    if node.attrs.get("trans_B"):
        t = ctx.fresh(out + "_tB")
        nodes.append(Node("Transpose", [b], [t], name=t, perm=[1, 0]))
        b = t
    nodes.append(Node("MatMul", [a, b], [out], name=out))
    return nodes


@register_exporter("Linear")
def _linear(node, ins, out, ctx):
    # Gemm does alpha*A'*B' + beta*C in one op
    return [Node("Gemm", ins, [out], name=out,
                 transA=int(bool(node.attrs.get("trans_A"))),
                 transB=int(bool(node.attrs.get("trans_B"))))]


@register_exporter("BatchMatrixMult")
def _bmm(node, ins, out, ctx):
    a, b = ins
    nodes = []
    if node.attrs.get("trans_A"):
        t = ctx.fresh(out + "_tA")
        nodes.append(Node("Transpose", [a], [t], name=t))
        a = t
    if node.attrs.get("trans_B"):
        t = ctx.fresh(out + "_tB")
        nodes.append(Node("Transpose", [b], [t], name=t))
        b = t
    nodes.append(Node("MatMul", [a, b], [out], name=out))
    return nodes


def _const_binary(onnx_op, swap=False):
    def fn(node, ins, out, ctx):
        cname = ctx.const(ctx.fresh(out + "_c"),
                          np.float32(node.attrs.get("const_attr", 0.0)))
        operands = [cname, ins[0]] if swap else [ins[0], cname]
        return [Node(onnx_op, operands, [out], name=out)]
    return fn


_EXPORTERS["AddConst"] = _const_binary("Add")
_EXPORTERS["MinusByConst"] = _const_binary("Sub")
_EXPORTERS["MultiplyConst"] = _const_binary("Mul")
# DivConst's lowering is a * const_attr (callers pre-invert, node.py:136)
_EXPORTERS["DivConst"] = _const_binary("Mul")
_EXPORTERS["ConstDiv"] = _const_binary("Div", swap=True)
_EXPORTERS["ConstPow"] = _const_binary("Pow", swap=True)


@register_exporter("Fmod")
def _fmod(node, ins, out, ctx):
    # fmod=1 → C-style float fmod (sign of dividend), matching jnp.fmod;
    # the default fmod=0 is integer-only and numerically different
    return [Node("Mod", ins, [out], name=out, fmod=1)]


@register_exporter("LeakyRelu")
def _leaky(node, ins, out, ctx):
    return [Node("LeakyRelu", ins, [out], name=out,
                 alpha=float(node.attrs.get("alpha", 0.01)))]


def _require_nchw(node):
    """ONNX Conv/Pool/BatchNormalization are channels-first by spec; an
    NHWC-authored graph must not export to silently-wrong semantics."""
    if node.attrs.get("data_format", "NCHW") != "NCHW":
        raise NotImplementedError(
            f"ONNX export of {node.op_type} with data_format="
            f"{node.attrs['data_format']!r}: ONNX is NCHW-only — author "
            f"the exported graph in NCHW (NHWC is a TPU runtime layout "
            f"choice, not an interchange format)")


@register_exporter("Conv2d")
def _conv(node, ins, out, ctx):
    _require_nchw(node)
    p = node.attrs.get("padding", 0)
    s = node.attrs.get("stride", 1)
    ph, pw = (p, p) if isinstance(p, int) else p
    sh, sw = (s, s) if isinstance(s, int) else s
    return [Node("Conv", ins, [out], name=out,
                 pads=[ph, pw, ph, pw], strides=[sh, sw])]


_EXPORTERS["Conv2dAddBias"] = _EXPORTERS["Conv2d"]


def _pool(onnx_op):
    def fn(node, ins, out, ctx):
        _require_nchw(node)
        a = node.attrs
        p, s = a.get("padding", 0), a.get("stride", 1)
        ph, pw = (p, p) if isinstance(p, int) else p
        sh, sw = (s, s) if isinstance(s, int) else s
        return [Node(onnx_op, ins, [out], name=out,
                     kernel_shape=[a["kernel_H"], a["kernel_W"]],
                     pads=[ph, pw, ph, pw], strides=[sh, sw])]
    return fn


_EXPORTERS["MaxPool2d"] = _pool("MaxPool")
_EXPORTERS["AvgPool2d"] = _pool("AveragePool")


@register_exporter("ArrayReshape")
def _reshape(node, ins, out, ctx):
    shape = ctx.const(ctx.fresh(out + "_shape"),
                      np.asarray(node.attrs["output_shape"], np.int64))
    return [Node("Reshape", [ins[0], shape], [out], name=out)]


@register_exporter("Transpose")
def _transpose(node, ins, out, ctx):
    perm = node.attrs.get("perm")
    attrs = {"perm": [int(p) for p in perm]} if perm is not None else {}
    return [Node("Transpose", ins, [out], name=out, **attrs)]


@register_exporter("Concat")
def _concat(node, ins, out, ctx):
    return [Node("Concat", ins, [out], name=out,
                 axis=int(node.attrs.get("axis", 0)))]


_EXPORTERS["Concatenate"] = _concat


def _reduce(onnx_op):
    def fn(node, ins, out, ctx):
        axes = node.attrs.get("axes")
        kd = int(bool(node.attrs.get("keepdims", False)))
        axes_c = ctx.const(ctx.fresh(out + "_axes"),
                           np.asarray(axes, np.int64))
        return [Node(onnx_op, [ins[0], axes_c], [out], name=out,
                     keepdims=kd)]
    return fn


_EXPORTERS["ReduceMean"] = _reduce("ReduceMean")
_EXPORTERS["ReduceSum"] = _reduce("ReduceSum")


@register_exporter("EmbeddingLookup")
def _embed(node, ins, out, ctx):
    table, ids = ins
    ids64 = ctx.fresh(out + "_ids64")
    return [Node("Cast", [ids], [ids64], name=ids64, to=proto.INT64),
            Node("Gather", [table, ids64], [out], name=out)]


@register_exporter("OneHot")
def _onehot(node, ins, out, ctx):
    depth = ctx.const(ctx.fresh(out + "_d"),
                      np.int64(node.attrs["num_classes"]))
    vals = ctx.const(ctx.fresh(out + "_v"),
                     np.asarray([0.0, 1.0], np.float32))
    ids64 = ctx.fresh(out + "_i64")
    return [Node("Cast", [ins[0]], [ids64], name=ids64, to=proto.INT64),
            Node("OneHot", [ids64, depth, vals], [out], name=out)]


@register_exporter("Where")
def _where(node, ins, out, ctx):
    cond = ctx.fresh(out + "_b")
    return [Node("Cast", [ins[0]], [cond], name=cond, to=proto.BOOL),
            Node("Where", [cond, ins[1], ins[2]], [out], name=out)]


@register_exporter("Dropout")
def _dropout(node, ins, out, ctx):  # inference export: identity
    return [Node("Identity", [ins[0]], [out], name=out)]


_EXPORTERS["Dropout2d"] = _dropout


@register_exporter("LayerNorm")
def _layernorm(node, ins, out, ctx):
    return [Node("LayerNormalization", ins, [out], name=out,
                 epsilon=float(node.attrs.get("eps", 1e-5)), axis=-1)]


@register_exporter("BatchNorm")
def _batchnorm(node, ins, out, ctx):
    _require_nchw(node)
    # inputs are (x, scale, bias, running_mean, running_var) — the trained
    # stats are real graph variables and export as initializers
    # BatchNormOp's momentum weights the BATCH; ONNX momentum weights the
    # running stat — emit the complement so re-import round-trips exactly
    return [Node("BatchNormalization", list(ins[:5]), [out],
                 name=out, epsilon=float(node.attrs.get("eps", 1e-5)),
                 momentum=1.0 - float(node.attrs.get("momentum", 0.1)))]


@register_exporter("SoftmaxCrossEntropy")
def _sce(node, ins, out, ctx):
    lsm = ctx.fresh(out + "_lsm")
    prod = ctx.fresh(out + "_prod")
    neg = ctx.fresh(out + "_neg")
    axes = ctx.const(ctx.fresh(out + "_axes"), np.asarray([-1], np.int64))
    return [Node("LogSoftmax", [ins[0]], [lsm], name=lsm, axis=-1),
            Node("Mul", [lsm, ins[1]], [prod], name=prod),
            Node("ReduceSum", [prod, axes], [neg], name=neg, keepdims=0),
            Node("Neg", [neg], [out], name=out)]


@register_exporter("SoftmaxCrossEntropySparse")
def _sces(node, ins, out, ctx):
    ids64 = ctx.fresh(out + "_i64")
    return [Node("Cast", [ins[1]], [ids64], name=ids64, to=proto.INT64),
            Node("SoftmaxCrossEntropyLoss", [ins[0], ids64], [out],
                 name=out, reduction="none")]


INT64_MAX = (1 << 63) - 1


@register_exporter("Slice")
def _slice(node, ins, out, ctx):
    starts = np.asarray(node.attrs["begin"], np.int64)
    if node.attrs.get("size") is not None:
        # hetu convention: size < 0 means "to the end of the dim"
        # (ops/transform.py _slice); ONNX clamps ends to the dim, so the
        # INT64_MAX sentinel expresses the same thing
        ends = np.asarray(
            [INT64_MAX if s < 0 else b + s
             for b, s in zip(starts, node.attrs["size"])], np.int64)
    else:
        ends = np.asarray(node.attrs["end"], np.int64)
    s_c = ctx.const(ctx.fresh(out + "_s"), starts)
    e_c = ctx.const(ctx.fresh(out + "_e"), ends)
    return [Node("Slice", [ins[0], s_c, e_c], [out], name=out)]


@register_exporter("Pad")
def _pad(node, ins, out, ctx):
    pads = node.attrs.get("paddings")
    flat = np.asarray(pads).reshape(-1, 2)
    onnx_pads = np.concatenate([flat[:, 0], flat[:, 1]]).astype(np.int64)
    p_c = ctx.const(ctx.fresh(out + "_p"), onnx_pads)
    return [Node("Pad", [ins[0], p_c], [out], name=out)]


@register_exporter("BroadcastTo")
def _bto(node, ins, out, ctx):
    shape = ctx.const(ctx.fresh(out + "_shape"),
                      np.asarray(node.attrs["output_shape"], np.int64))
    return [Node("Expand", [ins[0], shape], [out], name=out)]


@register_exporter("Unsqueeze")
def _unsq(node, ins, out, ctx):
    ax = ctx.const(ctx.fresh(out + "_ax"),
                   np.asarray([node.attrs.get("axis", 0)], np.int64))
    return [Node("Unsqueeze", [ins[0], ax], [out], name=out)]


@register_exporter("Squeeze")
def _sq(node, ins, out, ctx):
    ax = node.attrs.get("axis")
    if ax is None:
        return [Node("Squeeze", [ins[0]], [out], name=out)]
    ax_c = ctx.const(ctx.fresh(out + "_ax"), np.asarray([ax], np.int64))
    return [Node("Squeeze", [ins[0], ax_c], [out], name=out)]


# -- driver -----------------------------------------------------------------


def export(source, path, name="hetu_graph", feed_shapes=None, opset=20):
    """Export to an ONNX file.

    ``source``: an :class:`Executor` (variables exported with current
    values) or a fetch list of graph nodes. Feeds become graph inputs —
    supply ``feed_shapes={node: shape}`` when placeholders carry none.
    """
    if isinstance(source, Executor):
        fetches = [f for fs in (s.fetches for s in
                                source.subexecutors.values())
                   for f in fs if f is not None]
        # _fetch_host, not np.asarray: stage-3 ZeRO keeps params as
        # _ZeroView slab stand-ins that must be gathered to full arrays
        var_values = {n: np.asarray(source._fetch_host(v))
                      for n, v in source.var_values.items()}
    else:
        fetches = list(source)
        var_values = {}
    from ..optim.optimizer import OptimizerOp
    from ..graph.gradients import GradientOp
    fetches = [f for f in fetches
               if not isinstance(f, (OptimizerOp, GradientOp))]
    topo = topo_sort(fetches)
    ctx = _Ctx()
    names, nodes, inputs, inits = {}, [], [], []
    for node in topo:
        if isinstance(node, PlaceholderOp):
            nm = node.name
            names[node] = nm
            if node.is_variable or node in var_values:
                val = var_values.get(node)
                if val is None:
                    val = np.asarray(node.get_init_value())
                inits.append(Tensor(nm, val))
            else:
                shape = node.shape or (feed_shapes or {}).get(node)
                if shape is None:
                    raise ValueError(
                        f"feed {node} needs a shape: pass feed_shapes")
                dt = proto.NP2ONNX.get(np.dtype(node.dtype or np.float32),
                                       proto.FLOAT)
                inputs.append(ValueInfo(nm, dt, list(shape)))
            continue
        handler = _EXPORTERS.get(node.op_type)
        if handler is None:
            raise NotImplementedError(
                f"no ONNX exporter for op {node.op_type!r}")
        out = _n(node)
        names[node] = out
        ins = [names[i] for i in node.inputs]
        nodes.extend(handler(node, ins, out, ctx))
    outputs = [ValueInfo(names[f],
                         proto.NP2ONNX.get(
                             np.dtype(getattr(f, "dtype", None)
                                      or np.float32), proto.FLOAT),
                         list(getattr(f, "shape", None) or []))
               for f in fetches]
    graph = Graph(name=name, nodes=nodes, inputs=inputs, outputs=outputs,
                  initializers=inits + ctx.extra_inits)
    model = Model(graph, opset=opset)
    model.save(path)
    return model


__all__ = ["export", "register_exporter"]
