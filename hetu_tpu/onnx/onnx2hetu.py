"""Import an ONNX model into a hetu_tpu graph (reference ``onnx/onnx2hetu.py``).

``load(path)`` → :class:`ImportedModel` with placeholder feeds per graph
input and output graph nodes ready for an :class:`Executor`.
"""
from __future__ import annotations

import numpy as np

from .. import ops as _ops
from ..graph.node import Variable, placeholder_op
from .proto import Model, ONNX2NP

_IMPORTERS = {}


def register_importer(op_type):
    def deco(fn):
        _IMPORTERS[op_type] = fn
        return fn
    return deco


def _const_value(env, name):
    v = env.get(name)
    return v if isinstance(v, np.ndarray) else None


# unary/binary direct maps
for ox, ctor in {
        "Relu": _ops.relu_op, "Sigmoid": _ops.sigmoid_op,
        "Tanh": _ops.tanh_op, "Exp": _ops.exp_op, "Log": _ops.log_op,
        "Sqrt": _ops.sqrt_op, "Abs": _ops.abs_op, "Floor": _ops.floor_op,
        "Sin": _ops.sin_op, "Cos": _ops.cos_op, "Neg": _ops.opposite_op,
        "Gelu": _ops.gelu_op, "Erf": _ops.erf_op,
        "Identity": lambda x: x}.items():
    _IMPORTERS[ox] = (lambda c: lambda node, ins, env: c(ins[0]))(ctor)

for ox, ctor in {"Add": _ops.add_op, "Sub": _ops.minus_op,
                 "Mul": _ops.mul_op, "Div": _ops.div_op,
                 "Pow": _ops.pow_op, "MatMul": _ops.matmul_op}.items():
    def _bin(node, ins, env, _c=ctor, _ox=ox):
        a, b = ins
        # constant operand → const-op forms where available
        av, bv = _const_value(env, node.inputs[0]), \
            _const_value(env, node.inputs[1])
        if _ox in ("Add", "Sub", "Mul", "Div") and (
                (av is not None and av.ndim == 0)
                or (bv is not None and bv.ndim == 0)):
            if bv is not None and bv.ndim == 0:
                c = float(bv)
                return {"Add": lambda: _ops.addbyconst_op(a, const_attr=c),
                        "Sub": lambda: _ops.addbyconst_op(a, const_attr=-c),
                        "Mul": lambda: _ops.mulbyconst_op(a, const_attr=c),
                        "Div": lambda: _ops.mulbyconst_op(
                            a, const_attr=1.0 / c)}[_ox]()
            c = float(av)
            if _ox == "Div":
                return _ops.const_div_op(b, const_attr=c)
            if _ox == "Sub":
                return _ops.opposite_op(
                    _ops.addbyconst_op(b, const_attr=-c))
            return {"Add": lambda: _ops.addbyconst_op(b, const_attr=c),
                    "Mul": lambda: _ops.mulbyconst_op(b, const_attr=c)}[_ox]()
        return _c(a, b)
    _IMPORTERS[ox] = _bin


@register_importer("Gemm")
def _gemm(node, ins, env):
    a = node.attrs
    alpha = float(a.get("alpha", 1.0))
    beta = float(a.get("beta", 1.0))
    out = _ops.matmul_op(ins[0], ins[1],
                         trans_A=bool(a.get("transA")),
                         trans_B=bool(a.get("transB")))
    if alpha != 1.0:
        out = _ops.mulbyconst_op(out, const_attr=alpha)
    if len(ins) == 3 and beta != 0.0:
        c = ins[2] if beta == 1.0 else \
            _ops.mulbyconst_op(ins[2], const_attr=beta)
        out = out + c
    return out


@register_importer("Flatten")
def _flatten_onnx(node, ins, env):
    """ONNX Flatten is strictly 2-D: [prod(d[:axis]), prod(d[axis:])] —
    NOT torch's start_dim/end_dim flatten."""
    axis = node.attrs.get("axis", 1)
    shape = _node_shape(ins[0])
    if shape is None:
        if axis != 1:
            raise NotImplementedError(
                f"Flatten axis={axis} needs a static input shape "
                f"(ONNX output is strictly 2-D)")
        # axis=1 with unknown shape: collapsing all trailing dims IS the
        # ONNX 2-D result for the (batch, ...) layouts torch exports
        return _ops.flatten_op(ins[0], start_dim=1)
    lead = int(np.prod(shape[:axis] or [1]))
    tail = int(np.prod(shape[axis:] or [1]))
    return _ops.array_reshape_op(ins[0], output_shape=(lead, tail))


@register_importer("Constant")
def _constant(node, ins, env):
    """Inline constant: lands in env as a raw ndarray, so downstream
    shape-consuming handlers (Reshape) and const-op binary forms see it
    exactly like an initializer."""
    v = node.attrs.get("value")
    if v is None:
        for k in ("value_float", "value_int"):
            if k in node.attrs:
                return np.asarray(node.attrs[k])
        raise NotImplementedError(
            f"Constant node {node.name!r} without a value attribute")
    return v.array if hasattr(v, "array") else np.asarray(v)


@register_importer("Transpose")
def _transpose(node, ins, env):
    return _ops.transpose_op(ins[0], perm=node.attrs.get("perm"))


@register_importer("Reshape")
def _reshape(node, ins, env):
    shape = _const_value(env, node.inputs[1])
    if shape is None:
        raise NotImplementedError("dynamic Reshape shape unsupported")
    return _ops.array_reshape_op(ins[0],
                                 output_shape=tuple(int(d) for d in shape))


@register_importer("Concat")
def _concat(node, ins, env):
    return _ops.concatenate_op(list(ins), axis=int(node.attrs.get("axis", 0)))


@register_importer("Conv")
def _conv(node, ins, env):
    pads = node.attrs.get("pads", [0, 0, 0, 0])
    strides = node.attrs.get("strides", [1, 1])
    if len(ins) == 3:
        return _ops.conv2d_add_bias_op(
            ins[0], ins[1], ins[2], padding=(pads[0], pads[1]),
            stride=tuple(strides))
    return _ops.conv2d_op(ins[0], ins[1], padding=(pads[0], pads[1]),
                          stride=tuple(strides))


@register_importer("MaxPool")
def _maxpool(node, ins, env):
    k = node.attrs["kernel_shape"]
    pads = node.attrs.get("pads", [0, 0, 0, 0])
    strides = node.attrs.get("strides", [1, 1])
    return _ops.max_pool2d_op(ins[0], k[0], k[1],
                              padding=(pads[0], pads[1]),
                              stride=tuple(strides))


@register_importer("AveragePool")
def _avgpool(node, ins, env):
    k = node.attrs["kernel_shape"]
    pads = node.attrs.get("pads", [0, 0, 0, 0])
    strides = node.attrs.get("strides", [1, 1])
    return _ops.avg_pool2d_op(ins[0], k[0], k[1],
                              padding=(pads[0], pads[1]),
                              stride=tuple(strides))


@register_importer("Softmax")
def _softmax(node, ins, env):
    return _ops.softmax_op(ins[0])


@register_importer("LogSoftmax")
def _logsoftmax(node, ins, env):
    return _ops.log_softmax_op(ins[0])


@register_importer("LayerNormalization")
def _layernorm(node, ins, env):
    return _ops.layer_normalization_op(
        ins[0], ins[1], ins[2], eps=float(node.attrs.get("epsilon", 1e-5)))


@register_importer("BatchNormalization")
def _batchnorm(node, ins, env):
    # ONNX momentum m: running = m·running + (1−m)·batch; BatchNormOp's
    # momentum is the batch weight, hence 1 − m
    bn = _ops.batch_normalization_op(
        ins[0], ins[1], ins[2],
        momentum=1.0 - float(node.attrs.get("momentum", 0.9)),
        eps=float(node.attrs.get("epsilon", 1e-5)))
    # seed running stats from ONNX inputs 3/4 (trained mean/var) so the
    # imported model normalizes correctly in inference mode; the stats may
    # already be lifted to Variables (as_node), so read through either form
    def _arr(name):
        v = env.get(name)
        if isinstance(v, np.ndarray):
            return v
        val = getattr(v, "_value", None)
        return None if val is None else np.asarray(val)

    if len(node.inputs) >= 5:
        mean_v = _arr(node.inputs[3])
        var_v = _arr(node.inputs[4])
        if mean_v is not None:
            bn.running_mean.set_value(np.asarray(mean_v, np.float32))
        if var_v is not None:
            bn.running_var.set_value(np.asarray(var_v, np.float32))
    return bn


@register_importer("Gather")
def _gather(node, ins, env):
    return _ops.embedding_lookup_op(ins[0], ins[1])


@register_importer("Cast")
def _cast(node, ins, env):  # dtypes are handled inside lowerings
    return ins[0]


def _reduce_axes(node, env):
    axes = node.attrs.get("axes")
    if axes is None and len(node.inputs) > 1:
        vals = _const_value(env, node.inputs[1])
        axes = list(vals) if vals is not None else None
    if axes is None:
        # ONNX default = reduce over ALL axes; rank is unknown without
        # shape propagation, so this form is unsupported here
        raise NotImplementedError(
            f"{node.op_type} without explicit axes (reduce-all) is "
            "unsupported; re-export with axes")
    return [int(a) for a in axes]


@register_importer("ReduceMean")
def _rmean(node, ins, env):
    # ONNX default keepdims=1 (our exporter always writes it explicitly;
    # torch relies on the default)
    return _ops.reduce_mean_op(ins[0], _reduce_axes(node, env),
                               keepdims=bool(node.attrs.get("keepdims", 1)))


@register_importer("ReduceSum")
def _rsum(node, ins, env):
    return _ops.reduce_sum_op(ins[0], _reduce_axes(node, env),
                              keepdims=bool(node.attrs.get("keepdims", 1)))


@register_importer("Slice")
def _slice(node, ins, env):
    starts = _const_value(env, node.inputs[1])
    ends = _const_value(env, node.inputs[2])
    return _ops.slice_op(ins[0], begin=[int(s) for s in starts],
                         end=[int(e) for e in ends])


@register_importer("Expand")
def _expand(node, ins, env):
    shape = _const_value(env, node.inputs[1])
    return _ops.broadcastto_op(
        ins[0], output_shape=tuple(int(d) for d in shape))


def _node_shape(n):
    """Best-effort static shape via recursive infer_shape."""
    sh = getattr(n, "shape", None)
    if sh is not None:
        return sh
    ins = [_node_shape(i) for i in getattr(n, "inputs", [])]
    if any(s is None for s in ins):
        return None
    try:
        return n.infer_shape(ins)
    except Exception:
        return None


def _input_rank(node_in):
    shape = _node_shape(node_in)
    return None if shape is None else len(shape)


@register_importer("Unsqueeze")
def _unsq(node, ins, env):
    axes = [int(a) for a in (node.attrs.get("axes")
                             or _const_value(env, node.inputs[1]))]
    if any(a < 0 for a in axes):
        # ONNX: negative axes index the OUTPUT rank (input rank + len(axes))
        r = _input_rank(ins[0])
        if r is None:
            raise NotImplementedError(
                "Unsqueeze with negative axes needs a known input rank")
        axes = [a if a >= 0 else a + r + len(axes) for a in axes]
    out = ins[0]
    # insert in ascending axis order: each ONNX axis indexes the FINAL
    # shape, which ascending insertion reproduces incrementally
    for a in sorted(axes):
        out = _ops.unsqueeze_op(out, axis=a)
    return out


@register_importer("Squeeze")
def _sq(node, ins, env):
    axes = node.attrs.get("axes")
    if axes is None and len(node.inputs) > 1:
        axes = list(_const_value(env, node.inputs[1]))
    if not axes:
        return _ops.squeeze_op(ins[0], axis=None)
    axes = [int(a) for a in axes]
    if any(a < 0 for a in axes):
        r = _input_rank(ins[0])    # ONNX: negative axes index the input rank
        if r is None:
            raise NotImplementedError(
                "Squeeze with negative axes needs a known input rank")
        axes = [a if a >= 0 else a + r for a in axes]
    out = ins[0]
    # remove in descending order so earlier removals don't shift the
    # remaining (input-relative) axis indices
    for a in sorted(axes, reverse=True):
        out = _ops.squeeze_op(out, axis=a)
    return out


@register_importer("Where")
def _where(node, ins, env):
    return _ops.where_op(ins[0], ins[1], ins[2])


@register_importer("SoftmaxCrossEntropyLoss")
def _scel(node, ins, env):
    out = _ops.softmaxcrossentropy_sparse_op(ins[0], ins[1])
    if node.attrs.get("reduction", "mean") == "mean":
        out = _ops.reduce_mean_op(out, [0])
    return out


class ImportedModel:
    """Result of :func:`load`: feeds (name → placeholder), outputs, params."""

    def __init__(self, feeds, outputs, params):
        self.feeds = feeds
        self.outputs = outputs
        self.params = params

    def executor(self, **kw):
        from ..graph.executor import Executor
        return Executor({"default": list(self.outputs)}, **kw)


def load(path):
    model = Model.load(path)
    g = model.graph
    env = {}     # name -> graph node | np.ndarray (constants)
    params = {}
    for t in g.initializers:
        env[t.name] = t.array
    feeds = {}
    init_names = {t.name for t in g.initializers}
    for vi in g.inputs:
        if vi.name in init_names:
            continue
        dt = ONNX2NP.get(vi.dtype, np.dtype(np.float32))
        shape = tuple(d if isinstance(d, int) else None for d in vi.shape)
        feeds[vi.name] = placeholder_op(
            vi.name, dtype=dt,
            shape=shape if all(d is not None for d in shape) else None)
        env[vi.name] = feeds[vi.name]

    const_names = set()   # Constant-node outputs: data, not weights
    const_vars = {}       # one Variable per constant, however many users

    def as_node(name):
        v = env[name]
        if isinstance(v, np.ndarray):
            if name in const_names:
                # env keeps the raw ndarray (for _const_value / shape
                # consumers); the graph gets ONE shared non-trainable node
                if name not in const_vars:
                    const_vars[name] = Variable(name, value=v,
                                                trainable=False)
                return const_vars[name]
            var = Variable(name, value=v, trainable=True)
            params[name] = var
            env[name] = var
            return var
        return v

    for node in g.nodes:
        handler = _IMPORTERS.get(node.op_type)
        if handler is None:
            raise NotImplementedError(
                f"no importer for ONNX op {node.op_type!r}")
        # Cast/shape-consuming handlers read raw constants via env; regular
        # inputs become graph nodes lazily
        ins = []
        for i, iname in enumerate(node.inputs):
            v = env[iname]
            if isinstance(v, np.ndarray) and node.op_type in (
                    "Reshape", "Expand", "Slice", "ReduceMean", "ReduceSum",
                    "Unsqueeze", "Squeeze") and i >= 1:
                ins.append(v)  # shape-like constant consumed host-side
            else:
                ins.append(as_node(iname))
        out = handler(node, ins, env)
        if node.op_type == "Constant":
            const_names.add(node.outputs[0])
        env[node.outputs[0]] = out
    outputs = [env[vi.name] for vi in g.outputs]
    return ImportedModel(feeds, outputs, params)


__all__ = ["load", "register_importer", "ImportedModel"]
