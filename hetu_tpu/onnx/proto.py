"""Minimal protobuf wire-format codec for the ONNX schema subset.

The environment has no ``onnx`` package, so this module hand-encodes the
ONNX ``ModelProto`` family directly in protobuf wire format (varint /
length-delimited fields per the public onnx.proto3 schema). Files written
here open in onnxruntime / Netron; files from other exporters parse back.

Reference capability: ``python/hetu/onnx/`` (hetu2onnx.py:27, onnx2hetu.py).
"""
from __future__ import annotations

import struct

import numpy as np

# -- wire primitives ---------------------------------------------------------


def _varint(n):
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def _len_field(field, payload):
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field, value):
    return _tag(field, 0) + _varint(value)


def _str_field(field, s):
    return _len_field(field, s.encode("utf-8") if isinstance(s, str) else s)


def _packed_floats(field, vals):
    return _len_field(field, struct.pack(f"<{len(vals)}f", *vals))


def _packed_int64s(field, vals):
    return _len_field(field, b"".join(_varint(v) for v in vals))


class _Reader:
    def __init__(self, data):
        self.d = data
        self.p = 0

    def eof(self):
        return self.p >= len(self.d)

    def varint(self):
        shift = result = 0
        while True:
            b = self.d[self.p]
            self.p += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7

    def svarint(self):
        v = self.varint()
        return v - (1 << 64) if v >= (1 << 63) else v

    def field(self):
        key = self.varint()
        field, wire = key >> 3, key & 7
        if wire == 0:
            return field, self.svarint()
        if wire == 2:
            n = self.varint()
            out = self.d[self.p:self.p + n]
            self.p += n
            return field, out
        if wire == 5:
            out = struct.unpack("<f", self.d[self.p:self.p + 4])[0]
            self.p += 4
            return field, out
        if wire == 1:
            out = struct.unpack("<d", self.d[self.p:self.p + 8])[0]
            self.p += 8
            return field, out
        raise ValueError(f"unsupported wire type {wire}")


def _ints_any(v):
    """Repeated int field value → list of ints, whether the element came
    packed (length-delimited blob of varints) or unpacked (single
    varint)."""
    if not isinstance(v, bytes):
        return [v]
    rr = _Reader(v)
    out = []
    while not rr.eof():
        out.append(rr.svarint())
    return out


# -- ONNX dtypes -------------------------------------------------------------

FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE = \
    1, 2, 3, 6, 7, 9, 10, 11
BFLOAT16 = 16

NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.float64): DOUBLE,
           np.dtype(np.int32): INT32, np.dtype(np.int64): INT64,
           np.dtype(np.bool_): BOOL, np.dtype(np.float16): FLOAT16,
           np.dtype(np.uint8): UINT8, np.dtype(np.int8): INT8}
ONNX2NP = {v: k for k, v in NP2ONNX.items()}


# -- message classes ---------------------------------------------------------


class Tensor:
    """TensorProto: named constant data (initializers)."""

    def __init__(self, name, array):
        self.name = name
        self.array = np.asarray(array)

    def encode(self):
        a = self.array
        dt = NP2ONNX.get(a.dtype)
        if dt is None:
            a = a.astype(np.float32)
            dt = FLOAT
        out = b"".join(_int_field(1, int(d)) for d in a.shape)
        out += _int_field(2, dt)
        out += _str_field(8, self.name)
        out += _len_field(9, a.tobytes())       # raw_data
        return out

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        dims, dtype, name = [], FLOAT, ""
        raw = None
        floats, int64s, int32s = [], [], []
        while not r.eof():
            f, v = r.field()
            if f == 1:
                dims.append(v)
            elif f == 2:
                dtype = v
            elif f == 8:
                name = v.decode("utf-8")
            elif f == 9:
                raw = v
            # repeated scalar fields arrive PACKED (one length-delimited
            # blob — proto3 default, our own writer) or UNPACKED (one tag
            # per element — what torch's exporter emits); accept both
            elif f == 4:  # float_data
                if isinstance(v, bytes):
                    floats.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    floats.append(v)
            elif f == 7:  # int64_data
                int64s.extend(_ints_any(v))
            elif f == 5:  # int32_data
                int32s.extend(_ints_any(v))
        np_dt = ONNX2NP.get(dtype, np.dtype(np.float32))
        if raw is not None:
            arr = np.frombuffer(raw, np_dt).reshape(dims)
        elif floats:
            arr = np.asarray(floats, np_dt).reshape(dims)
        elif int64s:
            arr = np.asarray(int64s, np_dt).reshape(dims)
        elif int32s:
            arr = np.asarray(int32s, np_dt).reshape(dims)
        else:
            arr = np.zeros(dims, np_dt)
        return cls(name, arr)


class Attribute:
    """AttributeProto: name + one typed payload."""
    FLOAT_T, INT_T, STRING_T, TENSOR_T, FLOATS_T, INTS_T, STRINGS_T = \
        1, 2, 3, 4, 6, 7, 8

    def __init__(self, name, value):
        self.name = name
        self.value = value

    def encode(self):
        out = _str_field(1, self.name)
        v = self.value
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, float):
            out += _tag(2, 5) + struct.pack("<f", v)
            out += _int_field(20, self.FLOAT_T)
        elif isinstance(v, int):
            out += _int_field(3, v)
            out += _int_field(20, self.INT_T)
        elif isinstance(v, str):
            out += _str_field(4, v)
            out += _int_field(20, self.STRING_T)
        elif isinstance(v, Tensor):
            out += _len_field(5, v.encode())
            out += _int_field(20, self.TENSOR_T)
        elif isinstance(v, (list, tuple)) and v and \
                isinstance(v[0], float):
            out += _packed_floats(7, list(v))
            out += _int_field(20, self.FLOATS_T)
        elif isinstance(v, (list, tuple)):
            out += _packed_int64s(8, [int(x) for x in v])
            out += _int_field(20, self.INTS_T)
        else:
            raise TypeError(f"unsupported attribute {self.name}={v!r}")
        return out

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        name, atype = "", None
        f_v = i_v = s_v = t_v = None
        floats, ints = [], []
        while not r.eof():
            f, v = r.field()
            if f == 1:
                name = v.decode("utf-8")
            elif f == 2:
                f_v = v
            elif f == 3:
                i_v = v
            elif f == 4:
                s_v = v.decode("utf-8")
            elif f == 5:
                t_v = Tensor.decode(v)
            elif f == 7:  # floats: packed blob(s) or unpacked elements —
                # protobuf decoders must CONCATENATE repeated chunks
                if isinstance(v, bytes):
                    floats.extend(struct.unpack(f"<{len(v) // 4}f", v))
                else:
                    floats.append(v)
            elif f == 8:  # ints: packed blob or one unpacked element
                ints.extend(_ints_any(v))
            elif f == 20:
                atype = v
        if atype == cls.FLOAT_T:
            return cls(name, f_v)
        if atype == cls.INT_T:
            return cls(name, i_v)
        if atype == cls.STRING_T:
            return cls(name, s_v)
        if atype == cls.TENSOR_T:
            return cls(name, t_v)
        if atype == cls.FLOATS_T:
            return cls(name, floats)
        if atype == cls.INTS_T:
            return cls(name, ints)
        # untyped: best effort by presence
        for v in (i_v, f_v, s_v, t_v, ints or None, floats or None):
            if v is not None:
                return cls(name, v)
        return cls(name, None)


class Node:
    """NodeProto."""

    def __init__(self, op_type, inputs, outputs, name="", **attrs):
        self.op_type = op_type
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.name = name
        self.attrs = dict(attrs)

    def encode(self):
        out = b"".join(_str_field(1, s) for s in self.inputs)
        out += b"".join(_str_field(2, s) for s in self.outputs)
        out += _str_field(3, self.name or f"{self.op_type}_node")
        out += _str_field(4, self.op_type)
        for k in sorted(self.attrs):
            out += _len_field(5, Attribute(k, self.attrs[k]).encode())
        return out

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        ins, outs, name, op = [], [], "", ""
        attrs = {}
        while not r.eof():
            f, v = r.field()
            if f == 1:
                ins.append(v.decode("utf-8"))
            elif f == 2:
                outs.append(v.decode("utf-8"))
            elif f == 3:
                name = v.decode("utf-8")
            elif f == 4:
                op = v.decode("utf-8")
            elif f == 5:
                a = Attribute.decode(v)
                attrs[a.name] = a.value
        return cls(op, ins, outs, name, **attrs)


class ValueInfo:
    """ValueInfoProto: name + tensor type (elem type, static shape)."""

    def __init__(self, name, dtype, shape):
        self.name = name
        self.dtype = dtype  # onnx enum
        self.shape = list(shape)

    def encode(self):
        dims = b""
        for d in self.shape:
            if isinstance(d, str):
                dim = _str_field(2, d)      # dim_param
            else:
                dim = _int_field(1, int(d))  # dim_value
            dims += _len_field(1, dim)
        shape_proto = dims
        tensor_t = _int_field(1, self.dtype) + _len_field(2, shape_proto)
        type_proto = _len_field(1, tensor_t)
        return _str_field(1, self.name) + _len_field(2, type_proto)

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        name, dtype, shape = "", FLOAT, []
        while not r.eof():
            f, v = r.field()
            if f == 1:
                name = v.decode("utf-8")
            elif f == 2:  # TypeProto
                tr = _Reader(v)
                while not tr.eof():
                    tf, tv = tr.field()
                    if tf == 1:  # tensor_type
                        ttr = _Reader(tv)
                        while not ttr.eof():
                            ttf, ttv = ttr.field()
                            if ttf == 1:
                                dtype = ttv
                            elif ttf == 2:  # TensorShapeProto
                                sr = _Reader(ttv)
                                while not sr.eof():
                                    sf, sv = sr.field()
                                    if sf == 1:  # Dimension
                                        dr = _Reader(sv)
                                        dim = None
                                        while not dr.eof():
                                            df, dv = dr.field()
                                            if df == 1:
                                                dim = dv
                                            elif df == 2:
                                                dim = dv.decode("utf-8")
                                        shape.append(dim)
        return cls(name, dtype, shape)


class Graph:
    """GraphProto."""

    def __init__(self, name="graph", nodes=(), inputs=(), outputs=(),
                 initializers=()):
        self.name = name
        self.nodes = list(nodes)
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.initializers = list(initializers)

    def encode(self):
        out = b"".join(_len_field(1, n.encode()) for n in self.nodes)
        out += _str_field(2, self.name)
        out += b"".join(_len_field(5, t.encode())
                        for t in self.initializers)
        out += b"".join(_len_field(11, vi.encode()) for vi in self.inputs)
        out += b"".join(_len_field(12, vi.encode()) for vi in self.outputs)
        return out

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        g = cls()
        while not r.eof():
            f, v = r.field()
            if f == 1:
                g.nodes.append(Node.decode(v))
            elif f == 2:
                g.name = v.decode("utf-8")
            elif f == 5:
                g.initializers.append(Tensor.decode(v))
            elif f == 11:
                g.inputs.append(ValueInfo.decode(v))
            elif f == 12:
                g.outputs.append(ValueInfo.decode(v))
        return g


class Model:
    """ModelProto with a default opset import."""

    def __init__(self, graph, ir_version=9, opset=17,
                 producer="hetu_tpu"):
        self.graph = graph
        self.ir_version = ir_version
        self.opset = opset
        self.producer = producer

    def encode(self):
        opset = _str_field(1, "") + _int_field(2, self.opset)
        out = _int_field(1, self.ir_version)
        out += _str_field(2, self.producer)
        out += _len_field(7, self.graph.encode())
        out += _len_field(8, opset)
        return out

    @classmethod
    def decode(cls, data):
        r = _Reader(data)
        graph, ir, opset, producer = None, 8, 17, ""
        while not r.eof():
            f, v = r.field()
            if f == 1:
                ir = v
            elif f == 2:
                producer = v.decode("utf-8")
            elif f == 7:
                graph = Graph.decode(v)
            elif f == 8:
                rr = _Reader(v)
                while not rr.eof():
                    ff, vv = rr.field()
                    if ff == 2:
                        opset = vv
        m = cls(graph, ir, opset, producer)
        return m

    def save(self, path):
        with open(path, "wb") as f:
            f.write(self.encode())

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            return cls.decode(f.read())


__all__ = ["Model", "Graph", "Node", "Tensor", "Attribute", "ValueInfo",
           "NP2ONNX", "ONNX2NP", "FLOAT", "INT32", "INT64"]
