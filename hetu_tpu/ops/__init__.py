"""Op library — full surface parity with reference ``gpu_ops/__init__.py``."""
from .base import def_op, SimpleOp, OP_REGISTRY
from .arithmetic import (
    add_op, addbyconst_op, minus_op, minusbyconst_op, minus_byconst_op,
    mul_op, mulbyconst_op, mul_byconst_op, div_op, div_const_op, const_div_op,
    div_handle_zero_op, fmod_op, ne_op, outer_op, const_pow_op, abs_op,
    opposite_op, exp_op, log_op, sqrt_op, rsqrt_op, sigmoid_op, tanh_op,
    erf_op,
    sin_op, cos_op, floor_op, bool_op, pow_op, clamp_op, oneslike_op,
    zeroslike_op, where_op, where_const_op, full_op, full_like_op, eye_op,
    arange_op, rand_op)
from .matmul import (matmul_op, linear_op, batch_matmul_op, addmm_op,
                     baddbmm_op, matrix_dot_op)
from .transform import (
    array_reshape_op, flatten_op, transpose_op, unsqueeze_op, squeeze_op,
    concat_op, concatenate_op, split_op, slice_op, slice_assign_op,
    slice_assign_matrix_op, slice_by_matrix_op, pad_op, broadcastto_op,
    broadcast_shape_op, repeat_op, roll_op, flip_op, gather_op,
    index_select_op, scatter_op, scatter1d_op, scatter1d_grad_op, indexing_op,
    as_strided_op, argmax_op, argsort_op, max_op, min_op, topk_val_op,
    topk_idx_op, one_hot_op, cumsum_with_bias_op, triu_op, tril_op,
    masked_fill_op, interpolate_op, norm_op)
from .reduce import reduce_sum_op, reduce_mean_op, reducesumaxiszero_op, sum_op
from .nn import (relu_op, leaky_relu_op, gelu_op, softmax_op, log_softmax_op,
                 softmax_func, dropout_op, dropout2d_op, conv2d_op,
                 conv2d_add_bias_op, max_pool2d_op, avg_pool2d_op,
                 batch_normalization_op, layer_normalization_op,
                 instance_normalization2d_op, BatchNormOp)
from .losses import (softmaxcrossentropy_op, softmaxcrossentropy_sparse_op,
                     crossentropy_op, crossentropy_sparse_op,
                     binarycrossentropy_op, nll_loss_op)
from .embedding import embedding_lookup_op
from .moe import (topk_gate_op, ktop1_gate_op, sam_gate_op,
                  layout_transform_op, reverse_layout_transform_op,
                  hash_dispatch_op, balance_assignment_op, alltoall_op,
                  halltoall_op, topk_gate_sparse_op, sparse_dispatch_op,
                  sparse_combine_op)
from .attention import (sdpa_op, sdpa_masked_op, sdpa_bias_op,
                        sdpa_masked_bias_op, sdpa_varlen_op,
                        sdpa_decode_op, kv_cache_append_op,
                        sdpa_prefill_op, chunk_positions_op,
                        split_heads_chunk_op, merge_heads_chunk_op,
                        chunk_emit_gather_op,
                        ring_attention_op, ulysses_attention_op)
from .matmul import einsum_op
from .rnn import rnn_op, lstm_op, gru_op
from .transform import clone_op, cumsum_op, group_topk_idx_op

# reference-name aliases
slice_gradient_op = slice_op
array_reshape_gradient_op = array_reshape_op
