"""Elementwise / scalar ops.

Parity targets (reference ``src/ops``): Abs, AddConst, AddElewise, Bool, Clamp,
ConstPow, Division, Exp, Floor, Fmod, Log, MinusByConst/Elewise,
MultiplyConst/Elewise, Ne, Opposite, Pow, Sigmoid, Sin, Sqrt, Tanh, Gelu(act),
LeakyRelu(act), Where, Eye, Arange, Full, OnesLike, ZerosLike, Rand.
All lower to jnp; XLA fuses chains of these into single kernels (vs one CUDA
launch per op in the reference, SURVEY.md §3.1).
"""
import jax
import jax.numpy as jnp

from .base import def_op

_same = lambda a, **k: a  # shape rule: unary elementwise


def _bcast(a, b, **k):
    import numpy as np
    return np.broadcast_shapes(tuple(a), tuple(b))


# binary elementwise
add_op = def_op("AddElewise", lambda c, a, b: a + b, _bcast)
minus_op = def_op("MinusElewise", lambda c, a, b: a - b, _bcast)
mul_op = def_op("MultiplyElewise", lambda c, a, b: a * b, _bcast)
div_op = def_op("Division", lambda c, a, b: a / b, _bcast)
div_handle_zero_op = def_op(
    "DivisionHandleZero",
    lambda c, a, b: jnp.where(b == 0, jnp.zeros_like(a), a / jnp.where(b == 0, jnp.ones_like(b), b)),
    _bcast)
fmod_op = def_op("Fmod", lambda c, a, b: jnp.fmod(a, b), _bcast)
ne_op = def_op("Ne", lambda c, a, b: (a != b).astype(a.dtype), _bcast)
outer_op = def_op("Outer", lambda c, a, b: jnp.outer(a, b),
                  lambda a, b: (int(jnp.prod(jnp.array(a))), int(jnp.prod(jnp.array(b)))))

# const variants
addbyconst_op = def_op("AddConst", lambda c, a, const_attr=0.0: a + const_attr, _same)
minusbyconst_op = def_op("MinusByConst", lambda c, a, const_attr=0.0: a - const_attr, _same)
mulbyconst_op = def_op("MultiplyConst", lambda c, a, const_attr=1.0: a * const_attr, _same)
div_const_op = def_op("DivConst", lambda c, a, const_attr=1.0: a * const_attr, _same)
const_div_op = def_op("ConstDiv", lambda c, a, const_attr=1.0: const_attr / a, _same)
const_pow_op = def_op("ConstPow", lambda c, a, const_attr=1.0: jnp.power(const_attr, a), _same)

# reference-compat aliases
minus_byconst_op = minusbyconst_op
mul_byconst_op = mulbyconst_op

# unary elementwise
abs_op = def_op("Abs", lambda c, a: jnp.abs(a), _same)
opposite_op = def_op("Opposite", lambda c, a: -a, _same)
exp_op = def_op("Exp", lambda c, a: jnp.exp(a), _same)
log_op = def_op("Log", lambda c, a: jnp.log(a), _same)
sqrt_op = def_op("Sqrt", lambda c, a: jnp.sqrt(a), _same)
rsqrt_op = def_op("ReciprocalSqrt", lambda c, a: jax.lax.rsqrt(a), _same)
sigmoid_op = def_op("Sigmoid", lambda c, a: jax.nn.sigmoid(a), _same)
tanh_op = def_op("Tanh", lambda c, a: jnp.tanh(a), _same)
erf_op = def_op("Erf", lambda c, a: jax.lax.erf(a), _same)
sin_op = def_op("Sin", lambda c, a: jnp.sin(a), _same)
cos_op = def_op("Cos", lambda c, a: jnp.cos(a), _same)
floor_op = def_op("Floor", lambda c, a: jnp.floor(a), _same)
bool_op = def_op("Bool", lambda c, a: (a != 0).astype(jnp.float32), _same)
# no hand shape rule: Pow is built both as pow_op(a, p=scalar) and (via
# the ONNX importer) as pow_op(a, b) with a TENSOR exponent — `_same`
# mis-handled the second form (caught by the shape-rule-mismatch lint);
# the abstract-interpreter fallback covers both, broadcasting included
pow_op = def_op("Pow", lambda c, a, p=2.0: jnp.power(a, p))
clamp_op = def_op("Clamp",
                  lambda c, a, mmin=None, mmax=None: jnp.clip(a, mmin, mmax), _same)
oneslike_op = def_op("OnesLike", lambda c, a: jnp.ones_like(a), _same)
zeroslike_op = def_op("ZerosLike", lambda c, a: jnp.zeros_like(a), _same)

# where
where_op = def_op("Where", lambda c, cond, a, b: jnp.where(cond.astype(bool), a, b),
                  lambda cond, a, b: _bcast(a, b))
where_const_op = def_op(
    "WhereConst",
    lambda c, cond, a, const_attr=0.0: jnp.where(cond.astype(bool), a, const_attr),
    lambda cond, a, **k: tuple(a))

# generators (no tensor inputs)
full_op = def_op("Full", lambda c, shape=(), fill_value=0.0, dtype=jnp.float32:
                 jnp.full(shape, fill_value, dtype))
full_like_op = def_op("FullLike", lambda c, a, fill_value=0.0: jnp.full_like(a, fill_value), _same)
eye_op = def_op("Eye", lambda c, n=1, m=None, dtype=jnp.float32: jnp.eye(n, m, dtype=dtype))
arange_op = def_op("Arange", lambda c, start=0, end=None, step=1, dtype=jnp.float32:
                   jnp.arange(start, end, step, dtype=dtype))
rand_op = def_op("Rand", lambda c, shape=(), low=0.0, high=1.0:
                 jax.random.uniform(c.rng(), shape, minval=low, maxval=high))
