"""Attention ops.

The reference has NO attention kernel — its transformer examples compose
batch_matmul + softmax ops (SURVEY.md §5.7).  Here scaled-dot-product
attention is a first-class fused op so the hot path can lower to the Pallas
flash-attention kernel (:mod:`hetu_tpu.ops.pallas.flash_attention`) on TPU,
with a reference jnp lowering for CPU tests; ring/blockwise variants live in
:mod:`hetu_tpu.parallel.ring_attention`.
"""
import json
import os

import jax
import jax.numpy as jnp

from .base import def_op


def _load_flash_gate(default=256):
    """Empirical flash-vs-XLA dispatch threshold + measured block shapes.

    ``tools/flash_ab.py`` measures both paths on the real chip and commits
    the winner table to ``artifacts/flash_ab.json``; the gate and the
    per-seq (block_q, block_k) come from data when that artifact exists
    (round-2 verdict: a guessed gate meant the kernel was never in the
    measured hot path)."""
    blocks = {}
    path = os.environ.get("HETU_FLASH_AB_PATH") or os.path.join(
        os.path.dirname(__file__), os.pardir, os.pardir,
        "artifacts", "flash_ab.json")
    gate = None
    try:
        with open(path) as f:
            data = json.load(f)
        if data.get("backend") == "tpu":
            # a PARTIAL artifact (sweep killed mid-way) still serves its
            # measured block shapes, but its gate covers only a prefix of
            # the lengths — keep the default gate until the sweep completes
            if not data.get("partial"):
                gate = int(data["flash_min_len"])
            for seq, row in data.get("rows", {}).items():
                for tag in ("dense", "causal", "kmask"):
                    bl = row.get(f"blocks_{tag}")
                    if bl:
                        blocks[(int(seq), tag)] = tuple(bl)
    except (OSError, ValueError, KeyError, TypeError):
        pass
    env = os.environ.get("HETU_FLASH_MIN_LEN")
    if env:
        gate = int(env)
    return (default if gate is None else gate), blocks


#: below the gate, XLA's fusion is fine; blocks are measured per seq
_FLASH_MIN_LEN, _FLASH_BLOCKS = _load_flash_gate()


@jax.custom_vjp
def _scores_f32(q, k):
    """q·kᵀ with an f32 RESULT from low-precision operands (softmax needs
    the f32 range) — but with a custom backward that casts the f32
    cotangent down to the operand dtype before the dq/dk dots, the same
    discipline flash backward kernels use.  Without this, the f32 primal
    output makes dscores f32 and both backward dots run f32×f32 at half
    MXU throughput (the matmul.py dtype-discipline note; found by
    tools/hlo_audit.py — 24 residual f32 dots, 2 per layer)."""
    return jnp.einsum("bhqd,bhkd->bhqk", q, k,
                      preferred_element_type=jnp.float32)


def _scores_f32_fwd(q, k):
    return _scores_f32(q, k), (q, k)


def _scores_f32_bwd(res, g):
    q, k = res
    g = g.astype(q.dtype)
    dq = jnp.einsum("bhqk,bhkd->bhqd", g, k)
    dk = jnp.einsum("bhqk,bhqd->bhkd", g, q)
    return dq, dk.astype(k.dtype)


_scores_f32.defvjp(_scores_f32_fwd, _scores_f32_bwd)


def sdpa_reference(q, k, v, causal=False, scale=None, mask=None, bias=None):
    """(B, H, S, D) reference attention in plain jnp."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    logits = _scores_f32(q, k) * scale
    if bias is not None:  # additive position bias (T5-style), broadcastable
        logits = logits + bias
    valid = None
    if causal:
        s_q, s_k = logits.shape[-2:]
        valid = jnp.tril(jnp.ones((s_q, s_k), bool), s_k - s_q)
    if mask is not None:
        m = mask.astype(bool)
        valid = m if valid is None else jnp.logical_and(valid, m)
    if valid is not None:
        logits = jnp.where(valid, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    if valid is not None:
        # a query row with NO valid key (under the COMBINED causal∧mask
        # validity) yields ZERO output, not the uniform softmax fallback
        # (which would leak every value vector — e.g. the XLNet query
        # stream's first-in-permutation position)
        row_any = jnp.any(valid, axis=-1, keepdims=True)
        probs = jnp.where(row_any, probs, 0.0)
    # result dtype follows the operands (bf16 in → bf16 out): forcing an
    # f32 result here would make the cotangent f32 and run the backward
    # dots as f32×f32 (the matmul.py dtype-discipline note); the scores
    # einsum above keeps its f32 RESULT because softmax needs the range
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _note_flash_fallback(reason):
    """Record a dispatch that left the flash fast path — NEVER silent:
    the reason lands in the ``hetu_tpu.metrics`` counter registry
    (surfaced by ``HetuProfiler.flash_fallbacks()`` and bench.py), and
    ``HETU_REQUIRE_FLASH=1`` escalates it to a hard failure so a TPU run
    that silently compiled onto the einsum path cannot masquerade as a
    flash measurement."""
    from ..metrics import counters_suppressed, record_flash_fallback
    if counters_suppressed():
        return  # abstract shape trace (ht.lint), not a real dispatch
    record_flash_fallback(reason)
    if os.environ.get("HETU_REQUIRE_FLASH") == "1":
        raise RuntimeError(
            f"HETU_REQUIRE_FLASH=1: attention dispatch fell back off the "
            f"flash path ({reason})")


def _causal_bucketable(q, k, causal):
    """Ragged lengths bucket (pad+mask+unpad) EXCEPT under causal when
    q/kv lengths differ mod 128 — padding would shift the bottom-right-
    aligned diagonal (flash_attention raises for that combination)."""
    return not causal or (q.shape[-2] % 128) == (k.shape[-2] % 128)


def _gate_reason(q, k, causal=False):
    """Why the base gate refuses the flash path (None = it passes)."""
    be = jax.default_backend()
    if be != "tpu":
        return f"backend:{be}"
    s_q, s_kv = q.shape[-2], k.shape[-2]
    if s_q < _FLASH_MIN_LEN:
        return f"below_gate:seq{s_q}<{_FLASH_MIN_LEN}"
    if not _causal_bucketable(q, k, causal):
        return f"causal_ragged_mismatch:({s_q},{s_kv})"
    return None


def _use_flash(q, k):
    """One dispatch rule for every flash-capable op (keeps the varlen and
    dense paths from drifting apart).  Ragged (non-128-multiple) lengths
    no longer disqualify — the kernel entry buckets them."""
    s_q = q.shape[-2]
    return jax.default_backend() == "tpu" and s_q >= _FLASH_MIN_LEN


def _clipped_blocks(tag, q, k):
    """Measured (block_q, block_k) for this (seq, tag), dropped when they
    exceed or fail to divide the actual dims (the artifact measures square
    (s, s) shapes; cross-attention must not inherit a bad block)."""
    bq, bk = _FLASH_BLOCKS.get((q.shape[-2], tag), (None, None))
    if bq is not None and (bq > q.shape[-2] or q.shape[-2] % bq):
        bq = None
    if bk is not None and (bk > k.shape[-2] or k.shape[-2] % bk):
        bk = None
    return bq, bk


def dispatch_sdpa(q, k, v, causal=False, scale=None):
    """Backend-dispatched dense attention: the Pallas flash kernel when the
    empirical gate says it wins, XLA-composed otherwise.  The functional
    entry point for schedules that compose attention themselves (Ulysses'
    full-sequence local step, pipeline stages)."""
    if _use_flash(q, k) and _causal_bucketable(q, k, causal):
        from .pallas.flash_attention import flash_attention
        bq, bk = _clipped_blocks("causal" if causal else "dense", q, k)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               block_q=bq, block_k=bk)
    _note_flash_fallback(_gate_reason(q, k, causal) or "dispatch_gate")
    return sdpa_reference(q, k, v, causal=causal, scale=scale)


def _sdpa(c, q, k, v, causal=False, scale=None):
    return dispatch_sdpa(q, k, v, causal=causal, scale=scale)


sdpa_op = def_op("ScaledDotProductAttention", _sdpa)


def _split_mask_kinds(mask, q):
    """Route a broadcastable mask to the cheap kernel path.

    (B|1, 1, 1, S_kv) masks are pure key-padding masks — O(S) memory as the
    kernel's ``key_mask`` column strips; anything else rides the blockwise
    full-mask path.  Returns (key_mask, full_mask) with exactly one set."""
    b, h, s_q, _ = q.shape
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        km = mask.reshape(mask.shape[0], mask.shape[-1])
        if km.shape[0] == 1:
            km = jnp.broadcast_to(km, (b, km.shape[-1]))
        return km, None
    return None, mask


def _broadcastable_extra(q, k, x):
    """Shape check for a mask/bias the kernel's broadcast-group loader
    supports: (1|B, 1|H, 1|S_q, S_kv)."""
    b, h = q.shape[:2]
    return x.ndim == 4 and x.shape[0] in (1, b) \
        and x.shape[1] in (1, h) \
        and x.shape[2] in (1, q.shape[2]) and x.shape[3] == k.shape[2]


def _flash_maskable(q, k, mask):
    """Mask shapes the kernel's broadcast-group loader supports."""
    if not _use_flash(q, k):
        return False
    if mask is None:
        return True
    return _broadcastable_extra(q, k, mask)


def _masked_reason(q, k, causal, mask, what="mask"):
    """Fallback reason for a masked/biased dispatch (None = flash-able)."""
    r = _gate_reason(q, k, causal)
    if r is not None:
        return r
    if mask is not None and not _broadcastable_extra(q, k, mask):
        return f"{what}_shape:{tuple(mask.shape)}"
    return None


def dispatch_sdpa_masked(q, k, v, mask, causal=False, scale=None):
    """Backend-dispatched masked attention (functional entry — Ulysses'
    full-sequence local step with a padding mask)."""
    if _flash_maskable(q, k, mask) and _causal_bucketable(q, k, causal):
        from .pallas.flash_attention import flash_attention
        km, fm = _split_mask_kinds(mask, q)
        # the key-mask strip path (flagship) uses ITS OWN measured blocks
        bq, bk = (None, None)
        if km is not None and not causal:
            bq, bk = _clipped_blocks("kmask", q, k)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               key_mask=km, mask=fm, block_q=bq, block_k=bk)
    _note_flash_fallback(_masked_reason(q, k, causal, mask)
                         or "dispatch_gate")
    return sdpa_reference(q, k, v, causal=causal, scale=scale, mask=mask)


def _sdpa_masked(c, q, k, v, mask, causal=False, scale=None):
    return dispatch_sdpa_masked(q, k, v, mask, causal=causal, scale=scale)


sdpa_masked_op = def_op("ScaledDotProductAttentionMasked", _sdpa_masked)


def dispatch_sdpa_bias(q, k, v, bias, causal=False, scale=None):
    """Backend-dispatched attention with an additive logit bias — flash
    kernel when the gate and broadcast shape allow, XLA-composed otherwise
    (the functional entry for Ulysses' full-sequence local step)."""
    if _flash_maskable(q, k, bias) and _causal_bucketable(q, k, causal):
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               bias=bias)
    _note_flash_fallback(_masked_reason(q, k, causal, bias, what="bias")
                         or "dispatch_gate")
    return sdpa_reference(q, k, v, causal=causal, scale=scale, bias=bias)


def _sdpa_bias(c, q, k, v, bias, causal=False, scale=None):
    """Attention with an additive logit bias (T5 relative position bias)."""
    return dispatch_sdpa_bias(q, k, v, bias, causal=causal, scale=scale)


sdpa_bias_op = def_op("ScaledDotProductAttentionBias", _sdpa_bias)


def dispatch_sdpa_masked_bias(q, k, v, mask, bias, causal=False,
                              scale=None):
    """Backend-dispatched masked+biased attention (functional entry —
    the non-cp fallbacks of the masked CP ops and Ulysses' local step)."""
    if _flash_maskable(q, k, mask) and _flash_maskable(q, k, bias) \
            and _causal_bucketable(q, k, causal):
        from .pallas.flash_attention import flash_attention
        km, fm = _split_mask_kinds(mask, q)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               key_mask=km, mask=fm, bias=bias)
    _note_flash_fallback(_masked_reason(q, k, causal, mask)
                         or _masked_reason(q, k, causal, bias, what="bias")
                         or "dispatch_gate")
    return sdpa_reference(q, k, v, causal=causal, scale=scale, mask=mask,
                          bias=bias)


def _sdpa_masked_bias(c, q, k, v, mask, bias, causal=False, scale=None):
    """Masked attention with an additive bias (XLNet two-stream layers)."""
    return dispatch_sdpa_masked_bias(q, k, v, mask, bias, causal=causal,
                                     scale=scale)


sdpa_masked_bias_op = def_op("ScaledDotProductAttentionMaskedBias",
                             _sdpa_masked_bias)


def _sdpa_varlen(c, q, k, v, lengths, causal=False, scale=None):
    """Padding-masked attention: keys >= lengths[b] are invisible.

    TPU → the Pallas flash kernel's lengths path (no FLOPs spent on
    fully-masked key blocks; ragged shapes bucket); otherwise the jnp
    reference with a built column mask."""
    if _use_flash(q, k) and _causal_bucketable(q, k, causal):
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               lengths=lengths)
    _note_flash_fallback(_gate_reason(q, k, causal) or "dispatch_gate")
    s_kv = k.shape[-2]
    cols = jnp.arange(s_kv)[None, None, None, :]
    mask = cols < lengths.astype(jnp.int32)[:, None, None, None]
    return sdpa_reference(q, k, v, causal=causal, scale=scale, mask=mask)


sdpa_varlen_op = def_op("ScaledDotProductAttentionVarlen", _sdpa_varlen)


def _decode_gate_reason(k_cache):
    """Why a decode step leaves the flash path (None = flash-able).  The
    decode gate keys on the KV-CACHE length — the axis the kernel tiles
    and the axis that grows as generation proceeds — not the base gate's
    q_len (always 1 in decode, where the base gate would refuse every
    step)."""
    be = jax.default_backend()
    if be != "tpu":
        return f"backend:{be}"
    s_kv = k_cache.shape[-2]
    if s_kv < _FLASH_MIN_LEN:
        return f"decode_below_gate:kv{s_kv}<{_FLASH_MIN_LEN}"
    if s_kv % 128:
        return f"decode_kv_ragged:kv{s_kv}"
    return None


def dispatch_sdpa_decode(q, k_cache, v_cache, positions, scale=None):
    """One autoregressive decode step against a bucketed KV cache — the
    degenerate q_len=1 entry of the flash kernel's lengths path.

    ``q``: the current token's query, (B, H, 1, D).  ``k_cache`` /
    ``v_cache``: (B, H, L, D) with the new token already appended at
    ``positions`` (see ``kv_cache_append_op``).  ``positions``: (B,)
    int — the row each sequence just wrote; keys beyond it are invisible
    (so ``causal`` is implied: the query IS the last valid key).  On TPU
    a cache at a mod-128 bucket >= the flash gate rides the kernel's
    lengths path (fully-masked key blocks cost no FLOPs — exactly where
    a long cache pays); anything else is the counted jnp reference."""
    lengths = positions.astype(jnp.int32) + 1
    reason = _decode_gate_reason(k_cache)
    if reason is None:
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k_cache, v_cache, causal=False,
                               scale=scale, lengths=lengths)
    _note_flash_fallback(reason)
    s_kv = k_cache.shape[-2]
    cols = jnp.arange(s_kv)[None, None, None, :]
    mask = cols < lengths[:, None, None, None]
    return sdpa_reference(q, k_cache, v_cache, scale=scale, mask=mask)


def _sdpa_decode(c, q, k_cache, v_cache, positions, scale=None):
    return dispatch_sdpa_decode(q, k_cache, v_cache, positions,
                                scale=scale)


sdpa_decode_op = def_op("ScaledDotProductAttentionDecode", _sdpa_decode)


def _kv_cache_append(c, cache, new, positions, valid=None):
    """Append (B, H, C, D) token rows into the (B, H, L, D) cache at
    ``positions[b] .. positions[b]+C`` — a batched dynamic_update_slice,
    the incremental write that makes a generation O(S) total attention
    work instead of re-prefill's O(S^2).  C=1 is the classic decode
    write; C>1 is a chunked-prefill write (ISSUE 18).

    ``valid`` (optional 4th graph input, (B,) int): rows ``>= valid[b]``
    of the chunk are NOT written — the old cache bytes are preserved via
    a select, not a shorter slice, so a ragged chunk (a row consuming
    fewer than C prompt tokens, or an idle slot with valid=0) leaves the
    cache bitwise-identical to the token-by-token path.  That byte-level
    path-independence is what makes shared-prefix KV snapshots safe to
    reuse across ingestion modes.  The engine guarantees positions+C
    never exceeds the bucketed L (out-of-range starts clamp under XLA
    dynamic_update_slice semantics and would shift the write window)."""
    positions = positions.astype(jnp.int32)
    if valid is None:
        def upd(c_hld, n_hcd, p):
            return jax.lax.dynamic_update_slice(c_hld, n_hcd, (0, p, 0))
        return jax.vmap(upd)(cache, new, positions)
    chunk = new.shape[-2]
    keep = (jnp.arange(chunk)[None, :, None]
            < valid.astype(jnp.int32)[:, None, None])  # (B, C, 1)

    def updv(c_hld, n_hcd, p, k_c1):
        old = jax.lax.dynamic_slice(
            c_hld, (0, p, 0), (c_hld.shape[0], chunk, c_hld.shape[2]))
        return jax.lax.dynamic_update_slice(
            c_hld, jnp.where(k_c1, n_hcd, old), (0, p, 0))
    return jax.vmap(updv)(cache, new, positions, keep)


kv_cache_append_op = def_op("KVCacheAppend", _kv_cache_append)


def _prefill_gate_reason(q, k_cache):
    """Why a chunked-prefill step leaves the flash path (None =
    flash-able).  Like the decode gate it keys on the KV-cache length
    (the tiled axis); additionally the per-batch position offsets mean
    kernel-causal (bottom-right-aligned diagonal) cannot express the
    mask, so the kernel is entered through its full-mask path — legal
    only when q_len also tiles."""
    be = jax.default_backend()
    if be != "tpu":
        return f"backend:{be}"
    s_kv = k_cache.shape[-2]
    if s_kv < _FLASH_MIN_LEN:
        return f"prefill_below_gate:kv{s_kv}<{_FLASH_MIN_LEN}"
    if s_kv % 128:
        return f"prefill_kv_ragged:kv{s_kv}"
    if q.shape[-2] % 128 and q.shape[-2] != s_kv:
        return f"prefill_chunk_ragged:q{q.shape[-2]}"
    return None


def dispatch_sdpa_prefill(q, k_cache, v_cache, positions, scale=None):
    """A chunked prefill step against a bucketed KV cache — the q_len=C
    generalization of ``dispatch_sdpa_decode`` (ISSUE 18).

    ``q``: this chunk's queries, (B, H, C, D).  ``k_cache`` /
    ``v_cache``: (B, H, L, D) with the chunk's rows already appended at
    ``positions..positions+C`` (see ``kv_cache_append_op``).
    ``positions``: (B,) int — the cache row of each sequence's FIRST
    chunk token; chunk-local query j may see keys ``< positions+j+1``
    (causal-within-chunk, everything before the chunk visible).  The
    per-batch offsets put TPU dispatch on the kernel's full-mask path
    (kernel-causal can't shift its diagonal per batch row); elsewhere
    the counted jnp reference.  Rows past a sequence's real prompt are
    masked by the CALLER's cache-write ``valid`` and sliced away by the
    emit gather — their outputs are don't-cares here."""
    chunk = q.shape[-2]
    lengths = (positions.astype(jnp.int32)[:, None]
               + 1 + jnp.arange(chunk, dtype=jnp.int32)[None, :])  # (B, C)
    s_kv = k_cache.shape[-2]
    cols = jnp.arange(s_kv, dtype=jnp.int32)
    mask = cols[None, None, None, :] < lengths[:, None, :, None]
    reason = _prefill_gate_reason(q, k_cache)
    if reason is None:
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k_cache, v_cache, causal=False,
                               scale=scale, mask=mask)
    _note_flash_fallback(reason)
    return sdpa_reference(q, k_cache, v_cache, scale=scale, mask=mask)


def _sdpa_prefill(c, q, k_cache, v_cache, positions, scale=None):
    return dispatch_sdpa_prefill(q, k_cache, v_cache, positions,
                                 scale=scale)


sdpa_prefill_op = def_op("ScaledDotProductAttentionPrefill", _sdpa_prefill)


def _chunk_positions(c, positions, ids, limit=None):
    """Per-token cache positions for a (B, C) chunk:
    ``positions[b] + j`` for chunk-local token j, clamped to
    ``limit - 1`` so idle slots / ragged tails index a real (ignored)
    position-embedding row.  Shape-agnostic: one graph retraces per fed
    (B, C)."""
    chunk = ids.shape[-1]
    p = (positions.astype(jnp.int32)[:, None]
         + jnp.arange(chunk, dtype=jnp.int32)[None, :])
    if limit is not None:
        p = jnp.minimum(p, jnp.int32(limit - 1))
    return p


chunk_positions_op = def_op("ChunkPositions", _chunk_positions)


def _split_heads_chunk(c, t, ids, n_head=1):
    """(B*C, H*D) projected activations -> (B, H, C, D) heads, with the
    (B, C) shape recovered from the ``ids`` feed (shape-agnostic chunk
    twin of the decode graph's q_len=1 reshape)."""
    b, chunk = ids.shape
    return t.reshape(b, chunk, n_head, -1).transpose(0, 2, 1, 3)


split_heads_chunk_op = def_op("SplitHeadsChunk", _split_heads_chunk)


def _merge_heads_chunk(c, att):
    """(B, H, C, D) attention outputs -> (B*C, H*D) for the residual
    stream."""
    b, h, chunk, d = att.shape
    return att.transpose(0, 2, 1, 3).reshape(b * chunk, h * d)


merge_heads_chunk_op = def_op("MergeHeadsChunk", _merge_heads_chunk)


def _chunk_emit_gather(c, hidden, ids, valid):
    """Pick each sequence's LAST consumed chunk row out of the (B*C, E)
    hidden stream: row ``valid[b] - 1`` (clamped into the chunk) of
    batch b -> (B, E).  Sliced before ln_f/lm_head so a chunked step
    pays the vocab projection for B rows, not B*C."""
    b, chunk = ids.shape
    e = hidden.shape[-1]
    h3 = hidden.reshape(b, chunk, e)
    rows = jnp.clip(valid.astype(jnp.int32) - 1, 0, chunk - 1)
    return jnp.take_along_axis(h3, rows[:, None, None], axis=1)[:, 0, :]


chunk_emit_gather_op = def_op("ChunkEmitGather", _chunk_emit_gather)


def _has_cp(mesh):
    return mesh is not None and "cp" in mesh.axis_names \
        and mesh.shape["cp"] > 1


def _ring_attention(c, q, k, v, bias=None, causal=False, scale=None):
    """Ring attention over the 'cp' mesh axis; plain sdpa when no cp axis
    (identical numerics — parity-tested in tests/test_context_parallel.py).
    ``bias`` (optional 4th graph input): additive logit bias, ring-sliced
    per step (T5 relative position bias with context parallelism)."""
    if _has_cp(c.mesh):
        from ..parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, c.mesh, bias=bias, causal=causal,
                              scale=scale)
    if bias is not None:
        return dispatch_sdpa_bias(q, k, v, bias, causal=causal, scale=scale)
    return _sdpa(c, q, k, v, causal=causal, scale=scale)


ring_attention_op = def_op("RingAttention", _ring_attention)


def _ulysses_attention(c, q, k, v, bias=None, causal=False, scale=None):
    """Ulysses head-sharded all-to-all attention over the 'cp' axis.
    ``bias`` (optional 4th graph input): head-sharded additive bias."""
    if _has_cp(c.mesh):
        from ..parallel.ring_attention import ulysses_attention
        return ulysses_attention(q, k, v, c.mesh, bias=bias, causal=causal,
                                 scale=scale)
    if bias is not None:
        return dispatch_sdpa_bias(q, k, v, bias, causal=causal, scale=scale)
    return _sdpa(c, q, k, v, causal=causal, scale=scale)


ulysses_attention_op = def_op("UlyssesAttention", _ulysses_attention)


def _cp_mask_kwargs(mask):
    """Route a 4-D attention mask onto the cheapest cp schedule input:
    KEY-padding masks ((B|1, 1, 1, S_kv) — validity does not vary per
    query) ride the ring as (B, S_kv) column flags; anything else is a
    FULL per-query mask, query-sharded like the bias (round-4 verdict
    item 5 made these shard over the ring instead of raising)."""
    if mask.ndim != 4:
        raise ValueError(f"attention mask must be 4-D, got {mask.shape}")
    if mask.shape[1] == 1 and mask.shape[2] == 1:
        return {"key_mask": mask}
    return {"mask": mask}


def _ring_attention_masked(c, q, k, v, mask, bias=None, causal=False,
                           scale=None):
    """Ring attention with a key-padding OR full per-query mask; optional
    additive bias rides the same ring slicing."""
    if _has_cp(c.mesh):
        from ..parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, c.mesh, bias=bias, causal=causal,
                              scale=scale, **_cp_mask_kwargs(mask))
    if bias is not None:
        return dispatch_sdpa_masked_bias(q, k, v, mask, bias, causal=causal,
                                         scale=scale)
    return dispatch_sdpa_masked(q, k, v, mask, causal=causal, scale=scale)


ring_attention_masked_op = def_op("RingAttentionMasked",
                                  _ring_attention_masked)


def _ulysses_attention_masked(c, q, k, v, mask, bias=None, causal=False,
                              scale=None):
    """Ulysses attention with a key-padding OR full per-query mask."""
    if _has_cp(c.mesh):
        from ..parallel.ring_attention import ulysses_attention
        return ulysses_attention(q, k, v, c.mesh, bias=bias, causal=causal,
                                 scale=scale, **_cp_mask_kwargs(mask))
    if bias is not None:
        return dispatch_sdpa_masked_bias(q, k, v, mask, bias, causal=causal,
                                         scale=scale)
    return dispatch_sdpa_masked(q, k, v, mask, causal=causal, scale=scale)


ulysses_attention_masked_op = def_op("UlyssesAttentionMasked",
                                     _ulysses_attention_masked)
