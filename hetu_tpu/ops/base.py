"""Op factory: concise definition of symbolic ops with JAX lowering rules.

Replaces the reference's one-CUDA-file-per-op scheme (``src/ops/*.cu``, 108
files + ``python/hetu/gpu_ops/*.py`` wrappers) with a registry of lowering
rules onto ``jax.numpy``/``lax``.  XLA fuses these into large kernels; the few
ops that need hand-tuning get Pallas kernels (see :mod:`hetu_tpu.ops.pallas`).
"""
from __future__ import annotations

from ..graph.node import Op

OP_REGISTRY = {}


class SimpleOp(Op):
    """A node whose semantics are fully captured by a pure lowering function."""

    def __init__(self, op_type, inputs, lower_fn, shape_fn=None, name=None,
                 **attrs):
        self.op_type = op_type
        self._lower_fn = lower_fn
        self._shape_fn = shape_fn
        super().__init__(inputs, name=name, **attrs)

    def lower(self, ctx, *vals):
        return self._lower_fn(ctx, *vals, **self.attrs)

    def infer_shape(self, input_shapes):
        if input_shapes and any(s is None for s in input_shapes):
            return None   # unknown inputs stay unknown (never a crash)
        if self._shape_fn is None:
            # no hand rule: the abstract interpreter derives the shape
            # from the lowering itself (Op.infer_shape fallback)
            return super().infer_shape(input_shapes)
        return self._shape_fn(*input_shapes, **self.attrs)

    @property
    def has_shape_rule(self):
        """True iff a hand-written shape rule exists (the cross-check in
        :mod:`hetu_tpu.analysis` only validates HAND rules — comparing
        the abstract interpreter against itself proves nothing)."""
        return self._shape_fn is not None


class ItemOp(Op):
    """Extract one output of a multi-output op (tuple-valued lowering)."""

    op_type = "Item"

    def __init__(self, src, index, name=None):
        super().__init__([src], name=name)
        self.index = index

    def lower(self, ctx, val):
        return val[self.index]


def tuple_outputs(node, n):
    """Split a tuple-valued node into n single-output nodes."""
    return tuple(ItemOp(node, i, name=f"{node.name}.{i}") for i in range(n))


def def_op(op_type, lower_fn, shape_fn=None):
    """Register an op kind; returns its constructor.

    The constructor accepts the graph-node inputs positionally and attributes
    as keywords; a trailing ``ctx=`` kwarg is accepted for reference-API
    compatibility (placement is handled by ``ht.context`` scopes instead).
    """

    import inspect
    try:
        lower_params = [p for p in inspect.signature(lower_fn).parameters
                        if p != "c" and not p.startswith("*")]
    except (TypeError, ValueError):  # builtins / C funcs
        lower_params = []

    def ctor(*args, ctx=None, name=None, **attrs):
        del ctx  # placement comes from the ht.context scope
        # split positional args: leading Ops are graph inputs; the rest are
        # attributes matched to the lowering fn's parameter names in order
        # (reference signatures pass attrs positionally, e.g.
        # ``reduce_mean_op(node, axes, keepdims)``)
        inputs = []
        i = 0
        while i < len(args) and isinstance(args[i], Op):
            inputs.append(args[i])
            i += 1
        extra = args[i:]
        if extra:
            attr_names = lower_params[len(inputs):]
            if len(extra) > len(attr_names):
                raise TypeError(
                    f"{op_type}: too many positional args {extra}")
            for pname, val in zip(attr_names, extra):
                attrs[pname] = val
        return SimpleOp(op_type, inputs, lower_fn, shape_fn, name=name, **attrs)

    ctor.__name__ = op_type
    OP_REGISTRY[op_type] = ctor
    return ctor
