"""Embedding lookup (reference ``gpu_ops/EmbeddingLookUp.py:10`` +
``src/ops/EmbeddingLookup.cu``).

Dense path: ``jnp.take`` — XLA lowers the backward to a scatter-add, which is
the TPU-native equivalent of the reference's IndexedSlices machinery
(``ndarray.py:507``); no explicit sparse-gradient type is needed under jit.
Huge (HBM-exceeding) tables go through the host-resident embedding store in
:mod:`hetu_tpu.embedding` instead (HET cache semantics, SURVEY.md §5.8).
"""
import jax.numpy as jnp

from .base import def_op

embedding_lookup_op = def_op(
    "EmbeddingLookup",
    lambda c, table, idx: jnp.take(table, idx.astype(jnp.int32), axis=0),
    lambda table, idx: tuple(idx) + (table[1],))
