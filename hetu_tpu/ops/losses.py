"""Loss ops (reference: SoftmaxCrossEntropy(.Sparse).cu, CrossEntropy(Sparse).cu,
BinaryCrossEntropy.cu, NllLoss.cu).

Reference semantics: per-example losses are returned unreduced (shape (N,))
and the model applies reduce_mean — we keep that contract.
"""
import jax
import jax.numpy as jnp

from .base import def_op


def _softmax_ce(c, logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logp, axis=-1)


softmaxcrossentropy_op = def_op("SoftmaxCrossEntropy", _softmax_ce,
                                lambda a, b: tuple(a[:-1]))


def _softmax_ce_sparse(c, logits, labels, ignored_index=-1):
    logp = jax.nn.log_softmax(logits, axis=-1)
    lbl = labels.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, jnp.maximum(lbl, 0)[..., None], axis=-1)[..., 0]
    return jnp.where(lbl == ignored_index, 0.0, -picked)


softmaxcrossentropy_sparse_op = def_op("SoftmaxCrossEntropySparse",
                                       _softmax_ce_sparse,
                                       lambda a, b, ignored_index=-1: tuple(a[:-1]))

crossentropy_op = def_op(
    "CrossEntropy",
    lambda c, pred, labels, eps=1e-12: -jnp.sum(labels * jnp.log(pred + eps), axis=-1))

crossentropy_sparse_op = def_op(
    "CrossEntropySparse",
    lambda c, pred, labels, ignored_index=-1, eps=1e-12: jnp.where(
        labels.astype(jnp.int32) == ignored_index, 0.0,
        -jnp.log(jnp.take_along_axis(
            pred, jnp.maximum(labels.astype(jnp.int32), 0)[..., None], axis=-1)[..., 0] + eps)))

binarycrossentropy_op = def_op(
    "BinaryCrossEntropy",
    lambda c, pred, labels, eps=1e-12:
        -(labels * jnp.log(pred + eps) + (1 - labels) * jnp.log(1 - pred + eps)))


def _nll(c, logp, target):
    t = target.astype(jnp.int32)
    return -jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]


nll_loss_op = def_op("NllLoss", _nll)
