"""Matrix ops — the MXU path.

Reference: ``src/ops/MatrixMult.cu`` (cublasSgemm), ``BatchMatrixMult.cu``,
``Linear.cu``, ``Addmm.cu``, ``Baddbmm.cu``, ``Dot.cu``.  Here they lower to
``jnp.matmul``/``lax.dot_general`` which XLA tiles onto the 128x128 systolic
array.

Dtype discipline: the dot's result dtype follows its operands (bf16 in →
bf16 out).  The MXU accumulates bf16 operands in f32 internally regardless,
so forcing ``preferred_element_type=f32`` buys nothing on the forward — and
it COSTS the backward: an f32 primal output makes every cotangent f32, and
JAX's dot vjp then promotes the bf16 operand, running all dgrad/wgrad dots
as f32×f32 at half MXU throughput (found by tools/hlo_audit.py: 196 of 294
flagship-step dots were f32).  Softmax-feeding contractions that genuinely
need an f32 RESULT (attention scores) opt in locally in ops/attention.py.
"""
import jax.numpy as jnp

from .base import def_op


def _mm(c, a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = a.T
    if trans_B:
        b = b.T
    return jnp.matmul(a, b)


def _mm_shape(a, b, trans_A=False, trans_B=False):
    # mirrors the lowering exactly: `.T` REVERSES all axes (not a swap of
    # the trailing two), and jnp.matmul broadcasts leading batch dims /
    # promotes 1-D operands — the old 2-D-only rule was caught wrong on
    # ONNX-imported batched matmuls by the shape-rule-mismatch lint
    import numpy as np
    a = tuple(a)[::-1] if trans_A else tuple(a)
    b = tuple(b)[::-1] if trans_B else tuple(b)
    if len(a) == 1 and len(b) == 1:
        return ()
    if len(b) == 1:
        return a[:-1]
    if len(a) == 1:
        return b[:-2] + (b[-1],)
    batch = np.broadcast_shapes(a[:-2], b[:-2])
    return tuple(batch) + (a[-2], b[-1])


matmul_op = def_op("MatrixMult", _mm, _mm_shape)


def _linear(c, a, b, bias, trans_A=False, trans_B=False):
    return _mm(c, a, b, trans_A, trans_B) + bias


linear_op = def_op("Linear", _linear,
                   lambda a, b, bias, trans_A=False, trans_B=False:
                   _mm_shape(a, b, trans_A, trans_B))


def _bmm(c, a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = jnp.swapaxes(a, -1, -2)
    if trans_B:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


batch_matmul_op = def_op("BatchMatrixMult", _bmm)

addmm_op = def_op(
    "Addmm",
    lambda c, inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _mm(c, a, b))

baddbmm_op = def_op(
    "Baddbmm",
    lambda c, inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _bmm(c, a, b))

matrix_dot_op = def_op("MatrixDot", lambda c, a, b: jnp.sum(a * b))


def einsum_op(subscripts, *nodes, name=None):
    """General einsum node (new; subsumes the reference's special-case batched
    contractions and feeds the MXU directly)."""
    from .base import SimpleOp
    return SimpleOp("Einsum", list(nodes),
                    lambda c, *vals, subscripts=None: jnp.einsum(
                        subscripts, *vals),
                    name=name, subscripts=subscripts)
