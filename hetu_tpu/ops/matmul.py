"""Matrix ops — the MXU path.

Reference: ``src/ops/MatrixMult.cu`` (cublasSgemm), ``BatchMatrixMult.cu``,
``Linear.cu``, ``Addmm.cu``, ``Baddbmm.cu``, ``Dot.cu``.  Here they lower to
``jnp.matmul``/``lax.dot_general`` which XLA tiles onto the 128x128 systolic
array; ``preferred_element_type=f32`` keeps bf16 inputs accumulating in f32.
"""
import jax.numpy as jnp

from .base import def_op


def _mm(c, a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = a.T
    if trans_B:
        b = b.T
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


def _mm_shape(a, b, trans_A=False, trans_B=False):
    m = a[1] if trans_A else a[0]
    n = b[0] if trans_B else b[1]
    return (m, n)


matmul_op = def_op("MatrixMult", _mm, _mm_shape)


def _linear(c, a, b, bias, trans_A=False, trans_B=False):
    return _mm(c, a, b, trans_A, trans_B) + bias


linear_op = def_op("Linear", _linear,
                   lambda a, b, bias, trans_A=False, trans_B=False:
                   _mm_shape(a, b, trans_A, trans_B))


def _bmm(c, a, b, trans_A=False, trans_B=False):
    if trans_A:
        a = jnp.swapaxes(a, -1, -2)
    if trans_B:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(a.dtype)


batch_matmul_op = def_op("BatchMatrixMult", _bmm)

addmm_op = def_op(
    "Addmm",
    lambda c, inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _mm(c, a, b))

baddbmm_op = def_op(
    "Baddbmm",
    lambda c, inp, a, b, alpha=1.0, beta=1.0: beta * inp + alpha * _bmm(c, a, b))

matrix_dot_op = def_op("MatrixDot", lambda c, a, b: jnp.sum(a * b))


def einsum_op(subscripts, *nodes, name=None):
    """General einsum node (new; subsumes the reference's special-case batched
    contractions and feeds the MXU directly)."""
    from .base import SimpleOp
    return SimpleOp("Einsum", list(nodes),
                    lambda c, *vals, subscripts=None: jnp.einsum(
                        subscripts, *vals,
                        preferred_element_type=jnp.float32).astype(vals[0].dtype),
                    name=name, subscripts=subscripts)
