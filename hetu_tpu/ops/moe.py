"""MoE ops — gating, dispatch, expert-parallel collectives.

Reference machinery (SURVEY.md §2.6): LayoutTransform.cu (Tutel-style token
dispatch), ReverseLayoutTransform, AllToAll.cu / HAllToAll (hierarchical),
TopKIdx/TopKVal, Cumsum, OneHot, BalanceAssignment (BASE layer auction).

TPU-native redesign: dispatch/combine are *dense einsums* against one-hot
capacity masks (the GShard formulation) — MXU-friendly, static shapes, no
scatter; expert parallelism is expressed by sharding the expert axis over the
'ep' mesh axis, letting XLA emit all_to_all over ICI (the explicit
``lax.all_to_all`` path lives in :mod:`hetu_tpu.parallel.collectives` for
shard_map users).  Capacity overflow drops tokens exactly like the
reference's fixed-capacity LayoutTransform.
"""
import jax
import jax.numpy as jnp

from .base import def_op, SimpleOp, tuple_outputs


def _one_hot_f(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def _top1_gating(logits, capacity):
    """Returns (dispatch (s,e,c), combine (s,e,c), aux_loss) — GShard top-1."""
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot_f(idx1, e)                       # (s, e)
    # position of each token within its expert queue
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # (s, e), 0-based
    keep1 = mask1 * (pos1 < capacity)
    gate1 = jnp.sum(gates * keep1, axis=-1)           # (s,)
    # aux load-balance loss (reference TopGate.py balance_loss:6)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = jnp.sum(me * ce) * e
    pos_in_e = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)  # (s,)
    dispatch = keep1[:, :, None] * _one_hot_f(pos_in_e, capacity)[:, None, :]
    combine = gate1[:, None, None] * dispatch
    return dispatch, combine, aux


def _top2_gating(logits, capacity):
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot_f(idx1, e)
    gates2 = gates * (1 - mask1)
    idx2 = jnp.argmax(gates2, axis=-1)
    mask2 = _one_hot_f(idx2, e)

    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1
    # expert-2 queue positions come after all expert-1 tokens of that expert
    pos2 = (jnp.cumsum(mask2, axis=0) * mask2 - mask2) \
        + jnp.sum(mask1, axis=0, keepdims=True)
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(gates * keep1, axis=-1)
    g2 = jnp.sum(gates * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    aux = jnp.sum(me * ce) * e

    p1 = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)
    p2 = jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32)
    d1 = keep1[:, :, None] * _one_hot_f(p1, capacity)[:, None, :]
    d2 = keep2[:, :, None] * _one_hot_f(p2, capacity)[:, None, :]
    dispatch = jnp.maximum(d1, d2)
    combine = g1[:, None, None] * d1 + g2[:, None, None] * d2
    return dispatch, combine, aux


def _dispatch_from(keep, pos, capacity, gate_w=None):
    """Build (s,e,c) dispatch / combine tensors from a keep mask (s,e) and
    per-token queue positions (s,)."""
    d = keep[:, :, None] * _one_hot_f(pos, capacity)[:, None, :]
    if gate_w is None:
        return d
    return d, gate_w[:, None, None] * d


def _ktop1_gating(logits, k, capacity):
    """KTop1 (reference ``layers/KTop1Gate.py`` ktop1gating:14): experts are
    split into k prototype groups of e/k; each token routes top-1 within
    EVERY group (so k experts per token, one per group); balance loss summed
    per group."""
    s, e = logits.shape
    g = e // k
    dis_parts, com_parts = [], []
    aux = 0.0
    for i in range(k):
        gates = jax.nn.softmax(logits[:, i * g:(i + 1) * g], axis=-1)
        idx = jnp.argmax(gates, axis=-1)
        mask = _one_hot_f(idx, g)
        posm = jnp.cumsum(mask, axis=0) * mask - mask
        keep = mask * (posm < capacity)
        gate_w = jnp.sum(gates * keep, axis=-1)
        aux = aux + jnp.sum(jnp.mean(gates, 0) * jnp.mean(mask, 0)) * g
        p = jnp.sum(posm * keep, axis=-1).astype(jnp.int32)
        d, c = _dispatch_from(keep, p, capacity, gate_w)
        dis_parts.append(d)
        com_parts.append(c)
    dispatch = jnp.concatenate(dis_parts, axis=1)   # (s, e, c)
    combine = jnp.concatenate(com_parts, axis=1)
    return dispatch, combine, aux


def _sam_gating(logits, k, capacity, group_size):
    """SAM gate (reference ``layers/SAMGate.py`` samgating:22 + SamMax.cu,
    SamGroupSum.cu, GroupTopKIdx.cu): softmax over all experts; pick the
    group (node) with the largest summed prob; route top-k within that group;
    alignment loss = hinge on out-group probs exceeding the selected k-th
    expert's prob."""
    s, e = logits.shape
    ngroups = e // group_size
    gates = jax.nn.softmax(logits, axis=-1)
    gsum = gates.reshape(s, ngroups, group_size).sum(-1)
    top_group = jnp.argmax(gsum, axis=-1)                       # (s,)
    in_group = _one_hot_f(top_group, ngroups)                   # (s, ngroups)
    in_group_e = jnp.repeat(in_group, group_size, axis=1)       # (s, e)
    masked_gates = jnp.where(in_group_e > 0, gates, -jnp.inf)

    dispatch = jnp.zeros((s, e, capacity), jnp.float32)
    combine = jnp.zeros((s, e, capacity), jnp.float32)
    aux = 0.0
    used = jnp.zeros((s, e), jnp.float32)  # masks already routed experts
    kth_prob = None
    for i in range(k):
        idx = jnp.argmax(jnp.where(used > 0, -jnp.inf, masked_gates), axis=-1)
        mask = _one_hot_f(idx, e)
        used = used + mask
        # queue positions account for earlier-k selections (acc_base)
        posm = jnp.cumsum(mask, axis=0) * mask - mask \
            + jnp.sum(used - mask, axis=0, keepdims=True) * mask
        keep = mask * (posm < capacity)
        gate_w = jnp.sum(gates * keep, axis=-1)
        aux = aux + jnp.sum(jnp.mean(gates, 0) * jnp.mean(mask, 0)) * e
        p = jnp.sum(posm * keep, axis=-1).astype(jnp.int32)
        d, c = _dispatch_from(keep, p, capacity, gate_w)
        dispatch = dispatch + d
        combine = combine + c
        kth_prob = jnp.sum(gates * mask, axis=-1)               # (s,)
    # SamMax hinge: out-group probs exceeding the k-th selected prob
    out_group = 1.0 - in_group_e
    align = jnp.sum(jnp.maximum(gates - kth_prob[:, None], 0.0) * out_group)
    return dispatch, combine, aux, align


def ktop1_gate_op(logits_node, k, capacity, name=None):
    """Fused KTop1 gating node → (dispatch, combine, aux_loss)."""
    node = SimpleOp("KTop1Gate", [logits_node],
                    lambda c, logits, k=1, capacity=None:
                        _ktop1_gating(logits, k, capacity),
                    name=name, k=k, capacity=capacity)
    return tuple_outputs(node, 3)


def sam_gate_op(logits_node, k, capacity, group_size, name=None):
    """Fused SAM gating node → (dispatch, combine, aux_loss, align_loss)."""
    node = SimpleOp("SAMGate", [logits_node],
                    lambda c, logits, k=1, capacity=None, group_size=1:
                        _sam_gating(logits, k, capacity, group_size),
                    name=name, k=k, capacity=capacity, group_size=group_size)
    return tuple_outputs(node, 4)


def topk_gate_op(logits_node, k=1, capacity=None, name=None):
    """Fused GShard gating: returns (dispatch, combine, aux_loss) nodes."""
    assert k in (1, 2)

    def lower(c, logits, k=1, capacity=None):
        fn = _top1_gating if k == 1 else _top2_gating
        return fn(logits, capacity)

    node = SimpleOp("TopKGate", [logits_node], lower, name=name,
                    k=k, capacity=capacity)
    return tuple_outputs(node, 3)


# dense dispatch/combine einsums (the reference's layout_transform /
# reverse_layout_transform, ``LayoutTransform.py:12``)
layout_transform_op = def_op(
    "LayoutTransform",
    lambda c, dispatch, tokens: jnp.einsum(
        "sec,sm->ecm", dispatch.astype(tokens.dtype), tokens))

reverse_layout_transform_op = def_op(
    "ReverseLayoutTransform",
    lambda c, combine, expert_out: jnp.einsum(
        "sec,ecm->sm", combine.astype(expert_out.dtype), expert_out))


def _hash_dispatch(c, idx, num_experts=1, capacity=None):
    """Hash gating (reference HashGate.py): expert = token_id % E."""
    e = num_experts
    expert_of = (idx.astype(jnp.int32) % e)
    mask = _one_hot_f(expert_of, e)
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    keep = mask * (pos < capacity)
    p = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)
    dispatch = keep[:, :, None] * _one_hot_f(p, capacity)[:, None, :]
    return dispatch


def hash_dispatch_op(idx_node, num_experts, capacity, name=None):
    return SimpleOp("HashDispatch", [idx_node], _hash_dispatch, name=name,
                    num_experts=num_experts, capacity=capacity)


def _balanced_assignment(scores, rounds=4):
    """Balanced token→expert assignment: every expert gets exactly
    tokens/experts tokens and every token is assigned exactly once.

    TPU-native replacement for the reference's auction kernel
    (``BalanceAssignment.cu``): a fixed number of dense greedy rounds —
    each round, unassigned tokens bid for their best expert with remaining
    capacity and the top bidders win — then a deterministic fill matches any
    leftovers to the remaining slots.  All static shapes, no data-dependent
    loops (rounds is a compile-time constant).

    Returns slot→token ids, shape (s,), grouped by expert: slot q*cap+i holds
    the i-th token assigned to expert q — a true permutation of arange(s).
    """
    s, e = scores.shape
    cap = s // e
    # Sinkhorn normalization evens out scale differences between experts
    p = scores
    for _ in range(4):
        p = p - jax.nn.logsumexp(p, axis=1, keepdims=True)
        p = p - jax.nn.logsumexp(p, axis=0, keepdims=True)

    assigned = jnp.full((s,), -1, jnp.int32)      # token -> expert
    pos = jnp.zeros((s,), jnp.int32)              # token -> queue pos in expert
    used = jnp.zeros((e,), jnp.int32)             # expert -> #tokens taken
    NEG = jnp.asarray(-1e30, p.dtype)
    for _ in range(rounds):
        open_e = used < cap                       # (e,)
        unas = assigned < 0                       # (s,)
        masked = jnp.where(open_e[None, :] & unas[:, None], p, NEG)
        choice = jnp.argmax(masked, axis=1)       # (s,)
        bid = jnp.where(unas & jnp.take(open_e, choice),
                        jnp.take_along_axis(masked, choice[:, None], 1)[:, 0],
                        NEG)
        cmask = _one_hot_f(choice, e) * (bid > NEG / 2)[:, None]  # (s, e)
        score_col = jnp.where(cmask > 0, bid[:, None], NEG)
        # rank tokens per chosen expert by bid (descending, stable)
        order = jnp.argsort(-score_col, axis=0)
        rank = jnp.argsort(order, axis=0)         # (s, e) rank within column
        accept = (cmask > 0) & (rank < (cap - used)[None, :])
        tok_rank = jnp.sum(jnp.where(accept, rank, 0), axis=1)
        acc_any = jnp.any(accept, axis=1)
        new_pos = jnp.sum(jnp.where(accept, used[None, :], 0), axis=1) + tok_rank
        assigned = jnp.where(acc_any, choice.astype(jnp.int32), assigned)
        pos = jnp.where(acc_any, new_pos.astype(jnp.int32), pos)
        used = used + jnp.sum(accept, axis=0).astype(jnp.int32)

    # deterministic fill: k-th leftover token -> k-th free slot
    unas = assigned < 0
    token_rank = jnp.cumsum(unas.astype(jnp.int32)) - 1          # (s,)
    slot_expert = jnp.repeat(jnp.arange(e), cap)                 # (s,)
    slot_idx = jnp.tile(jnp.arange(cap), e)                      # pos within expert
    free = slot_idx >= jnp.take(used, slot_expert)               # (s,) slot free?
    free_rank = jnp.cumsum(free.astype(jnp.int32)) - 1
    # token with rank r takes the slot with rank r
    fill_expert = jnp.zeros((s,), jnp.int32).at[
        jnp.where(free, free_rank, s)].set(slot_expert.astype(jnp.int32),
                                           mode="drop")
    fill_pos = jnp.zeros((s,), jnp.int32).at[
        jnp.where(free, free_rank, s)].set(slot_idx.astype(jnp.int32),
                                           mode="drop")
    assigned = jnp.where(unas, jnp.take(fill_expert, token_rank), assigned)
    pos = jnp.where(unas, jnp.take(fill_pos, token_rank), pos)

    slot_of_token = assigned * cap + pos                          # (s,)
    token_of_slot = jnp.zeros((s,), jnp.int32).at[slot_of_token].set(
        jnp.arange(s, dtype=jnp.int32))
    return token_of_slot


def balance_assignment_op(scores_node, name=None):
    """BASE-layer balanced assignment node: scores (tokens, experts) →
    slot→token permutation (see :func:`_balanced_assignment`)."""
    return SimpleOp("BalanceAssignment", [scores_node],
                    lambda c, scores: _balanced_assignment(scores), name=name)


# explicit graph-level alltoall (EP over mesh): identity + sharding constraint;
# real lax.all_to_all lives in parallel.collectives for shard_map programs
def _alltoall(c, x):
    if c.mesh is not None and "ep" in c.mesh.axis_names:
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(c.mesh, PartitionSpec("ep", *([None] * (x.ndim - 1)))))
    return x


alltoall_op = def_op("AllToAll", _alltoall)


def _halltoall(c, x):
    """Hierarchical a2a (reference HAllToAll.cu + mpi_nccl dlarrayHAllToAll
    :396).  Under a 2-D ('ep_outer','ep_inner') mesh the leading dim is
    exchanged with the explicit intra-node → inter-node 2-phase schedule;
    on a flat 'ep' mesh it degrades to the sharding-constraint alltoall."""
    mesh = c.mesh
    if mesh is not None and "ep_outer" in mesh.axis_names \
            and "ep_inner" in mesh.axis_names:
        from jax.sharding import PartitionSpec as P
        from ..parallel.collectives import hierarchical_all_to_all
        spec = P(("ep_outer", "ep_inner"), *([None] * (x.ndim - 1)))
        return jax.shard_map(
            lambda v: hierarchical_all_to_all(v, "ep_outer", "ep_inner"),
            mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False)(x)
    return _alltoall(c, x)


halltoall_op = def_op("HAllToAll", _halltoall)


# ---------------------------------------------------------------------------
# Sparse (index-map) dispatch path — Pallas row-gather kernel, O(s·m) memory
# instead of the (s, e, c) one-hot tensors above; same routing/drop semantics.
# ---------------------------------------------------------------------------

def _topk_sparse_indices(logits, k, capacity):
    """GShard top-1/2 routing as index maps (no (s,e,c) tensors).

    Returns (token_of_slot (e*cap,), slot_of_token (s, k),
    k_of_slot (e*cap,), gate_w (s, k), aux_loss) with routing, capacity
    drops, gate normalisation, and aux loss identical to
    :func:`_top1_gating` / :func:`_top2_gating`.
    """
    s, e = logits.shape
    gates = jax.nn.softmax(logits, axis=-1)
    remaining = gates
    count_prev = jnp.zeros((1, e), jnp.float32)
    slots, gws, masks = [], [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = _one_hot_f(idx, e)
        pos = (jnp.cumsum(mask, axis=0) * mask - mask) + count_prev * mask
        keep = mask * (pos < capacity)
        kept = jnp.sum(keep, axis=-1) > 0                     # (s,) bool
        gws.append(jnp.sum(gates * keep, axis=-1))            # (s,)
        p = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)
        slot = jnp.where(kept, idx.astype(jnp.int32) * capacity + p, -1)
        slots.append(slot)
        masks.append(mask)
        count_prev = count_prev + jnp.sum(mask, axis=0, keepdims=True)
        remaining = remaining * (1 - mask)
    gate_w = jnp.stack(gws, axis=1)                           # (s, k)
    if k > 1:  # top-2 renormalisation (reference TopGate.py)
        denom = jnp.maximum(jnp.sum(gate_w, axis=1, keepdims=True), 1e-9)
        gate_w = gate_w / denom
    slot_of_token = jnp.stack(slots, axis=1)                  # (s, k)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(masks[0], axis=0)
    aux = jnp.sum(me * ce) * e

    n_slots = e * capacity
    tok_ids = jnp.arange(s, dtype=jnp.int32)
    token_of_slot = jnp.full((n_slots,), -1, jnp.int32)
    k_of_slot = jnp.zeros((n_slots,), jnp.int32)
    for j in range(k):
        tgt = jnp.where(slots[j] >= 0, slots[j], n_slots)
        token_of_slot = token_of_slot.at[tgt].set(tok_ids, mode="drop")
        k_of_slot = k_of_slot.at[tgt].set(j, mode="drop")
    return token_of_slot, slot_of_token, k_of_slot, gate_w, aux


def topk_gate_sparse_op(logits_node, k=1, capacity=None, name=None):
    """Sparse GShard gating → (token_of_slot, slot_of_token, k_of_slot,
    gate_w, aux_loss) nodes for the Pallas dispatch path."""
    node = SimpleOp("TopKGateSparse", [logits_node],
                    lambda c, logits, k=1, capacity=None:
                        _topk_sparse_indices(logits, k, capacity),
                    name=name, k=k, capacity=capacity)
    return tuple_outputs(node, 5)


def _pallas_interpret():
    return jax.default_backend() != "tpu"


def _sparse_dispatch_lower(c, tokens, token_of_slot, slot_of_token):
    from .pallas.moe_dispatch import sparse_dispatch
    return sparse_dispatch(tokens, token_of_slot, slot_of_token,
                           _pallas_interpret())


sparse_dispatch_op = def_op("SparseDispatch", _sparse_dispatch_lower)


def _sparse_combine_lower(c, buffers, gate_w, slot_of_token, token_of_slot,
                          k_of_slot):
    from .pallas.moe_dispatch import sparse_combine
    return sparse_combine(buffers, gate_w, slot_of_token, token_of_slot,
                          k_of_slot, _pallas_interpret())


sparse_combine_op = def_op("SparseCombine", _sparse_combine_lower)
