"""Neural-net ops: conv/pool/norm/dropout/activations/softmax.

Reference kernels: ``src/ops/CudnnConv2d.cu``, ``CudnnBn.cu``, ``LayerNorm.cu``,
``InstanceNorm2d.cu``, ``CudnnDropout.cu``, ``MaxPool.cu``, ``AvgPool.cu``,
``Relu/Gelu/LeakyRelu.cu``, ``CudnnSoftmax.cu``.  Layout follows the reference
API (NCHW / OIHW); XLA:TPU re-lays-out internally so the user-visible layout
costs nothing.

Stateful ops (BatchNorm running stats, Dropout RNG) are functional here:
BN writes its new running stats into the ``LowerCtx.state_updates``
side-channel and the executor commits them after the step — no mutation
inside the traced computation.
"""
import jax
import jax.numpy as jnp
import numpy as np

from .base import def_op
from ..graph.node import Op, PlaceholderOp

# -- activations ------------------------------------------------------------
relu_op = def_op("Relu", lambda c, a: jax.nn.relu(a), lambda a: tuple(a))
leaky_relu_op = def_op("LeakyRelu",
                       lambda c, a, alpha=0.01: jax.nn.leaky_relu(a, alpha),
                       lambda a, alpha=0.01: tuple(a))
gelu_op = def_op("Gelu", lambda c, a: jax.nn.gelu(a, approximate=True),
                 lambda a: tuple(a))
softmax_op = def_op("Softmax", lambda c, a: jax.nn.softmax(a, axis=-1),
                    lambda a: tuple(a))
log_softmax_op = def_op("LogSoftmax", lambda c, a: jax.nn.log_softmax(a, axis=-1))


def softmax_func(x):
    return jax.nn.softmax(x, axis=-1)


# -- dropout ----------------------------------------------------------------


def _dropout(c, a, keep_prob=0.9):
    if not c.training or keep_prob >= 1.0:
        return a
    mask = jax.random.bernoulli(c.rng(), keep_prob, a.shape)
    return jnp.where(mask, a / keep_prob, jnp.zeros_like(a))


dropout_op = def_op("Dropout", _dropout, lambda a, keep_prob=0.9: tuple(a))


def _dropout2d(c, a, keep_prob=0.9):
    """Channel dropout: zero whole (N, C) feature maps (reference Dropout2d.cu)."""
    if not c.training or keep_prob >= 1.0:
        return a
    mask = jax.random.bernoulli(c.rng(), keep_prob, a.shape[:2] + (1,) * (a.ndim - 2))
    return jnp.where(mask, a / keep_prob, jnp.zeros_like(a))


dropout2d_op = def_op("Dropout2d", _dropout2d)

# -- conv / pool ------------------------------------------------------------


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _conv2d(c, x, w, padding=0, stride=1, data_format="NCHW"):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    # no preferred_element_type: the TPU MXU accumulates in f32 regardless,
    # and requesting f32 output breaks the conv transpose rule under bf16
    # mixed precision (f32 cotangent vs bf16 residual).
    # data_format="NHWC" keeps activations channels-last END TO END —
    # the layout XLA wants on both CPU (oneDNN) and TPU (C on lanes);
    # authoring NCHW makes XLA bracket every conv with layout-conversion
    # transposes (measured: 1.8x the whole resnet18 CPU step).  Weights
    # stay OIHW either way — dimension_numbers handles mixed specs.
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(sh, sw), padding=[(ph, ph), (pw, pw)],
        dimension_numbers=(data_format, "OIHW", data_format))


def _conv2d_shape(x, w, padding=0, stride=1, data_format="NCHW"):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    if data_format == "NHWC":
        n, h, ww, _ = x
    else:
        n, _, h, ww = x
    o, _, kh, kw = w
    oh, ow = (h + 2 * ph - kh) // sh + 1, (ww + 2 * pw - kw) // sw + 1
    return (n, oh, ow, o) if data_format == "NHWC" else (n, o, oh, ow)


conv2d_op = def_op("Conv2d", _conv2d, _conv2d_shape)


def _bias_shape(data_format):
    return (1, 1, 1, -1) if data_format == "NHWC" else (1, -1, 1, 1)


conv2d_add_bias_op = def_op(
    "Conv2dAddBias",
    lambda c, x, w, b, padding=0, stride=1, data_format="NCHW":
        _conv2d(c, x, w, padding, stride, data_format)
        + b.reshape(_bias_shape(data_format)),
    lambda x, w, b, padding=0, stride=1, data_format="NCHW":
        _conv2d_shape(x, w, padding, stride, data_format))


def _pool(c, x, kernel_H, kernel_W, padding, stride, kind,
          data_format="NCHW"):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    if data_format == "NHWC":
        window = (1, kernel_H, kernel_W, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
    else:
        window = (1, 1, kernel_H, kernel_W)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if kind == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    else:
        out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
        out = out / (kernel_H * kernel_W)
    return out


def _pool_shape(x, kernel_H, kernel_W, padding, stride, data_format="NCHW"):
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    if data_format == "NHWC":
        n, h, w, ch = x
    else:
        n, ch, h, w = x
    oh, ow = (h + 2 * ph - kernel_H) // sh + 1, \
        (w + 2 * pw - kernel_W) // sw + 1
    return (n, oh, ow, ch) if data_format == "NHWC" else (n, ch, oh, ow)


def max_pool2d_op(node, kernel_H, kernel_W, padding=0, stride=1, ctx=None,
                  name=None, data_format="NCHW"):
    from .base import SimpleOp
    return SimpleOp("MaxPool2d", [node],
                    lambda c, x, **kw: _pool(c, x, kind="max", **kw),
                    lambda x, **kw: _pool_shape(x, **kw), name=name,
                    kernel_H=kernel_H, kernel_W=kernel_W, padding=padding,
                    stride=stride, data_format=data_format)


def avg_pool2d_op(node, kernel_H, kernel_W, padding=0, stride=1, ctx=None,
                  name=None, data_format="NCHW"):
    from .base import SimpleOp
    return SimpleOp("AvgPool2d", [node],
                    lambda c, x, **kw: _pool(c, x, kind="avg", **kw),
                    lambda x, **kw: _pool_shape(x, **kw), name=name,
                    kernel_H=kernel_H, kernel_W=kernel_W, padding=padding,
                    stride=stride, data_format=data_format)


# -- normalization ----------------------------------------------------------


class BatchNormOp(Op):
    """BatchNorm2d over NCHW with functional running stats.

    Reference: ``gpu_ops/BatchNorm.py`` / ``src/ops/CudnnBn.cu``. Running
    mean/var live as internal non-trainable Variables whose updates flow
    through ``ctx.state_updates`` (committed by the executor after the step).
    """

    op_type = "BatchNorm"

    def __init__(self, node_in, bn_scale, bn_bias, momentum=0.1, eps=1e-5,
                 name=None, data_format="NCHW"):
        self.running_mean = PlaceholderOp(
            f"{name or 'bn'}_running_mean", trainable=False,
            initializer=lambda shape, key: np.zeros(shape, np.float32))
        self.running_var = PlaceholderOp(
            f"{name or 'bn'}_running_var", trainable=False,
            initializer=lambda shape, key: np.ones(shape, np.float32))
        # running-stat shapes follow the scale param's shape
        self.running_mean.shape_from = bn_scale
        self.running_var.shape_from = bn_scale
        super().__init__([node_in, bn_scale, bn_bias,
                          self.running_mean, self.running_var], name=name,
                         momentum=momentum, eps=eps, data_format=data_format)

    def lower(self, ctx, x, scale, bias, rmean, rvar):
        momentum = self.attrs["momentum"]
        eps = self.attrs["eps"]
        if self.attrs.get("data_format") == "NHWC":
            axes = tuple(range(x.ndim - 1))      # stats over all but C
            cshape = (1,) * (x.ndim - 1) + (-1,)
        else:
            axes = (0,) + tuple(range(2, x.ndim))
            cshape = (1, -1) + (1,) * (x.ndim - 2)
        if ctx.training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            ctx.state_updates[self.running_mean] = \
                (1 - momentum) * rmean.reshape(-1) + momentum * mean
            ctx.state_updates[self.running_var] = \
                (1 - momentum) * rvar.reshape(-1) + momentum * var
        else:
            mean = rmean.reshape(-1)
            var = rvar.reshape(-1)
        inv = jax.lax.rsqrt(var.reshape(cshape) + eps)
        return (x - mean.reshape(cshape)) * inv * scale.reshape(cshape) \
            + bias.reshape(cshape)

    def infer_shape(self, input_shapes):
        return tuple(input_shapes[0])


def batch_normalization_op(node_in, bn_scale, bn_bias, momentum=0.1, eps=1e-5,
                           ctx=None, name=None, data_format="NCHW"):
    return BatchNormOp(node_in, bn_scale, bn_bias, momentum, eps, name=name,
                       data_format=data_format)


def _layer_norm(c, x, scale, bias, eps=0.01):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


layer_normalization_op = def_op("LayerNorm", _layer_norm,
                                lambda x, s, b, eps=0.01: tuple(x))


def _instance_norm2d(c, x, eps=1e-7):
    mean = jnp.mean(x, axis=(2, 3), keepdims=True)
    var = jnp.var(x, axis=(2, 3), keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps)


instance_normalization2d_op = def_op("InstanceNorm2d", _instance_norm2d)
