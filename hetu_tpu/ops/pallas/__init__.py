"""Pallas TPU kernels for ops where XLA fusion is insufficient
(SURVEY.md §7: fused attention, MoE dispatch, embedding scatter-add).

- :mod:`flash_attention` — blockwise online-softmax attention, fwd+bwd.
- :mod:`moe_dispatch` — row-gather sparse dispatch/combine (O(s·m) memory).
- :mod:`segment_sum` — sorted-run segment sum / IndexedSlices dedup.
- :mod:`emb_cache` — device-resident HET-cache slab: slot-indexed row
  gather + unique-inverse grad scatter-add (ISSUE 11).

Every kernel runs under ``interpret=True`` in CPU CI (tests/test_pallas.py)
so the exact TPU kernel code is exercised without hardware.
"""
from .flash_attention import flash_attention
from .moe_dispatch import row_gather, sparse_dispatch, sparse_combine
from .segment_sum import sorted_segment_sum, dedup_rows
from .emb_cache import emb_gather, emb_scatter_add, fill_rows
