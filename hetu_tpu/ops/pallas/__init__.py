"""Pallas TPU kernels for ops where XLA fusion is insufficient
(SURVEY.md §7: fused attention, MoE dispatch, embedding scatter-add)."""
