"""Device-resident HET-cache embedding kernels (ISSUE 11 tentpole).

The HET client cache (``ps/dist_store.py:DistCacheTable``, PR 3) keeps
its slot table, eviction clocks and transactional commit protocol
host-side — but the *math* of the hot path used to be host numpy too:
every cached row rode host→device each step, and the grad segment-sum
came back through a scipy-CSR host pass.  This module moves the math
onto the chip over a device-resident ``(limit + scratch + 1, width)``
float32 slab:

* :func:`gather_rows` — Pallas gather by slot index: per-row async DMA
  from the HBM slab into the output block (the rows of one block are
  all in flight before the first wait — the ``moe_dispatch.row_gather``
  discipline, re-specialized for the always-valid slot indices the
  cache hands out).
* :func:`scatter_add_grads` — the training-path grad reduction:
  device-side sort by the batch's unique-inverse map + the existing
  :func:`~hetu_tpu.ops.pallas.segment_sum.sorted_segment_sum` MXU
  kernel.  Replaces the scipy-CSR host pass of ``_segment_sum`` for
  device-resident tables: row ``j`` of the result is the summed grad
  of the batch's ``j``-th sorted unique key.
* :func:`fill_rows` — the miss landing: scatter freshly-pulled rows
  into their committed slots (an XLA ``.at[].set`` — the only H2D
  traffic left per step is the miss rows themselves; hits never cross
  the host boundary again).

Dispatch mirrors the flash-attention discipline (PR 1): the
``emb_*`` entry points take the Pallas path on TPU (or under
``interpret=True`` in CPU CI), otherwise fall back to ``jnp.take`` /
``jax.ops.segment_sum`` with the reason counted in the
``emb_pallas_fallbacks`` family (``metrics.emb_pallas_fallback_counts``,
surfaced by ``HetuProfiler.emb_pallas_fallbacks()``); never silent.
``HETU_REQUIRE_PALLAS_EMB=1`` escalates any fallback to a hard failure
so a TPU run cannot quietly train off the kernel path.
"""
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .segment_sum import sorted_segment_sum

#: slot indices handled per grid step — each row is one async DMA, so a
#: block is also the DMA queue depth kept in flight
ROW_BLOCK = 8


def _note_fallback(reason):
    """Count one embedding dispatch that left the Pallas path.  Like the
    flash counters, counts are per jax TRACE (dispatch happens when the
    program traces), so a count climbing across steps means the jit
    cache is thrashing and ONE nonzero entry means the workload compiled
    onto the slow path."""
    from ...metrics import counters_suppressed, record_emb_pallas_fallback
    # the recorder guards counting itself; THIS guard exists for the
    # HETU_REQUIRE_PALLAS_EMB raise below — an abstract eval_shape
    # trace must not hard-fail a lint pass (the flash _note_* idiom)
    if counters_suppressed():
        return
    record_emb_pallas_fallback(reason)
    if os.environ.get("HETU_REQUIRE_PALLAS_EMB") == "1":
        raise RuntimeError(
            f"HETU_REQUIRE_PALLAS_EMB=1: embedding-cache dispatch fell "
            f"back off the Pallas path (reason: {reason})")


# ----------------------------------------------------------------- gather
def _gather_kernel(slots_ref, slab_ref, out_ref, sems, *, block):
    b = pl.program_id(0)
    for i in range(block):
        row = slots_ref[b * block + i]
        pltpu.make_async_copy(slab_ref.at[row], out_ref.at[i],
                              sems.at[i]).start()
    for i in range(block):
        row = slots_ref[b * block + i]
        pltpu.make_async_copy(slab_ref.at[row], out_ref.at[i],
                              sems.at[i]).wait()


def gather_rows(slab, slots, block=ROW_BLOCK, interpret=False):
    """``out[i] = slab[slots[i]]`` — Pallas per-row async DMA gather.

    ``slots`` (n,) int must all be valid slab rows (the cache's slot
    plan guarantees it: hits gather their committed slot, misses were
    filled first, overflow keys gather their scratch row)."""
    n = slots.shape[0]
    w = slab.shape[1]
    if n == 0:
        return jnp.zeros((0, w), slab.dtype)
    n_pad = -(-n // block) * block
    slots_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(
        slots.astype(jnp.int32))
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block=block),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_pad // block,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((block, w), lambda g, *_: (g, 0)),
            scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, w), slab.dtype),
        interpret=interpret,
    )(slots_p, slab)
    return out[:n]


# ------------------------------------------------------------ scatter-add
def scatter_add_grads(grad, inv, block=128, interpret=False):
    """Per-unique-key grad sums on device (the scipy-CSR replacement).

    ``grad`` (n, w) row gradients, ``inv`` (n,) the batch's
    unique-inverse map (``np.unique(..., return_inverse=True)`` —
    values in [0, U)).  Sorts the rows by segment in XLA (fast bitonic
    sort on TPU) and reduces each run with the
    :func:`sorted_segment_sum` MXU kernel.  Returns (n, w): rows [0, U)
    hold the per-sorted-unique-key sums, the tail is zero padding (U is
    only known host-side — static shapes rule)."""
    n = grad.shape[0]
    if n == 0:
        return jnp.zeros_like(grad)
    inv = inv.astype(jnp.int32)
    order = jnp.argsort(inv)            # stable (lax.sort)
    seg = jnp.take(inv, order)
    rows = jnp.take(grad, order, axis=0)
    return sorted_segment_sum(rows, seg, n, block=block,
                              interpret=interpret)


# ------------------------------------------------------------- miss fill
def fill_rows(slab, rows, targets):
    """Land freshly-pulled miss rows in their committed slots:
    ``slab[targets[i]] = rows[i]``.  Padding entries all point at the
    cache's dump row (never gathered), so the fill arrays can ride in a
    small set of fixed bucket shapes without retracing per miss count.
    Plain XLA scatter — the expensive half of a miss is the PS pull,
    which the executor overlaps with the dense forward on the
    feed-pipeline thread; this lands the pulled bytes in their slots."""
    if rows.shape[0] == 0:
        return slab
    return slab.at[targets].set(rows.astype(slab.dtype))


#: the fill executables, keyed by donate flag (built on first use; one
#: tiny program per fill-bucket shape in jax's own jit cache)
_FILL_JIT = {}


def fill_bucket(m):
    """Pad a step's miss-fill arrays to a small pow2 bucket set (min 8):
    miss-count jitter then cycles a bounded set of compiled fill
    programs instead of compiling one per distinct miss count."""
    return 8 if m <= 8 else 1 << (m - 1).bit_length()


def fill_rows_inplace(slab, rows, targets):
    """The cache-commit fill: :func:`fill_rows` jitted with the slab
    DONATED on TPU, so XLA updates the resident slab in place instead
    of copying ``(limit + scratch, width)`` bytes per step.  (CPU/other
    backends cannot honor buffer donation — they copy either way — so
    donation is skipped there rather than warning on every fill.)  Runs
    EAGERLY at ``finish_lookup`` — keeping the fill out of the training
    step's program means the big jit sees only fixed shapes (slab,
    slots, inv) and never retraces on miss-count jitter; the fill
    itself is one tiny per-bucket executable."""
    donate = jax.default_backend() == "tpu"
    fn = _FILL_JIT.get(donate)
    if fn is None:
        fn = _FILL_JIT[donate] = jax.jit(
            fill_rows, donate_argnums=(0,) if donate else ())
    return fn(slab, rows, targets)


# ------------------------------------------------------------ dispatchers
def _want_pallas(interpret):
    """(use_pallas, interpret) under the flash dispatch rules: Pallas on
    TPU, Pallas-interpret when explicitly asked (CPU CI), fallback —
    counted — otherwise."""
    if interpret:
        return True, True
    if interpret is None and jax.default_backend() == "tpu":
        return True, False
    return False, False


def emb_gather(slab, slots, interpret=None):
    """Slot-indexed row gather with explicit fallback accounting.

    ``interpret``: None = auto (Pallas on TPU, counted ``jnp.take``
    fallback elsewhere), True = force the Pallas kernel in interpret
    mode (CPU CI parity tests), False = force the compiled kernel."""
    use, interp = _want_pallas(interpret)
    if use or interpret is False:
        return gather_rows(slab, slots, interpret=interp)
    _note_fallback(f"gather:backend_{jax.default_backend()}")
    return jnp.take(slab, slots.astype(jnp.int32), axis=0)


#: jitted gather entries per dispatch policy — the per-step gather runs
#: EAGERLY (device→device, enqueued just before the training step), and
#: routing it through one cached jit keeps the dispatcher body (and its
#: fallback counter) at trace-time cost: one recording per shape, not
#: one per step
_GATHER_JIT = {}


def gather_for_step(slab, slots, interpret=None):
    """The executor's per-step gather: ``emb_gather`` under a cached
    ``jax.jit`` so steady-state steps replay a compiled executable and
    the fallback counter keeps flash per-trace semantics."""
    fn = _GATHER_JIT.get(interpret)
    if fn is None:
        fn = _GATHER_JIT[interpret] = jax.jit(
            functools.partial(emb_gather, interpret=interpret))
    return fn(slab, slots)


def emb_scatter_add(grad, inv, interpret=None):
    """Unique-inverse grad segment-sum with explicit fallback
    accounting (same knob semantics as :func:`emb_gather`)."""
    use, interp = _want_pallas(interpret)
    if use or interpret is False:
        return scatter_add_grads(grad, inv, interpret=interp)
    _note_fallback(f"scatter_add:backend_{jax.default_backend()}")
    return jax.ops.segment_sum(grad, inv.astype(jnp.int32),
                               num_segments=grad.shape[0])
