"""Flash attention — Pallas TPU kernel (forward + backward).

The reference has no attention kernel at all (SURVEY.md §5.7): its
transformers compose batch_matmul + softmax ops, materialising the (S, S)
score matrix in HBM.  This kernel is the TPU-native replacement: blockwise
online-softmax attention that keeps scores in VMEM, with a custom VJP whose
backward recomputes scores per block (flash-attention-2 style), so memory is
O(S·D) instead of O(S²).

Layout: inputs are (B, H, S, D); the kernel runs on (B·H, S, D) with a
sequential TPU grid (bh, q_block, kv_block) — accumulators live in VMEM
scratch and persist across the minor-most kv grid steps; outputs are written
once on the final kv step (standard TPU revisiting-grid pattern).

Causal masking prunes fully-masked blocks via ``pl.when`` (no FLOPs spent
above the diagonal) and masks the diagonal blocks with -1e30 logits.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


# ---------------------------------------------------------------- forward
def _mask_and_live(qi, ki, len_ref, *, causal, has_lengths, block_q,
                   block_k, kv_off):
    """(live predicate, mask fn) for one (qi, ki) block.

    ``has_lengths`` is a STATIC trace-time flag: the dense path keeps the
    original straight-line code (static ``live`` when non-causal, no
    per-block iota/where), so varlen support costs the hot path nothing.
    The length scalar itself lives in SMEM (the supported scalar pattern).
    """
    causal_live = (qi * block_q + block_q - 1 + kv_off >= ki * block_k) \
        if causal else True
    if has_lengths:
        kvlen = len_ref[0, 0]
        live = jnp.logical_and(causal_live, ki * block_k < kvlen)
    else:
        kvlen = None
        live = causal_live

    def mask(s):
        valid = None
        if has_lengths:
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
                + ki * block_k
            valid = cols < kvlen                       # padding mask
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
                + qi * block_q + kv_off
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
                + ki * block_k
            c = rows >= cols
            valid = c if valid is None else jnp.logical_and(valid, c)
        return s if valid is None else jnp.where(valid, s, NEG_INF)

    return live, mask


def _fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, has_lengths,
                block_q, block_k, num_kv, kv_off):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live, mask = _mask_and_live(qi, ki, len_ref, causal=causal,
                                has_lengths=has_lengths, block_q=block_q,
                                block_k=block_k, kv_off=kv_off)

    @pl.when(live)
    def _block():
        q = q_ref[0]                                   # (bq, d)
        k = k_ref[0]                                   # (bk, d)
        v = v_ref[0]                                   # (bk, d)
        s = mask(jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale)  # (bq, bk)
        m_prev = m_scr[:, :1]                          # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # (bq, bk)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[:, :1] + jnp.log(l_safe))[:, 0]


def _len_spec():
    """(1,1) per-bh scalar in SMEM — the supported scalar-input pattern."""
    return pl.BlockSpec((1, 1), lambda b, i, j: (b, 0),
                        memory_space=pltpu.SMEM)


def _flash_fwd(q, k, v, lengths, scale, causal, block_q, block_k,
               interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    num_q = s_q // block_q
    num_kv = s_kv // block_k
    grid = (bh, num_q, num_kv)
    has_lengths = lengths is not None
    if not has_lengths:  # dummy scalar keeps the kernel arity uniform
        lengths = jnp.zeros((bh, 1), jnp.int32)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, has_lengths=has_lengths,
        block_q=block_q, block_k=block_k, num_kv=num_kv,
        kv_off=s_kv - s_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            _len_spec(),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(q, k, v, lengths)
    return out, lse


# ---------------------------------------------------------------- backward
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, len_ref,
               dq_ref, dq_scr, *, scale, causal, has_lengths, block_q,
               block_k, num_kv, kv_off):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live, mask = _mask_and_live(qi, ki, len_ref, causal=causal,
                                has_lengths=has_lengths, block_q=block_q,
                                block_k=block_k, kv_off=kv_off)

    @pl.when(live)
    def _block():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                                  # (bq, d)
        lse = lse_ref[0][:, None]                       # (bq, 1)
        delta = delta_ref[0][:, None]                   # (bq, 1)
        s = mask(jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale)
        p = jnp.exp(s - lse)                            # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, len_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                has_lengths, block_q, block_k, num_q, kv_off):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live, mask = _mask_and_live(qi, ki, len_ref, causal=causal,
                                has_lengths=has_lengths, block_q=block_q,
                                block_k=block_k, kv_off=kv_off)

    @pl.when(live)
    def _block():
        q = q_ref[0]                                    # (bq, d)
        k = k_ref[0]                                    # (bk, d)
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        s = mask(jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale)  # (bq, bk)
        p = jnp.exp(s - lse)                             # (bq, bk)
        # dV += P^T @ dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        ds = p * (dp - delta) * scale                    # (bq, bk)
        # dK += dS^T @ Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, lengths, out, lse, do, scale, causal, block_q,
               block_k, interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    num_q = s_q // block_q
    num_kv = s_kv // block_k
    has_lengths = lengths is not None
    if not has_lengths:
        lengths = jnp.zeros((bh, 1), jnp.int32)
    # delta_i = rowsum(dO ⊙ O): tiny elementwise+reduce — XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                              # (bh, s_q)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          has_lengths=has_lengths,
                          block_q=block_q, block_k=block_k, num_kv=num_kv,
                          kv_off=s_kv - s_q),
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
            _len_spec(),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta, lengths)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          has_lengths=has_lengths,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          kv_off=s_kv - s_q),
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, j, i: (b, i)),
            pl.BlockSpec((1, 1), lambda b, j, i: (b, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_kv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s_kv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta, lengths)
    return dq, dk, dv


# ---------------------------------------------------------------- public op
def _f0(x):
    import numpy as _np
    from jax import dtypes as _jd
    return _np.zeros(x.shape, _jd.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q3, k3, v3, lengths, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q3, k3, v3, lengths, scale, causal, block_q,
                        block_k, interpret)
    return out


def _flash_vjp_fwd(q3, k3, v3, lengths, scale, causal, block_q, block_k,
                   interpret):
    out, lse = _flash_fwd(q3, k3, v3, lengths, scale, causal, block_q,
                          block_k, interpret)
    return out, (q3, k3, v3, lengths, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q3, k3, v3, lengths, out, lse = res
    dq, dk, dv = _flash_bwd(q3, k3, v3, lengths, out, lse, do, scale,
                            causal, block_q, block_k, interpret)
    return (dq, dk, dv, None if lengths is None else _f0(lengths))


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None, lengths=None,
                    block_q=None, block_k=None, interpret=False):
    """Blockwise flash attention for (B, H, S, D) inputs.

    ``lengths``: optional (B,) int32 valid-KEY counts per sequence — keys
    at positions >= lengths[b] are masked out (padding mask); fully masked
    key blocks spend no FLOPs (the block body is predicated off; the
    block's K/V DMA still occurs — true block pruning would need
    scalar-prefetch grid shrinking).  With ``lengths=None`` the kernels
    compile the original dense code with zero masking overhead.  Requires S divisible by the block size (the ``sdpa_op``
    dispatcher falls back to the XLA-composed reference otherwise).
    ``interpret=True`` runs the Pallas interpreter so CPU CI exercises the
    same kernel code.
    """
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    if s_q % 128 or s_kv % 128:
        raise ValueError(
            f"flash_attention needs seq lengths divisible by 128, got "
            f"({s_q}, {s_kv}) — use sdpa_reference for ragged shapes")
    block_q = block_q or min(DEFAULT_BLOCK_Q, s_q)
    block_k = block_k or min(DEFAULT_BLOCK_K, s_kv)
    if s_q % block_q or s_kv % block_k:
        raise ValueError(
            f"flash_attention needs seq divisible by block "
            f"({s_q}, {s_kv}) vs ({block_q}, {block_k})")
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    q3 = q.reshape(b * h, s_q, d)
    k3 = k.reshape(b * h, s_kv, d)
    v3 = v.reshape(b * h, s_kv, d)
    if lengths is None:
        len3 = None    # static: kernels compile the dense straight-line path
    else:
        len3 = jnp.broadcast_to(
            jnp.asarray(lengths, jnp.int32).reshape(b, 1), (b, h)
        ).reshape(b * h, 1)
    out = _flash(q3, k3, v3, len3, scale, causal, block_q, block_k,
                 interpret)
    return out.reshape(b, h, s_q, d)
