"""Flash attention — Pallas TPU kernel (forward + backward).

The reference has no attention kernel at all (SURVEY.md §5.7): its
transformers compose batch_matmul + softmax ops, materialising the (S, S)
score matrix in HBM (and its BERT composes attention with explicit additive
masks — ``examples/transformers/bert/hetu_bert.py``).  This kernel is the
TPU-native replacement: blockwise online-softmax attention that keeps scores
in VMEM, with a custom VJP whose backward recomputes scores per block
(flash-attention-2 style), so memory is O(S·D) instead of O(S²).

Layout: inputs are (B, H, S, D); the kernel runs on (B·H, S, D) with a
sequential TPU grid (bh, q_block, kv_block) — accumulators live in VMEM
scratch and persist across the minor-most kv grid steps; outputs are written
once on the final kv step (standard TPU revisiting-grid pattern).

Masking/bias menu (every combination is a STATIC trace-time specialization,
so the dense hot path compiles the original straight-line code):

* ``causal``        — diagonal blocks masked, above-diagonal blocks pruned
                      via ``pl.when`` (no FLOPs);
* ``lengths``       — per-sequence valid-KEY counts (padding), SMEM scalar,
                      fully-padded key blocks pruned;
* ``key_mask``      — arbitrary per-key boolean mask (B, S_kv), loaded as
                      (1, block_k) column strips — O(S) memory, the BERT
                      padded-pretraining path;
* ``mask``          — full boolean mask broadcast as (1|B, 1|H, S_q, S_kv)
                      (XLNet two-stream perms), loaded blockwise without
                      materialising the broadcast;
* ``bias``          — additive logit bias broadcast likewise (T5 relative
                      position bias), differentiable: backward emits per-
                      block dbias tiles (dbias is inherently O(S²) — same
                      footprint as the bias itself).  A (·, ·, 1, S_kv)
                      row-broadcast bias is auto-routed to a per-key strip
                      path: O(S) loads forward, O(S) column-sum gradient
                      backward — never materialised to (S_q, S_kv).

Fully-masked rows/blocks produce ZERO output (not a uniform-softmax leak):
probabilities are multiplied by the block validity mask, so an all-masked
block contributes nothing even though exp(s - m) == 1 there.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
#: flash-legal sequence lengths are multiples of this (the Mosaic lane
#: width); ragged lengths are padded UP to the next bucket (128/256/384/…)
FLASH_BUCKET = 128


def flash_bucket(s):
    """Smallest flash-legal (bucketed) length >= ``s``."""
    return -(-int(s) // FLASH_BUCKET) * FLASH_BUCKET


def _pad_seq(x, axis, pad, value=0.0):
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


# ------------------------------------------------------------- index maps
def _g_index(gmode, heads):
    """Map the flattened (b·h) grid index to a broadcast-group row for a
    mask/bias stored un-broadcast as (G, S_q, S_kv):
    'one' G=1, 'h' G=H (shared over batch), 'b' G=B (shared over heads),
    'bh' G=B·H (full)."""
    return {
        "one": lambda bh: 0,
        "h": lambda bh: bh % heads,
        "b": lambda bh: bh // heads,
        "bh": lambda bh: bh,
    }[gmode]


def _extra_specs(order, heads, gmode_mask, gmode_bias, gmode_kbias, block_q,
                 block_k, *, has_lengths, has_kmask, has_kbias, has_fmask,
                 has_bias):
    """BlockSpecs for the optional inputs, in kernel-argument order.
    ``order`` maps grid indices to (bh, qi, ki) — the dkv kernel iterates
    (bh, ki, qi)."""
    specs = []
    if has_lengths:
        # stored (bh, 1, 1): block (1, 1, 1) keeps the last two dims equal
        # to the array's (the rank-2 (1, 1) block violated Mosaic tiling)
        specs.append(pl.BlockSpec(
            (1, 1, 1), lambda *g: (order(*g)[0], 0, 0),
            memory_space=pltpu.SMEM))
    if has_kmask:
        # stored (B, 1, S_kv): the unit middle dim keeps the block's last
        # two dims (1, block_k) legal under Mosaic's tiling rule (a
        # (1, block_k) block over a rank-2 (B, S_kv) array is NOT — the
        # sublane dim must divide 8 or equal the array dim)
        specs.append(pl.BlockSpec(
            (1, 1, block_k),
            lambda *g: (order(*g)[0] // heads, 0, order(*g)[2])))
    if has_kbias:
        # per-KEY additive bias, stored un-broadcast as (G, 1, S_kv) and
        # loaded as O(block_k) column strips — a (·, ·, 1, S_kv) bias never
        # materialises its (S_q, S_kv) broadcast (round-3 advisor finding)
        gkb = _g_index(gmode_kbias, heads)
        specs.append(pl.BlockSpec(
            (1, 1, block_k),
            lambda *g: (gkb(order(*g)[0]), 0, order(*g)[2])))
    if has_fmask:
        gm = _g_index(gmode_mask, heads)
        specs.append(pl.BlockSpec(
            (1, block_q, block_k),
            lambda *g: (gm(order(*g)[0]), order(*g)[1], order(*g)[2])))
    if has_bias:
        gb = _g_index(gmode_bias, heads)
        specs.append(pl.BlockSpec(
            (1, block_q, block_k),
            lambda *g: (gb(order(*g)[0]), order(*g)[1], order(*g)[2])))
    return specs


# ---------------------------------------------------------------- masking
def _block_logits(qi, ki, q, k, len_ref, kmask_ref, kbias_ref, fmask_ref,
                  bias_ref, *, scale, causal, block_q, block_k, kv_off):
    """Masked+biased logits for one (qi, ki) block → (s, valid).

    ``valid`` is None on the pure-dense path (no masking of any kind) so
    the hot path keeps the original straight-line code; otherwise it is the
    boolean validity of every score — callers MUST multiply probabilities
    by it (exp(s - m) == 1 on an all-masked block, which would otherwise
    leak a uniform average of the value vectors)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # (bq, bk)
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    if kbias_ref is not None:
        # (1, 1, block_k) strip broadcasts over the query rows
        s = s + kbias_ref[0].astype(jnp.float32)
    valid = None

    def _and(a, b):
        return b if a is None else jnp.logical_and(a, b)

    if len_ref is not None:
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + ki * block_k
        valid = _and(valid, cols < len_ref[0, 0, 0])
    if kmask_ref is not None:
        # (1, 1, block_k) block → (1, block_k) load broadcasts over rows
        valid = _and(valid, kmask_ref[0] != 0)
    if fmask_ref is not None:
        valid = _and(valid, fmask_ref[0] != 0)
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) \
            + qi * block_q + kv_off
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) \
            + ki * block_k
        valid = _and(valid, rows >= cols)
    if valid is not None:
        s = jnp.where(valid, s, NEG_INF)
    return s, valid


def _live(qi, ki, len_ref, *, causal, block_q, block_k, kv_off):
    """Block-prune predicate: blocks entirely above the causal diagonal or
    entirely past the valid-key count are skipped (no FLOPs).  key_mask /
    full-mask blocks are never pruned (their validity is vector data)."""
    live = (qi * block_q + block_q - 1 + kv_off >= ki * block_k) \
        if causal else True
    if len_ref is not None:
        cond = ki * block_k < len_ref[0, 0, 0]
        live = cond if live is True else jnp.logical_and(live, cond)
    return live


def _unpack(refs, *, has_lengths, has_kmask, has_kbias, has_fmask, has_bias):
    """Split the flat pallas ref list into (fixed-ins, extras, outs+scratch).
    Optional inputs are present only when their static flag is set, keeping
    the kernel arity minimal per specialization."""
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    len_ref = kmask_ref = kbias_ref = fmask_ref = bias_ref = None
    if has_lengths:
        len_ref = refs[i]; i += 1                       # noqa: E702
    if has_kmask:
        kmask_ref = refs[i]; i += 1                     # noqa: E702
    if has_kbias:
        kbias_ref = refs[i]; i += 1                     # noqa: E702
    if has_fmask:
        fmask_ref = refs[i]; i += 1                     # noqa: E702
    if has_bias:
        bias_ref = refs[i]; i += 1                      # noqa: E702
    return (q_ref, k_ref, v_ref), \
        (len_ref, kmask_ref, kbias_ref, fmask_ref, bias_ref), refs[i:]


# ---------------------------------------------------------------- forward
def _fwd_kernel(*refs, scale, causal, flags, block_q, block_k, num_kv,
                kv_off):
    (q_ref, k_ref, v_ref), extras, rest = _unpack(refs, **flags)
    len_ref, kmask_ref, kbias_ref, fmask_ref, bias_ref = extras
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    live = _live(qi, ki, len_ref, causal=causal, block_q=block_q,
                 block_k=block_k, kv_off=kv_off)

    @pl.when(live)
    def _block():
        q = q_ref[0]                                   # (bq, d)
        k = k_ref[0]                                   # (bk, d)
        v = v_ref[0]                                   # (bk, d)
        s, valid = _block_logits(
            qi, ki, q, k, len_ref, kmask_ref, kbias_ref, fmask_ref,
            bias_ref, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_off=kv_off)
        m_prev = m_scr[:, :1]                          # (bq, 1)
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # (bq, bk)
        if valid is not None:
            p = p * valid                               # no all-masked leak
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_kv - 1)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # lse is (bh, s_q, 1): sublane-oriented column write — no
        # in-kernel transpose, no 128x lane broadcast in HBM
        lse_ref[0] = m_scr[:, :1] + jnp.log(l_safe)


def _flash_fwd(q, k, v, lengths, kmask, kbias, fmask, bias, scale, causal,
               gmode_mask, gmode_bias, gmode_kbias, heads, block_q, block_k,
               interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    num_q = s_q // block_q
    num_kv = s_kv // block_k
    grid = (bh, num_q, num_kv)
    flags = dict(has_lengths=lengths is not None, has_kmask=kmask is not None,
                 has_kbias=kbias is not None, has_fmask=fmask is not None,
                 has_bias=bias is not None)
    inputs = [q, k, v] + [x for x in (lengths, kmask, kbias, fmask, bias)
                          if x is not None]

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, flags=flags,
        block_q=block_q, block_k=block_k, num_kv=num_kv,
        kv_off=s_kv - s_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ] + _extra_specs(lambda b, i, j: (b, i, j), heads, gmode_mask,
                         gmode_bias, gmode_kbias, block_q, block_k, **flags),
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------- backward
def _dq_kernel(*refs, scale, causal, flags, emit_dbias, block_q, block_k,
               num_kv, kv_off):
    (q_ref, k_ref, v_ref), extras, rest = _unpack(refs, **flags)
    len_ref, kmask_ref, kbias_ref, fmask_ref, bias_ref = extras
    do_ref, lse_ref, delta_ref = rest[:3]
    rest = rest[3:]
    if emit_dbias:
        dq_ref, dbias_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
        dbias_ref = None
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = _live(qi, ki, len_ref, causal=causal, block_q=block_q,
                 block_k=block_k, kv_off=kv_off)
    live_static = live is True

    def _body(write_dbias):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]                                  # (bq, d)
        lse = lse_ref[0]                                # (bq, 1)
        delta = delta_ref[0]                            # (bq, 1)
        s, valid = _block_logits(
            qi, ki, q, k, len_ref, kmask_ref, kbias_ref, fmask_ref,
            bias_ref, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_off=kv_off)
        p = jnp.exp(s - lse)                            # (bq, bk)
        if valid is not None:
            p = p * valid
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, bk)
        t = p * (dp - delta)       # = dL/d(logits) block (pre-scale)
        if write_dbias:
            dbias_ref[0] = t.astype(dbias_ref.dtype)
        ds = t * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if live_static:
        _body(emit_dbias)
    else:
        @pl.when(live)
        def _b():
            _body(emit_dbias)
        if emit_dbias:
            # pruned blocks must still define their dbias tile
            @pl.when(jnp.logical_not(live))
            def _z():
                dbias_ref[0] = jnp.zeros_like(dbias_ref[0])

    @pl.when(ki == num_kv - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, causal, flags, emit_dkbias, block_q, block_k,
                num_q, kv_off):
    (q_ref, k_ref, v_ref), extras, rest = _unpack(refs, **flags)
    len_ref, kmask_ref, kbias_ref, fmask_ref, bias_ref = extras
    if emit_dkbias:
        (do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dkb_ref,
         dk_scr, dv_scr, dkb_scr) = rest
    else:
        do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
        dkb_ref = dkb_scr = None
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)
        if emit_dkbias:
            dkb_scr[:] = jnp.zeros_like(dkb_scr)

    live = _live(qi, ki, len_ref, causal=causal, block_q=block_q,
                 block_k=block_k, kv_off=kv_off)

    @pl.when(live)
    def _block():
        q = q_ref[0]                                    # (bq, d)
        k = k_ref[0]                                    # (bk, d)
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                                 # (bq, 1)
        delta = delta_ref[0]                             # (bq, 1)
        s, valid = _block_logits(
            qi, ki, q, k, len_ref, kmask_ref, kbias_ref, fmask_ref,
            bias_ref, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, kv_off=kv_off)
        p = jnp.exp(s - lse)                             # (bq, bk)
        if valid is not None:
            p = p * valid
        # dV += P^T @ dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # (bq, bk)
        t = p * (dp - delta)            # dL/d(logits) block (pre-scale)
        if emit_dkbias:
            # d(key-bias)[k] = sum over query rows of t — accumulated
            # across this ki column's q blocks (broadcast over the scratch
            # sublanes; row 0 is written out)
            dkb_scr[:] += jnp.broadcast_to(
                jnp.sum(t, axis=0, keepdims=True), dkb_scr.shape)
        ds = t * scale                                   # (bq, bk)
        # dK += dS^T @ Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)
        if emit_dkbias:
            dkb_ref[0] = dkb_scr[:1]


def _flash_bwd(q, k, v, lengths, kmask, kbias, fmask, bias, out, lse, do,
               scale, causal, gmode_mask, gmode_bias, gmode_kbias, heads,
               block_q, block_k, interpret):
    bh, s_q, d = q.shape
    s_kv = k.shape[1]
    num_q = s_q // block_q
    num_kv = s_kv // block_k
    flags = dict(has_lengths=lengths is not None, has_kmask=kmask is not None,
                 has_kbias=kbias is not None, has_fmask=fmask is not None,
                 has_bias=bias is not None)
    emit_dbias = bias is not None
    emit_dkbias = kbias is not None
    extras = [x for x in (lengths, kmask, kbias, fmask, bias)
              if x is not None]
    # delta_i = rowsum(dO ⊙ O): tiny elementwise+reduce — XLA fuses it.
    # Shaped (bh, s_q, 1) like lse: the unit lane dim keeps the row
    # blocks legal under Mosaic tiling AND reads back in sublane
    # orientation (no in-kernel transpose).
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[..., None]                   # (bh, s_q, 1)

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq_outs = [qspec]
    dq_shapes = [jax.ShapeDtypeStruct((bh, s_q, d), q.dtype)]
    if emit_dbias:
        # dbias is dense — O(B·H·S²) like the score matrix; unavoidable,
        # the bias gradient has that shape before broadcast-reduction
        dq_outs.append(pl.BlockSpec((1, block_q, block_k),
                                    lambda b, i, j: (b, i, j)))
        dq_shapes.append(jax.ShapeDtypeStruct((bh, s_q, s_kv), jnp.float32))
    res = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          flags=flags, emit_dbias=emit_dbias,
                          block_q=block_q, block_k=block_k, num_kv=num_kv,
                          kv_off=s_kv - s_q),
        grid=(bh, num_q, num_kv),
        in_specs=[qspec, kspec, kspec]
        + _extra_specs(lambda b, i, j: (b, i, j), heads, gmode_mask,
                       gmode_bias, gmode_kbias, block_q, block_k, **flags)
        + [qspec, rowspec, rowspec],
        out_specs=dq_outs if emit_dbias else dq_outs[0],
        out_shape=dq_shapes if emit_dbias else dq_shapes[0],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, *extras, do, lse, delta)
    if emit_dbias:
        dq, dbias = res
    else:
        dq, dbias = res, None

    # dkv iterates (bh, kv_block, q_block): remap grid→(bh, qi, ki)
    order = lambda b, j, i: (b, i, j)                    # noqa: E731
    dkv_outs = [
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
    ]
    dkv_shapes = [
        jax.ShapeDtypeStruct((bh, s_kv, d), k.dtype),
        jax.ShapeDtypeStruct((bh, s_kv, d), v.dtype),
    ]
    dkv_scratch = [
        pltpu.VMEM((block_k, d), jnp.float32),
        pltpu.VMEM((block_k, d), jnp.float32),
    ]
    if emit_dkbias:
        # O(S) per bh: column-strip gradient, reduced over the broadcast
        # group by the VJP wrapper
        dkv_outs.append(pl.BlockSpec((1, 1, block_k),
                                     lambda b, j, i: (b, 0, j)))
        dkv_shapes.append(jax.ShapeDtypeStruct((bh, 1, s_kv), jnp.float32))
        dkv_scratch.append(pltpu.VMEM((8, block_k), jnp.float32))
    res2 = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          flags=flags, emit_dkbias=emit_dkbias,
                          block_q=block_q, block_k=block_k,
                          num_q=num_q, kv_off=s_kv - s_q),
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ] + _extra_specs(order, heads, gmode_mask, gmode_bias, gmode_kbias,
                         block_q, block_k, **flags)
        + [
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=dkv_outs,
        out_shape=dkv_shapes,
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(q, k, v, *extras, do, lse, delta)
    if emit_dkbias:
        dk, dv, dkbias = res2
    else:
        (dk, dv), dkbias = res2, None
    return dq, dk, dv, dbias, dkbias


# ---------------------------------------------------------------- public op
def _f0(x):
    import numpy as _np
    from jax import dtypes as _jd
    return _np.zeros(x.shape, _jd.float0)


def _group_reduce(d, gmode, b, heads, shape, dtype):
    """Sum a per-(b·h) gradient over its broadcast group → original
    storage shape."""
    g = d.reshape(b, heads, *d.shape[1:])
    if gmode == "one":
        d = g.sum(axis=(0, 1))[None]
    elif gmode == "h":
        d = g.sum(axis=0)
    elif gmode == "b":
        d = g.sum(axis=1)
    return d.reshape(shape).astype(dtype)


_STATIC = (8, 9, 10, 11, 12, 13, 14, 15, 16)


@functools.partial(jax.custom_vjp, nondiff_argnums=_STATIC)
def _flash(q3, k3, v3, lengths, kmask, kbias, fmask, bias, scale, causal,
           gmode_mask, gmode_bias, gmode_kbias, heads, block_q, block_k,
           interpret):
    out, _ = _flash_fwd(q3, k3, v3, lengths, kmask, kbias, fmask, bias,
                        scale, causal, gmode_mask, gmode_bias, gmode_kbias,
                        heads, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q3, k3, v3, lengths, kmask, kbias, fmask, bias, scale,
                   causal, gmode_mask, gmode_bias, gmode_kbias, heads,
                   block_q, block_k, interpret):
    out, lse = _flash_fwd(q3, k3, v3, lengths, kmask, kbias, fmask, bias,
                          scale, causal, gmode_mask, gmode_bias, gmode_kbias,
                          heads, block_q, block_k, interpret)
    return out, (q3, k3, v3, lengths, kmask, kbias, fmask, bias, out, lse)


def _flash_vjp_bwd(scale, causal, gmode_mask, gmode_bias, gmode_kbias, heads,
                   block_q, block_k, interpret, res, do):
    q3, k3, v3, lengths, kmask, kbias, fmask, bias, out, lse = res
    dq, dk, dv, dbias, dkbias = _flash_bwd(
        q3, k3, v3, lengths, kmask, kbias, fmask, bias, out, lse, do, scale,
        causal, gmode_mask, gmode_bias, gmode_kbias, heads, block_q, block_k,
        interpret)
    b = q3.shape[0] // heads
    if bias is not None:
        # reduce the dense (B·H, S, S) tile grads over the broadcast group
        dbias = _group_reduce(dbias, gmode_bias, b, heads, bias.shape,
                              bias.dtype)
    if kbias is not None:
        dkbias = _group_reduce(dkbias, gmode_kbias, b, heads, kbias.shape,
                               kbias.dtype)
    return (dq, dk, dv,
            None if lengths is None else _f0(lengths),
            None if kmask is None else _f0(kmask),
            None if kbias is None else dkbias,
            None if fmask is None else _f0(fmask),
            None if bias is None else dbias)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _classify_group(x, b, h, s_q, s_kv, name):
    """Validate a (1|B, 1|H, S_q|1, S_kv)-broadcastable tensor and return
    its broadcast-group mode — the ONE place group semantics live (the
    dense-bias and key-bias paths both classify through here)."""
    if x.ndim != 4:
        raise ValueError(f"{name} must be rank-4 broadcastable, "
                         f"got {x.shape}")
    xb, xh, xq, xk = x.shape
    if xk != s_kv or xq not in (1, s_q) or xb not in (1, b) \
            or xh not in (1, h):
        raise ValueError(f"{name} shape {x.shape} not broadcastable to "
                         f"({b}, {h}, {s_q}, {s_kv})")
    return {(True, True): "one", (True, False): "h",
            (False, True): "b", (False, False): "bh"}[(xb == 1, xh == 1)]


def _broadcast_group(x, b, h, s_q, s_kv, name):
    """Classify into un-broadcast (G, S_q, S_kv) storage + gmode — no
    materialisation of the broadcast (beyond q-row expansion)."""
    gmode = _classify_group(x, b, h, s_q, s_kv, name)
    if x.shape[2] == 1 and s_q != 1:
        x = jnp.broadcast_to(
            x, (x.shape[0], x.shape[1], s_q, s_kv))  # rows only: O(S²/Sq)
    return x.reshape(-1, s_q, s_kv), gmode


def flash_attention(q, k, v, causal=False, scale=None, lengths=None,
                    key_mask=None, mask=None, bias=None,
                    block_q=None, block_k=None, interpret=False):
    """Blockwise flash attention for (B, H, S, D) inputs.

    ``lengths``: optional (B,) int32 valid-KEY counts per sequence — keys
    at positions >= lengths[b] are masked out (padding mask); fully masked
    key blocks spend no FLOPs (the block body is predicated off; the
    block's K/V DMA still occurs — true block pruning would need
    scalar-prefetch grid shrinking).
    ``key_mask``: optional (B, S_kv) (or (B, 1, 1, S_kv)) boolean per-key
    mask — the general padding-mask form when validity is not a prefix.
    ``mask``: optional full boolean mask, broadcastable
    (1|B, 1|H, 1|S_q, S_kv); loaded blockwise without materialising the
    broadcast.
    ``bias``: optional additive logit bias, same broadcast menu,
    differentiable (T5 relative position bias).
    With none of these the kernels compile the original dense
    straight-line code with zero masking overhead.  Ragged (non-128-
    multiple) sequence lengths are BUCKETED: padded up to the next
    flash-legal bucket (128/256/384/…), the pad keys masked through the
    kernel's existing lengths/key-mask strip path, and the output sliced
    back to the caller's length — ``seq=384+r`` stays on the fast path.
    The one unbucketable case is causal CROSS-attention whose lengths
    differ mod 128 (padding would shift the bottom-right-aligned
    diagonal); that raises, and the dispatcher falls back with an
    explicit ``flash_fallback_reason``.  ``interpret=True`` runs the
    Pallas interpreter so CPU CI exercises the same kernel code.
    """
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    pad_q = flash_bucket(s_q) - s_q
    pad_k = flash_bucket(s_kv) - s_kv
    if causal and pad_q != pad_k:
        # padding q and kv by different amounts would move the kernel's
        # kv_off diagonal against the reference's tril(s_kv - s_q)
        raise ValueError(
            f"causal flash attention cannot bucket lengths ({s_q}, {s_kv})"
            f" — they differ mod {FLASH_BUCKET}, so padding would shift "
            f"the bottom-right-aligned diagonal")
    s_q_orig = s_q
    if pad_q or pad_k:
        q = _pad_seq(q, 2, pad_q)
        k = _pad_seq(k, 2, pad_k)
        v = _pad_seq(v, 2, pad_k)
        if pad_k:
            # pad KEYS must be invisible: ``lengths`` already masks cols
            # >= lengths[b] <= s_kv; a given key_mask/mask extends with
            # invalid columns; with no key validity input at all, the pad
            # rides the O(1) SMEM lengths path (fully-padded key blocks
            # are pruned, not computed)
            if key_mask is not None:
                km = jnp.asarray(key_mask)
                km = _pad_seq(km, km.ndim - 1, pad_k,
                              value=jnp.zeros((), km.dtype))
                key_mask = km
            if mask is not None and jnp.ndim(mask) == 4:
                m = jnp.asarray(mask)
                m = _pad_seq(m, 3, pad_k, value=jnp.zeros((), m.dtype))
                mask = m
            if lengths is None and key_mask is None and mask is None:
                lengths = jnp.full((b,), s_kv, jnp.int32)
            if bias is not None and jnp.ndim(bias) == 4:
                bias = _pad_seq(jnp.asarray(bias, jnp.float32), 3, pad_k)
        if pad_q:
            # pad QUERY rows compute garbage that is sliced off below;
            # their kernel inputs only need legal shapes
            if mask is not None and jnp.ndim(mask) == 4 \
                    and mask.shape[2] != 1:
                mask = _pad_seq(jnp.asarray(mask), 2, pad_q,
                                value=jnp.zeros((), jnp.asarray(mask).dtype))
            if bias is not None and jnp.ndim(bias) == 4 \
                    and bias.shape[2] != 1:
                bias = _pad_seq(jnp.asarray(bias, jnp.float32), 2, pad_q)
        s_q += pad_q
        s_kv += pad_k
    block_q = block_q or min(DEFAULT_BLOCK_Q, s_q)
    block_k = block_k or min(DEFAULT_BLOCK_K, s_kv)
    if s_q % block_q or s_kv % block_k:
        raise ValueError(
            f"flash_attention needs seq divisible by block "
            f"({s_q}, {s_kv}) vs ({block_q}, {block_k})")
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    q3 = q.reshape(b * h, s_q, d)
    k3 = k.reshape(b * h, s_kv, d)
    v3 = v.reshape(b * h, s_kv, d)
    if lengths is None:
        len3 = None    # static: kernels compile the dense straight-line path
    else:
        len3 = jnp.broadcast_to(
            jnp.asarray(lengths, jnp.int32).reshape(b, 1), (b, h)
        ).reshape(b * h, 1, 1)
    gmode_mask = gmode_bias = gmode_kbias = "one"
    kmask2 = kbias3 = fmask3 = bias3 = None
    if key_mask is not None:
        km = jnp.asarray(key_mask)
        if km.ndim == 4:     # (B, 1, 1, S_kv) attention-mask convention
            km = km.reshape(km.shape[0], km.shape[-1])
        if km.shape != (b, s_kv):
            raise ValueError(f"key_mask must be (B, S_kv), got "
                            f"{key_mask.shape}")
        # stored (B, 1, S_kv) — see the kmask BlockSpec note
        kmask2 = km.astype(jnp.int32)[:, None, :]
    if mask is not None:
        fmask3, gmode_mask = _broadcast_group(
            jnp.asarray(mask).astype(jnp.int32), b, h, s_q, s_kv, "mask")
    if bias is not None:
        ba = jnp.asarray(bias, jnp.float32)
        if ba.ndim == 4 and ba.shape[2] == 1 and s_q != 1:
            # per-KEY (row-broadcast) bias: O(S) column strips, never
            # materialised to (S_q, S_kv) (round-3 advisor finding)
            gmode_kbias = _classify_group(ba, b, h, s_q, s_kv, "bias")
            kbias3 = ba.reshape(-1, 1, s_kv)
        else:
            bias3, gmode_bias = _broadcast_group(ba, b, h, s_q, s_kv, "bias")
    out = _flash(q3, k3, v3, len3, kmask2, kbias3, fmask3, bias3, scale,
                 causal, gmode_mask, gmode_bias, gmode_kbias, h, block_q,
                 block_k, interpret)
    out = out.reshape(b, h, s_q, d)
    if s_q != s_q_orig:
        out = out[:, :, :s_q_orig]    # unpad: bucketing is caller-invisible
    return out
