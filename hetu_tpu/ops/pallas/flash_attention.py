"""Flash attention — Pallas TPU kernel (placeholder lowering for now).

Falls back to the fused-XLA reference attention until the blockwise kernel
lands; the call signature is stable so callers don't change.
"""


def flash_attention(q, k, v, causal=False, scale=None):
    from ..attention import sdpa_reference
    return sdpa_reference(q, k, v, causal=causal, scale=scale)
