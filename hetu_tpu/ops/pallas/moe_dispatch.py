"""Sparse MoE dispatch/combine — Pallas row-gather kernel.

The dense GShard dispatch in :mod:`hetu_tpu.ops.moe` materialises (s, e, c)
one-hot tensors, whose memory/FLOPs grow with expert count × capacity —
fine for small expert pools, ruinous for large ones.  This module replaces
both layout transforms with index maps + a single Pallas primitive:

    row_gather(src, idx)[i] = src[idx[i]]   (zeros where idx < 0)

implemented as per-row async DMA from HBM (the rows of one block are all
in flight before the first wait).  Both directions of both transforms are
gathers given the forward (slot→token) and inverse (token→slot) maps, so
no scatter is ever emitted:

    dispatch fwd:  buffers[j]  = tokens[token_of_slot[j]]
    dispatch bwd:  d_tokens[t] = Σ_k d_buffers[slot_of_token[t, k]]
    combine  fwd:  out[t]      = Σ_k w[t,k] · buffers[slot_of_token[t, k]]
    combine  bwd:  d_buffers[j]= w_of_slot[j] · d_out[token_of_slot[j]]

Reference parity: LayoutTransform.cu / ReverseLayoutTransform.cu (Tutel
scatter kernels, SURVEY.md §2.6) — redesigned as gathers because TPU DMA
has no scatter engine but a sequential grid makes gather-by-index cheap.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import dtypes as jdtypes
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROW_BLOCK = 32


def _gather_kernel(idx_ref, src_ref, out_ref, sems, *, block):
    b = pl.program_id(0)
    for i in range(block):
        row = idx_ref[b * block + i]

        @pl.when(row >= 0)
        def _start(i=i, row=row):
            pltpu.make_async_copy(
                src_ref.at[row], out_ref.at[i], sems.at[i]).start()

        @pl.when(row < 0)
        def _zero(i=i):
            out_ref[i, :] = jnp.zeros((out_ref.shape[1],), out_ref.dtype)

    for i in range(block):
        row = idx_ref[b * block + i]

        @pl.when(row >= 0)
        def _wait(i=i, row=row):
            pltpu.make_async_copy(
                src_ref.at[row], out_ref.at[i], sems.at[i]).wait()


def row_gather(src, idx, block=ROW_BLOCK, interpret=False):
    """out[i] = src[idx[i]] (rows; idx < 0 → zeros).  Non-differentiable —
    callers wire their own VJP from the inverse index map."""
    n = idx.shape[0]
    m = src.shape[1]
    n_pad = -(-n // block) * block
    idx_p = jnp.full((n_pad,), -1, jnp.int32).at[:n].set(idx.astype(jnp.int32))
    out = pl.pallas_call(
        functools.partial(_gather_kernel, block=block),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_pad // block,),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec((block, m), lambda g, *_: (g, 0)),
            scratch_shapes=[pltpu.SemaphoreType.DMA((block,))],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, m), src.dtype),
        interpret=interpret,
    )(idx_p, src)
    return out[:n]


def _f0(x):
    """float0 cotangent for integer primals (custom_vjp requirement)."""
    return np.zeros(x.shape, jdtypes.float0)


# ------------------------------------------------------------- dispatch
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def sparse_dispatch(tokens, token_of_slot, slot_of_token, interpret=False):
    """tokens (s, m) → expert buffers (n_slots, m).

    ``token_of_slot``: (n_slots,) int32, -1 for empty slots.
    ``slot_of_token``: (s, k) int32, -1 where the token was dropped.
    """
    return row_gather(tokens, token_of_slot, interpret=interpret)


def _dispatch_fwd(tokens, token_of_slot, slot_of_token, interpret):
    return (row_gather(tokens, token_of_slot, interpret=interpret),
            (token_of_slot, slot_of_token))


def _dispatch_bwd(interpret, res, g):
    token_of_slot, slot_of_token = res
    k = slot_of_token.shape[1]
    d_tokens = row_gather(g, slot_of_token[:, 0], interpret=interpret)
    for j in range(1, k):
        d_tokens = d_tokens + row_gather(g, slot_of_token[:, j],
                                         interpret=interpret)
    return d_tokens, _f0(token_of_slot), _f0(slot_of_token)


sparse_dispatch.defvjp(_dispatch_fwd, _dispatch_bwd)


# -------------------------------------------------------------- combine
@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def sparse_combine(buffers, w, slot_of_token, token_of_slot, k_of_slot,
                   interpret=False):
    """buffers (n_slots, m), gate weights w (s, k) → tokens out (s, m).

    ``k_of_slot``: (n_slots,) which of the token's k routes this slot is.
    """
    out = 0.0
    for j in range(w.shape[1]):
        part = row_gather(buffers, slot_of_token[:, j], interpret=interpret)
        out = out + w[:, j:j + 1] * part
    return out


def _combine_fwd(buffers, w, slot_of_token, token_of_slot, k_of_slot,
                 interpret):
    out = sparse_combine(buffers, w, slot_of_token, token_of_slot, k_of_slot,
                         interpret)
    return out, (buffers, w, slot_of_token, token_of_slot, k_of_slot)


def _combine_bwd(interpret, res, g):
    buffers, w, slot_of_token, token_of_slot, k_of_slot = res
    k = w.shape[1]
    # d_w[t, j] = <g[t], buffers[slot_of_token[t, j]]>  (gather recompute)
    dw_cols = []
    for j in range(k):
        part = row_gather(buffers, slot_of_token[:, j], interpret=interpret)
        dw_cols.append(jnp.sum(g * part, axis=-1))
    d_w = jnp.stack(dw_cols, axis=1).astype(w.dtype)
    # d_buffers[slot] = w_of_slot · g[token_of_slot]
    valid = token_of_slot >= 0
    t_safe = jnp.maximum(token_of_slot, 0)
    w_of_slot = jnp.where(
        valid, w[t_safe, jnp.clip(k_of_slot, 0, k - 1)], 0.0)
    gm = row_gather(g, token_of_slot, interpret=interpret)
    d_buffers = (gm * w_of_slot[:, None]).astype(buffers.dtype)
    return (d_buffers, d_w, _f0(slot_of_token), _f0(token_of_slot),
            _f0(k_of_slot))


sparse_combine.defvjp(_combine_fwd, _combine_bwd)
