"""Sorted-segment row-sum — Pallas TPU kernel (embedding scatter-add).

TPU-native replacement for the reference's sparse-gradient machinery
(``IndexedSlices.cpu_deduplicate`` ndarray.py:507, ``OptimizersSparse.cu``):
duplicate embedding-row gradients are summed by (1) sorting rows by id in
XLA (fast bitonic sort on TPU) and (2) reducing each sorted run in this
kernel.  Per token block the reduction is ONE MXU matmul — a (bt × bt)
0/1 segment-indicator contracted with the (bt × d) row block — so the whole
scatter-add is matmul-shaped instead of serialized row updates.  A run that
spans block boundaries is carried forward in VMEM scratch (the sequential
TPU grid makes the carry exact), and each block DMA-writes its window of
completed segment sums to the output in HBM.

Used by the PS embedding push path (dedup before host transfer) and
available as ``sorted_segment_sum`` for any segment-reduce.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _seg_kernel(seg_ref, rows_ref, out_ref, partial, carry_row, carry_seg,
                sem, *, block, num_blocks):
    b = pl.program_id(0)
    seg = seg_ref[:]                                   # (bt, 1) int32
    seg_first = seg[0, 0]
    seg_last = seg[block - 1, 0]
    local = seg - seg_first                            # (bt, 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    onehot = (local == cols).astype(jnp.float32)       # (bt, W=bt)
    partial[:] = jax.lax.dot_general(
        onehot, rows_ref[:].astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # (W, d)

    @pl.when((b > 0) & (seg_first == carry_seg[0]))
    def _merge_carry():
        partial[0, :] += carry_row[0, :]

    # stash the (possibly incomplete) last run for the next block
    local_open = seg_last - seg_first
    carry_row[0, :] = partial[pl.ds(local_open, 1), :][0, :]
    carry_seg[0] = seg_last

    # write this block's window; later blocks overwrite any rows whose run
    # continues past the boundary (sequential grid ⇒ last write wins)
    cp = pltpu.make_async_copy(partial, out_ref.at[pl.ds(seg_first, block)],
                               sem)
    cp.start()
    cp.wait()


def sorted_segment_sum(rows, seg_ids, num_segments, block=128,
                       interpret=False):
    """Sum ``rows`` (n, d) over sorted, contiguous ``seg_ids`` (n,) int32.

    ``seg_ids`` MUST be non-decreasing starting at 0 (sort upstream).
    Returns (num_segments, d) float32.
    """
    n, d = rows.shape
    n_pad = -(-n // block) * block
    if n_pad != n:
        last = seg_ids[-1]
        seg_ids = jnp.concatenate(
            [seg_ids, jnp.full((n_pad - n,), last, jnp.int32)])
        rows = jnp.concatenate(
            [rows, jnp.zeros((n_pad - n, d), rows.dtype)])
    num_blocks = n_pad // block
    out = pl.pallas_call(
        functools.partial(_seg_kernel, block=block, num_blocks=num_blocks),
        grid=(num_blocks,),
        in_specs=[
            pl.BlockSpec((block, 1), lambda b: (b, 0)),
            pl.BlockSpec((block, d), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((num_segments + block, d),
                                       jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),    # window partials
            pltpu.VMEM((1, d), jnp.float32),        # carry row
            pltpu.SMEM((1,), jnp.int32),            # carry segment id
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(seg_ids.astype(jnp.int32)[:, None], rows)
    # rows past the last actual segment are uninitialised HBM (blocks only
    # DMA their own windows) — zero them so the padding contract holds
    n_actual = seg_ids[-1] + 1
    valid = jnp.arange(num_segments)[:, None] < n_actual
    return jnp.where(valid, out[:num_segments], 0.0)


def dedup_rows(ids, rows, interpret=False):
    """Sum rows sharing an id (reference ``cpu_deduplicate``).

    Returns (unique_ids (n,), summed (n, d), n_unique) — padded to the
    static input length with id -1 / zero rows (XLA static shapes).
    """
    n, d = rows.shape
    order = jnp.argsort(ids)
    sid = jnp.take(ids, order).astype(jnp.int32)
    r = jnp.take(rows, order, axis=0)
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(starts.astype(jnp.int32)) - 1          # (n,)
    summed = sorted_segment_sum(r, seg, n, interpret=interpret)
    n_unique = seg[-1] + 1
    uniq = jnp.full((n,), -1, jnp.int32).at[seg].set(sid)
    return uniq, summed, n_unique
