"""Reduction ops (reference: ReduceSum.cu, ReduceMean.cu, ReduceSumAxisZero.cu,
Sum op ``gpu_ops/Sum.py``)."""
import jax.numpy as jnp
import numpy as np

from .base import def_op, SimpleOp


def _reduce_shape(fn):
    def shape(a, axes=None, keepdims=False):
        return tuple(fn(np.empty(a), axis=tuple(axes) if isinstance(axes, (list, tuple)) else axes,
                        keepdims=keepdims).shape)
    return shape


def _norm_axes(axes):
    if isinstance(axes, (list, tuple)):
        return tuple(axes)
    return axes


reduce_sum_op = def_op(
    "ReduceSum",
    lambda c, a, axes=None, keepdims=False: jnp.sum(a, axis=_norm_axes(axes), keepdims=keepdims),
    _reduce_shape(np.sum))

reduce_mean_op = def_op(
    "ReduceMean",
    lambda c, a, axes=None, keepdims=False: jnp.mean(a, axis=_norm_axes(axes), keepdims=keepdims),
    _reduce_shape(np.mean))

reducesumaxiszero_op = def_op(
    "ReduceSumAxisZero", lambda c, a: jnp.sum(a, axis=0),
    lambda a: tuple(a[1:]))


def sum_op(node_list, ctx=None, name=None):
    """Elementwise sum of a list of nodes (reference ``gpu_ops/Sum.py``)."""
    return SimpleOp("Sum", list(node_list),
                    lambda c, *vals: sum(vals[1:], vals[0]), name=name)
