"""Recurrent ops lowered to ``lax.scan`` (TPU-friendly static control flow).

Reference: ``examples/rnn/models/`` composes RNNs from per-timestep matmul/
slice ops in Python; here the whole sequence is ONE scanned XLA loop — the
compiler-friendly equivalent (no per-step op dispatch, weights stay in
registers/VMEM across steps).

Layout: inputs (batch, time, features); hidden state (batch, hidden).
Weights follow the torch convention: w_ih (in, 4H/3H/H), w_hh (H, ...),
bias (4H/3H/H,). Returns the full output sequence (batch, time, H).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import def_op


def _scan_time(cell, x, init_carry):
    xt = jnp.swapaxes(x, 0, 1)  # (T, B, F) for scan

    def body(carry, x_t):
        carry, out = cell(carry, x_t)
        return carry, out

    _, outs = jax.lax.scan(body, init_carry, xt)
    return jnp.swapaxes(outs, 0, 1)  # back to (B, T, H)


def _rnn(c, x, w_ih, w_hh, b, activation="tanh"):
    H = w_hh.shape[0]
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    h0 = jnp.zeros((x.shape[0], H), x.dtype)

    def cell(h, x_t):
        h = act(x_t @ w_ih + h @ w_hh + b)
        return h, h

    return _scan_time(cell, x, h0)


rnn_op = def_op(
    "RNN", _rnn,
    lambda x, w_ih, w_hh, b, activation="tanh": (x[0], x[1], w_hh[0]))


def _lstm(c, x, w_ih, w_hh, b):
    H = w_hh.shape[0]
    B = x.shape[0]
    init = (jnp.zeros((B, H), x.dtype), jnp.zeros((B, H), x.dtype))

    def cell(carry, x_t):
        h, cs = carry
        gates = x_t @ w_ih + h @ w_hh + b
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        cs = f * cs + i * g
        h = o * jnp.tanh(cs)
        return (h, cs), h

    return _scan_time(cell, x, init)


lstm_op = def_op("LSTM", _lstm,
                 lambda x, w_ih, w_hh, b: (x[0], x[1], w_hh[0]))


def _gru(c, x, w_ih, w_hh, b):
    H = w_hh.shape[0]
    h0 = jnp.zeros((x.shape[0], H), x.dtype)

    def cell(h, x_t):
        gi = x_t @ w_ih + b
        gh = h @ w_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        h = (1 - z) * n + z * h
        return h, h

    return _scan_time(cell, x, h0)


gru_op = def_op("GRU", _gru,
                lambda x, w_ih, w_hh, b: (x[0], x[1], w_hh[0]))


__all__ = ["rnn_op", "lstm_op", "gru_op"]
