"""Shape/layout/indexing ops.

Reference: Reshape, Transpose, Concat(enate), Split, Slice(Assign/ByMatrix),
Pad, Broadcast(Shape), Repeat, Roll, Flip, Unsqueeze, Gather, Scatter,
IndexSelect, AsStrided, Argmax, Argsort, OneHot, CumSum, Triu, MaskedFill,
Interpolate, Max, Min, TopK* (``src/ops/*.cu``).  On TPU these are pure
data-movement; XLA folds most of them into surrounding fusions.
"""
import jax
import jax.numpy as jnp
import numpy as np

from .base import def_op

# -- reshape family ---------------------------------------------------------
array_reshape_op = def_op(
    "ArrayReshape",
    lambda c, a, output_shape=None: jnp.reshape(a, output_shape),
    lambda a, output_shape=None: tuple(np.empty(a).reshape(output_shape).shape))


def _flatten(c, a, start_dim=0, end_dim=-1):
    shape = list(a.shape)
    nd = len(shape)
    s = start_dim % nd
    e = end_dim % nd
    new = shape[:s] + [int(np.prod(shape[s:e + 1] or [1]))] + shape[e + 1:]
    return jnp.reshape(a, new)


flatten_op = def_op("Flatten", _flatten)

transpose_op = def_op(
    "Transpose", lambda c, a, perm=None: jnp.transpose(a, perm),
    lambda a, perm=None: tuple(np.empty(a).transpose(perm).shape))

def _unsqueeze_shape(a, axis=0):
    s = list(a)
    s.insert(axis if axis >= 0 else axis + len(a) + 1, 1)
    return tuple(s)


def _squeeze_shape(a, axis=None):
    if axis is None:
        return tuple(d for d in a if d != 1)
    return tuple(d for i, d in enumerate(a)
                 if i != (axis if axis >= 0 else axis + len(a)))


unsqueeze_op = def_op("Unsqueeze",
                      lambda c, a, axis=0: jnp.expand_dims(a, axis),
                      _unsqueeze_shape)
squeeze_op = def_op("Squeeze", lambda c, a, axis=None: jnp.squeeze(a, axis),
                    _squeeze_shape)

# -- concat / split ---------------------------------------------------------
concat_op = def_op("Concat", lambda c, a, b, axis=0: jnp.concatenate([a, b], axis))


def concatenate_op(node_list, axis=0, ctx=None, name=None):
    from .base import SimpleOp
    return SimpleOp("Concatenate", list(node_list),
                    lambda c, *vals, axis=0: jnp.concatenate(vals, axis),
                    name=name, axis=axis)


def _split(c, a, axes=None, indices=None, splits=None):
    """Reference Split.py semantics: cut dim ``axes[i]`` into ``splits[i]``
    equal parts and keep part ``indices[i]`` (used for model-parallel demos)."""
    axes = axes if isinstance(axes, (list, tuple)) else [axes]
    indices = indices if isinstance(indices, (list, tuple)) else [indices]
    splits = splits if isinstance(splits, (list, tuple)) else [splits]
    for ax, idx, sp in zip(axes, indices, splits):
        size = a.shape[ax] // sp
        a = jax.lax.slice_in_dim(a, idx * size, (idx + 1) * size, axis=ax)
    return a


split_op = def_op("Split", _split)

# -- slice family -----------------------------------------------------------


def _slice(c, a, begin=None, size=None, end=None):
    begin = list(begin)
    if size is not None:
        end = [b + s if s >= 0 else dim for b, s, dim in zip(begin, size, a.shape)]
    return a[tuple(slice(b, e) for b, e in zip(begin, end))]


slice_op = def_op("Slice", _slice)


def _slice_assign(c, a, begin=None, end=None, val=0.0):
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return a.at[idx].set(val)


slice_assign_op = def_op("SliceAssign", _slice_assign)


def _slice_assign_matrix(c, a, b, begin=None, end=None, begin2=None, end2=None):
    dst = tuple(slice(x, y) for x, y in zip(begin, end))
    src = tuple(slice(x, y) for x, y in zip(begin2, end2))
    return a.at[dst].set(b[src])


slice_assign_matrix_op = def_op("SliceAssignMatrix", _slice_assign_matrix)


def _slice_by_matrix(c, a, idx1, idx2):
    return a[idx1.astype(jnp.int32), idx2.astype(jnp.int32)]


slice_by_matrix_op = def_op("SliceByMatrix", _slice_by_matrix)

# -- pad / broadcast / repeat ----------------------------------------------


def _pad(c, a, paddings=None, mode="CONSTANT", constant_values=0):
    return jnp.pad(a, paddings, mode=mode.lower(),
                   **({"constant_values": constant_values}
                      if mode.upper() == "CONSTANT" else {}))


pad_op = def_op("Pad", _pad)

broadcastto_op = def_op("BroadcastTo",
                        lambda c, a, b: jnp.broadcast_to(a, b.shape),
                        lambda a, b: tuple(b))


def _broadcast_shape(c, a, shape=None, add_axes=None):
    if add_axes:
        for ax in sorted(add_axes):
            a = jnp.expand_dims(a, ax)
    return jnp.broadcast_to(a, shape)


broadcast_shape_op = def_op("BroadcastShape", _broadcast_shape)

repeat_op = def_op("Repeat", lambda c, a, reps=None: jnp.tile(a, reps))
roll_op = def_op("Roll", lambda c, a, shift=None, axis=None: jnp.roll(a, shift, axis))
flip_op = def_op("Flip", lambda c, a, dims=None: jnp.flip(a, dims))

# -- gather / scatter / indexing -------------------------------------------
gather_op = def_op(
    "Gather",
    lambda c, a, idx, dim=0: jnp.take_along_axis(a, idx.astype(jnp.int32), axis=dim))

index_select_op = def_op(
    "IndexSelect",
    lambda c, a, idx, dim=0: jnp.take(a, idx.astype(jnp.int32), axis=dim))


def _scatter(c, a, idx, src, dim=0):
    return a.at[tuple(
        idx.astype(jnp.int32) if d == dim else
        jnp.arange(a.shape[d]).reshape([-1 if dd == d else 1 for dd in range(a.ndim)])
        for d in range(a.ndim))].set(src)


scatter_op = def_op("Scatter", _scatter)

scatter1d_op = def_op(
    "Scatter1D", lambda c, a, idx: a[idx.astype(jnp.int32)])
scatter1d_grad_op = def_op(
    "Scatter1DGrad",
    lambda c, g, idx, size=None: jnp.zeros((size,) + g.shape[1:], g.dtype)
    .at[idx.astype(jnp.int32)].set(g))

indexing_op = def_op(
    "Indexing", lambda c, a, idx: a[idx.astype(jnp.int32)])


def _as_strided(c, a, shape=None, stride=None, storage_offset=0):
    flat = jnp.ravel(a)
    idx = np.zeros(shape, dtype=np.int64) + storage_offset
    for d, (s, st) in enumerate(zip(shape, stride)):
        ix = np.arange(s) * st
        idx += ix.reshape([-1 if dd == d else 1 for dd in range(len(shape))])
    return flat[idx]


as_strided_op = def_op("AsStrided", _as_strided)

# -- arg / topk / sort ------------------------------------------------------
argmax_op = def_op("Argmax", lambda c, a, dim=0: jnp.argmax(a, axis=dim).astype(jnp.float32))
argsort_op = def_op("Argsort", lambda c, a, dim=-1, descending=False:
                    jnp.argsort(-a if descending else a, axis=dim).astype(jnp.float32))

max_op = def_op("Max", lambda c, a, dim=0, keepdim=False: jnp.max(a, axis=dim, keepdims=keepdim))
min_op = def_op("Min", lambda c, a, dim=0, keepdim=False: jnp.min(a, axis=dim, keepdims=keepdim))

topk_val_op = def_op("TopKVal",
                     lambda c, a, k=1: jax.lax.top_k(a, k)[0])
topk_idx_op = def_op("TopKIdx",
                     lambda c, a, k=1: jax.lax.top_k(a, k)[1].astype(jnp.int32))

# -- misc -------------------------------------------------------------------
one_hot_op = def_op("OneHot",
                    lambda c, a, num_classes=2: jax.nn.one_hot(a.astype(jnp.int32), num_classes))

clone_op = def_op("Clone", lambda c, a: jnp.array(a), lambda a: tuple(a))

cumsum_op = def_op("CumSum",
                   lambda c, a, axis=0: jnp.cumsum(a, axis=axis),
                   lambda a, axis=0: tuple(a))


def _group_topk_idx(c, a, k=1, group_size=1):
    """Top-k indices within contiguous groups of the last dim
    (reference GroupTopKIdx.cu, used by SAM gating)."""
    g = a.reshape(a.shape[:-1] + (a.shape[-1] // group_size, group_size))
    import jax
    _, idx = jax.lax.top_k(g, k)
    return idx


group_topk_idx_op = def_op("GroupTopKIdx", _group_topk_idx)

cumsum_with_bias_op = def_op(
    "CumsumWithBias",
    lambda c, a, bias=0.0, dim=0: jnp.cumsum(a, axis=dim) + bias)

triu_op = def_op("Triu", lambda c, a, diagonal=0: jnp.triu(a, diagonal))
tril_op = def_op("Tril", lambda c, a, diagonal=0: jnp.tril(a, diagonal))

masked_fill_op = def_op(
    "MaskedFill",
    lambda c, a, mask, val=0.0: jnp.where(mask.astype(bool), jnp.asarray(val, a.dtype), a))


def _interpolate(c, a, scale_factor=None, size=None, mode="bilinear", align_corners=False):
    n, ch, h, w = a.shape
    if size is None:
        size = (int(h * scale_factor), int(w * scale_factor))
    method = {"bilinear": "bilinear", "nearest": "nearest"}[mode]
    return jax.image.resize(a, (n, ch) + tuple(size), method=method)


interpolate_op = def_op("Interpolate", _interpolate)

norm_op = def_op("Norm", lambda c, a, axis=None, p=2:
                 jnp.sum(jnp.abs(a) ** p, axis=axis) ** (1.0 / p))
