from .optimizer import (Optimizer, OptimizerOp, SGDOptimizer, MomentumOptimizer,
                        AdaGradOptimizer, AdamOptimizer, AdamWOptimizer,
                        LambOptimizer)
from .lr_scheduler import (LRScheduler, FixedScheduler, StepScheduler,
                           MultiStepScheduler, ExponentialScheduler,
                           ReduceOnPlateauScheduler, CosineScheduler)
