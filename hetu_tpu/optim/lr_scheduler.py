"""LR schedulers (reference ``python/hetu/lr_scheduler.py``: FixedScheduler:2,
StepScheduler:13, MultiStepScheduler:39, ExponentialScheduler:59,
ReduceOnPlateauScheduler:83).

Two evaluation paths, one schedule definition:

* ``get(step)`` — the host-side value (checkpoint metadata, logging, and
  the executor's fallback path).
* ``traced(step)`` — the SAME schedule as a jax expression of the traced
  ``step_idx`` scalar, evaluated INSIDE the jitted training step.  The
  executor prefers this path (``graph/run_plan.py``): a pure
  step-indexed schedule then costs zero per-step Python (no ``get``
  call, no per-step ``np.asarray(lrs)`` on the hot path) and never
  retraces — ``step_idx`` is a runtime input.  Schedules whose next
  value depends on DATA rather than the step index
  (``ReduceOnPlateauScheduler``'s monitored metric) return ``None`` and
  stay host-computed per step.  A traced schedule's parameters are baked
  into the compiled program (and hashed into the compiled-step cache
  signature); mutate a live schedule only through the data-dependent
  kind, or disable tracing with ``HETU_TRACED_LR=0``.  Traced math runs
  in float32 (the step input dtype) — equal to the float64 host value
  within one f32 ulp.
"""
from __future__ import annotations

import numpy as np


class LRScheduler:
    def get(self, step: int) -> float:
        raise NotImplementedError

    def traced(self, step):
        """jax lr expression of the traced ``step`` scalar, or ``None``
        when the schedule is data-dependent (host-computed per step)."""
        return None

    def on_step(self, step: int):
        pass


class FixedScheduler(LRScheduler):
    def __init__(self, learning_rate):
        self.lr = learning_rate

    def get(self, step):
        return self.lr

    def traced(self, step):
        import jax.numpy as jnp
        return jnp.float32(self.lr)


class StepScheduler(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1):
        assert step_size > 0
        self.lr, self.step_size, self.gamma = learning_rate, step_size, gamma

    def get(self, step):
        return self.lr * self.gamma ** (step // self.step_size)

    def traced(self, step):
        import jax.numpy as jnp
        k = (step // self.step_size).astype(jnp.float32)
        return jnp.float32(self.lr) * jnp.float32(self.gamma) ** k


class MultiStepScheduler(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1):
        self.lr = learning_rate
        self.milestones = sorted(milestones)
        self.gamma = gamma

    def get(self, step):
        k = int(np.searchsorted(self.milestones, step, side="right"))
        return self.lr * self.gamma ** k

    def traced(self, step):
        import jax.numpy as jnp
        ms = jnp.asarray(self.milestones, jnp.int32)
        k = jnp.searchsorted(ms, step, side="right").astype(jnp.float32)
        return jnp.float32(self.lr) * jnp.float32(self.gamma) ** k


class ExponentialScheduler(LRScheduler):
    def __init__(self, learning_rate, gamma=0.99):
        self.lr, self.gamma = learning_rate, gamma

    def get(self, step):
        return self.lr * self.gamma ** step

    def traced(self, step):
        import jax.numpy as jnp
        return jnp.float32(self.lr) \
            * jnp.float32(self.gamma) ** step.astype(jnp.float32)


class ReduceOnPlateauScheduler(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0.0):
        self.lr = learning_rate
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr = cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_left = 0

    def _better(self, metric):
        if self.best is None:
            return True
        t = self.threshold
        if self.threshold_mode == "rel":
            bound = self.best * (1 - t) if self.mode == "min" else self.best * (1 + t)
        else:
            bound = self.best - t if self.mode == "min" else self.best + t
        return metric < bound if self.mode == "min" else metric > bound

    def step(self, metric):
        """User calls this with the monitored metric (e.g. val loss)."""
        metric = float(metric)
        if self._better(metric):
            self.best = metric
            self.num_bad = 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self.cooldown_left = self.cooldown
                self.num_bad = 0

    def get(self, step):
        return self.lr


class CosineScheduler(LRScheduler):
    """Cosine decay with linear warmup — the standard LLM-pretrain schedule
    (new; not in the reference, needed by the BERT MFU target)."""

    def __init__(self, learning_rate, warmup_steps, total_steps, min_ratio=0.0):
        self.lr = learning_rate
        self.warmup = max(1, warmup_steps)
        self.total = total_steps
        self.min_ratio = min_ratio

    def get(self, step):
        if step < self.warmup:
            return self.lr * (step + 1) / self.warmup
        p = min(1.0, (step - self.warmup) / max(1, self.total - self.warmup))
        cos = 0.5 * (1 + np.cos(np.pi * p))
        return self.lr * (self.min_ratio + (1 - self.min_ratio) * cos)

    def traced(self, step):
        import jax.numpy as jnp
        s = step.astype(jnp.float32)
        warm = jnp.float32(self.lr) * (s + 1) / self.warmup
        p = jnp.minimum(1.0, (s - self.warmup)
                        / max(1, self.total - self.warmup))
        cos = 0.5 * (1 + jnp.cos(jnp.float32(np.pi) * p))
        decay = jnp.float32(self.lr) \
            * (self.min_ratio + (1 - self.min_ratio) * cos)
        return jnp.where(step < self.warmup, warm, decay)
