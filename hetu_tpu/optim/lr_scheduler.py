"""LR schedulers (reference ``python/hetu/lr_scheduler.py``: FixedScheduler:2,
StepScheduler:13, MultiStepScheduler:39, ExponentialScheduler:59,
ReduceOnPlateauScheduler:83).  Schedulers are host-side — the executor feeds
the scalar lr into the jitted step each call, so schedule changes never
retrace.
"""
from __future__ import annotations

import numpy as np


class LRScheduler:
    def get(self, step: int) -> float:
        raise NotImplementedError

    def on_step(self, step: int):
        pass


class FixedScheduler(LRScheduler):
    def __init__(self, learning_rate):
        self.lr = learning_rate

    def get(self, step):
        return self.lr


class StepScheduler(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1):
        assert step_size > 0
        self.lr, self.step_size, self.gamma = learning_rate, step_size, gamma

    def get(self, step):
        return self.lr * self.gamma ** (step // self.step_size)


class MultiStepScheduler(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1):
        self.lr = learning_rate
        self.milestones = sorted(milestones)
        self.gamma = gamma

    def get(self, step):
        k = int(np.searchsorted(self.milestones, step, side="right"))
        return self.lr * self.gamma ** k


class ExponentialScheduler(LRScheduler):
    def __init__(self, learning_rate, gamma=0.99):
        self.lr, self.gamma = learning_rate, gamma

    def get(self, step):
        return self.lr * self.gamma ** step


class ReduceOnPlateauScheduler(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0.0):
        self.lr = learning_rate
        self.mode, self.factor, self.patience = mode, factor, patience
        self.threshold, self.threshold_mode = threshold, threshold_mode
        self.cooldown, self.min_lr = cooldown, min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_left = 0

    def _better(self, metric):
        if self.best is None:
            return True
        t = self.threshold
        if self.threshold_mode == "rel":
            bound = self.best * (1 - t) if self.mode == "min" else self.best * (1 + t)
        else:
            bound = self.best - t if self.mode == "min" else self.best + t
        return metric < bound if self.mode == "min" else metric > bound

    def step(self, metric):
        """User calls this with the monitored metric (e.g. val loss)."""
        metric = float(metric)
        if self._better(metric):
            self.best = metric
            self.num_bad = 0
        elif self.cooldown_left > 0:
            self.cooldown_left -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self.cooldown_left = self.cooldown
                self.num_bad = 0

    def get(self, step):
        return self.lr


class CosineScheduler(LRScheduler):
    """Cosine decay with linear warmup — the standard LLM-pretrain schedule
    (new; not in the reference, needed by the BERT MFU target)."""

    def __init__(self, learning_rate, warmup_steps, total_steps, min_ratio=0.0):
        self.lr = learning_rate
        self.warmup = max(1, warmup_steps)
        self.total = total_steps
        self.min_ratio = min_ratio

    def get(self, step):
        if step < self.warmup:
            return self.lr * (step + 1) / self.warmup
        p = min(1.0, (step - self.warmup) / max(1, self.total - self.warmup))
        cos = 0.5 * (1 + np.cos(np.pi * p))
        return self.lr * (self.min_ratio + (1 - self.min_ratio) * cos)
