"""Optimizers (reference ``python/hetu/optimizer.py``: SGD:171, Momentum:229,
AdaGrad:293, Adam:356, AdamW:429, Lamb:493; fused CUDA updates in
``src/ops/Optimizers.cu``).

TPU-native: each optimizer is a pure ``apply(params, grads, state, lr)``
pytree transform executed INSIDE the jitted training step, so the update
fuses with the backward pass (the reference needed hand-fused kernels for
this).  ``OptimizerOp`` keeps the graph-level contract: ``opt.minimize(loss)``
returns a fetchable node, and gradient wrapping for data-parallel happens via
mesh sharding instead of inserted AllReduce ops (``optimizer.py:145-164``).

Layout polymorphism (ZeRO weight-update sharding, ``parallel/zero.py``):
``apply`` never sees graph nodes — just a dict of same-shaped param/grad
arrays — so the sharded update feeds it ``(dp, width)`` bucket SLABS
instead of per-param arrays and the SAME code updates each replica's 1/dp
slice of state.  That only holds while the update is ELEMENTWISE per dict
entry (each output element depends only on that element's p/g/state plus
scalars like ``t``).  An optimizer that couples elements of one parameter
— LAMB's per-parameter trust-ratio norms — must set ``lamb = True``-style
markers so the ZeRO planner packs one param per bucket: a multi-param
slab would blend norms across parameters (the cross-REPLICA half is fine
— the partitioner turns the sharded slab's ``sum(p*p)`` into a partial
sum + all-reduce automatically).  New optimizers with cross-element terms
must do the same or stay off the ZeRO path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..graph.node import Op, PlaceholderOp, topo_sort
from ..graph.gradients import gradients


class OptimizerOp(Op):
    """Graph node that applies ``optimizer`` to its GradientOp inputs."""

    op_type = "OptimizerUpdate"

    def __init__(self, grad_nodes, optimizer, name=None):
        super().__init__(grad_nodes, name=name)
        self.optimizer = optimizer
        self.params = [g.wrt for g in grad_nodes]
        # reference parity: expert-parallel params (name contains 'expert')
        # are excluded from DP grad sync (optimizer.py:150-152); under SPMD
        # the mesh sharding handles this, recorded here for the strategies.
        self.dp_excluded = [p for p in self.params if "expert" in p.name]

    def lower(self, ctx, *vals):  # resolved specially by the executor
        raise RuntimeError("OptimizerOp must be resolved by the executor")


class Optimizer:
    def __init__(self, learning_rate, l2reg=0.0):
        self.lr = learning_rate  # float or LRScheduler
        self.l2reg = l2reg

    # -- graph API --------------------------------------------------------
    def minimize(self, loss, var_list=None):
        if var_list is None:
            var_list = [n for n in topo_sort([loss])
                        if isinstance(n, PlaceholderOp) and n.is_variable
                        and n.trainable]
        grad_nodes = gradients(loss, var_list)
        return OptimizerOp(grad_nodes, self)

    # -- host-side lr -----------------------------------------------------
    def host_lr(self, step):
        from .lr_scheduler import LRScheduler
        if isinstance(self.lr, LRScheduler):
            return float(self.lr.get(step))
        return float(self.lr)

    # -- traced lr (inside the jitted step) -------------------------------
    def traced_lr(self, step):
        """lr as a jax expression of the traced ``step_idx`` scalar, or
        ``None`` when the schedule is data-dependent (the executor then
        computes ``host_lr`` per step and feeds it as a runtime input).
        A constant float lr and every pure step-indexed scheduler trace
        (the per-step Python call and the ``np.asarray(lrs)`` disappear
        from the dispatch path — ``graph/run_plan.py``); the traced
        schedule is baked into the compiled program and hashed into the
        compiled-step cache signature.  ``HETU_TRACED_LR=0`` forces the
        host path everywhere (see :func:`traced_lr_enabled`)."""
        from .lr_scheduler import LRScheduler
        if isinstance(self.lr, LRScheduler):
            return self.lr.traced(step)
        import jax.numpy as jnp
        return jnp.float32(float(self.lr))

    def on_step(self, step):
        from .lr_scheduler import LRScheduler
        if isinstance(self.lr, LRScheduler):
            self.lr.on_step(step)

    # -- pure update ------------------------------------------------------
    def init_state(self, params):
        return {}

    def _reg(self, p, g):
        return g + self.l2reg * p if self.l2reg else g

    def apply(self, params, grads, state, lr):
        raise NotImplementedError


def traced_lr_enabled():
    """Traced-lr gate: ``HETU_TRACED_LR=0`` forces every optimizer onto
    the host ``lrs``-input path (parity debugging; the escape hatch for
    code that mutates a live ``optimizer.lr`` mid-training)."""
    import os
    return os.environ.get("HETU_TRACED_LR", "1") != "0"


def traced_lr_fn(opt):
    """``step -> lr`` callable evaluated inside the jitted step, or
    ``None`` when this optimizer's lr must stay a per-step host input
    (data-dependent schedule, tracing disabled, or a custom ``traced_lr``
    that errors).  Probed EAGERLY with a concrete step so the decision —
    which drives the host ``lrs`` input's shape and the compiled-step
    cache signature (``graph/step_cache.py`` hashes traced schedules) —
    is made before any tracing happens."""
    if not traced_lr_enabled():
        return None
    import jax.numpy as jnp
    try:
        probe = opt.traced_lr(jnp.int32(0))
    except Exception:
        return None
    if probe is None:
        return None
    return opt.traced_lr


class SGDOptimizer(Optimizer):
    def apply(self, params, grads, state, lr):
        new = {k: p - lr * self._reg(p, grads[k]) if k in grads else p
               for k, p in params.items()}
        return new, state


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, nesterov=False, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.momentum = momentum
        self.nesterov = nesterov

    def init_state(self, params):
        return {"v": jax.tree.map(jnp.zeros_like, params)}

    def apply(self, params, grads, state, lr):
        new_p, new_v = {}, {}
        for k, p in params.items():
            if k not in grads:
                new_p[k] = p
                new_v[k] = state["v"][k]
                continue
            g = self._reg(p, grads[k])
            v = self.momentum * state["v"][k] - lr * g
            new_v[k] = v
            new_p[k] = p + (self.momentum * v - lr * g if self.nesterov else v)
        return new_p, {"v": new_v}


class AdaGradOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, initial_accumulator_value=0.0,
                 eps=1e-7, l2reg=0.0):
        super().__init__(learning_rate, l2reg)
        self.init_acc = initial_accumulator_value
        self.eps = eps

    def init_state(self, params):
        return {"acc": jax.tree.map(
            lambda p: jnp.full_like(p, self.init_acc), params)}

    def apply(self, params, grads, state, lr):
        new_p, new_acc = {}, {}
        for k, p in params.items():
            if k not in grads:
                new_p[k], new_acc[k] = p, state["acc"][k]
                continue
            g = self._reg(p, grads[k])
            acc = state["acc"][k] + g * g
            new_acc[k] = acc
            new_p[k] = p - lr * g / (jnp.sqrt(acc) + self.eps)
        return new_p, {"acc": new_acc}


class AdamOptimizer(Optimizer):
    weight_decay = 0.0
    lamb = False

    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, l2reg=0.0, amsgrad=False):
        super().__init__(learning_rate, l2reg)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.amsgrad = amsgrad

    def init_state(self, params):
        st = {"m": jax.tree.map(jnp.zeros_like, params),
              "v": jax.tree.map(jnp.zeros_like, params),
              "t": jnp.zeros((), jnp.int32)}
        if self.amsgrad:
            st["vmax"] = jax.tree.map(jnp.zeros_like, params)
        return st

    def apply(self, params, grads, state, lr):
        t = state["t"] + 1
        bc1 = 1 - self.beta1 ** t.astype(jnp.float32)
        bc2 = 1 - self.beta2 ** t.astype(jnp.float32)
        new_p, new_m, new_v, new_vmax = {}, {}, {}, {}
        for k, p in params.items():
            if k not in grads:
                new_p[k], new_m[k], new_v[k] = p, state["m"][k], state["v"][k]
                if self.amsgrad:
                    new_vmax[k] = state["vmax"][k]
                continue
            g = self._reg(p, grads[k])
            m = self.beta1 * state["m"][k] + (1 - self.beta1) * g
            v = self.beta2 * state["v"][k] + (1 - self.beta2) * g * g
            new_m[k], new_v[k] = m, v
            vhat = v / bc2
            if self.amsgrad:
                vhat = jnp.maximum(state["vmax"][k], vhat)
                new_vmax[k] = vhat
            upd = (m / bc1) / (jnp.sqrt(vhat) + self.epsilon) \
                + self.weight_decay * p
            if self.lamb:
                wn = jnp.sqrt(jnp.sum(p * p))
                un = jnp.sqrt(jnp.sum(upd * upd))
                trust = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
                upd = trust * upd
            new_p[k] = p - lr * upd
        st = {"m": new_m, "v": new_v, "t": t}
        if self.amsgrad:
            st["vmax"] = new_vmax
        return new_p, st


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, weight_decay=0.0, l2reg=0.0):
        super().__init__(learning_rate, beta1, beta2, epsilon, l2reg)
        self.weight_decay = weight_decay


class LambOptimizer(AdamWOptimizer):
    lamb = True
