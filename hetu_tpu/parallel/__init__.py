from .strategies import Strategy, DataParallel, ModelParallel
from .dispatch import dispatch
from . import collectives
from .collectives import CommGroup, new_group_comm
