from .strategies import Strategy, DataParallel, ModelParallel
from .dispatch import dispatch
from . import collectives
from .collectives import CommGroup, new_group_comm
from .pipeline import (PipelineParallel, pipeline_block, pipeline_apply,
                       serial_apply, spmd_pipeline_local, gpipe_schedule,
                       pipedream_schedule, hetpipe_sync_steps)
from .ring_attention import (ContextParallel, ring_attention,
                             ulysses_attention)
from .preduce import PartialReduce, preduce_mean, preduce_scatter_mean
from . import zero
from .zero import ZeroPlan, ZeroBucket
from . import elastic
from .elastic import ElasticController, FlapDamper, LogicalRank
from . import remat
from .remat import RematPlan, RematSegment
