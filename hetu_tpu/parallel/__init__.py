from .strategies import Strategy, DataParallel, ModelParallel
