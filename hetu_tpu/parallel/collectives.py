"""Collective communication layer — the TPU-native equivalent of the
reference's MPI+NCCL bridge (``src/communication/mpi_nccl_communication.cu``:
dlarrayAllReduce:313, Reduce:326, Broadcast:340, AllGather:353,
ReduceScatter:369, AllToAll:383, HAllToAll:396, Send:409/Recv:421) and its
Python wrapper (``communicator/mpi_nccl_comm.py``).

Design (SURVEY.md §5.8): collectives are expressed over NAMED MESH AXES and
executed by XLA over ICI.  Two complementary surfaces:

1. Implicit — jit + shardings: XLA inserts the collectives (used by the
   Executor; covers the reference's allreduce-behind-optimizer pattern).
2. Explicit — these wrappers inside ``shard_map`` per-device programs, for
   schedules XLA can't infer (pipeline microbatching, ring attention,
   hierarchical MoE a2a).  ``ppermute`` is the native ICI primitive that
   replaces NCCL grouped Send/Recv.

Group communicators over device subsets (``mpi_nccl_comm.py:157-250``) map
to sub-meshes / axis subsets: every wrapper takes ``axis_name`` and operates
on exactly that mesh dimension.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


# -- explicit collectives (inside shard_map) --------------------------------

def all_reduce(x, axis_name, op="sum"):
    """NCCL allreduce parity (ncclAllReduce; avg used by preduce)."""
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op in ("avg", "mean"):
        return jax.lax.pmean(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=tiled)


# -- implicit-surface PartitionSpecs (GSPMD sharding constraints) -----------
# The ZeRO weight-update layer (parallel/zero.py) pins its slabs to these
# specs and lets the SPMD partitioner emit the reduce-scatter / all-gather
# pair itself — surface 1 of the module docstring, where the collective is
# IMPLIED by a layout change instead of called explicitly.

def slab_spec(axis_name="dp"):
    """Spec of a ``(dp, width)`` ZeRO slab: rows sharded over ``axis_name``
    (each replica holds its own 1/dp slice)."""
    return P(axis_name, None)


def replicated_spec():
    """Spec of a fully replicated tensor — constraining a slab to this is
    the implicit all-gather."""
    return P()


def all_to_all(x, axis_name, split_axis=0, concat_axis=0):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(x, axis_name, root=0):
    """Broadcast from ``root`` along the axis (ncclBroadcast parity)."""
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)),
                        axis_name)


def reduce(x, axis_name, root=0, op="sum"):
    """Reduce-to-root (ncclReduce parity): non-roots get zeros."""
    total = all_reduce(x, axis_name, op)
    idx = jax.lax.axis_index(axis_name)
    return jnp.where(idx == root, total, jnp.zeros_like(total))


def ppermute(x, axis_name, perm):
    """Collective-permute — the ICI-native replacement for NCCL grouped
    Send/Recv (GroupStart/End, mpi_nccl_communication.cu:129-134)."""
    return jax.lax.ppermute(x, axis_name, perm)


def send_next(x, axis_name, n):
    """Shift by +1 around the ring (pipeline send to next stage)."""
    return ppermute(x, axis_name, [(i, (i + 1) % n) for i in range(n)])


def send_prev(x, axis_name, n):
    return ppermute(x, axis_name, [(i, (i - 1) % n) for i in range(n)])


def hierarchical_all_to_all(x, outer_axis, inner_axis):
    """2-level a2a (reference HAllToAll:396 + HA2AGather/Scatter: intra-node
    exchange → inter-node exchange).

    Semantically IDENTICAL to a flat ``all_to_all`` over the combined
    (outer-major) axis — parity-tested in tests/test_collectives.py — but
    expressed as a fast-axis (ICI) exchange followed by a slow-axis (DCN)
    exchange, the explicit schedule for DCN-bound MoE (SURVEY.md §5.8).

    ``x``: (E·k, ...) per-device send buffer, chunk j destined for flat
    rank j (j = o·inner + i).  Returns the received buffer in flat source
    order, exactly like ``all_to_all(x, flat_axis)``.
    """
    O = jax.lax.psum(1, outer_axis)
    I = jax.lax.psum(1, inner_axis)
    k = x.shape[0] // (O * I)
    rest = x.shape[1:]
    y = x.reshape((O, I, k) + rest)
    # phase 1 (fast axis): peer i' receives our slice [:, i', ...]
    y = jax.lax.all_to_all(y, inner_axis, split_axis=1, concat_axis=1)
    # phase 2 (slow axis): peer o' receives the regrouped slice [o', ...]
    y = jax.lax.all_to_all(y, outer_axis, split_axis=0, concat_axis=0)
    return y.reshape((O * I * k,) + rest)


# -- group communicators (reference mpi_nccl_comm group concept) ------------

class CommGroup:
    """A named-axis communicator over a sub-mesh — the analogue of
    ``new_group_comm(DeviceGroup)`` (executor.py re-export).  Wraps shard_map
    so callers write per-device code with the group's axis in scope."""

    def __init__(self, mesh: Mesh, axis_name: str):
        assert axis_name in mesh.axis_names
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def size(self):
        return self.mesh.shape[self.axis_name]

    def run(self, fn, *args, in_specs=None, out_specs=None):
        in_specs = in_specs or tuple(P(self.axis_name) for _ in args)
        out_specs = out_specs if out_specs is not None else P(self.axis_name)
        return jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)(*args)

    def allreduce(self, x, op="sum"):
        return self.run(functools.partial(all_reduce, axis_name=self.axis_name,
                                          op=op), x,
                        in_specs=(P(self.axis_name),), out_specs=P())


def new_group_comm(mesh, axis_name="dp"):
    """Reference-parity constructor (``new_group_comm``)."""
    return CommGroup(mesh, axis_name)
