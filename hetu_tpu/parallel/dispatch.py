"""``ht.dispatch`` — intra-op (tensor) model parallelism.

The reference DECLARED this API but never built the rewriter
(``gpu_ops/Dispatch.py`` — vestigial, SURVEY.md §2.3: "no graph rewriter
consumes DispatchOp").  Here the declared semantics become real: a dispatch
is a GSPMD sharding annotation; the XLA SPMD partitioner generates the
halo/allreduce/all-gather program the reference never got to.

``parts`` follows the reference surface: a tuple with one entry per tensor
dim — an int (ignored: the mesh axis size determines the split), a mesh axis
name ('dp'/'tp'/'ep'/'cp'/'pp'), or None/-1 for replicated dims.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec

from ..context import MESH_AXES


_INT_AXIS_ORDER = ("tp", "dp", "ep")  # dims split by bare ints, in order


def _to_spec(parts):
    axes = []
    next_int_axis = 0
    for p in parts:
        if p in MESH_AXES:
            if p in axes:
                raise ValueError(f"mesh axis {p!r} used twice in {parts!r}")
            axes.append(p)
        elif isinstance(p, int) and p > 1:
            # reference int parts = "split this dim"; successive int dims map
            # to distinct mesh axes (tp, then dp, then ep)
            while (next_int_axis < len(_INT_AXIS_ORDER)
                   and _INT_AXIS_ORDER[next_int_axis] in axes):
                next_int_axis += 1
            if next_int_axis >= len(_INT_AXIS_ORDER):
                raise ValueError(f"too many int split dims in {parts!r}; "
                                 "use explicit mesh axis names")
            axes.append(_INT_AXIS_ORDER[next_int_axis])
            next_int_axis += 1
        else:
            axes.append(None)
    return PartitionSpec(*axes)


def dispatch(node, parts):
    """Annotate ``node`` (and return it) with a partition over the mesh.

    ``ht.dispatch(x, (2, 1))`` → shard dim 0 over 'tp' (reference int style);
    ``ht.dispatch(x, ('dp', None))`` → explicit axis names.
    """
    node.sharding = parts if isinstance(parts, PartitionSpec) else _to_spec(parts)
    return node


def apply_plan_directive(layer, directive, fsdp_via_zero=False):
    """Attach one auto-parallel layer directive
    (:meth:`hetu_tpu.autoparallel.ParallelPlan.layer_specs`) to a model
    layer through this module's annotation machinery: column-parallel
    ``kernel_spec`` on ``in_kernels``, row-parallel ``out_kernel_spec``
    on ``out_kernels`` (the canonical Megatron pair), and — unless
    ``fsdp_via_zero`` says the executor's ZeRO slab packing realizes the
    fsdp sharding instead — the 'dp' ``param_spec`` on every remaining
    un-annotated kernel (ZeRO-style GSPMD param sharding)."""
    if directive["tp"] > 1:
        for v in getattr(layer, "in_kernels", []) or []:
            dispatch(v, directive["kernel_spec"])
        for v in getattr(layer, "out_kernels", []) or []:
            dispatch(v, directive["out_kernel_spec"])
        w = getattr(layer, "weight_var", None)
        if w is not None and not getattr(layer, "in_kernels", None):
            dispatch(w, directive["kernel_spec"])
    if directive["fsdp"] and not fsdp_via_zero:
        # ZeRO-style: params sharded over 'dp'; XLA inserts the
        # all-gather before use. tp-sharded kernels already carry the
        # combined (dp, tp) spec from the branch above; this covers the
        # remaining (tp-unsharded) kernels
        ks = list(getattr(layer, "in_kernels", []) or []) \
            + list(getattr(layer, "out_kernels", []) or [])
        w = getattr(layer, "weight_var", None)
        if w is not None and w not in ks:
            ks.append(w)
        for v in ks:
            if getattr(v, "sharding", None) is None:
                dispatch(v, directive["param_spec"])
