"""Elastic data-parallel training: resize the dp world without a restart
(ISSUE 12 tentpole).

Hetu's partial-reduce story (PAPER.md) lets a straggling or dead rank
drop out of a *single collective*; this module takes it to its
conclusion: a lost rank drops out of the *job*.  On a dead rank the
:class:`ElasticController` drives the resize dance —

1. **detect** — heartbeat liveness (``DistributedStore.liveness_report``,
   ISSUE 8) or a pluggable ``alive_fn`` mask; a rank is shrunk out only
   after it has been heartbeat-silent for one full wait window
   (``heartbeat_deadline_ms`` — the same window
   :class:`~hetu_tpu.parallel.preduce.DistPartialReduce` stops waiting
   on it);
2. **quiesce** — in-flight ``run(sync=False)`` steps drain
   (``Executor._drain_async``, ISSUE 9) so no dispatched program still
   references the old world's buffers;
3. **re-plan** — :meth:`hetu_tpu.graph.executor.Executor.resize_world`
   re-packs the ZeRO buckets for the new world (ISSUE 6's packing is
   dp-parameterized), redistributes the surviving ranks' param/moment
   slabs bitwise, and rebuilds the jitted step THROUGH the compiled-step
   cache — the dp−1 executable is a one-time compile, and any later
   revisit of a world size (the grow-back) is a ``step_cache_hit``, not
   a recompile;
4. **rescale** — gradient semantics are preserved by construction: the
   mean-loss psum over the dp−1 mesh equals the partial-reduce
   alive-mask mean ``psum(mask*g)/psum(mask)`` over the old world with
   the dead rank masked (:func:`alive_mask` + ``preduce_mean``; the
   parity test holds this BITWISE through an optimizer step);
5. **rejoin** — a standby coming back first has its PS shard state
   seeded by the ISSUE 4 re-replication machinery (OP_INIT / OP_SYNC
   snapshot / op-log catch-up via ``store.maybe_re_replicate``), then
   the controller grows the world back — hitting the original world
   size's cached executable.

Every resize is a first-class event: ``elastic_*`` counters in the
metrics registry, an ``elastic.resize`` span plus ``elastic:shrink`` /
``elastic:grow`` instant events on the Perfetto trace (ISSUE 10), and a
timeline entry (step, dp transition, recovery_ms) in
:attr:`ElasticController.events` for the bench artifact.

**Failure model (fail-stop, the ISSUE 4 convention).**  A rank is
either correct or silent: the controller shrinks over ranks that
stopped heartbeating AND fail a direct probe.  A rank that is
heartbeat-silent but still answers a probe is *partitioned*, not dead —
resizing over it would run two worlds against one PS lineage, so the
controller HOLDS (``elastic_unreachable_held``) and leaves fencing to
the epoch machinery (ISSUE 8).  Byzantine ranks (wrong answers) are out
of scope.  The resize itself is single-controller: one process owns the
mesh and the decision; multi-controller (jax.distributed) elasticity is
future work and ``resize_world`` refuses multiprocess meshes loudly.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..analysis.protocol import PROTO as _PROTO
from ..metrics import record_elastic
from .. import obs
from .. import race as _race
from .preduce import preduce_mean  # noqa: F401  (re-export: the rescale half)


def alive_mask(world, dead=()):
    """Float32 liveness mask over ``world`` ranks with ``dead`` zeroed —
    the partial-reduce mask under which a masked mean over the full
    world equals the shrunk world's plain mean (the grad-rescale
    equivalence the elastic shrink relies on; bitwise-tested)."""
    mask = np.ones(int(world), np.float32)
    for r in dead:
        mask[int(r)] = 0.0
    return mask


class LogicalRank:
    """One in-process data-parallel worker identity — the unit the
    elastic harness kills and rejoins.

    On real clusters a "rank" is a process (killed by the launcher /
    preemption); the in-process simulation the tier-1 tests and
    ``bench.py --config elastic`` run makes it an object with the same
    two behaviours that matter to elasticity: it can **die**
    (``stop()`` — also the ``kill:proc@rank<r>:step<n>`` chaos target,
    via :func:`hetu_tpu.chaos.ChaosInjector.register_proc`) and it can
    **heartbeat** (``attach_heartbeat(store)`` pings the dist store's
    rank-0 heartbeat table on a daemon thread, so liveness flows
    through the REAL ISSUE 8 machinery instead of a test shim).
    ``rejoin()`` models the standby coming back."""

    def __init__(self, rank):
        self.rank = int(rank)
        self.alive = True
        self._hb_thread = None
        self._hb_stop = None

    def attach_heartbeat(self, store, interval_ms=50.0):
        """Ping ``store.heartbeat(rank)`` every ``interval_ms`` while
        alive (daemon thread, named for the trace track)."""
        self._hb_stop = threading.Event()

        def ping():
            while not self._hb_stop.is_set():
                if self.alive:
                    try:
                        store.heartbeat(self.rank)
                    except (RuntimeError, OSError, ConnectionError):
                        pass    # liveness will notice; death is the point
                self._hb_stop.wait(interval_ms / 1e3)

        self._hb_thread = threading.Thread(
            target=ping, daemon=True, name=f"elastic-hb-r{self.rank}")
        self._hb_thread.start()
        return self

    def stop(self):
        """Die (fail-stop): stop answering liveness.  Chaos's
        ``kill:proc`` step-clock kills call exactly this."""
        self.alive = False

    def rejoin(self):
        """The standby comes back: resume answering liveness."""
        self.alive = True

    def close(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_thread.join(timeout=2.0)

    def __repr__(self):
        return (f"<LogicalRank {self.rank} "
                f"{'alive' if self.alive else 'dead'}>")


class FlapDamper:
    """Consecutive-poll grace gate — the flap-damping half of the
    elastic machinery, extracted (ISSUE 17) so the serving fleet's SLO
    autoscaler reuses it instead of reinventing it.

    A keyed condition must hold for ``grace`` CONSECUTIVE polls before
    :meth:`ready` returns True; a single False poll resets the streak.
    The elastic controller keys it by rejoining rank (a flapping rank
    must not thrash recompiles); the autoscaler keys it by resize
    direction (a noisy p99 must not thrash replica churn).  Poll-driven
    single-caller like its owners — no lock."""

    def __init__(self, grace):
        self.grace = max(1, int(grace))
        self._seen = {}

    def ready(self, key, ok):
        """Record one poll of ``key``'s condition; True once it has held
        ``grace`` consecutive polls (and keeps returning True until the
        condition breaks or :meth:`clear`)."""
        if not ok:
            self._seen.pop(key, None)
            return False
        n = self._seen.get(key, 0) + 1
        self._seen[key] = n
        return n >= self.grace

    def streak(self, key):
        """Current consecutive-ok count for ``key``."""
        return self._seen.get(key, 0)

    def clear(self, key=None):
        """Reset one key's streak (or every streak): the caller acted on
        the signal, the next decision starts from fresh evidence."""
        if key is None:
            self._seen.clear()
        else:
            self._seen.pop(key, None)


def handles_alive_fn(handles):
    """``alive_fn`` over a list of :class:`LogicalRank` handles —
    deterministic liveness for the step-clock chaos tests (a kill at
    step n is visible to the very next ``poll``, no wall-clock wait
    window)."""
    def fn():
        return np.asarray([1.0 if h.alive else 0.0 for h in handles],
                          np.float32)
    return fn


class ElasticController:
    """Drives elastic world resizes for one :class:`Executor`.

    The training loop calls :meth:`poll` once per step boundary (after
    ``executor.run``); the controller consults liveness and, when the
    world changed, drives the shrink/grow dance described in the module
    docstring.  ``executor.resize_world`` does the state
    redistribution; this class owns detection, the wait-window
    semantics, rejoin seeding, and the telemetry.

    Liveness source (exactly one):

    * ``alive_fn`` — callable returning a length-``world`` 0/1 mask
      (in-process harnesses: :func:`handles_alive_fn`);
    * ``store`` — a :class:`~hetu_tpu.ps.dist_store.DistributedStore`
      whose ``liveness_report(heartbeat_deadline_ms)`` classifies
      heartbeat-silent ranks as dead vs unreachable (ISSUE 8).  Dead
      ranks shrink; unreachable ranks HOLD (see the failure-model note).

    ``min_dp`` floors the shrink (below it the controller refuses and
    leaves recovery to the supervisor's restart budget — the two
    mechanisms compose, they don't compete).  ``rejoin_grace`` polls of
    consecutive liveness are required before a grow (a flapping rank
    must not thrash recompiles).
    """

    def __init__(self, executor, world=None, alive_fn=None, store=None,
                 heartbeat_deadline_ms=1000.0, min_dp=2, rejoin_grace=1,
                 re_replicate_on_rejoin=True):
        if (alive_fn is None) == (store is None):
            raise ValueError("ElasticController needs exactly one "
                             "liveness source: alive_fn= or store=")
        self.ex = executor
        if world is None:
            if executor.mesh is None:
                raise ValueError("no mesh: pass world= explicitly")
            world = int(np.prod(executor.mesh.devices.shape))
        self.world = int(world)
        self.alive_fn = alive_fn
        self.store = store
        self.heartbeat_deadline_ms = float(heartbeat_deadline_ms)
        self.min_dp = max(1, int(min_dp))
        self.rejoin_grace = max(1, int(rejoin_grace))
        self.re_replicate_on_rejoin = bool(re_replicate_on_rejoin)
        self.active = list(range(self.world))
        #: resize timeline for the bench artifact: dicts with step, kind,
        #: from_dp/to_dp, the ranks involved, and recovery_ms (detection
        #: poll -> resized executor ready to step)
        self.events = []
        self._rejoin = FlapDamper(self.rejoin_grace)

    @property
    def dp(self):
        return len(self.active)

    # -- liveness ----------------------------------------------------------

    def _liveness(self):
        """(mask over world, set of unreachable ranks)."""
        if self.alive_fn is not None:
            mask = np.asarray(self.alive_fn(),
                              np.float32)[:self.world]
            return mask, frozenset()
        rep = self.store.liveness_report(self.heartbeat_deadline_ms,
                                         n_workers=self.world)
        mask = np.zeros(self.world, np.float32)
        for r in rep["alive"]:
            if r < self.world:
                mask[r] = 1.0
        return mask, frozenset(rep["unreachable"])

    # -- the per-step hook -------------------------------------------------

    def poll(self, step=None):
        """Consult liveness; resize if the world changed.  Returns the
        timeline event dict of a resize that happened, else None.  Call
        at step boundaries only (mid-step the executor's state is being
        swapped)."""
        t0 = time.perf_counter()
        mask, unreachable = self._liveness()
        step = self.ex.step_counter if step is None else int(step)

        dead = [r for r in self.active if not mask[r]]
        held = [r for r in dead if r in unreachable]
        if held:
            # partitioned, not crashed: fencing's problem, not ours
            record_elastic("elastic_unreachable_held", len(held))
            obs.event("elastic:unreachable_held", cat="elastic",
                      ranks=list(held), step=step)
            if _PROTO.on:
                for r in held:
                    _PROTO.emit("elastic", "hold", rank=r, step=step)
            dead = [r for r in dead if r not in held]
        if dead:
            if _PROTO.on:
                for r in dead:
                    _PROTO.emit("elastic", "dead", rank=r, step=step)
            survivors = [r for r in self.active if r not in dead]
            if len(survivors) < self.min_dp:
                record_elastic("elastic_shrink_refused")
                obs.event("elastic:shrink_refused", cat="elastic",
                          step=step, survivors=len(survivors))
                if _PROTO.on:
                    _PROTO.emit("elastic", "refused", step=step,
                                survivors=len(survivors),
                                min_dp=self.min_dp)
            else:
                record_elastic("elastic_dead_rank", len(dead))
                return self._resize("shrink", survivors, dead, step, t0)

        backs = frozenset(r for r in range(self.world)
                          if r not in self.active and mask[r]
                          and r not in unreachable)
        ready = []
        for r in range(self.world):
            if r in self.active:
                continue
            # one damper poll per standby rank: a rank seen back for
            # rejoin_grace consecutive polls is ready; a rank that
            # flapped away restarts its grace (ok=False resets)
            if self._rejoin.ready(r, r in backs):
                ready.append(r)
        if ready:
            record_elastic("elastic_rejoin", len(ready))
            if self.store is not None and self.re_replicate_on_rejoin \
                    and getattr(self.store, "replication", 1) > 1:
                # seed the rejoiner's PS shard state through the ISSUE 4
                # re-replication machinery (OP_INIT / OP_SYNC snapshot /
                # op-log catch-up) BEFORE it carries training traffic
                try:
                    self.store.maybe_re_replicate()
                except (RuntimeError, OSError, ConnectionError):
                    pass    # deferred: the executor's tick retries
            grown = sorted(self.active + ready)
            return self._resize("grow", grown, ready, step, t0)
        return None

    # -- the resize dance --------------------------------------------------

    def _resize(self, kind, new_active, changed, step, t0):
        from_dp, to_dp = self.dp, len(new_active)
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("elastic.resize")
        obs.event(f"elastic:{kind}", cat="elastic", step=step,
                  ranks=list(changed), from_dp=from_dp, to_dp=to_dp)
        with obs.span("elastic.resize", cat="elastic", kind=kind,
                      step=step, from_dp=from_dp, to_dp=to_dp):
            self.ex.resize_world(new_active)
        self.active = list(new_active)
        for r in changed:
            self._rejoin.clear(r)
        ms = (time.perf_counter() - t0) * 1e3
        record_elastic(f"elastic_{kind}")
        record_elastic("elastic_resize_ms", max(1, int(round(ms))))
        ev = {"step": step, "kind": kind, "from_dp": from_dp,
              "to_dp": to_dp, "ranks": list(changed),
              "recovery_ms": round(ms, 3)}
        self.events.append(ev)
        if _PROTO.on:
            _PROTO.emit("elastic", "resize", way=kind, step=step,
                        removed=list(changed) if kind == "shrink" else [],
                        added=list(changed) if kind == "grow" else [],
                        active=list(self.active), min_dp=self.min_dp)
        return ev


__all__ = ["ElasticController", "FlapDamper", "LogicalRank", "alive_mask",
           "handles_alive_fn", "preduce_mean"]
