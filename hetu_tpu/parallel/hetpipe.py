"""HetPipe execution semantics — local optimizer steps + periodic sync.

Reference (``pipedream_subexecutor.py:77-83, 317-328``): under
``pipeline='hetpipe'`` each dp replica of a stage accumulates grads and
applies its optimizer LOCALLY every batch, and the parameter server
reconciles the replicas every ``pp_nrank`` batches — bounded-staleness
data parallelism layered over the pipeline (the HetPipe paper's WSP).

TPU-native design: there is no parameter server between synchronous SPMD
replicas, so the WSP semantics are expressed functionally — each replica
owns a diverging copy of the parameters (stacked leading 'dp' axis,
sharded over the mesh), steps are per-replica ``shard_map`` programs with
NO gradient collective, and the periodic PS reconciliation is a pmean over
the replica axis every ``sync_every`` steps.  For SGD with sync_every=1
this is exactly BSP data parallelism (mean-of-updates == update-of-mean),
parity-tested; larger sync_every trades gradient freshness for zero
per-step collectives — the reference's bounded-staleness knob.
"""
from __future__ import annotations

import numpy as np


class HetPipeTrainer:
    """Local-update data parallelism with periodic parameter averaging.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` for ONE replica's
        microbatch (params: dict name → array).
      params: dict name → initial value (replicated to every replica).
      optimizer: a :mod:`hetu_tpu.optim` optimizer instance.
      mesh: 1-D mesh whose ``axis`` dimension enumerates replicas.
      sync_every: reconcile interval in steps (reference pp_nrank).
    """

    def __init__(self, loss_fn, params, optimizer, mesh, sync_every,
                 axis="dp"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.sync_every = int(sync_every)
        self.optimizer = optimizer
        self.step_count = 0

        stack = lambda v: jnp.broadcast_to(
            jnp.asarray(v)[None], (self.n,) + np.shape(v))
        sharded = NamedSharding(mesh, P(axis))
        self.params = {k: jax.device_put(stack(v), sharded)
                       for k, v in params.items()}
        st = optimizer.init_state({k: np.asarray(v)
                                   for k, v in params.items()})
        self.opt_state = jax.tree.map(
            lambda v: jax.device_put(stack(v), sharded), st)

        p_spec = jax.tree.map(lambda _: P(axis), self.params)
        st_spec = jax.tree.map(lambda _: P(axis), self.opt_state)
        b_spec = P(axis)

        def local_step(params, opt_state, batch, lr):
            # leading stacked axis is 1 per replica inside shard_map
            p = jax.tree.map(lambda v: v[0], params)
            st = jax.tree.map(lambda v: v[0], opt_state)
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            new_p, new_st = optimizer.apply(p, grads, st, lr)
            expand = lambda t: jax.tree.map(lambda v: v[None], t)
            return expand(new_p), expand(new_st), loss[None]

        self._step = jax.jit(jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(p_spec, st_spec, b_spec, P()),
            out_specs=(p_spec, st_spec, P(axis)), check_vma=False))

        def sync(params):
            from jax import lax
            p = jax.tree.map(lambda v: v[0], params)
            avg = jax.tree.map(lambda v: lax.pmean(v, axis), p)
            return jax.tree.map(lambda v: v[None], avg)

        self._sync = jax.jit(jax.shard_map(
            sync, mesh=mesh, in_specs=(p_spec,), out_specs=p_spec,
            check_vma=False))

    def step(self, batch, lr=None):
        """One local step per replica (batch leading dim shards over the
        replica axis); returns per-replica losses.  Applies the periodic
        reconciliation when due."""
        import numpy as _np
        lr = self.optimizer.host_lr(self.step_count) if lr is None else lr
        self.params, self.opt_state, losses = self._step(
            self.params, self.opt_state, batch, _np.float32(lr))
        self.step_count += 1
        if self.step_count % self.sync_every == 0:
            self.params = self._sync(self.params)
        return losses

    def replica_params(self, r=0):
        import jax
        return {k: np.asarray(v)[r] for k, v in self.params.items()}

    def max_divergence(self):
        """Max abs difference of any parameter across replicas (0 right
        after a sync)."""
        worst = 0.0
        for v in self.params.values():
            a = np.asarray(v)
            worst = max(worst, float(np.max(np.abs(a - a[0:1]))))
        return worst
