"""Pipeline parallelism over the 'pp' mesh axis — TPU-native schedules.

Re-design of the reference pipeline engine (``python/hetu/gpu_ops/
pipeline_subexecutor.py:13`` partitioning, ``gpipe_subexecutor.py:7`` GPipe,
``pipedream_subexecutor.py:51`` 1F1B with weight stashing, HetPipe local
accumulation ``pipedream_subexecutor.py:77-83,317-328``).  The reference runs
a *Python scheduler per rank* that pushes microbatches through NCCL
send/recv (``PipelineSend.py:5`` / ``PipelineReceive.py:5``) with group-call
deadlock avoidance; here the whole schedule is ONE scanned SPMD program:

* stages live on the ``pp`` axis of a ``jax.sharding.Mesh``; stage weights
  are *stacked* along a leading axis sharded over ``pp``;
* activations move stage→stage with ``lax.ppermute`` (the native ICI
  collective-permute) inside ``jax.shard_map``;
* the tick loop is ``lax.scan`` over ``M + S - 1`` ticks (M microbatches,
  S stages) — the GPipe schedule, compiled once by XLA;
* backward is simply ``jax.grad`` through the scanned program: transposing
  ``ppermute`` reverses the permutation, so the backward pipeline runs in
  the opposite direction automatically — no hand-written 1F1B scheduler,
  no weight stashing (sync SPMD training has exactly one weight version,
  removing PipeDream's staleness machinery by construction);
* memory: ``remat=True`` recomputes each stage in backward
  (``jax.checkpoint``); ``pipeline='pipedream'`` instead runs the TRUE
  1F1B schedule (:mod:`hetu_tpu.parallel.pipeline_1f1b`) whose explicit
  tick program keeps only S live microbatch activations;
* HetPipe's local-update + periodic-PS-sync (WSP) semantics live in
  :class:`hetu_tpu.parallel.hetpipe.HetPipeTrainer` — per-replica
  diverging parameters with a pmean reconciliation every ``sync_every``
  steps; ``pipeline='hetpipe'`` at block level schedules like GPipe.

Stage functions must be shape-homogeneous (input shape == output shape),
the standard contract for transformer-stack pipelining.
"""
from __future__ import annotations

import numpy as np

from ..graph.node import Op, LowerCtx, PlaceholderOp, topo_sort, \
    placeholder_op
from .strategies import Strategy


def spmd_pipeline_local(stage_fn, params, x_mb, axis_name="pp", remat=False,
                        key=None):
    """GPipe tick loop — call INSIDE ``shard_map`` over the ``pp`` axis.

    Args:
      stage_fn: ``(params, x, key) -> y`` for ONE mesh stage, with
        ``y.shape == x.shape``.  ``params`` may stack several model layers
        per rank (leading dim v) — the caller composes them.
      params: this device's stage parameters (any pytree).
      x_mb: ``[M, mb, ...]`` microbatched input (replicated over ``pp``).
      key: optional PRNG key; each (rank, tick) gets a distinct fold.
    Returns:
      ``[M, mb, ...]`` outputs of the last stage (identical on every
      pp rank after the closing psum).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    S = lax.psum(1, axis_name)
    s = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    dev_key = None if key is None else jax.random.fold_in(key, s)

    def tick(carry, t):
        state, outputs = carry
        inject = lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        inp = jnp.where(s == 0, inject, state)
        k = None if dev_key is None else jax.random.fold_in(dev_key, t)
        y = fn(params, inp, k)
        out_t = t - (S - 1)
        valid = jnp.logical_and(s == S - 1,
                                jnp.logical_and(out_t >= 0, out_t < M))
        upd = lax.dynamic_update_index_in_dim(
            outputs, y, jnp.clip(out_t, 0, M - 1), 0)
        outputs = jnp.where(valid, upd, outputs)
        from .collectives import send_next
        state = send_next(y, axis_name, S)
        return (state, outputs), None

    state0 = jnp.zeros(x_mb.shape[1:], x_mb.dtype)
    outs0 = jnp.zeros_like(x_mb)
    (state, outputs), _ = lax.scan(
        tick, (state0, outs0), jnp.arange(M + S - 1))
    del state
    # only the last stage wrote non-zeros; psum replicates its buffer
    return lax.psum(outputs, axis_name)


def _normalize_stage_fn(stage_fn):
    """Accept both (params, x) and (params, x, key) stage functions."""
    import inspect
    try:
        n = len(inspect.signature(stage_fn).parameters)
    except (TypeError, ValueError):
        n = 3
    if n >= 3:
        return stage_fn
    return lambda p, x, key: stage_fn(p, x)


def pipeline_apply(stage_fn, stacked_params, x, n_microbatches, mesh,
                   axis_name="pp", batch_axis="dp", remat=False, key=None):
    """Run a stacked-stage pipeline over a mesh (the jit-level entry).

    Args:
      stage_fn: ``(params, x[, key]) -> y`` for one model stage
        (shape-preserving).
      stacked_params: pytree whose leaves have leading dim ``n_stages``; must
        be a multiple of the mesh's ``pp`` size — with ``v = n_stages // pp``
        stages per rank, each rank applies its ``v`` stages sequentially
        (the standard looping layout).
      x: ``[B, ...]`` full batch, ``B % n_microbatches == 0``.
      mesh: the active :class:`jax.sharding.Mesh` (must contain ``axis_name``).
      batch_axis: mesh axis sharding the within-microbatch batch dim (or
        ``None``); combined dp×pp runs shard activations over dp too.
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    stage_fn = _normalize_stage_fn(stage_fn)
    S = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages % S:
        raise ValueError(f"{n_stages} stages not divisible over pp={S} ranks")
    M = int(n_microbatches)
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    dp = batch_axis if (batch_axis in mesh.axis_names) else None
    x_spec = P(None, dp, *([None] * (x.ndim - 1)))
    p_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)

    def rank_fn(params, h, k):
        # apply this rank's v stages sequentially (scan over local stack)
        if k is None:
            def body(hh, p_i):
                return stage_fn(p_i, hh, None), None
            out, _ = lax.scan(body, h, params)
        else:
            v = jax.tree.leaves(params)[0].shape[0]

            def body(hh, xs):
                p_i, ki = xs
                return stage_fn(p_i, hh, ki), None
            out, _ = lax.scan(body, h, (params, jax.random.split(k, v)))
        return out

    def local(params, xm):
        return spmd_pipeline_local(rank_fn, params, xm,
                                   axis_name=axis_name, remat=remat, key=key)

    y_mb = jax.shard_map(local, mesh=mesh, in_specs=(p_spec, x_spec),
                         out_specs=x_spec, check_vma=False)(
        stacked_params, x_mb)
    return y_mb.reshape((B,) + y_mb.shape[2:])


def serial_apply(stage_fn, stacked_params, x, remat=False, key=None):
    """Reference semantics: apply S stages sequentially (scan-over-layers).

    Numerically identical to :func:`pipeline_apply` for batch-elementwise
    deterministic stages; used on single-device/no-'pp' meshes and in
    parity tests.
    """
    import jax
    from jax import lax

    stage_fn = _normalize_stage_fn(stage_fn)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    S = jax.tree.leaves(stacked_params)[0].shape[0]
    keys = jax.random.split(key, S) if key is not None else None

    if keys is None:
        def body(h, params):
            return fn(params, h, None), None
        y, _ = lax.scan(body, x, stacked_params)
    else:
        def body(h, xs):
            params, k = xs
            return fn(params, h, k), None
        y, _ = lax.scan(body, x, (stacked_params, keys))
    return y


# ---------------------------------------------------------------------------
# Schedules as explicit generators (reference-parity introspection surface:
# ``pipedream_subexecutor.pipedream_scheduler:25-48``). The SPMD program above
# realizes these orders implicitly; the generators document/teach them and
# drive the schedule-visualization tests.
# ---------------------------------------------------------------------------

def gpipe_schedule(n_stages, n_microbatches):
    """Yield per-tick lists of (stage, microbatch, phase) for GPipe:
    all-forward then all-backward (reference ``gpipe_subexecutor.py:79-89``)."""
    ticks = []
    for t in range(n_microbatches + n_stages - 1):
        ticks.append([(s, t - s, "fwd") for s in range(n_stages)
                      if 0 <= t - s < n_microbatches])
    for t in range(n_microbatches + n_stages - 1):
        ticks.append([(s, t - (n_stages - 1 - s), "bwd")
                      for s in range(n_stages)
                      if 0 <= t - (n_stages - 1 - s) < n_microbatches])
    return ticks


def pipedream_schedule(n_stages, n_microbatches):
    """1F1B order per stage: warmup (n_stages - stage) forwards, then
    alternate 1F1B, then drain (reference ``pipedream_scheduler``:25-48)."""
    per_stage = {}
    for s in range(n_stages):
        warmup = min(n_stages - s, n_microbatches)
        order = [("fwd", m) for m in range(warmup)]
        f, b = warmup, 0
        while b < n_microbatches:
            order.append(("bwd", b)); b += 1
            if f < n_microbatches:
                order.append(("fwd", f)); f += 1
        per_stage[s] = order
    return per_stage


def hetpipe_sync_steps(step, pp_nrank):
    """HetPipe applies the global (PS) sync every ``pp_nrank`` local steps
    (reference ``pipedream_subexecutor.py:317-328``)."""
    return (step + 1) % pp_nrank == 0


def heterogeneous_dp_schedule(stage_dps, n_microbatches):
    """Microbatch→replica routing for per-stage dp degrees (reference
    ``get_schedule_for_different_dp``, pipeline_subexecutor.py:83-106, and
    PipelineSend's round-robin targets :36-39).

    Returns ``[{stage: replica}] * n_microbatches``: microbatch m runs on
    replica ``m % dp[s]`` of stage s — the gcd-cycle pattern: the routing
    between stages s and s+1 repeats with period lcm(dp[s], dp[s+1]).

    In the SPMD executor this schedule is *subsumed* by resharding between
    per-segment meshes (``graph.interop``); the generator documents the
    reference order and drives tests.
    """
    return [{s: m % dp for s, dp in enumerate(stage_dps)}
            for m in range(n_microbatches)]


# ---------------------------------------------------------------------------
# Graph-frontend op: ht.pipeline_block — define ONE stage as a subgraph,
# replicate S× with stacked pp-sharded weights.
# ---------------------------------------------------------------------------

def pipeline_block(x, builder, n_stages, n_microbatches=None, remat=False,
                   schedule=None, name="pipe"):
    """Build an S-stage pipelined block in the define-then-run graph.

    ``builder(stage_in_node) -> out_node`` constructs ONE stage's subgraph
    (Variables created inside become per-stage weights; each stage gets an
    independently-initialized copy, stacked ``[S, ...]`` and sharded over
    'pp').  Under a mesh with a 'pp' axis the op lowers to the shard_map
    GPipe program; otherwise to scan-over-stages (identical numerics).

    This realizes the reference's *intended but incomplete* auto-partition
    path (``pipeline_subexecutor.py:46`` reads config fields that are never
    set — SURVEY.md §7 vestigial list) as a first-class TPU construct.
    """
    stage_in = placeholder_op(f"{name}.stage_in")
    watermark = stage_in.id  # nodes created by the builder have larger ids
    out_node = builder(stage_in)
    topo = topo_sort([out_node])
    if any(isinstance(n, PlaceholderOp) and not n.is_variable
           and n is not stage_in for n in topo):
        raise ValueError("pipeline stage builder may only consume its input "
                         "node (Variables are allowed)")
    template_vars = [n for n in topo
                     if isinstance(n, PlaceholderOp) and n.is_variable]
    outer = [v for v in template_vars if v.id < watermark]
    if outer:
        raise ValueError(
            f"pipeline stage builder references pre-existing Variables "
            f"{[v.name for v in outer]}; each stage gets independent "
            "stacked weights, so sharing a variable with the outer graph "
            "would silently fork it — create the Variables inside the "
            "builder instead")

    stacked_vars = [_make_stacked_var(v, n_stages, name)
                    for v in template_vars]
    return PipelineBlockOp(x, stacked_vars, stage_in, out_node, topo,
                           template_vars, n_stages, n_microbatches, remat,
                           schedule, name=name)


def _make_stacked_var(template, n_stages, prefix):
    from jax.sharding import PartitionSpec as P

    def stacked_init(shape, key):
        import jax
        vals = [np.asarray(template.get_init_value(
            None if key is None else jax.random.fold_in(key, s)))
            for s in range(n_stages)]
        return np.stack(vals, 0)

    if template.shape is None:
        raise ValueError(f"pipeline stage variable {template.name} needs a "
                         "static shape")
    v = PlaceholderOp(f"{prefix}.{template.name}",
                      initializer=stacked_init, trainable=template.trainable,
                      shape=(n_stages,) + tuple(template.shape),
                      dtype=template.dtype)
    v.sharding = P("pp", *([None] * len(template.shape)))
    return v


class PipelineBlockOp(Op):
    op_type = "PipelineBlock"

    def __init__(self, x, stacked_vars, stage_in, out_node, topo,
                 template_vars, n_stages, n_microbatches, remat, schedule,
                 name):
        super().__init__([x] + stacked_vars, name=name)
        self.stage_in = stage_in
        self.out_node = out_node
        self.topo = topo
        self.template_vars = template_vars
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.remat = remat
        self.schedule = schedule  # None → executor's pipeline= setting

    def _stage_fn(self, ctx):
        def fn(params, xval, key):
            env = {self.stage_in: xval}
            env.update(dict(zip(self.template_vars, params)))
            # per-stage/per-tick key threaded in as a traced value,
            # so stages and microbatches get independent dropout
            # masks (distinct from the enclosing graph's keys)
            sub = LowerCtx(ctx.training, key, ctx.mesh)
            for node in self.topo:
                if node in env:
                    continue
                env[node] = node.lower(
                    sub, *[env[i] for i in node.inputs])
            if sub.state_updates:
                raise NotImplementedError(
                    "stateful ops (e.g. BatchNorm running stats) "
                    "inside a pipeline_block stage are not supported"
                    " — their per-stage state updates cannot be "
                    "committed through the stacked-stage scan")
            return env[self.out_node]
        return fn

    def lower(self, ctx, xval, *stacked_vals):
        mesh = ctx.mesh
        fn = self._stage_fn(ctx)
        params = list(stacked_vals)
        key = ctx.rng() if ctx._base_key is not None else None
        if mesh is not None and "pp" in mesh.axis_names \
                and mesh.shape["pp"] > 1:
            M = (self.n_microbatches or ctx.num_microbatches
                 or mesh.shape["pp"])
            sched = self.schedule or getattr(ctx, "pipeline", None) \
                or "gpipe"
            if sched in ("pipedream", "1f1b"):
                sched = "1f1b"
            elif sched not in ("gpipe", "hetpipe"):
                raise ValueError(
                    f"unknown pipeline schedule {sched!r}; expected gpipe, "
                    "pipedream/1f1b, or hetpipe")
            if sched == "1f1b":
                from .pipeline_1f1b import pipeline_apply_1f1b
                return pipeline_apply_1f1b(fn, params, xval, M, mesh,
                                           key=key)
            return pipeline_apply(fn, params, xval, M, mesh,
                                  remat=self.remat, key=key)
        return serial_apply(fn, params, xval, remat=self.remat,
                            key=key)

    def infer_shape(self, input_shapes):
        return input_shapes[0]


class PipelineParallel(Strategy):
    """Strategy: dp×pp mesh (reference ``Executor(..., pipeline=...)`` +
    DeviceGroup stage placement, SURVEY.md §2.3)."""

    def __init__(self, pp, dp=1, schedule="gpipe"):
        assert schedule in ("gpipe", "pipedream", "hetpipe")
        self.pp, self.dp, self.schedule = int(pp), int(dp), schedule

    def make_mesh(self):
        import jax
        from ..context import make_mesh
        return make_mesh({"dp": self.dp, "pp": self.pp},
                         jax.devices()[:self.dp * self.pp])

    def feed_spec(self, node, ndim):
        from jax.sharding import PartitionSpec
        if ndim and self.dp > 1:
            return PartitionSpec("dp", *([None] * (ndim - 1)))
        return PartitionSpec()
