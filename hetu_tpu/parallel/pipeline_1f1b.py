"""True 1F1B (PipeDream-flush) pipeline schedule as one SPMD program.

Round 1's ``pipeline='pipedream'`` was GPipe + remat; this module implements
the real interleaved schedule (reference ``pipedream_subexecutor.py:25-48``
scheduler, ``:130-147`` weight stashing): the backward pass is an explicitly
scheduled ``lax.scan`` where every tick runs one forward-recompute slot and
one backward slot per stage, with stage inputs kept in a VMEM/HBM **ring
buffer of S slots** — so live activations are O(S) per stage instead of the
O(M) that grad-of-GPipe-scan stores.

Mechanics
---------
* The tick→(stage, microbatch, phase) assignment is event-simulated on the
  host at trace time (:func:`compute_1f1b_tables`) from the reference's
  per-stage 1F1B order, giving static (T, S) int tables; each rank picks its
  column with ``lax.axis_index``.
* Forward value pass = forward-only GPipe scan (custom_vjp saves just
  (params, x)).  Backward = the scheduled scan: per tick, the fwd slot
  recomputes one microbatch's stage activation into the ring (PipeDream's
  weight *stash* is unnecessary: synchronous flush semantics mean exactly
  one weight version per step — the recompute plays the stash's role), and
  the bwd slot pulls the stage input from the ring, runs ``jax.vjp`` of the
  stage, accumulates param grads, and ppermutes the input cotangent to the
  previous stage.
* Numerics are identical to GPipe (same per-(stage, microbatch) dropout
  keys in both passes) — parity-tested in tests/test_pipeline.py.
"""
from __future__ import annotations

import numpy as np


def compute_1f1b_tables(n_stages, n_microbatches):
    """Event-simulate synchronous 1F1B; returns (fwd_tab, bwd_tab, T).

    ``fwd_tab[t, s]`` = microbatch whose forward runs on stage s at tick t
    (-1 = idle), likewise ``bwd_tab``.  One op per (tick, stage); an op
    waits until its dependency finished on a *strictly earlier* tick (the
    ppermute delivers between ticks).
    """
    from .pipeline import pipedream_schedule
    S, M = n_stages, n_microbatches
    order = pipedream_schedule(S, M)
    pos = [0] * S
    fwd_done, bwd_done = {}, {}
    fwd_rows, bwd_rows = [], []
    t = 0
    while any(pos[s] < len(order[s]) for s in range(S)):
        frow, brow = [-1] * S, [-1] * S
        for s in range(S):
            if pos[s] >= len(order[s]):
                continue
            phase, m = order[s][pos[s]]
            if phase == "fwd":
                ok = s == 0 or fwd_done.get((s - 1, m), t) < t
            else:
                ok = (s == S - 1 or bwd_done.get((s + 1, m), t) < t) \
                    and fwd_done.get((s, m), t) < t
            if ok:
                if phase == "fwd":
                    frow[s] = m
                    fwd_done[(s, m)] = t
                else:
                    brow[s] = m
                    bwd_done[(s, m)] = t
                pos[s] += 1
        fwd_rows.append(frow)
        bwd_rows.append(brow)
        t += 1
        if t > 4 * (M + S) + 8:
            raise RuntimeError("1F1B schedule failed to converge")
    return (np.asarray(fwd_rows, np.int32), np.asarray(bwd_rows, np.int32),
            len(fwd_rows))


def max_live_activations(n_stages, n_microbatches):
    """Peak in-flight (fwd done, bwd pending) microbatches on any stage —
    the 1F1B memory claim (== n_stages, vs n_microbatches for GPipe)."""
    fwd_tab, bwd_tab, T = compute_1f1b_tables(n_stages, n_microbatches)
    peak = 0
    live = [0] * n_stages
    for t in range(T):
        for s in range(n_stages):
            if fwd_tab[t, s] >= 0:
                live[s] += 1
            if bwd_tab[t, s] >= 0:
                live[s] -= 1
        peak = max(peak, max(live))
    return peak


def pipeline_apply_1f1b(stage_fn, stacked_params, x, n_microbatches, mesh,
                        axis_name="pp", batch_axis="dp", key=None):
    """1F1B counterpart of :func:`hetu_tpu.parallel.pipeline.pipeline_apply`.

    Same contract (stage_fn ``(params, x[, key]) -> y`` shape-preserving,
    stacked params leading dim = n_stages, multiple of mesh pp size); the
    value pass is forward-only, the cotangent pass is the scheduled 1F1B
    scan with an S-slot activation ring per stage.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from .pipeline import _normalize_stage_fn
    from .collectives import send_next, send_prev

    stage_fn = _normalize_stage_fn(stage_fn)
    S = mesh.shape[axis_name]
    n_stages = jax.tree.leaves(stacked_params)[0].shape[0]
    if n_stages % S:
        raise ValueError(f"{n_stages} stages not divisible over pp={S} ranks")
    v = n_stages // S
    M = int(n_microbatches)
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    dp = batch_axis if (batch_axis in mesh.axis_names) else None
    x_spec = P(None, dp, *([None] * (x.ndim - 1)))
    p_spec = jax.tree.map(lambda _: P(axis_name), stacked_params)

    fwd_tab, bwd_tab, T = compute_1f1b_tables(S, M)
    fwd_tab = jnp.asarray(fwd_tab)
    bwd_tab = jnp.asarray(bwd_tab)

    def rank_fn(params, h, m, s_rank):
        """Apply this rank's v consecutive stages; dropout key is folded by
        (global stage, microbatch) so forward and recompute agree exactly."""
        if key is None:
            def body(hh, xs):
                p_i, g_idx = xs
                return stage_fn(p_i, hh, None), None
        else:
            def body(hh, xs):
                p_i, g_idx = xs
                k = jax.random.fold_in(jax.random.fold_in(key, g_idx), m)
                return stage_fn(p_i, hh, k), None
        g_indices = s_rank * v + jnp.arange(v)
        out, _ = lax.scan(body, h, (params, g_indices))
        return out

    # ---------------- forward-only pipeline (value pass) -----------------
    def fwd_local(params, xm):
        s = lax.axis_index(axis_name)

        def tick(carry, t):
            state, outputs = carry
            inject = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(s == 0, inject, state)
            # stage s processes microbatch (t - s) at tick t; the key fold
            # must use that microbatch index so the 1F1B recompute matches
            m_proc = jnp.clip(t - s, 0, M - 1)
            y = rank_fn(params, inp, m_proc, s)
            out_t = t - (S - 1)
            valid = jnp.logical_and(
                s == S - 1, jnp.logical_and(out_t >= 0, out_t < M))
            upd = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(out_t, 0, M - 1), 0)
            outputs = jnp.where(valid, upd, outputs)
            state = send_next(y, axis_name, S)
            return (state, outputs), None

        state0 = jnp.zeros(xm.shape[1:], xm.dtype)
        (state, outputs), _ = lax.scan(
            tick, (state0, jnp.zeros_like(xm)), jnp.arange(M + S - 1))
        del state
        return lax.psum(outputs, axis_name)

    # ---------------- scheduled 1F1B cotangent pass ----------------------
    # Stages idle at different ticks (warmup/drain bubbles), so a received
    # value can sit several ticks before its consumer slot runs: arrivals
    # land in S-slot receive rings keyed by the SENDER's table entry
    # (every rank can read its neighbour's column of the static tables).
    # fwd_tab/bwd_tab are padded with a -1 row so row t reads "what was
    # sent at tick t-1".
    pad = jnp.full((1, S), -1, jnp.int32)
    fwd_prev_tab = jnp.concatenate([pad, fwd_tab])
    bwd_prev_tab = jnp.concatenate([pad, bwd_tab])

    def bwd_local(params, xm, gm):
        s = lax.axis_index(axis_name)
        mb_shape = xm.shape[1:]

        def ring_put(ring, val, m, active):
            upd = lax.dynamic_update_index_in_dim(ring, val, m % S, 0)
            return jnp.where(active, upd, ring)

        def tick(carry, t):
            (fwd_raw, bwd_raw, fwd_ring, bwd_ring, act_ring, dp_acc,
             dx_mb) = carry

            # file last tick's arrivals under the sender's microbatch
            src_f = fwd_prev_tab[t, jnp.clip(s - 1, 0, S - 1)]
            fwd_ring = ring_put(fwd_ring, fwd_raw, jnp.clip(src_f, 0, M - 1),
                                jnp.logical_and(s > 0, src_f >= 0))
            src_b = bwd_prev_tab[t, jnp.clip(s + 1, 0, S - 1)]
            bwd_ring = ring_put(bwd_ring, bwd_raw, jnp.clip(src_b, 0, M - 1),
                                jnp.logical_and(s < S - 1, src_b >= 0))

            fm = fwd_tab[t, s]
            bm = bwd_tab[t, s]

            # forward-recompute slot: stage input into the S-slot ring
            mf = jnp.clip(fm, 0, M - 1)
            x0 = lax.dynamic_index_in_dim(xm, mf, 0, keepdims=False)
            x_rcv = lax.dynamic_index_in_dim(fwd_ring, mf % S, 0,
                                             keepdims=False)
            x_in = jnp.where(s == 0, x0, x_rcv)
            y = rank_fn(params, x_in, mf, s)
            act_ring = ring_put(act_ring, x_in, mf, fm >= 0)

            # backward slot: vjp of this rank's stages at the ringed input
            mb = jnp.clip(bm, 0, M - 1)
            g0 = lax.dynamic_index_in_dim(gm, mb, 0, keepdims=False)
            g_rcv = lax.dynamic_index_in_dim(bwd_ring, mb % S, 0,
                                             keepdims=False)
            g_in = jnp.where(s == S - 1, g0, g_rcv)
            x_saved = lax.dynamic_index_in_dim(act_ring, mb % S, 0,
                                               keepdims=False)
            _, vjp_fn = jax.vjp(
                lambda p, xx: rank_fn(p, xx, mb, s), params, x_saved)
            dp_m, dx_m = vjp_fn(g_in)
            live = bm >= 0
            dp_acc = jax.tree.map(
                lambda a, d: a + jnp.where(live, d, 0), dp_acc, dp_m)
            dx_upd = lax.dynamic_update_index_in_dim(dx_mb, dx_m, mb, 0)
            dx_mb = jnp.where(jnp.logical_and(live, s == 0), dx_upd, dx_mb)

            fwd_raw = send_next(y, axis_name, S)
            bwd_raw = send_prev(dx_m, axis_name, S)
            return (fwd_raw, bwd_raw, fwd_ring, bwd_ring, act_ring, dp_acc,
                    dx_mb), None

        zeros_mb = jnp.zeros(mb_shape, xm.dtype)
        ring0 = jnp.zeros((S,) + mb_shape, xm.dtype)
        dp0 = jax.tree.map(jnp.zeros_like, params)
        carry0 = (zeros_mb, zeros_mb, ring0, ring0, ring0, dp0,
                  jnp.zeros_like(xm))
        (_, _, _, _, _, dp_acc, dx_mb), _ = lax.scan(
            tick, carry0, jnp.arange(T))
        if dp is not None:
            dp_acc = lax.psum(dp_acc, dp)   # params replicated over dp
        dx_mb = lax.psum(dx_mb, axis_name)  # only stage 0 wrote
        return dp_acc, dx_mb

    @jax.custom_vjp
    def run(params, xm):
        return jax.shard_map(fwd_local, mesh=mesh, in_specs=(p_spec, x_spec),
                             out_specs=x_spec, check_vma=False)(params, xm)

    def run_fwd(params, xm):
        return run(params, xm), (params, xm)

    def run_bwd(res, gm):
        params, xm = res
        dparams, dxm = jax.shard_map(
            bwd_local, mesh=mesh, in_specs=(p_spec, x_spec, x_spec),
            out_specs=(p_spec, x_spec), check_vma=False)(params, xm, gm)
        return dparams, dxm

    run.defvjp(run_fwd, run_bwd)
    y_mb = run(stacked_params, x_mb)
    return y_mb.reshape((B,) + y_mb.shape[2:])
