"""Partial reduce — straggler-tolerant dynamic-group gradient averaging.

Reference: ``python/hetu/preduce.py:8`` (P-Reduce, SIGMOD'21): each step a
worker asks the PS for the subset of workers that arrived within a wait
window (``preduce_get_partner``, ps-lite ``preduce_handler.h``), then
NCCL-avg-allreduces over that dynamic subgroup.

TPU-native redesign: XLA SPMD programs are lockstep, so group membership
cannot change *inside* a compiled step — instead membership is an INPUT.
The controller (host side) decides the active mask per step (arrival
simulation, data availability, failed-host report, ...) and the compiled
step computes

    mean_active(g) = psum(mask * g) / psum(mask)

over the full axis — numerically identical to an allreduce over the active
subgroup, with no recompilation and no communicator rebuilds when
membership changes (the reference caches per-subset NCCL comms instead,
preduce.py:32-42).
"""
from __future__ import annotations

import time

import numpy as np

from ..metrics import record_fault


class PartialReduce:
    """Controller + SPMD helpers for dynamic-group gradient averaging.

    ``get_partner(rank, step)`` mirrors the reference API: returns the
    active-worker mask for this step. Arrival bookkeeping lives host-side:
    a pluggable ``arrival_fn`` in-process, or the distributed store's SSP
    clocks across processes (:class:`DistPartialReduce`).

    ``alive_fn`` (optional) supplies a liveness mask (1 = rank alive, from
    e.g. heartbeats): dead ranks are excluded from the group within one
    wait window — graceful degradation instead of a hung collective — and
    every exclusion is counted (``preduce_dead_rank_excluded``).
    """

    def __init__(self, n_workers, max_wait_ms=100.0, min_workers=2,
                 arrival_fn=None, alive_fn=None):
        self.n_workers = n_workers
        self.max_wait_ms = max_wait_ms
        self.min_workers = max(1, min_workers)
        self.arrival_fn = arrival_fn
        self.alive_fn = alive_fn
        self._arrivals = {}

    def _alive(self, rank):
        """Liveness mask (own rank always alive — a worker asking for a
        group is self-evidently not dead); None when liveness is off."""
        if self.alive_fn is None:
            return None
        # copy: the in-place own-rank overwrite below must never mutate
        # (or crash on a read-only view of) the provider's array
        alive = np.array(self.alive_fn(),
                         np.float32)[:self.n_workers].copy()
        alive[rank] = 1.0
        return alive

    def _finalize(self, mask, rank, alive):
        """Own-rank + dead-exclusion + min-workers discipline shared by
        both group formers."""
        mask[rank] = 1.0
        if alive is not None:
            dead = int((alive == 0).sum())
            if dead:
                record_fault("preduce_dead_rank_excluded", dead)
            mask = mask * alive
        if mask.sum() < self.min_workers:
            # degrade to "everyone believed alive", never to ranks known
            # dead — a full-ones fallback would hang the collective on
            # exactly the failure liveness just detected
            mask = np.ones(self.n_workers, np.float32) if alive is None \
                else alive.copy()
            mask[rank] = 1.0
        return mask

    # -- host-side group formation ------------------------------------------
    def report_arrival(self, rank, step, t=None):
        """A worker announces it reached the sync point for ``step``."""
        self._arrivals.setdefault(step, {})[rank] = \
            time.monotonic() if t is None else t

    def get_partner(self, rank, step):
        """Active mask (float32, shape (n_workers,)) for this step.

        Workers that arrived within ``max_wait_ms`` of the first arrival
        are in; the caller's own rank is always in (reference semantics:
        you are part of whatever group the PS hands you).
        """
        alive = self._alive(rank)
        if self.arrival_fn is not None:
            mask = np.asarray(self.arrival_fn(step), np.float32)
        else:
            arr = self._arrivals.get(step, {})
            if not arr:
                mask = np.ones(self.n_workers, np.float32)
            else:
                t0 = min(arr.values())
                mask = np.zeros(self.n_workers, np.float32)
                for r, t in arr.items():
                    if (t - t0) * 1e3 <= self.max_wait_ms:
                        mask[r] = 1.0
        return self._finalize(mask, rank, alive)

    # -- SPMD reduction ------------------------------------------------------
    @staticmethod
    def preduce(grad, mask, axis_name):
        """Inside shard_map/jit: average grads over the active subgroup.

        ``mask`` is the per-device activity scalar (this device's entry of
        the get_partner mask). Inactive devices contribute zeros and still
        receive the group mean (they apply it or ignore it — reference
        PipeDream applies it, pipedream_subexecutor.py:301-313).
        """
        import jax
        num = jax.lax.psum(jax.tree.map(lambda g: g * mask, grad), axis_name)
        den = jax.lax.psum(mask, axis_name)
        return jax.tree.map(lambda v: v / den, num)

    @staticmethod
    def preduce_scatter(grad, mask, axis_name):
        """Alive-mask mean composed with the ZeRO grad layout: each device
        receives its own 1/n slice (leading dim) of ``mean_active(grad)``
        instead of the full mean — ``psum_scatter(mask*g) / psum(mask)``,
        one reduce-scatter where :meth:`preduce` pays a full all-reduce.

        This is how partial reduce feeds the sharded weight update
        (parallel/zero.py): the scattered masked mean IS the per-replica
        grad slice the sharded optimizer consumes, so straggler/dead-rank
        tolerance and ZeRO memory sharding compose in a single collective.
        Every leaf's leading dim must divide the axis size (pack/pad via
        ``zero.pack_slab`` first — its ``(dp, width)`` slabs satisfy this
        by construction).
        """
        import jax
        num = jax.tree.map(
            lambda g: jax.lax.psum_scatter(
                g * mask, axis_name, scatter_dimension=0, tiled=True),
            grad)
        den = jax.lax.psum(mask, axis_name)
        return jax.tree.map(lambda v: v / den, num)


class DistPartialReduce(PartialReduce):
    """Multi-process group formation backed by the distributed store's SSP
    clocks (reference ``preduce_get_partner`` asks the PS the same way,
    ``ps-lite preduce_handler.h``; clocks live on rank 0 — the scheduler
    role).

    Protocol per step: a worker announces arrival by ticking its clock,
    then polls the global clock vector for up to ``max_wait_ms``; workers
    whose clock has reached this worker's own tick count are in the mask.
    Stragglers that miss the window contribute ``mask=0`` for the step —
    the compiled collective stays lockstep, only the averaging weights
    change (see module docstring).
    """

    #: dedicated clock channel — the executor's SSP loop ticks channel 0
    #: every step; sharing it would double-increment and break the
    #: 'arrival at step s ⇔ clock >= s+1' assumption below
    CHANNEL = 1

    def __init__(self, store, n_workers=None, max_wait_ms=100.0,
                 min_workers=2, poll_ms=5.0, heartbeat_deadline_ms=None):
        super().__init__(n_workers or store.world,
                         max_wait_ms=max_wait_ms, min_workers=min_workers)
        self.store = store
        self.poll_ms = poll_ms
        # liveness: with a deadline set, ranks whose heartbeat on rank 0
        # is older than this are DEAD — excluded from the group and, more
        # importantly, not waited for (a dead rank never arrives; waiting
        # out max_wait_ms for it every step is the hang this kills)
        self.heartbeat_deadline_ms = heartbeat_deadline_ms
        # idempotent server-side: safe for every rank to call
        store.ssp_init(self.n_workers, channel=self.CHANNEL)

    def report_arrival(self, rank, step, t=None):
        self.store.clock(rank, channel=self.CHANNEL)

    def _alive(self, rank):
        if self.heartbeat_deadline_ms is None:
            return super()._alive(rank)     # explicit alive_fn still works
        alive = self.store.alive_mask(
            self.heartbeat_deadline_ms,
            n_workers=self.n_workers).astype(np.float32)
        alive[rank] = 1.0
        return alive

    def get_partner(self, rank, step):
        """Active mask for this step from the shared clock vector.

        Assumes one ``report_arrival`` per worker per step, so arrival at
        step s ⇔ clock >= s+1 (every caller's own clock satisfies this
        the moment it reports).  With liveness enabled, the wait loop
        only holds for ranks still believed alive: a dead rank stops
        gating group formation within one wait window."""
        target = step + 1
        deadline = time.monotonic() + self.max_wait_ms / 1e3
        # liveness is sampled ONCE per group formation: it cannot change
        # faster than the heartbeat interval, and an alive_mask RPC per
        # 5 ms poll tick would multiply rank 0's load for nothing (a rank
        # dying mid-window is excluded at the NEXT step's formation).  A
        # failing liveness query degrades to liveness-off for this step —
        # the graceful-degradation path must never itself be the crash.
        try:
            alive = self._alive(rank)
        except RuntimeError:
            record_fault("alive_mask_unavailable")
            alive = None
        while True:
            clocks = self.store.clocks(channel=self.CHANNEL)
            if clocks.size < self.n_workers:
                raise RuntimeError(
                    f"preduce clock vector has {clocks.size} entries < "
                    f"n_workers={self.n_workers} — ssp_init raced or ran "
                    f"with a smaller world")
            mask = (clocks[:self.n_workers] >= target).astype(np.float32)
            # done = every rank we still wait for (all, or alive-only
            # under liveness) has arrived
            done = mask.sum() >= self.n_workers if alive is None \
                else bool((mask >= alive).all())
            if done or time.monotonic() >= deadline:
                break
            time.sleep(self.poll_ms / 1e3)
        return self._finalize(mask, rank, alive)


def preduce_mean(grad, mask, axis_name="dp"):
    """Functional alias of :meth:`PartialReduce.preduce`."""
    return PartialReduce.preduce(grad, mask, axis_name)


def preduce_scatter_mean(grad, mask, axis_name="dp"):
    """Functional alias of :meth:`PartialReduce.preduce_scatter` — the
    dead-rank-tolerant masked mean delivered in the ZeRO reduce-scatter
    layout (each device gets its 1/n leading-dim slice)."""
    return PartialReduce.preduce_scatter(grad, mask, axis_name)


__all__ = ["PartialReduce", "DistPartialReduce", "preduce_mean",
           "preduce_scatter_mean"]
