"""Memory-aware selective rematerialization + activation offload (ISSUE 13).

``Executor(remat=...)`` grows from the round-1 boolean into a POLICY
LADDER — each rung trades a different amount of recompute (or host
traffic) for activation memory:

* ``'off'``    — save every activation (the jax default).
* ``'dots'``   — ``jax.checkpoint`` with the standard
  ``dots_with_no_batch_dims_saveable`` policy: matmul outputs stay
  saved, elementwise chains recompute.  This is exactly what the old
  ``remat=True`` did (``True`` still maps here).
* ``'full'``   — SEGMENTED remat: the forward topo is partitioned into
  contiguous segments anchored at matmul-family ops
  (``HETU_REMAT_SEGMENT_ANCHORS`` anchors per segment, default 6 — about
  one transformer block), each segment lowers inside its own nested
  ``jax.checkpoint``, so the only activations living across the
  forward/backward boundary are the segment BOUNDARY values.  A single
  whole-graph ``nothing_saveable`` wrap does NOT deliver this: the one
  monolithic backward replay keeps every recomputed activation live at
  once (measured: 5% peak saving vs 40% for the segmented form on
  bert-tiny).
* ``'offload'`` — save dot outputs to HOST memory
  (``offload_dot_with_no_batch_dims`` device→pinned_host) where the
  backend supports it (TPU); elsewhere a COUNTED fallback to ``'dots'``
  (``remat_offload_fallback`` — flash-counter style, per build;
  ``HETU_REQUIRE_OFFLOAD=1`` hard-fails instead).
* ``'auto'``   — per-segment policy chosen by the PR 5 shape-inferred
  cost model: each segment is priced (activation bytes it would free vs
  matmul FLOPs a backward replay would re-pay, from
  ``analysis.infer_graph`` shapes — the same pricing
  ``autoparallel.graph_layer_spec`` uses), then segments are greedily
  rematted CHEAPEST-RECOMPUTE-PER-BYTE-FIRST until the projected
  persistent + activation bytes fit the HBM budget
  (``HETU_HBM_BUDGET_MB``, else the backend-reported memory limit).  No
  resolvable budget (or an unpriceable graph) remats every segment —
  the memory-conservative direction — and the ``remat-policy`` lint
  rule says so at construction.

The chosen plan is reported (``Executor.remat_plan()``) and its
fingerprint is hashed into the compiled-step-cache signature
(``graph/step_cache.py``) so two policies — or two ``auto`` plans under
different budgets — can never alias one executable.

Bitwise discipline: remat replays the SAME ops the forward ran (the
per-step RNG folds happen once at trace time, so dropout masks replay
identically), hence every policy's losses are exactly equal to
``'off'`` — the parity tests assert bitwise equality, not tolerance.

Segments never swallow state-writing ops (BatchNorm running stats,
``StateWrite``): their ``ctx.state_updates`` side-channel values must
stay outer-trace tracers, so those nodes lower inline and break the
segment around them.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..metrics import record_remat

POLICIES = ("off", "dots", "full", "offload", "auto")

#: matmul-family + attention op types: segment ANCHORS (their outputs are
#: the expensive-to-recompute values the pricing charges for) — the same
#: families ``autoparallel.cost_model`` prices FLOPs for
ANCHOR_OPS = {"MatrixMult", "Linear", "BatchMatrixMult", "Addmm",
              "Baddbmm", "Einsum", "Conv2d", "Conv2dAddBias"}
ANCHOR_PREFIXES = ("ScaledDotProductAttention", "RingAttention",
                   "UlyssesAttention")

#: ops that write ``ctx.state_updates`` during lowering — inside a
#: checkpointed segment fn those side-channel values would be leaked
#: inner tracers, so these lower inline and break the segment
STATE_WRITING_OPS = {"BatchNorm", "StateWrite"}


def _is_anchor(node):
    t = node.op_type
    return t in ANCHOR_OPS or t.startswith(ANCHOR_PREFIXES)


def anchors_per_segment():
    """Segment granularity: anchors (matmuls) per segment
    (``HETU_REMAT_SEGMENT_ANCHORS``, default 6 ≈ one transformer
    block's q/k/v/o + 2 FFN matmuls)."""
    try:
        return max(1, int(os.environ.get("HETU_REMAT_SEGMENT_ANCHORS",
                                         "6")))
    except ValueError:
        return 6


def resolve_policy(value):
    """Normalize a user ``remat=`` setting to a policy name.

    Booleans keep their pre-ISSUE-13 meaning: ``True`` is the old
    dots-saveable checkpoint wrap, ``False``/``None`` is off.  Unknown
    strings raise (the ``Executor(pipeline=...)`` convention); the
    ``remat-policy`` lint rule additionally diagnoses them for direct
    ``ht.lint(remat=...)`` callers."""
    if value is None or value is False:
        return "off"
    if value is True:
        return "dots"
    pol = str(value).lower()
    if pol not in POLICIES:
        raise ValueError(
            f"remat={value!r}: expected one of {'|'.join(POLICIES)} "
            f"(True == 'dots', False == 'off')")
    return pol


def resolve_budget():
    """HBM budget in bytes for the ``auto`` policy: ``(bytes, source)``
    or ``(None, None)`` when nothing is resolvable.

    ``HETU_HBM_BUDGET_MB`` wins; otherwise the backend-reported memory
    limit (``device.memory_stats()['bytes_limit']`` — TPU reports it,
    XLA-CPU keeps no stats)."""
    env = os.environ.get("HETU_HBM_BUDGET_MB")
    if env:
        try:
            return int(float(env) * 2**20), "HETU_HBM_BUDGET_MB"
        except ValueError:
            pass
    try:
        import jax
        st = jax.devices()[0].memory_stats() or {}
        limit = int(st.get("bytes_limit", 0))
        if limit > 0:
            return limit, "backend"
    except Exception:
        pass
    return None, None


@dataclass
class RematSegment:
    """One contiguous run of forward nodes, anchored at matmuls.

    ``act_bytes`` prices every value the segment produces (what saving
    them costs), ``out_bytes`` the subset that must survive as segment
    BOUNDARIES either way (consumed outside the segment, or fetched),
    ``recompute_flops`` the matmul FLOPs a backward replay re-pays.
    ``saved_bytes`` — what remat actually frees — is the difference."""

    index: int
    nodes: list
    anchors: int = 0
    act_bytes: float = 0.0
    out_bytes: float = 0.0
    recompute_flops: float = 0.0
    remat: bool = False

    @property
    def saved_bytes(self):
        return max(0.0, self.act_bytes - self.out_bytes)

    @property
    def cost_per_byte(self):
        """Greedy ranking key: recompute FLOPs per byte freed (lower =
        cheaper to remat)."""
        return self.recompute_flops / max(1.0, self.saved_bytes)


@dataclass
class RematPlan:
    """The resolved per-segment remat decisions for one subgraph."""

    policy: str
    segments: list = field(default_factory=list)
    budget_bytes: object = None        # int | None
    budget_source: object = None       # str | None
    persistent_bytes: int = 0
    priced: bool = True
    note: str = ""

    @property
    def n_remat(self):
        return sum(1 for s in self.segments if s.remat)

    @property
    def bytes_saved(self):
        return int(sum(s.saved_bytes for s in self.segments if s.remat))

    @property
    def recompute_flops(self):
        return int(sum(s.recompute_flops for s in self.segments
                       if s.remat))

    @property
    def total_act_bytes(self):
        return int(sum(s.act_bytes for s in self.segments))

    def remat_node_lists(self):
        """Node lists for ``lower_forward``'s segmented path — only the
        segments the plan actually remats."""
        return [s.nodes for s in self.segments if s.remat]

    def fingerprint(self):
        """Stable decision fingerprint hashed into the compiled-step-
        cache signature: two plans differing in ANY segment decision (or
        segmentation) must not alias one executable."""
        return (self.policy,
                tuple((len(s.nodes), s.anchors, bool(s.remat))
                      for s in self.segments))

    def report(self):
        """JSON-able plan summary (``Executor.remat_plan()``, the bench
        artifact's per-cell ``remat_plan``)."""
        return {
            "policy": self.policy,
            "segments": len(self.segments),
            "segments_rematted": self.n_remat,
            "budget_bytes": self.budget_bytes,
            "budget_source": self.budget_source,
            "persistent_bytes": int(self.persistent_bytes),
            "activation_bytes_total": self.total_act_bytes,
            "activation_bytes_saved": self.bytes_saved,
            "recompute_flops": self.recompute_flops,
            "priced": bool(self.priced),
            "note": self.note,
            "per_segment": [
                {"index": s.index, "ops": len(s.nodes),
                 "anchors": s.anchors,
                 "act_bytes": int(s.act_bytes),
                 "saved_bytes": int(s.saved_bytes),
                 "recompute_flops": int(s.recompute_flops),
                 "remat": bool(s.remat)}
                for s in self.segments],
        }


def build_segments(topo, skip=()):
    """Partition the lowerable forward nodes of ``topo`` into contiguous
    anchored segments.

    Placeholders resolve outside segments; gradient markers and ``skip``
    (optimizer) nodes never lower; state-writing ops lower inline and
    CLOSE the current segment (their side-channel writes must happen on
    the outer trace).  A segment closes after ``anchors_per_segment()``
    anchors.  Returns only segments containing at least one anchor and
    more than one node — elementwise-only tails free almost nothing."""
    from ..graph.node import PlaceholderOp
    from ..graph.gradients import GradientOp

    per = anchors_per_segment()
    skip = set(skip)
    segments, cur, nanch = [], [], 0

    def close():
        nonlocal cur, nanch
        if cur:
            segments.append(cur)
        cur, nanch = [], 0

    for node in topo:
        if isinstance(node, (PlaceholderOp, GradientOp)) or node in skip:
            continue
        if node.op_type in STATE_WRITING_OPS:
            close()                 # state writer lowers inline
            continue
        cur.append(node)
        if _is_anchor(node):
            nanch += 1
            if nanch >= per:
                close()
    close()
    return [s for s in segments
            if len(s) > 1 and any(_is_anchor(n) for n in s)]


def _price_segments(segments, fetches, topo, skip=()):
    """Per-segment (act_bytes, out_bytes, recompute_flops) from the PR 5
    shape-inferred cost model.  Returns True when every segment priced;
    a failed inference leaves prices at 0 (the caller records
    ``priced=False`` and decides conservatively)."""
    from ..graph.node import PlaceholderOp
    from ..graph.gradients import GradientOp
    try:
        from ..analysis.shapes import infer_graph
        from ..autoparallel.cost_model import matmul_flops, MATMUL_OPS
        gs = infer_graph(fetches)
    except Exception:
        return False

    def nbytes(node):
        st = gs.struct(node)
        if st is None or isinstance(st, (tuple, list)):
            return None
        dt = np.dtype(st.dtype)
        return float(np.prod(st.shape)) * dt.itemsize if st.shape \
            else float(dt.itemsize)

    # consumers over the lowerable node set: a segment value consumed
    # outside its segment survives remat as a boundary
    skip = set(skip)
    lowerable = [n for n in topo
                 if not (isinstance(n, GradientOp) or n in skip)]
    consumers = {}
    for n in lowerable:
        for i in n.inputs:
            consumers.setdefault(i, []).append(n)
    fetch_set = {f for f in fetches if f is not None}

    ok = True
    for seg in segments:
        segset = set(seg.nodes)
        act = out = flops = 0.0
        for node in seg.nodes:
            b = nbytes(node)
            if b is None:
                ok = False
                continue
            act += b
            cons = consumers.get(node, [])
            if node in fetch_set or not cons \
                    or any(c not in segset for c in cons):
                out += b
            if node.op_type in MATMUL_OPS or node.op_type == "Einsum":
                f = None
                st = gs.struct(node)
                if st is not None and not isinstance(st, (tuple, list)):
                    try:
                        f = matmul_flops(node, gs, st.shape)
                    except Exception:
                        f = None
                if f:
                    flops += f
                else:
                    ok = False
            elif node.op_type.startswith("Conv"):
                # conv: 2 · output elements · (kernel numel / out
                # channels) — the contracted Cin·kH·kW per output value
                # (OIHW kernel layout, ops/nn.py)
                try:
                    out_shape = gs.shape(node)
                    w_shape = gs.shape(node.inputs[1])
                    if out_shape and w_shape:
                        flops += 2.0 * float(np.prod(out_shape)) \
                            * float(np.prod(w_shape)) / w_shape[0]
                    else:
                        ok = False
                except Exception:
                    ok = False
            elif node.op_type.startswith(ANCHOR_PREFIXES):
                # attention: scores+values contractions from q/k shapes
                # (graph_layer_spec's formula)
                try:
                    q = gs.shape(node.inputs[0])
                    kv = gs.shape(node.inputs[1])
                    if q and kv:
                        flops += 2.0 * 2.0 * float(np.prod(q[:-2])) \
                            * q[-2] * kv[-2] * q[-1]
                    else:
                        ok = False
                except Exception:
                    ok = False
        seg.act_bytes, seg.out_bytes, seg.recompute_flops = act, out, flops
    return ok


def build_plan(topo, fetches, policy, skip=(), persistent_bytes=0,
               budget=None, budget_source=None):
    """Resolve the per-segment decisions for ``policy`` over one fetch
    subgraph; returns a :class:`RematPlan` (or None for non-segmented
    policies).  Records the ``remat_*`` counters per BUILD (flash-
    counter semantics: per trace, not per step)."""
    if policy not in ("full", "auto"):
        return None
    segs = [RematSegment(index=i, nodes=nodes)
            for i, nodes in enumerate(build_segments(topo, skip=skip))]
    for s in segs:
        s.anchors = sum(1 for n in s.nodes if _is_anchor(n))
    priced = _price_segments(segs, fetches, topo, skip=skip)
    note = ""
    if policy == "full":
        for s in segs:
            s.remat = True
    else:                                   # auto
        if budget is None:
            budget, budget_source = resolve_budget()
        if budget is None or not priced:
            # memory-conservative default: no resolvable budget (or an
            # unpriceable graph) remats everything; the remat-policy
            # lint rule surfaces this at construction
            for s in segs:
                s.remat = True
            note = "no HBM budget resolvable — rematting every segment" \
                if budget is None else \
                "graph not fully priceable — rematting every segment"
        else:
            live = persistent_bytes + sum(s.act_bytes for s in segs)
            for s in sorted(segs, key=lambda s: s.cost_per_byte):
                if live <= budget:
                    break
                s.remat = True
                live -= s.saved_bytes
            if live > budget:
                note = (f"budget {budget} B not reachable even with "
                        f"every segment rematted (projected {int(live)} "
                        f"B)")
    plan = RematPlan(policy=policy, segments=segs, budget_bytes=budget,
                     budget_source=budget_source,
                     persistent_bytes=int(persistent_bytes),
                     priced=priced, note=note)
    record_remat("remat_layers_total", len(segs))
    record_remat("remat_layers_rematted", plan.n_remat)
    record_remat("remat_bytes_saved", plan.bytes_saved)
    record_remat("remat_recompute_flops", plan.recompute_flops)
    return plan


def plan_for(sub):
    """Build the remat plan for one training SubExecutor (``'full'`` /
    ``'auto'`` policies only; forward-only subgraphs have nothing to
    remat).  Called at SubExecutor construction so
    ``Executor.remat_plan()`` answers before the first run and the
    step-cache signature can hash the decisions."""
    ex = sub.ex
    if ex.remat not in ("full", "auto") or not sub.grad_ops:
        return None
    persistent = 0
    try:
        mem = ex.memory_accounting()
        persistent = (mem["param_bytes_per_device"]
                      + mem["zero_slab_bytes_per_device"]
                      + mem["opt_state_bytes_per_device"]
                      + mem["grad_bytes_per_device"])
    except Exception:
        pass
    return build_plan(sub.topo, sub.fetches, ex.remat,
                      skip=sub.opt_ops, persistent_bytes=persistent)


def offload_checkpoint_policy():
    """The activation-offload checkpoint policy, or ``None`` with a
    counted fallback where the backend cannot host-offload (flash-
    dispatcher style: ``remat_offload_fallback`` per build;
    ``HETU_REQUIRE_OFFLOAD=1`` raises instead)."""
    import jax
    reason = None
    if jax.default_backend() != "tpu":
        reason = f"backend_{jax.default_backend()}"
    elif not hasattr(jax.checkpoint_policies,
                     "offload_dot_with_no_batch_dims"):
        reason = "jax_version"
    if reason is None:
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    record_remat("remat_offload_fallback")
    if os.environ.get("HETU_REQUIRE_OFFLOAD") == "1":
        raise RuntimeError(
            f"HETU_REQUIRE_OFFLOAD=1 but activation offload is "
            f"unavailable here (reason: {reason})")
    return None


def wrap_loss(loss_fn, policy):
    """Apply a WRAP-STYLE policy to the whole loss function.

    ``'dots'`` and ``'offload'`` (and the pipeline schedulers'
    per-microbatch default, ``'microbatch'``) are single
    ``jax.checkpoint`` wraps; the segmented policies (``full``/``auto``)
    act inside ``lower_forward`` instead and must not be double-wrapped
    here."""
    import jax
    if policy == "dots":
        return jax.checkpoint(
            loss_fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if policy == "offload":
        pol = offload_checkpoint_policy()
        if pol is None:      # counted fallback: save dots on device
            pol = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        return jax.checkpoint(loss_fn, policy=pol)
    if policy == "microbatch":
        # 1F1B/hetpipe per-microbatch footprint: recompute everything
        # (the microbatch forward is small — the pre-13 behavior)
        return jax.checkpoint(loss_fn)
    raise ValueError(f"wrap_loss: not a wrap-style policy: {policy!r}")


def checkpoint_segment(fn):
    """The nested per-segment checkpoint ``lower_forward`` applies to a
    rematted segment (one seam, so tests can observe wrap counts)."""
    import jax
    return jax.checkpoint(fn)


__all__ = ["POLICIES", "ANCHOR_OPS", "STATE_WRITING_OPS",
           "resolve_policy", "resolve_budget", "anchors_per_segment",
           "RematSegment", "RematPlan", "build_segments", "build_plan",
           "plan_for", "offload_checkpoint_policy", "wrap_loss",
           "checkpoint_segment"]
