"""Context/sequence parallelism: ring attention + Ulysses (all-to-all).

The reference has NO sequence-parallel machinery (SURVEY.md §5.7 — its long-
sequence story is algorithmic models like longformer/bigbird, and its
adjacent plumbing is the MoE AllToAll, ``src/communication/
mpi_nccl_communication.cu:383``).  Capability parity for "scale the sequence
length" is therefore delivered the TPU-native way, as first-class schedules
over a ``cp`` mesh axis:

* **Ring attention** (Liu et al. '23 pattern): K/V chunks rotate around the
  ``cp`` ring via ``lax.ppermute`` while each device keeps an online-softmax
  accumulator over its resident Q chunk — peak memory O(S/cp), comms ride
  the ICI ring, and blockwise compute overlaps with the permute.
* **Ulysses** (DeepSpeed-Ulysses pattern): ``lax.all_to_all`` reshards
  [B, H, S/cp, D] → [B, H/cp, S, D] so each device runs FULL-sequence
  attention over a head subset, then the inverse all-to-all restores the
  sequence sharding — the same a2a plumbing expert parallelism uses.

Both are differentiable (ppermute/all_to_all transpose to their inverses,
so the backward pass is itself ring-/a2a-scheduled) and compose with dp
(batch axis) and tp (head axis, Ulysses excepted) on the same mesh.
"""
from __future__ import annotations

import functools


from .strategies import Strategy

_NEG_INF = -1e30  # finite: keeps exp(m - m_new) well-defined on masked rows


def ring_attention_local(q, k, v, bias=None, key_mask=None, mask=None,
                         axis_name="cp", causal=False, scale=None):
    """Online-softmax ring attention — call INSIDE shard_map over ``cp``.

    q, k, v: local chunks [B, H, Sc, D] (sequence dim sharded over the ring).
    ``bias``: optional additive logit bias, [1|B, 1|H, Sc|1, S_kv] — the
    query dim is ring-sharded like q, the KEY dim stays FULL locally and the
    ring step slices the resident chunk's columns (T5 relative position
    bias through context parallelism).  Differentiable: the scan transposes
    to a reverse ring, so dbias flows back automatically.
    ``key_mask``: optional [1|B, S_kv] key-validity flags, kept FULL locally
    and column-sliced per ring step (padded pretraining through cp; rows
    with no valid key yield zero output via the l==0 guard below).
    ``mask``: optional FULL per-query validity [1|B, 1|H, Sc|1, S_kv] —
    query dim ring-sharded like the bias's, key dim full locally and
    column-sliced per step (XLNet-style permutation masks at long
    context — round-4 verdict item 5).
    Returns the local output chunk [B, H, Sc, D].
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .ring_flash import flash_ring_reason, ring_flash_attention_local
    reason = flash_ring_reason(q, k)
    if reason is None:
        # per-step Pallas flash kernel + LSE merge (TPU; the einsum ring
        # below is the reference path and the CPU/odd-shape fallback).
        # Bias rides the kernel too — no einsum detour for T5-style
        # relative-position-bias workloads under context parallelism.
        return ring_flash_attention_local(
            q, k, v, bias=bias, key_mask=key_mask, mask=mask,
            axis_name=axis_name, causal=causal, scale=scale)
    from ..ops.attention import _note_flash_fallback
    _note_flash_fallback(f"ring:{reason}")

    S = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    B, H, Sc, D = q.shape
    sc = scale if scale is not None else 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32) * sc
    bias_f = None if bias is None else bias.astype(jnp.float32)
    km = None if key_mask is None else (key_mask != 0)
    fm = None if mask is None else (mask != 0)

    q_pos = r * Sc + jnp.arange(Sc)

    def step(carry, t):
        kc, vc, m, l, o = carry
        src = (r - t) % S  # which global chunk we currently hold
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32))
        if bias_f is not None:
            logits = logits + lax.dynamic_slice_in_dim(
                bias_f, src * Sc, Sc, axis=3)
        valid = None
        if km is not None:
            cols = lax.dynamic_slice_in_dim(km, src * Sc, Sc, axis=1)
            valid = jnp.broadcast_to(cols[:, None, None, :], logits.shape)
        if fm is not None:
            cols = lax.dynamic_slice_in_dim(fm, src * Sc, Sc, axis=3)
            cols = jnp.broadcast_to(cols, logits.shape)
            valid = cols if valid is None else jnp.logical_and(valid, cols)
        if causal:
            k_pos = src * Sc + jnp.arange(Sc)
            cmask = jnp.broadcast_to(q_pos[:, None] >= k_pos[None, :],
                                     logits.shape)
            valid = cmask if valid is None else jnp.logical_and(valid, cmask)
        if valid is not None:
            logits = jnp.where(valid, logits, _NEG_INF)
        m_new = jnp.maximum(m, logits.max(-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[..., None])
        if valid is not None:
            # an all-masked chunk before any valid one has m == m_new ==
            # _NEG_INF, where exp(logits - m_new) == 1 would leak a uniform
            # average of the value vectors (kernel-side guard mirrored)
            p = p * valid
        l = l * alpha + p.sum(-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        perm = [(i, (i + 1) % S) for i in range(S)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (kc, vc, m_new, l, o), None

    m0 = jnp.full((B, H, Sc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Sc), jnp.float32)
    o0 = jnp.zeros((B, H, Sc, D), jnp.float32)
    (kc, vc, m, l, o), _ = jax.lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(S))
    del kc, vc, m
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows → zero output
    return (o / l[..., None]).astype(q.dtype)


def ulysses_attention_local(q, k, v, bias=None, key_mask=None, mask=None,
                            axis_name="cp", causal=False, scale=None,
                            attn_fn=None):
    """Ulysses head/sequence all-to-all attention — INSIDE shard_map.

    q, k, v: local chunks [B, H, Sc, D]; H must divide by the ``cp`` size.
    ``bias``: optional additive logit bias [1|B, Hc|1, S, S] — already the
    LOCAL head block (the jit entry shards a multi-head bias over 'cp',
    matching the contiguous head blocks ``all_to_all`` deals out).
    ``key_mask``: optional [1|B, S_kv] key-validity flags (head-independent,
    so the a2a does not touch them) — applied on the full-sequence local
    attention (padded pretraining through cp).
    ``mask``: optional FULL per-query validity [1|B, Hc|1, S, S_kv] —
    like the bias, a multi-head mask arrives pre-sharded to the local
    head block; both sequence dims are full after the a2a.
    """
    import jax.numpy as jnp
    from jax import lax

    S = lax.psum(1, axis_name)
    if q.shape[1] % S:
        raise ValueError(f"heads {q.shape[1]} not divisible by cp={S}")
    # [B, H, Sc, D] → [B, H/cp, S, D]: trade head shards for full sequence
    a2a = functools.partial(lax.all_to_all, axis_name=axis_name,
                            split_axis=1, concat_axis=2, tiled=True)
    qh, kh, vh = a2a(q), a2a(k), a2a(v)
    if attn_fn is None:
        # after the a2a each device holds the FULL sequence for its head
        # subset — exactly the shape where the flash kernel pays off, so
        # route through the backend dispatcher (reference path on CPU)
        from ..ops.attention import (dispatch_sdpa, dispatch_sdpa_bias,
                                     dispatch_sdpa_masked,
                                     dispatch_sdpa_masked_bias)
        mask4 = None
        if key_mask is not None:
            mask4 = key_mask[:, None, None, :]
        if mask is not None:
            mask4 = mask if mask4 is None \
                else jnp.logical_and(mask4 != 0, mask != 0)
        if mask4 is not None:
            if bias is not None:
                attn_fn = functools.partial(dispatch_sdpa_masked_bias,
                                            mask=mask4, bias=bias,
                                            causal=causal, scale=scale)
            else:
                attn_fn = functools.partial(dispatch_sdpa_masked, mask=mask4,
                                            causal=causal, scale=scale)
        elif bias is not None:
            attn_fn = functools.partial(dispatch_sdpa_bias, bias=bias,
                                        causal=causal, scale=scale)
        else:
            attn_fn = functools.partial(dispatch_sdpa, causal=causal,
                                        scale=scale)
    oh = attn_fn(qh, kh, vh)
    # inverse: [B, H/cp, S, D] → [B, H, Sc, D]
    return lax.all_to_all(oh, axis_name=axis_name, split_axis=2,
                          concat_axis=1, tiled=True)


def _cp_spec(mesh, batch_axis="dp"):
    from jax.sharding import PartitionSpec as P
    dp = batch_axis if batch_axis in mesh.axis_names else None
    return P(dp, None, "cp", None)


def _norm_key_mask(key_mask, s_kv):
    """Accept (B|1, S_kv) or the (B|1, 1, 1, S_kv) attention-mask
    convention → (B|1, S_kv)."""
    import jax.numpy as jnp
    km = jnp.asarray(key_mask)
    if km.ndim == 4:
        km = km.reshape(km.shape[0], km.shape[-1])
    if km.ndim != 2 or km.shape[-1] != s_kv:
        raise ValueError(f"key_mask must be (B, {s_kv}), got "
                         f"{key_mask.shape}")
    return km


def ring_attention(q, k, v, mesh, bias=None, key_mask=None, mask=None,
                   axis_name="cp", causal=False, scale=None,
                   batch_axis="dp"):
    """jit-level entry: q/k/v are full [B, H, S, D]; S shards over 'cp'.

    ``bias``: optional [1|B, 1|H, S|1, S] additive bias — its query dim
    rides the ring shards, the key dim stays full (sliced per ring step).
    ``key_mask``: optional (B|1, S) or (B|1, 1, 1, S) key-validity flags —
    kept full locally, column-sliced per ring step.
    ``mask``: optional FULL per-query validity [1|B, 1|H, S|1, S] — query
    dim ring-sharded exactly like the bias's, key dim column-sliced per
    step (XLNet-style permutation masks under cp)."""
    import jax
    from jax.sharding import PartitionSpec as P
    spec = _cp_spec(mesh, batch_axis)
    # batched extras must follow q/k/v's batch sharding, or local shapes
    # mismatch on a dp x cp mesh; broadcast dims stay replicated
    dp = batch_axis if batch_axis in mesh.axis_names else None
    args, in_specs, keys = [q, k, v], [spec, spec, spec], []
    if bias is not None:
        args.append(bias)
        in_specs.append(P(dp if bias.shape[0] > 1 else None, None,
                          "cp" if bias.shape[2] > 1 else None, None))
        keys.append("bias")
    if key_mask is not None:
        km = _norm_key_mask(key_mask, k.shape[2])
        args.append(km)
        in_specs.append(P(dp if km.shape[0] > 1 else None, None))
        keys.append("key_mask")
    if mask is not None:
        if mask.ndim != 4:
            raise ValueError(f"full mask must be 4-D (B|1, H|1, S|1, S); "
                             f"got {mask.shape}")
        args.append(mask)
        in_specs.append(P(dp if mask.shape[0] > 1 else None, None,
                          "cp" if mask.shape[2] > 1 else None, None))
        keys.append("mask")

    def fn(q, k, v, *extras):
        kw = dict(zip(keys, extras))
        return ring_attention_local(q, k, v, axis_name=axis_name,
                                    causal=causal, scale=scale, **kw)

    return jax.shard_map(fn, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=spec, check_vma=False)(*args)


def _head_extra_spec(x, what, b0, cp_size):
    """Spec for a [B|1, H|1, S, S] extra whose HEAD dim (not sequence)
    shards over 'cp' — matching the contiguous head blocks all_to_all
    deals out in the Ulysses schedule."""
    from jax.sharding import PartitionSpec as P
    if x.shape[1] == 1:
        return P(b0, None, None, None)
    if x.shape[1] % cp_size == 0:
        return P(b0, "cp", None, None)
    raise ValueError(f"ulysses {what} heads {x.shape[1]} not divisible "
                     f"by cp={cp_size}")


def ulysses_attention(q, k, v, mesh, bias=None, key_mask=None, mask=None,
                      axis_name="cp", causal=False, scale=None,
                      batch_axis="dp"):
    """jit-level entry: q/k/v are full [B, H, S, D]; S shards over 'cp'.

    ``bias``: optional [1|B, H|1, S, S] — a multi-head bias shards its head
    dim over 'cp' (matching all_to_all's contiguous head blocks).
    ``key_mask``: optional (B|1, S) or (B|1, 1, 1, S) — head-independent,
    applied after the a2a on the full sequence.
    ``mask``: optional FULL per-query validity [1|B, H|1, S, S] — sharded
    like the bias (head dim over 'cp'; sequence dims full after the a2a)."""
    import jax
    from jax.sharding import PartitionSpec as P
    spec = _cp_spec(mesh, batch_axis)
    dp = batch_axis if batch_axis in mesh.axis_names else None
    cp_size = mesh.shape[axis_name]
    args, in_specs, keys = [q, k, v], [spec, spec, spec], []
    if bias is not None:
        b0 = dp if bias.shape[0] > 1 else None  # follow q/k/v batch shard
        args.append(bias)
        in_specs.append(_head_extra_spec(bias, "bias", b0, cp_size))
        keys.append("bias")
    if key_mask is not None:
        km = _norm_key_mask(key_mask, k.shape[2])
        args.append(km)
        in_specs.append(P(dp if km.shape[0] > 1 else None, None))
        keys.append("key_mask")
    if mask is not None:
        if mask.ndim != 4:
            raise ValueError(f"full mask must be 4-D (B|1, H|1, S, S); "
                             f"got {mask.shape}")
        b0 = dp if mask.shape[0] > 1 else None
        args.append(mask)
        in_specs.append(_head_extra_spec(mask, "mask", b0, cp_size))
        keys.append("mask")

    def fn(q, k, v, *extras):
        kw = dict(zip(keys, extras))
        return ulysses_attention_local(q, k, v, axis_name=axis_name,
                                       causal=causal, scale=scale, **kw)

    return jax.shard_map(fn, mesh=mesh, in_specs=tuple(in_specs),
                         out_specs=spec, check_vma=False)(*args)


class ContextParallel(Strategy):
    """Strategy: dp×cp mesh for long-sequence training (new axis vs the
    reference — SURVEY.md §7 design mapping 'SP/CP')."""

    def __init__(self, cp, dp=1):
        self.cp, self.dp = int(cp), int(dp)

    def make_mesh(self):
        import jax
        from ..context import make_mesh
        return make_mesh({"dp": self.dp, "cp": self.cp},
                         jax.devices()[:self.dp * self.cp])

    def feed_spec(self, node, ndim):
        from jax.sharding import PartitionSpec
        if ndim and self.dp > 1:
            return PartitionSpec("dp", *([None] * (ndim - 1)))
        return PartitionSpec()
