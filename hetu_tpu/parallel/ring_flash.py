"""Ring attention with the Pallas flash kernel per ring step.

The plain ring schedule (:func:`ring_attention_local`) computes each
resident K/V chunk with an XLA einsum — materialising (B, H, Sc, Sc)
score blocks per step.  On TPU the flash kernel is the faster and
O(Sc·D)-memory way to process a chunk, so this module composes the two:

* **forward** — each ring step runs the EXISTING flash forward
  (``_flash_fwd``: out + per-row LSE) on the resident chunk; chunk
  results merge with the standard log-sum-exp combination (the same
  online-softmax algebra the kernel uses internally, lifted one level).
* **backward** — flash-attention-2's chunked backward needs only the
  GLOBAL out/LSE: per chunk, ``p_ij = exp(s_ij − lse_i)`` reconstructs
  the exact global softmax, so each ring step runs the EXISTING
  ``_flash_bwd`` on its resident chunk; dq accumulates locally while
  dk/dv ride the ring home with their chunk.

No kernel changes: both pallas_calls are the hardware-validated
specializations from :mod:`hetu_tpu.ops.pallas.flash_attention`; this
module is pure orchestration under ``jax.custom_vjp`` (pallas_call has no
autodiff — the ring IS the vjp).  CPU CI runs the same code with
``interpret=True``.

Supported: dense, causal, key-padding masks, full per-query masks.
Additive bias stays on the einsum ring (its gradient needs per-chunk
column accumulation that is not worth a second code path until a workload
demands it) — the dispatcher in :mod:`ring_attention` falls back.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.flash_attention import (_broadcast_group, _f0, _flash_bwd,
                                          _flash_fwd)

_NEG = -1e30


def _chunk_cols(x, src, sc, axis):
    return lax.dynamic_slice_in_dim(x, src * sc, sc, axis=axis)


def _masks_for_chunk(key_mask, fmask, src, sc, b, h):
    """Per-step kernel inputs: key-mask column strip (B, 1, Sc) and/or
    full-mask block in un-broadcast (G, Sc, Sc) storage + gmode."""
    kmask2 = fmask3 = None
    gmode = "one"
    if key_mask is not None:
        kmask2 = _chunk_cols(key_mask, src, sc, 1).astype(jnp.int32)[:, None, :]
    if fmask is not None:
        blk = _chunk_cols(fmask, src, sc, 3).astype(jnp.int32)
        fmask3, gmode = _broadcast_group(blk, b, h, sc, sc, "mask")
    return kmask2, fmask3, gmode


def _ring_perm(axis_name, S):
    return [(i, (i + 1) % S) for i in range(S)]


def _fwd_step(q3, kc3, vc3, kmask2, fmask3, gmode, scale, causal_flag,
              h, blocks, interpret):
    """branch body: flash forward on one resident chunk → (o, lse)."""
    bq, bk = blocks
    return _flash_fwd(q3, kc3, vc3, None, kmask2, None, fmask3, None,
                      scale, causal_flag, gmode, "one", "one", h, bq, bk,
                      interpret)


def ring_flash_attention_local(q, k, v, key_mask=None, mask=None,
                               axis_name="cp", causal=False, scale=None,
                               block_q=None, block_k=None,
                               interpret=False):
    """Flash-kernel ring attention — call INSIDE shard_map over ``cp``.

    Same contract as :func:`ring_attention_local` (q/k/v local chunks
    [B, H, Sc, D]; ``key_mask`` [1|B, S_kv] full-key local; ``mask``
    [1|B, 1|H, Sc|1, S_kv] query-sharded/full-key local), minus ``bias``.
    Sc and D must satisfy the kernel's 128-divisibility.
    """
    B, H, Sc, D = q.shape
    sc_val = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    blocks = (block_q or min(128, Sc), block_k or min(128, Sc))
    km = None
    if key_mask is not None:
        km = jnp.broadcast_to(jnp.asarray(key_mask),
                              (B, key_mask.shape[-1]))
    fm = None
    if mask is not None:
        fm = jnp.broadcast_to(
            jnp.asarray(mask),
            (mask.shape[0], mask.shape[1], Sc, mask.shape[3]))

    return _ring_flash(q, k, v, km, fm, axis_name, bool(causal), sc_val,
                       blocks, B, H, Sc, D, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10,
                                                    11, 12, 13))
def _ring_flash(q, k, v, km, fm, axis_name, causal, scale, blocks,
                B, H, Sc, D, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, km, fm, axis_name, causal,
                                  scale, blocks, B, H, Sc, D, interpret)
    return out


def _ring_flash_fwd_impl(q, k, v, km, fm, axis_name, causal, scale, blocks,
                         B, H, Sc, D, interpret):
    S = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, S)
    q3 = q.reshape(B * H, Sc, D)

    m = jnp.full((B * H, Sc, 1), _NEG, jnp.float32)   # running max of lse
    s = jnp.zeros((B * H, Sc, 1), jnp.float32)        # Σ exp(lse_i − m)
    o = jnp.zeros((B * H, Sc, D), jnp.float32)        # Σ w_i · o_i
    kc, vc = k, v
    for t in range(S):
        src = (r - t) % S
        kc3 = kc.reshape(B * H, Sc, D)
        vc3 = vc.reshape(B * H, Sc, D)
        kmask2, fmask3, gmode = _masks_for_chunk(km, fm, src, Sc, B, H)

        def dense_or_causal(flag):
            def f(_):
                return _fwd_step(q3, kc3, vc3, kmask2, fmask3, gmode,
                                 scale, flag, H, blocks, interpret)
            return f

        def skipped(_):
            return (jnp.zeros_like(q3),
                    jnp.full((B * H, Sc, 1), 2 * _NEG, jnp.float32))

        if causal:
            # src == r: diagonal chunk (kernel causal); src < r: every key
            # precedes every query (dense); src > r: fully masked — skip
            # the kernel entirely (the causal-ring FLOP saving)
            branch = jnp.where(src == r, 2, jnp.where(src < r, 1, 0))
            oi, lse = lax.switch(branch, [skipped,
                                          dense_or_causal(False),
                                          dense_or_causal(True)],
                                 operand=None)
        else:
            oi, lse = dense_or_causal(False)(None)

        m_new = jnp.maximum(m, lse)
        # guard the all-masked rows: exp(−inf − (−inf)) must be 0, not 1
        w_old = jnp.where(m > _NEG, jnp.exp(m - m_new), 0.0)
        w_new = jnp.where(lse > _NEG, jnp.exp(lse - m_new), 0.0)
        s = s * w_old + w_new
        o = o * w_old + oi.astype(jnp.float32) * w_new
        m = m_new
        if t < S - 1:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)

    s_safe = jnp.where(s == 0.0, 1.0, s)
    out = (o / s_safe).astype(q.dtype).reshape(B, H, Sc, D)
    lse_g = jnp.where(s > 0.0, m + jnp.log(s_safe),
                      jnp.full_like(m, 2 * _NEG))       # (B·H, Sc, 1)
    return out, lse_g


def _ring_flash_vjp_fwd(q, k, v, km, fm, axis_name, causal, scale, blocks,
                        B, H, Sc, D, interpret):
    out, lse_g = _ring_flash_fwd_impl(q, k, v, km, fm, axis_name, causal,
                                      scale, blocks, B, H, Sc, D, interpret)
    return out, (q, k, v, km, fm, out, lse_g)


def _ring_flash_vjp_bwd(axis_name, causal, scale, blocks, B, H, Sc, D,
                        interpret, res, do):
    q, k, v, km, fm, out, lse_g = res
    # fully-masked rows carry the 2·_NEG LSE sentinel; fed raw into the
    # kernel's p = exp(s − lse) it overflows to inf and NaNs the whole
    # chunk's dk/dv.  Re-pin those rows to lse=0: their s entries are all
    # ≈ −1e30, so p = exp(−1e30) = 0 and the row's gradients vanish —
    # matching the forward's zero output.  Valid rows are untouched.
    lse_g = jnp.where(lse_g <= _NEG, 0.0, lse_g)
    S = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, S)
    q3 = q.reshape(B * H, Sc, D)
    out3 = out.reshape(B * H, Sc, D)
    do3 = do.reshape(B * H, Sc, D)

    dq = jnp.zeros((B * H, Sc, D), jnp.float32)
    # dk/dv accumulators ride the ring WITH their chunk: after S rotations
    # every chunk is home again carrying the sum over all query owners
    kc, vc = k, v
    dkc = jnp.zeros_like(k, dtype=jnp.float32)
    dvc = jnp.zeros_like(v, dtype=jnp.float32)
    for t in range(S):
        src = (r - t) % S
        kc3 = kc.reshape(B * H, Sc, D)
        vc3 = vc.reshape(B * H, Sc, D)
        kmask2, fmask3, gmode = _masks_for_chunk(km, fm, src, Sc, B, H)

        def run(flag):
            def f(_):
                dqi, dki, dvi, _db, _dkb = _flash_bwd(
                    q3, kc3, vc3, None, kmask2, None, fmask3, None,
                    out3, lse_g, do3, scale, flag, gmode, "one", "one",
                    H, blocks[0], blocks[1], interpret)
                return dqi, dki, dvi
            return f

        def skipped(_):
            return (jnp.zeros_like(q3), jnp.zeros_like(q3),
                    jnp.zeros_like(q3))

        if causal:
            branch = jnp.where(src == r, 2, jnp.where(src < r, 1, 0))
            dqi, dki, dvi = lax.switch(branch, [skipped, run(False),
                                                run(True)], operand=None)
        else:
            dqi, dki, dvi = run(False)(None)

        dq = dq + dqi.astype(jnp.float32)
        dkc = dkc + dki.astype(jnp.float32).reshape(B, H, Sc, D)
        dvc = dvc + dvi.astype(jnp.float32).reshape(B, H, Sc, D)
        if t < S - 1:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            dkc = lax.ppermute(dkc, axis_name, perm)
            dvc = lax.ppermute(dvc, axis_name, perm)
    # one final rotation brings chunk (r−(S−1))%S ≡ (r+1)%S home
    dkc = lax.ppermute(dkc, axis_name, perm)
    dvc = lax.ppermute(dvc, axis_name, perm)
    return (dq.astype(q.dtype).reshape(B, H, Sc, D),
            dkc.astype(k.dtype), dvc.astype(v.dtype),
            None if km is None else _f0(km),
            None if fm is None else _f0(fm))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def flash_ring_supported(q, k, bias=None, backend=None):
    """Gate: the flash ring needs kernel-legal CHUNK sequence lengths
    (both local chunks divisible by the 128 block) and no bias."""
    if bias is not None:
        return False
    ok_shapes = q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0
    be = backend or jax.default_backend()
    return ok_shapes and be == "tpu"


__all__ = ["ring_flash_attention_local", "flash_ring_supported"]
