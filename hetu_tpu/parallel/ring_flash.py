"""Ring attention with the Pallas flash kernel per ring step.

The plain ring schedule (:func:`ring_attention_local`) computes each
resident K/V chunk with an XLA einsum — materialising (B, H, Sc, Sc)
score blocks per step.  On TPU the flash kernel is the faster and
O(Sc·D)-memory way to process a chunk, so this module composes the two:

* **forward** — each ring step runs the EXISTING flash forward
  (``_flash_fwd``: out + per-row LSE) on the resident chunk; chunk
  results merge with the standard log-sum-exp combination (the same
  online-softmax algebra the kernel uses internally, lifted one level).
* **backward** — flash-attention-2's chunked backward needs only the
  GLOBAL out/LSE: per chunk, ``p_ij = exp(s_ij − lse_i)`` reconstructs
  the exact global softmax, so each ring step runs the EXISTING
  ``_flash_bwd`` on its resident chunk; dq accumulates locally while
  dk/dv ride the ring home with their chunk.

No kernel changes: both pallas_calls are the hardware-validated
specializations from :mod:`hetu_tpu.ops.pallas.flash_attention`; this
module is pure orchestration under ``jax.custom_vjp`` (pallas_call has no
autodiff — the ring IS the vjp).  CPU CI runs the same code with
``interpret=True``.

Supported: dense, causal, key-padding masks, full per-query masks, AND
additive bias (T5 relative-position bias under context parallelism): the
bias keeps its KEY dim full locally like the masks, each ring step slices
the resident chunk's bias columns into the kernel (dense blocks, or the
O(S) key-strip path for row-broadcast biases), and the backward writes
each step's ``dbias`` column slice back into the local bias cotangent —
so a biased workload no longer falls off to the einsum ring.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.flash_attention import (_broadcast_group, _classify_group,
                                          _f0, _flash_bwd, _flash_fwd,
                                          _group_reduce)

_NEG = -1e30


def _chunk_cols(x, src, sc, axis):
    return lax.dynamic_slice_in_dim(x, src * sc, sc, axis=axis)


def _masks_for_chunk(key_mask, fmask, src, sc, b, h):
    """Per-step kernel inputs: key-mask column strip (B, 1, Sc) and/or
    full-mask block in un-broadcast (G, Sc, Sc) storage + gmode."""
    kmask2 = fmask3 = None
    gmode = "one"
    if key_mask is not None:
        kmask2 = _chunk_cols(key_mask, src, sc, 1).astype(jnp.int32)[:, None, :]
    if fmask is not None:
        blk = _chunk_cols(fmask, src, sc, 3).astype(jnp.int32)
        fmask3, gmode = _broadcast_group(blk, b, h, sc, sc, "mask")
    return kmask2, fmask3, gmode


def _bias_for_chunk(bias, src, sc, b, h):
    """Slice the resident chunk's bias columns into kernel storage:
    ``(kbias3, bias3, gmode)`` — a (·, ·, 1, S_kv) row-broadcast bias
    rides the kernel's O(S) key-strip path, anything else the dense
    blockwise path (the same routing ``flash_attention`` applies)."""
    blk = _chunk_cols(bias, src, sc, 3).astype(jnp.float32)
    if bias.shape[2] == 1 and sc != 1:
        gmode = _classify_group(blk, b, h, sc, sc, "bias")
        return blk.reshape(-1, 1, sc), None, gmode
    bias3, gmode = _broadcast_group(blk, b, h, sc, sc, "bias")
    return None, bias3, gmode


def _ring_perm(axis_name, S):
    return [(i, (i + 1) % S) for i in range(S)]


def _fwd_step(q3, kc3, vc3, kmask2, kbias3, fmask3, bias3, gmodes, scale,
              causal_flag, h, blocks, interpret):
    """branch body: flash forward on one resident chunk → (o, lse)."""
    bq, bk = blocks
    gmode_mask, gmode_bias, gmode_kbias = gmodes
    return _flash_fwd(q3, kc3, vc3, None, kmask2, kbias3, fmask3, bias3,
                      scale, causal_flag, gmode_mask, gmode_bias,
                      gmode_kbias, h, bq, bk, interpret)


def ring_flash_attention_local(q, k, v, bias=None, key_mask=None, mask=None,
                               axis_name="cp", causal=False, scale=None,
                               block_q=None, block_k=None,
                               interpret=False):
    """Flash-kernel ring attention — call INSIDE shard_map over ``cp``.

    Same contract as :func:`ring_attention_local` (q/k/v local chunks
    [B, H, Sc, D]; ``bias`` [1|B, 1|H, Sc|1, S_kv] query-sharded/full-key
    local additive bias, differentiable; ``key_mask`` [1|B, S_kv]
    full-key local; ``mask`` [1|B, 1|H, Sc|1, S_kv] query-sharded/
    full-key local).  Sc and D must satisfy the kernel's
    128-divisibility.
    """
    B, H, Sc, D = q.shape
    sc_val = float(scale) if scale is not None else 1.0 / (D ** 0.5)
    blocks = (block_q or min(128, Sc), block_k or min(128, Sc))
    km = None
    if key_mask is not None:
        km = jnp.broadcast_to(jnp.asarray(key_mask),
                              (B, key_mask.shape[-1]))
    fm = None
    if mask is not None:
        fm = jnp.broadcast_to(
            jnp.asarray(mask),
            (mask.shape[0], mask.shape[1], Sc, mask.shape[3]))
    if bias is not None and bias.ndim != 4:
        raise ValueError(f"ring-flash bias must be 4-D "
                         f"(1|B, 1|H, Sc|1, S_kv), got {bias.shape}")

    return _ring_flash(q, k, v, km, fm, bias, axis_name, bool(causal),
                       sc_val, blocks, B, H, Sc, D, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11,
                                                    12, 13, 14))
def _ring_flash(q, k, v, km, fm, bias, axis_name, causal, scale, blocks,
                B, H, Sc, D, interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, km, fm, bias, axis_name, causal,
                                  scale, blocks, B, H, Sc, D, interpret)
    return out


def _ring_flash_fwd_impl(q, k, v, km, fm, bias, axis_name, causal, scale,
                         blocks, B, H, Sc, D, interpret):
    S = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, S)
    q3 = q.reshape(B * H, Sc, D)

    m = jnp.full((B * H, Sc, 1), _NEG, jnp.float32)   # running max of lse
    s = jnp.zeros((B * H, Sc, 1), jnp.float32)        # Σ exp(lse_i − m)
    o = jnp.zeros((B * H, Sc, D), jnp.float32)        # Σ w_i · o_i
    kc, vc = k, v
    for t in range(S):
        src = (r - t) % S
        kc3 = kc.reshape(B * H, Sc, D)
        vc3 = vc.reshape(B * H, Sc, D)
        kmask2, fmask3, gmode = _masks_for_chunk(km, fm, src, Sc, B, H)
        kbias3 = bias3 = None
        gmode_bias = gmode_kbias = "one"
        if bias is not None:
            kbias3, bias3, gb = _bias_for_chunk(bias, src, Sc, B, H)
            gmode_kbias = gb if kbias3 is not None else "one"
            gmode_bias = gb if bias3 is not None else "one"
        gmodes = (gmode, gmode_bias, gmode_kbias)

        def dense_or_causal(flag):
            def f(_):
                return _fwd_step(q3, kc3, vc3, kmask2, kbias3, fmask3,
                                 bias3, gmodes, scale, flag, H, blocks,
                                 interpret)
            return f

        def skipped(_):
            return (jnp.zeros_like(q3),
                    jnp.full((B * H, Sc, 1), 2 * _NEG, jnp.float32))

        if causal:
            # src == r: diagonal chunk (kernel causal); src < r: every key
            # precedes every query (dense); src > r: fully masked — skip
            # the kernel entirely (the causal-ring FLOP saving)
            branch = jnp.where(src == r, 2, jnp.where(src < r, 1, 0))
            oi, lse = lax.switch(branch, [skipped,
                                          dense_or_causal(False),
                                          dense_or_causal(True)],
                                 operand=None)
        else:
            oi, lse = dense_or_causal(False)(None)

        m_new = jnp.maximum(m, lse)
        # guard the all-masked rows: exp(−inf − (−inf)) must be 0, not 1
        w_old = jnp.where(m > _NEG, jnp.exp(m - m_new), 0.0)
        w_new = jnp.where(lse > _NEG, jnp.exp(lse - m_new), 0.0)
        s = s * w_old + w_new
        o = o * w_old + oi.astype(jnp.float32) * w_new
        m = m_new
        if t < S - 1:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)

    s_safe = jnp.where(s == 0.0, 1.0, s)
    out = (o / s_safe).astype(q.dtype).reshape(B, H, Sc, D)
    lse_g = jnp.where(s > 0.0, m + jnp.log(s_safe),
                      jnp.full_like(m, 2 * _NEG))       # (B·H, Sc, 1)
    return out, lse_g


def _ring_flash_vjp_fwd(q, k, v, km, fm, bias, axis_name, causal, scale,
                        blocks, B, H, Sc, D, interpret):
    out, lse_g = _ring_flash_fwd_impl(q, k, v, km, fm, bias, axis_name,
                                      causal, scale, blocks, B, H, Sc, D,
                                      interpret)
    return out, (q, k, v, km, fm, bias, out, lse_g)


def _ring_flash_vjp_bwd(axis_name, causal, scale, blocks, B, H, Sc, D,
                        interpret, res, do):
    q, k, v, km, fm, bias, out, lse_g = res
    # fully-masked rows carry the 2·_NEG LSE sentinel; fed raw into the
    # kernel's p = exp(s − lse) it overflows to inf and NaNs the whole
    # chunk's dk/dv.  Re-pin those rows to lse=0: their s entries are all
    # ≈ −1e30, so p = exp(−1e30) = 0 and the row's gradients vanish —
    # matching the forward's zero output.  Valid rows are untouched.
    lse_g = jnp.where(lse_g <= _NEG, 0.0, lse_g)
    S = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    perm = _ring_perm(axis_name, S)
    q3 = q.reshape(B * H, Sc, D)
    out3 = out.reshape(B * H, Sc, D)
    do3 = do.reshape(B * H, Sc, D)

    dq = jnp.zeros((B * H, Sc, D), jnp.float32)
    # dk/dv accumulators ride the ring WITH their chunk: after S rotations
    # every chunk is home again carrying the sum over all query owners
    kc, vc = k, v
    dkc = jnp.zeros_like(k, dtype=jnp.float32)
    dvc = jnp.zeros_like(v, dtype=jnp.float32)
    # the bias cotangent stays LOCAL: its query rows belong to this device
    # and each ring step owns a disjoint column slice, written back below
    # (shard_map's transpose psums the broadcast dims across the ring)
    dbias = None if bias is None else jnp.zeros(bias.shape, jnp.float32)
    for t in range(S):
        src = (r - t) % S
        kc3 = kc.reshape(B * H, Sc, D)
        vc3 = vc.reshape(B * H, Sc, D)
        kmask2, fmask3, gmode = _masks_for_chunk(km, fm, src, Sc, B, H)
        kbias3 = bias3 = None
        gmode_bias = gmode_kbias = "one"
        if bias is not None:
            kbias3, bias3, gb = _bias_for_chunk(bias, src, Sc, B, H)
            gmode_kbias = gb if kbias3 is not None else "one"
            gmode_bias = gb if bias3 is not None else "one"
        db_shape = None if bias is None else bias.shape[:3] + (Sc,)

        def run(flag):
            def f(_):
                dqi, dki, dvi, dbi, dkbi = _flash_bwd(
                    q3, kc3, vc3, None, kmask2, kbias3, fmask3, bias3,
                    out3, lse_g, do3, scale, flag, gmode, gmode_bias,
                    gmode_kbias, H, blocks[0], blocks[1], interpret)
                if bias is None:
                    return dqi, dki, dvi
                # reduce the per-(b·h) kernel grads over the broadcast
                # group → this chunk's column slice of the local bias
                raw = dbi if bias3 is not None else dkbi
                gmd = gmode_bias if bias3 is not None else gmode_kbias
                dbc = _group_reduce(raw, gmd, B, H, db_shape, jnp.float32)
                return dqi, dki, dvi, dbc
            return f

        def skipped(_):
            zs = (jnp.zeros_like(q3), jnp.zeros_like(q3),
                  jnp.zeros_like(q3))
            if bias is not None:
                zs = zs + (jnp.zeros(db_shape, jnp.float32),)
            return zs

        if causal:
            branch = jnp.where(src == r, 2, jnp.where(src < r, 1, 0))
            res_t = lax.switch(branch, [skipped, run(False), run(True)],
                               operand=None)
        else:
            res_t = run(False)(None)
        if bias is not None:
            dqi, dki, dvi, dbc = res_t
            # each src is visited exactly once, so the column slice is a
            # plain write, not an accumulate
            dbias = lax.dynamic_update_slice_in_dim(dbias, dbc, src * Sc,
                                                    axis=3)
        else:
            dqi, dki, dvi = res_t

        dq = dq + dqi.astype(jnp.float32)
        dkc = dkc + dki.astype(jnp.float32).reshape(B, H, Sc, D)
        dvc = dvc + dvi.astype(jnp.float32).reshape(B, H, Sc, D)
        if t < S - 1:
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            dkc = lax.ppermute(dkc, axis_name, perm)
            dvc = lax.ppermute(dvc, axis_name, perm)
    # one final rotation brings chunk (r−(S−1))%S ≡ (r+1)%S home
    dkc = lax.ppermute(dkc, axis_name, perm)
    dvc = lax.ppermute(dvc, axis_name, perm)
    return (dq.astype(q.dtype).reshape(B, H, Sc, D),
            dkc.astype(k.dtype), dvc.astype(v.dtype),
            None if km is None else _f0(km),
            None if fm is None else _f0(fm),
            None if bias is None else dbias.astype(bias.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def flash_ring_reason(q, k, backend=None):
    """None when the flash ring can run, else why it cannot: the ring
    needs kernel-legal CHUNK lengths (both local chunks divisible by the
    128 block — ragged GLOBAL lengths should be bucketed before sharding)
    on a TPU backend.  Bias is NOT a disqualifier any more — it rides the
    kernel's dense/key-strip bias paths per ring step."""
    be = backend or jax.default_backend()
    if be != "tpu":
        return f"backend:{be}"
    if q.shape[-2] % 128 or k.shape[-2] % 128:
        return (f"ring_chunk_not_128_divisible:"
                f"({q.shape[-2]},{k.shape[-2]})")
    return None


def flash_ring_supported(q, k, bias=None, backend=None):
    """Gate predicate over :func:`flash_ring_reason` (``bias`` kept for
    signature compatibility — biased workloads are supported now)."""
    del bias
    return flash_ring_reason(q, k, backend=backend) is None


__all__ = ["ring_flash_attention_local", "flash_ring_supported",
           "flash_ring_reason"]
