"""Distributed strategies (reference ``python/hetu/distributed_strategies/``:
Strategy base.py:11, DataParallel simple.py:6).

TPU-native: a strategy owns a named ``jax.sharding.Mesh`` and answers "how is
this tensor sharded" (PartitionSpec) instead of inserting NCCL comm ops into
the graph.  Under ``jax.jit`` the XLA SPMD partitioner then emits the
collectives (psum for DP grads, all_to_all for EP, ...) over ICI — the role
the reference's OptimizerOp.backward_hook + mpirun launch played
(``optimizer.py:145-164``, SURVEY.md §5.8).
"""
from __future__ import annotations


from ..context import make_mesh


class Strategy:
    def make_mesh(self):
        raise NotImplementedError

    def feed_spec(self, node, ndim):
        """PartitionSpec for a fed placeholder value."""
        from jax.sharding import PartitionSpec
        return PartitionSpec()

    def param_spec(self, node, ndim):
        from jax.sharding import PartitionSpec
        return PartitionSpec()


class DataParallel(Strategy):
    """Pure data parallelism: batch dim sharded over the 'dp' axis; grad
    allreduce is emitted by XLA from the mean-loss psum.

    ``aggregate`` ∈ {allreduce, ps, hybrid} kept for reference API parity
    (simple.py:6); on TPU all three map to ICI collectives for dense params,
    while embeddings marked ``is_embed`` can live in the host store
    (:mod:`hetu_tpu.embedding`) — the hybrid path's equivalent.

    ``zero``: ZeRO-style weight-update sharding stage (0=off, 1=shard
    optimizer state, 2=+reduce-scattered grads, 3=+dp-sharded master
    params; :mod:`hetu_tpu.parallel.zero`).  Params are replicated at
    stages 0-2 and live as dp-sharded bucket slabs at stage 3.  An
    ``Executor(zero=...)`` kwarg or ``HETU_ZERO`` overrides this.
    """

    def __init__(self, aggregate="allreduce", num_devices=None, zero=None):
        aggregate = (aggregate or "allreduce").lower()
        assert aggregate in ("allreduce", "ps", "hybrid")
        self.aggregate = aggregate
        self.num_devices = num_devices
        from .zero import resolve_stage
        self.zero = resolve_stage(zero)

    def make_mesh(self):
        import jax
        n = self.num_devices or len(jax.devices())
        return make_mesh({"dp": n}, jax.devices()[:n])

    def feed_spec(self, node, ndim):
        from jax.sharding import PartitionSpec
        if ndim == 0:
            return PartitionSpec()
        return PartitionSpec("dp", *([None] * (ndim - 1)))


class ModelParallel(Strategy):
    """Generic mesh strategy: explicit axis sizes, per-node shardings come
    from ``ht.dispatch``/layer annotations (realized as GSPMD constraints —
    the reference's vestigial Dispatch API made real, SURVEY.md §2.3)."""

    def __init__(self, axis_sizes):
        self.axis_sizes = dict(axis_sizes)

    def make_mesh(self):
        return make_mesh(self.axis_sizes)

    def feed_spec(self, node, ndim):
        from jax.sharding import PartitionSpec
        if ndim and "dp" in self.axis_sizes:
            return PartitionSpec("dp", *([None] * (ndim - 1)))
        return PartitionSpec()
