"""ZeRO-style cross-replica sharding of the weight update.

Reference: "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., PAPERS.md) — instead of every replica
paying the full Adam update (and 3x param memory for m/v), the gradient is
reduce-SCATTERED over the ``dp`` axis, each replica updates only its 1/dp
slice of every parameter, and the updated params are all-GATHERED back.
GC3's collective-scheduling framing (PAPERS.md) supplies the overlap
discipline: at stage 3 the gather of step N's params moves INTO step N+1's
program, where XLA's async scheduler overlaps it with early compute.

TPU-native realization: no hand-inserted collectives.  The update runs
under GSPMD sharding CONSTRAINTS — grads and optimizer state are pinned to
a ``PartitionSpec('dp', None)`` slab layout, so the SPMD partitioner emits
the reduce-scatter / all-gather pair itself (the paper's "automatic"
half), while this module owns the layout: every parameter is flattened,
padded to a ``dp`` multiple and packed into fixed buckets
(``HETU_ZERO_BUCKET_MB``), so arbitrary shapes shard evenly and small
params ride one collective instead of one each.

Stages (``Executor(zero=...)`` / ``HETU_ZERO``):

* ``1`` — shard optimizer state only: grads stay replicated (XLA
  all-reduces them as before), each replica updates its slice, params are
  all-gathered.  Memory win: optimizer moments / dp.
* ``2`` — stage 1 + the grad slab is constrained to the sharded layout, so
  the partitioner may lower the mean-loss reduction as a reduce-scatter
  (it does on TPU; XLA-CPU lowers it as all-reduce + slice): transient
  grad buffers shrink to 1/dp too.
* ``3`` — stage 2 + master params LIVE sharded between steps: the step
  consumes and returns slabs, and the all-gather of step N's updated
  params happens at the top of step N+1 where it overlaps forward
  compute.  Param memory between steps drops to 1/dp as well.

Bitwise discipline (load-bearing — the parity tests assert EXACT equality
with the replicated path): the WHOLE update chain (moment updates, the
``p - lr*upd`` axpy) must be computed under the slab sharding before
anything is gathered.  If the final subtract is left outside the sharded
region, the partitioner gathers ``p`` and ``lr*upd`` separately and the
mul+sub lands in two fusions — losing the FMA contraction the replicated
program gets, a 1-ulp drift that compounds over steps.  Hence every
intermediate below is explicitly re-constrained to the slab spec.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..metrics import record_zero

#: the data-parallel mesh axis the weight update shards over
ZERO_AXIS = "dp"

#: default collective bucket size (MB); 0 = one bucket per parameter
DEFAULT_BUCKET_MB = 4.0


def bucket_bytes():
    """Configured bucket size in bytes (``HETU_ZERO_BUCKET_MB``)."""
    try:
        mb = float(os.environ.get("HETU_ZERO_BUCKET_MB",
                                  str(DEFAULT_BUCKET_MB)))
    except ValueError:
        mb = DEFAULT_BUCKET_MB
    return int(mb * 2**20)


@dataclass
class ZeroBucket:
    """One fused collective: a group of params packed into a flat slab.

    The slab layout is ``concat(flatten(p) for p in params) + zero pad``
    reshaped to ``(dp, width)`` — contiguous, so packing/unpacking is pure
    data movement (bitwise-preserving) and the per-device row is exactly
    the replica's 1/dp slice."""

    key: str                    # step-input key of this bucket's slab
    param_keys: list            # canonical step keys of member params
    shapes: list                # original array shapes, same order
    offsets: list               # start of each param in the flat concat
    numel: int                  # total unpadded elements
    dp: int
    dtype: str = "float32"

    @property
    def padded(self):
        return -(-self.numel // self.dp) * self.dp

    @property
    def pad(self):
        return self.padded - self.numel

    @property
    def width(self):
        return self.padded // self.dp

    @property
    def nbytes(self):
        return self.padded * np.dtype(self.dtype).itemsize


@dataclass
class ZeroPlan:
    """Per-OptimizerOp sharding plan: stage + bucket layout."""

    stage: int
    dp: int
    buckets: list = field(default_factory=list)
    axis: str = ZERO_AXIS

    @property
    def param_keys(self):
        return [k for b in self.buckets for k in b.param_keys]


def resolve_stage(value):
    """Normalize a user/env zero setting to an int stage in {0,1,2,3}."""
    if value is None or value is False:
        return 0
    if value is True:
        return 2            # the canonical reduce-scatter mode
    try:
        stage = int(value)
    except (TypeError, ValueError):
        stage = -1          # HETU_ZERO=on etc. get the range message
    if stage < 0 or stage > 3:
        raise ValueError(f"zero={value!r}: expected a stage in 0..3 "
                         "(0=off, 1=opt-state, 2=+reduce-scatter, "
                         "3=+sharded params)")
    return stage


def ineligible_reason(param, dtype):
    """Why ``param`` keeps its WHOLE optimizer off the ZeRO plan, or
    ``None`` if it doesn't.

    Single source of truth for the eligibility filter, shared by the
    executor's plan builder (``Executor._build_zero_plans``) and the
    ``zero-sharding`` lint rule so the two can never drift: an explicit
    sharding annotation marks a model-parallel layout the dp slab
    packing (and stage <3's replicated gather) would silently destroy,
    and a non-float dtype has no moments worth sharding.  ``dtype=None``
    (shape inference failed) is treated as eligible — the lint side
    reports uninferable nodes separately.
    """
    if any(s is not None for s in (getattr(param, "sharding", None) or ())):
        return ("carries an explicit sharding annotation "
                "(model parallelism)")
    if dtype is not None and not np.issubdtype(np.dtype(dtype),
                                               np.floating):
        return f"is not a float array (dtype {np.dtype(dtype).name})"
    return None


def build_plan(param_items, dp, stage, max_bytes=None, per_param=False,
               prefix=""):
    """Pack ``param_items`` (``[(key, shape, dtype), ...]`` in a stable
    order) into buckets of at most ``max_bytes`` each.

    ``per_param=True`` forces one bucket per parameter — required by
    LAMB-style optimizers whose update needs per-PARAMETER norms, and by
    stage 3 consumers that restore individual params into their slab.
    Params are grouped by dtype (a slab is one homogeneous buffer).
    ``prefix`` namespaces the bucket keys (several OptimizerOps' slabs
    share one step-input dict)."""
    if max_bytes is None:
        max_bytes = bucket_bytes()
    plan = ZeroPlan(stage=stage, dp=dp)
    cur = None
    for key, shape, dtype in param_items:
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        dts = np.dtype(dtype).name
        itemsize = np.dtype(dtype).itemsize
        if (per_param or cur is None or cur.dtype != dts
                or (cur.numel + size) * itemsize > max_bytes):
            cur = ZeroBucket(key=f"{prefix}zb{len(plan.buckets)}",
                             param_keys=[],
                             shapes=[], offsets=[], numel=0, dp=dp,
                             dtype=dts)
            plan.buckets.append(cur)
        cur.param_keys.append(key)
        cur.shapes.append(tuple(shape))
        cur.offsets.append(cur.numel)
        cur.numel += size
    return plan


# -- shardings ---------------------------------------------------------------

def slab_sharding(mesh, axis=ZERO_AXIS):
    from jax.sharding import NamedSharding
    from .collectives import slab_spec
    return NamedSharding(mesh, slab_spec(axis))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding
    from .collectives import replicated_spec
    return NamedSharding(mesh, replicated_spec())


# -- slab packing (trace-time: pure data movement, bitwise-preserving) -------

def pack_slab(vals, bucket):
    """``{param_key: array}`` → ``(dp, width)`` slab (flatten+concat+pad)."""
    import jax.numpy as jnp
    flat = [jnp.ravel(vals[k]) for k in bucket.param_keys]
    cat = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
    if bucket.pad:
        cat = jnp.pad(cat, (0, bucket.pad))
    return cat.reshape(bucket.dp, bucket.width)


def unpack_slab(slab, bucket):
    """Inverse of :func:`pack_slab` → ``{param_key: array}``."""
    flat = slab.reshape(-1)
    out = {}
    for k, shape, off in zip(bucket.param_keys, bucket.shapes,
                             bucket.offsets):
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        seg = flat[off:off + size]
        out[k] = seg.reshape(shape)
    return out


def host_pack_slab(np_vals, bucket):
    """Host-side (numpy) slab packing for initial placement / restore."""
    flat = [np.asarray(np_vals[k], np.dtype(bucket.dtype)).reshape(-1)
            for k in bucket.param_keys]
    cat = flat[0] if len(flat) == 1 else np.concatenate(flat)
    if bucket.pad:
        cat = np.pad(cat, (0, bucket.pad))
    return cat.reshape(bucket.dp, bucket.width)


def host_unpack_slab(slab, bucket):
    """Host-side inverse: slab (numpy) → ``{param_key: array}``."""
    return unpack_slab(np.asarray(slab), bucket)


# -- the sharded update ------------------------------------------------------

def gather_full(slab, bucket, mesh):
    """All-gather a slab back to the full per-param arrays (inside jit).

    At stage 3 this runs at the TOP of the next step, where XLA's async
    scheduler can overlap the gather with compute that does not yet need
    these params (GC3's scheduling discipline)."""
    import jax
    full = jax.lax.with_sharding_constraint(slab, replicated_sharding(mesh))
    record_zero("zero_all_gather_bytes", bucket.nbytes)
    return unpack_slab(full, bucket)


def apply_sharded(optimizer, plan, params, grads, state, lr, mesh):
    """One optimizer step with the update sharded over ``dp``.

    ``params``/``grads``: full arrays keyed by canonical param key
    (stages 1/2), or — stage 3 — ``params`` holds ``(dp, width)`` slabs
    keyed by bucket key (grads are always full: they fall out of
    ``jax.grad`` in param shape).  ``state`` is the slab-layout state this
    module's plan initialized.  Returns ``(new_params, new_state)`` where
    ``new_params`` is keyed like ``params`` came in (full per-param
    updates for stages 1/2; new slabs for stage 3).

    Counter semantics (``HetuProfiler.zero_counters()``): recorded per
    TRACE like the flash-fallback counters — a growing count across steps
    means the jit cache is thrashing."""
    import jax

    slab_sh = slab_sharding(mesh, plan.axis)
    p_slabs, g_slabs = {}, {}
    for b in plan.buckets:
        g = pack_slab(grads, b)
        if plan.stage >= 2:
            # pin the grad slab to the sharded layout: the partitioner may
            # now satisfy the mean-loss reduction with a reduce-scatter
            # instead of a full all-reduce (the paper's core move)
            g = jax.lax.with_sharding_constraint(g, slab_sh)
            record_zero("zero_reduce_scatter_bytes", b.nbytes)
        if plan.stage >= 3:
            p = params[b.key]           # already a slab
        else:
            p = pack_slab(params, b)
        # params enter the update sharded even when replicated outside:
        # slicing a replicated buffer is free, and it keeps the WHOLE
        # update chain inside the sharded region (see module docstring)
        p = jax.lax.with_sharding_constraint(p, slab_sh)
        record_zero("zero_pad_bytes",
                    b.pad * np.dtype(b.dtype).itemsize)
        p_slabs[b.key], g_slabs[b.key] = p, g

    new_slabs, new_state = optimizer.apply(p_slabs, g_slabs, state, lr)

    def _pin(x):
        if hasattr(x, "ndim") and x.ndim == 2:
            return jax.lax.with_sharding_constraint(x, slab_sh)
        return x                        # scalars (Adam t) stay replicated

    # re-constrain every slab-shaped output: the new params AND the new
    # moments must be COMPUTED sharded (bitwise discipline + they must
    # leave the step still sharded so the donated buffers stay 1/dp)
    new_slabs = {k: _pin(v) for k, v in new_slabs.items()}
    new_state = jax.tree.map(_pin, new_state)

    if plan.stage >= 3:
        return new_slabs, new_state
    upd = {}
    for b in plan.buckets:
        upd.update(gather_full(new_slabs[b.key], b, mesh))
    return upd, new_state


__all__ = ["ZERO_AXIS", "ZeroBucket", "ZeroPlan", "resolve_stage",
           "ineligible_reason", "build_plan", "bucket_bytes",
           "slab_sharding",
           "replicated_sharding", "pack_slab", "unpack_slab",
           "host_pack_slab", "host_unpack_slab", "gather_full",
           "apply_sharded"]
