"""Profilers: per-op replay timing, HLO cost analysis, collective benchmarks.

Capability parity with the reference's ``python/hetu/profiler.py``:

* ``HetuProfiler`` (reference ``HetuProfiler:55``) — times each graph op by
  replaying it with synthesized inputs. Under XLA the *fused* step cost is
  what really matters, so the profiler additionally reports whole-step wall
  time and the compiled step's HLO cost analysis (FLOPs / bytes accessed /
  peak memory) — the honest TPU analogue of per-op CUDA-event timing.
* ``CollectiveProfiler`` (reference ``NCCLProfiler:390``) — measures
  allreduce / sendrecv (ppermute) / alltoall latency and bandwidth over the
  device mesh; feeds the auto-parallel cost models.
* Device memory via ``device.memory_stats()`` (reference uses pynvml:69-75).
"""
from __future__ import annotations

import time

import numpy as np


def _rand_like(shape_struct, rng):
    """Synthesize a concrete input for a ShapeDtypeStruct (reference
    profiler feeds random arrays, profiler.py:120)."""
    import jax.numpy as jnp
    dt = np.dtype(shape_struct.dtype)
    if np.issubdtype(dt, np.integer):
        return jnp.zeros(shape_struct.shape, dt)
    return jnp.asarray(rng.standard_normal(shape_struct.shape), dt)


class HetuProfiler:
    """Per-op replay + whole-step + HLO-cost profiling for one subexecutor.

    Usage::

        prof = ht.HetuProfiler(executor, 'train')
        per_op = prof.profile_ops(feed_dict)       # op name -> ms
        step_ms = prof.profile_step(feed_dict)     # fused step wall time
        cost = prof.hlo_cost(feed_dict)            # flops/bytes from XLA
    """

    def __init__(self, executor, name="default", repeats=10, warmup=2):
        self.ex = executor
        self.sub = executor.subexecutors[name]
        self.repeats = repeats
        self.warmup = warmup

    # -- input packing / shape inference -------------------------------------
    def _pack(self, feed_dict, materialize=False):
        """Assemble (tparams, sparams, feeds, master_key, step_idx)
        exactly like sub.run (the step folds the key itself).

        ``materialize=True`` forces stage-3 ZeRO params to full
        replicated values instead of bucket slabs — the forward-only
        abstract shape evaluation needs per-param keys."""
        from .data.dataloader import DataloaderOp
        sub, ex = self.sub, self.ex
        feeds = {}
        for node in sub.feed_nodes:
            if isinstance(node, DataloaderOp) and node not in feed_dict:
                val = node.get_arr(sub.name)
            elif node in feed_dict:
                val = feed_dict[node]
            else:
                raise ValueError(f"missing feed for {node}")
            feeds[ex._k(node)] = ex._place_feed(node, val)
        if hasattr(sub, "_pack_state"):   # ZeRO-aware packing (SubExecutor)
            tparams, sparams = sub._pack_state(materialize=materialize)
        else:
            tparams = {ex._k(n): ex.var_values[n]
                       for n in sub.trainable_vars}
            sparams = {ex._k(n): ex.var_values[n] for n in sub.state_vars}
        # PS embeddings: pull rows host-side like sub.run does, else the
        # placeholder lookup in _forward falls through to feeds and KeyErrors
        for node in sub.ps_nodes:
            idn = node.ids_node
            if ex._k(idn) in feeds:
                ids = np.asarray(feeds[ex._k(idn)])
            elif idn in feed_dict:
                ids = np.asarray(feed_dict[idn])
            elif isinstance(idn, DataloaderOp):
                ids = np.asarray(idn.get_arr(sub.name))
            else:
                raise ValueError(f"cannot resolve ids for PS embedding {node}")
            val = ex._place_feed(node, node.pull(ids))
            (tparams if sub.grad_ops else sparams)[ex._k(node)] = val
        # the executor folds per-step RNG INSIDE the jitted program; the
        # pack mirrors its (master_key, step_idx:int32) calling convention
        # (int32 keeps the traced dtype identical with and without x64)
        return tparams, sparams, feeds, ex.master_key, \
            np.int32(ex.step_counter)

    def _node_shapes(self, feed_dict):
        """Abstractly evaluate the forward graph → {node: ShapeDtypeStruct}."""
        import jax

        sub = self.sub
        tparams, sparams, feeds, key, step_idx = self._pack(
            feed_dict, materialize=True)
        key = jax.random.fold_in(key, step_idx)
        nodes = [n for n in sub.topo
                 if not hasattr(n, "loss") and n not in sub.opt_ops]

        def fwd(tp, sp, fd, k):
            env, _ = sub._forward(tp, sp, fd, k)
            return {str(n.id): env[n] for n in nodes if n in env}

        shapes = jax.eval_shape(fwd, tparams, sparams, feeds, key)
        return {n: shapes[str(n.id)] for n in nodes if str(n.id) in shapes}

    def profile_ops(self, feed_dict, log_file=None):
        """Replay every op in isolation with random inputs → {name: ms}.

        Ops whose lowering needs collective context (mesh axes) are skipped —
        their cost shows up in :meth:`profile_step` where they run fused.
        """
        import jax
        from .graph.node import LowerCtx

        shapes = self._node_shapes(feed_dict)
        rng = np.random.default_rng(0)
        results = {}
        self.skipped = {}  # op label -> reason (kept visible, not swallowed)
        for node in self.sub.topo:
            if node not in shapes or not node.inputs:
                continue
            if any(i not in shapes for i in node.inputs):
                continue
            ins = [_rand_like(shapes[i], rng) for i in node.inputs]
            key = jax.random.PRNGKey(0)

            def one(args, _node=node, _key=key):
                ctx = LowerCtx(False, _key, self.ex.mesh)
                return _node.lower(ctx, *args)

            try:
                fn = jax.jit(one)
                out = fn(ins)
                self._sync([out])
                for _ in range(self.warmup):
                    out = fn(ins)
                self._sync([out])  # warmup drained before timing
                t0 = time.perf_counter()
                for _ in range(self.repeats):
                    out = fn(ins)
                self._sync([out])
                dt = (time.perf_counter() - t0) / self.repeats
            except Exception as e:  # collective ops outside their mesh scope
                self.skipped[f"{node.op_type}:{node.name}"] = repr(e)
                continue
            results[f"{node.op_type}:{node.name}"] = dt * 1e3
        if log_file:
            with open(log_file, "a") as f:
                for k, v in sorted(results.items(), key=lambda kv: -kv[1]):
                    f.write(f"{k}\t{v:.4f} ms\n")
                for k, why in self.skipped.items():
                    f.write(f"{k}\tSKIPPED\t{why}\n")
        return results

    @staticmethod
    def _sync(outs):
        """Force completion of a step's outputs.

        ``block_until_ready`` is not honored by remote-tunnel platforms
        (axon), so read one element back to host — consecutive training
        steps form a data-dependent chain through the params, so syncing
        the last outputs syncs every dispatched step.  The per-leaf read
        is the ONE shared discipline (``graph.executor._sync_outs``).
        """
        import jax
        from .graph.executor import _sync_outs
        for o in outs:
            if o is None:
                continue
            arr = o.jax() if hasattr(o, "jax") else o
            _sync_outs(jax.tree.leaves(arr))

    def profile_step(self, feed_dict):
        """Fused whole-step wall time (ms) — the number that matters on TPU."""
        self.sub.run(feed_dict)  # compile
        outs = None
        for _ in range(self.warmup):
            outs = self.sub.run(feed_dict)
        if outs is not None:
            self._sync(outs)  # warmup must finish before the timer starts
        t0 = time.perf_counter()
        for _ in range(self.repeats):
            outs = self.sub.run(feed_dict)
        self._sync(outs)
        return (time.perf_counter() - t0) / self.repeats * 1e3

    def _lowered(self, feed_dict):
        """Lower (cache-hitting) the executor's jitted step for analysis."""
        sub, ex = self.sub, self.ex
        if sub._jit is None:
            sub._build_step()
        tparams, sparams, feeds, key, step_idx = self._pack(feed_dict)
        opt_states = {ex._k(op): ex.opt_states[op] for op in sub.opt_ops}
        # only data-dependent schedules ride the host lrs input (traced
        # ones live inside the step) — mirror the live calling convention
        lrs = sub._host_lrs(ex.step_counter) if hasattr(sub, "_host_lrs") \
            else np.zeros((len(sub.opt_ops),), np.float32)
        # reuse the executor's jitted step — .lower on the same jit object
        # hits jax's compilation cache instead of recompiling
        return sub._jit.lower(tparams, sparams, opt_states, feeds, key,
                              step_idx, lrs)

    def _compiled(self, feed_dict):
        """Compile (cache-hitting) the executor's jitted step for analysis."""
        return self._lowered(feed_dict).compile()

    def hlo_cost(self, feed_dict):
        """XLA's cost analysis of the compiled step: flops, bytes accessed.

        Replaces per-op replay as the source of cost-model inputs (SURVEY.md
        §7 'per-op profiler semantics under fusion').
        """
        cost = self._compiled(feed_dict).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost) if cost else {}

    def hlo_text(self, feed_dict):
        """Compiled-step HLO text (evidence of custom-call kernels, fusion
        decisions) — what the reference reads off nvprof timelines."""
        return self._compiled(feed_dict).as_text()

    def lowered_text(self, feed_dict):
        """Pre-backend (StableHLO) program text: the step's own dtype and
        donation semantics, uncontaminated by backend quirks (XLA-CPU
        upcasts bf16 dots and drops donation; tools/hlo_audit.py reads
        this for the program-level checks)."""
        return self._lowered(feed_dict).as_text()

    @staticmethod
    def all_counters():
        """{family: {kind: count}} over EVERY counter family on the
        observability registry in one call (``hetu_tpu.metrics``
        ``all_counts``): flash_fallbacks, emb_pallas_fallbacks, faults,
        elastic, autoparallel, cache, zero, step_cache, run_plan, serve,
        decode, prefix_cache, decode_recovery, serve_rejection_reason,
        fleet, protocol, ps_rpc_bytes.  The per-family
        accessors below are thin slices of this — same registry, same
        numbers; ``obs.metrics_dump()`` adds the histogram/gauge half."""
        from .metrics import all_counts
        return all_counts()

    @staticmethod
    def latency_stats():
        """Latency-distribution snapshots from the observability
        registry's log-bucketed histograms (count/sum/min/max/mean/
        p50/p90/p99 per label): ``ps_rpc_us`` per opcode (+ payload
        bytes), ``serve_latency_us`` (per-request queue wait /
        per-batch device call), ``decode_latency_us`` (time-to-token /
        join wait / time-to-first-token ``ttft`` / engine step /
        detach->reseat stream ``recovery`` on the decode plane),
        ``step_time_us``
        per subexecutor (opt-in — ``metrics.enable_step_timing`` or
        ``HETU_STEP_TIMING=1``), and the per-run ``mfu`` /
        ``step_time_ms`` gauges."""
        from .metrics import (decode_latency_stats, rpc_stats,
                              run_gauges, serve_latency_stats,
                              step_time_stats)
        return {"ps_rpc": rpc_stats(),
                "serve_latency_us": serve_latency_stats(),
                "decode_latency_us": decode_latency_stats(),
                "step_time_us": step_time_stats(),
                "gauges": run_gauges()}

    @staticmethod
    def flash_fallbacks():
        """{reason: count} of attention dispatches that LEFT the Pallas
        flash fast path (``hetu_tpu.metrics`` registry).  Counts are per
        trace, not per step — any nonzero entry means some compiled
        program runs einsum attention; pair with ``hlo_text`` (custom-call
        evidence) to pin which.  ``HETU_REQUIRE_FLASH=1`` makes these
        hard failures instead of counters."""
        from .metrics import flash_fallback_counts
        return flash_fallback_counts()

    @staticmethod
    def emb_pallas_fallbacks():
        """{reason: count} of embedding-cache dispatches that LEFT the
        Pallas device-kernel path (``hetu_tpu.metrics`` registry) — the
        slot-indexed gather or the grad scatter-add compiled onto the
        ``jnp.take`` / ``jax.ops.segment_sum`` fallback instead
        (``ops/pallas/emb_cache.py``).  Flash semantics: per trace, not
        per step; ``HETU_REQUIRE_PALLAS_EMB=1`` makes these hard
        failures instead of counters."""
        from .metrics import emb_pallas_fallback_counts
        return emb_pallas_fallback_counts()

    @staticmethod
    def remat_counters():
        """{kind: count} of selective-remat plan builds
        (``hetu_tpu.metrics`` registry; ``parallel/remat.py``): segments
        found (``remat_layers_total``) and chosen for remat
        (``remat_layers_rematted``), activation bytes the plan frees
        (``remat_bytes_saved``) vs the matmul FLOPs a backward replay
        re-pays (``remat_recompute_flops``), and activation-offload
        requests served by the counted on-device fallback
        (``remat_offload_fallback`` — ``HETU_REQUIRE_OFFLOAD=1`` makes
        these hard failures).  Per plan BUILD, not per step; a run
        without ``Executor(remat=...)`` reports an empty dict."""
        from .metrics import remat_counts
        return remat_counts()

    @staticmethod
    def elastic_counters():
        """{kind: count} of elastic data-parallel resize events
        (``hetu_tpu.metrics`` registry; ``parallel/elastic.py``):
        dead-rank detections (``elastic_dead_rank``), shrinks/grows
        executed (``elastic_shrink``/``elastic_grow``), shrinks refused
        at the ``min_dp`` floor, rejoins detected, partitioned ranks
        HELD instead of resized over (``elastic_unreachable_held``),
        and cumulative resize wall time (``elastic_resize_ms``).
        Whether a grow-back recompiled is :meth:`step_cache_counters`'s
        story (``step_cache_hit`` = executable reused).  A fixed-world
        run reports an empty dict."""
        from .metrics import elastic_counts
        return elastic_counts()

    @staticmethod
    def concurrency_counters():
        """{kind: count} of concurrency-verifier runtime events
        (``hetu_tpu.metrics`` registry; ISSUE 14): lock-witness graph
        facts published by ``obs.lock_witness.WITNESS.check()`` —
        distinct lock classes seen (``concurrency_witness_locks``),
        acquisition edges observed (``concurrency_witness_edges``),
        cycles detected (``concurrency_witness_cycles`` — any nonzero
        value is a deadlock-able order) — and deterministic race-harness
        activity (``hetu_tpu.race``): forced preemptions fired
        (``concurrency_preemptions``) and rendezvous timeouts
        (``concurrency_race_timeouts``).  A run with the witness off
        and no race schedule installed reports an empty dict."""
        from .metrics import concurrency_counts
        return concurrency_counts()

    @staticmethod
    def autoparallel_counters():
        """{kind: count} of auto-parallel loop events
        (``hetu_tpu.metrics`` registry; ``autoparallel/``): plans
        searched (``autoparallel_plans_searched`` — one per
        ``search``/``search_graph`` call), candidate executables built
        fresh during measurement (``autoparallel_plans_compiled``) vs
        reused through the compiled-step cache
        (``autoparallel_candidate_cache_hits`` — one compile per
        distinct candidate, re-measures hit), candidates run for
        measured step times (``autoparallel_plans_measured``), and
        measured re-ranks that overturned the predicted best
        (``autoparallel_rerank_flips``).  A run that never searches or
        measures plans reports an empty dict."""
        from .metrics import autoparallel_counts
        return autoparallel_counts()

    @staticmethod
    def cache_counters():
        """{kind: count} of HET-cache / sparse-transport batching events
        (``hetu_tpu.metrics`` registry): cache hit/miss/evict rows, rows
        per batched push RPC, wire rows+bytes saved by ``np.unique``
        dedup, fused push+pull round trips.  Only sparse-PS traffic
        records here — a clean dense run reports an empty dict."""
        from .metrics import cache_counts
        return cache_counts()

    @staticmethod
    def zero_counters():
        """{kind: bytes} of ZeRO sharded-update traffic
        (``hetu_tpu.metrics`` registry): grad-slab bytes pinned to the
        reduce-scatter layout (``zero_reduce_scatter_bytes``),
        updated-param bytes all-gathered back (``zero_all_gather_bytes``)
        and zero-fill padding added so ragged shapes shard evenly
        (``zero_pad_bytes``).  Per-trace semantics like
        :meth:`flash_fallbacks`; a run without ``Executor(zero=...)``
        reports an empty dict."""
        from .metrics import zero_counts
        return zero_counts()

    @staticmethod
    def step_cache_counters():
        """{kind: count} of compiled-step cache events
        (``hetu_tpu.metrics`` registry): ``step_cache_hit`` — a jitted
        step was reused across Executor instances (no retrace),
        ``step_cache_miss`` — built fresh and stored,
        ``step_cache_uncachable`` — the graph signature could not be
        computed so caching was skipped."""
        from .metrics import step_cache_counts
        return step_cache_counts()

    @staticmethod
    def run_plan_counters():
        """{kind: count} of cached-run-plan / async-dispatch events
        (``hetu_tpu.metrics`` registry): ``plan_cache_hit`` /
        ``plan_cache_miss`` — per-step plan lookups (a steady feed schema
        misses once and hits every step after; climbing misses mean the
        schema churns — see the ``feed-schema-churn`` warning),
        ``feeds_pipelined`` — feed arrays whose host→device transfer was
        issued ahead of the consuming step (dataloader double-buffering
        and the ``Executor.run_steps`` driver), ``feed_pipeline_depth_hw``
        — high-water count of dataloader feed nodes with an outstanding
        prefetched transfer (one step deep per node; a max gauge, not a
        sum), and ``async_sync_points`` — forced materializations on the
        ``run(..., sync=False)`` path (numpy conversion, PS push
        boundary, checkpoint save, bounded-window overflow)."""
        from .metrics import run_plan_counts
        return run_plan_counts()

    @staticmethod
    def serve_counters():
        """{kind: count} of online-serving events (``hetu_tpu.metrics``
        registry): requests admitted/answered, batches dispatched with
        their total bucket rows (``serve_batch_rows``, real plus
        padding) of which ``serve_pad_rows`` were padding (the micro-
        batcher's bucket waste), queue-full rejections (backpressure), queue-depth high-water
        (``serve_queue_depth_hw`` — a max gauge, not a sum), PS
        failovers absorbed mid-serve, per-bucket executable builds
        (``serve_bucket_compiles`` — compile-once means this equals the
        number of distinct buckets used), and read-only embedding
        refresh rows.  A process that never serves reports an empty
        dict."""
        from .metrics import serve_counts
        return serve_counts()

    @staticmethod
    def decode_counters():
        """{kind: count} of continuous-batching autoregressive-decode
        events (``hetu_tpu.metrics`` registry): tokens streamed to
        callers (``decode_tokens``), sequences joining/leaving the
        in-flight batch (``decode_joins`` / ``decode_leaves``), KV-cache
        slots recycled to a later sequence (``decode_slot_recycles``),
        engine steps (``decode_steps`` — one jitted call per token
        batch) with their per-row prefill/generate split
        (``decode_prefill_rows`` / ``decode_generate_rows``), bucket
        ladder growths (``decode_batch_grows`` / ``decode_len_grows`` —
        each at most one fresh compile), queue-full rejections, the
        device-resident KV-cache footprint high-water mark
        (``decode_kv_bytes_hw`` — a max gauge, not a sum), and the
        chunked-prefill accounting (ISSUE 18): steps through the
        q_len=C entry (``decode_prefill_steps``), dispatches saved vs
        token-by-token ingestion (``decode_prefill_steps_saved``), and
        logits D2H copies skipped on pure-prefill steps
        (``decode_logits_skipped``).  Per-token latency rides
        ``metrics.decode_latency_stats()``.  A process that never
        decodes reports an empty dict."""
        from .metrics import decode_counts
        return decode_counts()

    @staticmethod
    def prefix_cache_counters():
        """{kind: count} of shared-prefix KV-store events
        (``hetu_tpu.metrics`` registry, ISSUE 18): lookups that seated a
        sequence with pre-filled cache rows (``prefix_cache_hits``) vs
        not (``prefix_cache_misses``), prompt tokens whose prefill was
        skipped outright (``prefix_cache_hit_rows``), snapshots stored /
        deduplicated (``prefix_cache_inserts`` /
        ``prefix_cache_dup_inserts``), LRU evictions and the bytes they
        freed (``prefix_cache_evictions`` /
        ``prefix_cache_evicted_bytes``), and the resident-bytes
        high-water mark (``prefix_cache_bytes_hw`` — a max gauge, not a
        sum).  A process with no :class:`PrefixKVStore` reports an
        empty dict."""
        from .metrics import prefix_cache_counts
        return prefix_cache_counts()

    @staticmethod
    def decode_recovery_counters():
        """{kind: count} of exactly-once in-flight stream migrations
        (``hetu_tpu.metrics`` registry, ISSUE 19): streams detached off
        a dead/wedged replica with their emitted-token journal
        (``decode_recovery_detached``) and re-seated on a survivor
        through chunked prefill (``decode_recovery_reseated``), the KV
        rows that reseat actually re-prefilled
        (``decode_recovery_replayed_rows``) vs seated free off a
        PrefixKVStore hit (``decode_recovery_prefix_assisted``),
        streams failed fast with ``recovery_exhausted`` instead of
        resurrected (``decode_recovery_exhausted``), second-and-later
        recoveries of one stream (``decode_recovery_retries``), and
        stale emissions the replay-epoch fence dropped
        (``decode_recovery_fenced``).  Detach->reseat latency rides the
        ``recovery`` label of ``metrics.decode_latency_stats()``.  A
        process that never migrates a stream reports an empty dict."""
        from .metrics import decode_recovery_counts
        return decode_recovery_counts()

    @staticmethod
    def serve_rejection_counters():
        """{reason: count} of serving rejections keyed by the structured
        ``ServeRejected.reason`` taxonomy (``queue_full`` |
        ``over_max_len`` | ``deadline`` | ``shed:<class>`` |
        ``recovery_exhausted`` | ``draining``) — the per-cause breakdown
        behind the coarse ``*_rejections`` totals in ``serve_counters``
        / ``decode_counters``.  Bench artifacts and tests read this
        instead of string-matching exception text."""
        from .metrics import serve_rejection_counts
        return serve_rejection_counts()

    @staticmethod
    def fleet_counters():
        """{kind: count} of replica-set serving-tier events
        (``hetu_tpu.metrics`` registry): front-door admissions and
        replica dispatches (``fleet_admitted`` / ``fleet_dispatch``),
        replicas added/retired (``fleet_scale_out`` /
        ``fleet_scale_in``), dead-or-wedged ejections and post-recovery
        re-admissions (``fleet_replica_ejected`` /
        ``fleet_replica_readmitted``), queued requests rescued onto a
        survivor (``fleet_rescued``), admitted requests whose future
        failed (``fleet_request_failures`` — the fleet bench gates this
        at zero), autoscaler polls and bound-refused resizes
        (``fleet_autoscaler_polls`` / ``fleet_scale_refused``), and the
        live-replica high-water mark (``fleet_replicas_hw`` — a max
        gauge, not a sum).  A process with no FrontDoor reports an
        empty dict."""
        from .metrics import fleet_counts
        return fleet_counts()

    @staticmethod
    def protocol_counters():
        """{kind: count} of protocol model-checking and trace-
        conformance events (``hetu_tpu.metrics`` registry, ISSUE 20):
        transition events the ``analysis.protocol.PROTO`` recorder
        captured at the live protocol sites and buffer-cap drops
        (``protocol_events`` / ``protocol_events_dropped``), recorded
        events replayed against the models' transition relations
        (``protocol_conformance_checks``) with the replays a monitor
        rejected (``protocol_divergences`` — the chaos benches gate on
        zero) or accepted under a documented allowlist entry
        (``protocol_divergences_allowlisted``), plus checker activity:
        canonical states the BFS explored
        (``protocol_states_explored``) and invariant violations found
        (``protocol_violations`` — nonzero only under a seeded
        mutation).  A process that never verifies a protocol reports an
        empty dict."""
        from .metrics import protocol_counts
        return protocol_counts()

    @staticmethod
    def fault_counters():
        """{kind: count} of fault-tolerance events (``hetu_tpu.metrics``
        registry): transport retries/exhaustions, chaos injections,
        dead-rank exclusions, auto/emergency saves, resumes, supervisor
        restarts, the PS replication plane — shard failovers and
        promotions (``ps_failover*``/``ps_promoted``), op-log forward
        breakage (``repl_forward_failed``), redundancy repair
        (``ps_re_replicated``/``ps_re_replicate_*``), standby respawns —
        and the partition-tolerance plane: chaos-partition frame drops
        (``partition_frames_dropped``), fencing-epoch bumps/refusals
        (``ps_epoch_bumps``/``ps_epoch_refused``), stale ex-primary
        demotions (``ps_demotions``), and partitioned-but-alive ranks
        (``ps_unreachable``).  Every entry except the routine
        ``auto_save`` bookkeeping is evidence of a detected fault or a
        recovery action; a clean run — replicated or not — reports none
        of those (and an empty dict when auto-checkpointing is off)."""
        from .metrics import fault_counts
        return fault_counts()

    def memory_stats(self):
        """Per-device memory stats (reference polls pynvml)."""
        import jax
        out = {}
        for d in jax.local_devices():
            st = d.memory_stats() if hasattr(d, "memory_stats") else None
            if st:
                out[str(d)] = {k: int(v) for k, v in st.items()}
        return out

    def trace(self, feed_dict, log_dir, steps=3):
        """Capture a hardware trace of real steps into ``log_dir``
        (TensorBoard/XProf format via ``jax.profiler`` — the TPU-native
        replacement for the reference's per-op CUDA-event timeline;
        SURVEY.md §5.1).  Each step is wrapped in
        ``jax.profiler.StepTraceAnnotation`` so XProf groups its device
        slices under the host step index — with ``HETU_TRACE=1`` the
        host-side ``obs`` spans carry the same step numbers, giving
        host-span <-> device-trace correlation (match ``step_num``
        against the ``step`` span's ``step`` arg).  Returns the
        directory for convenience."""
        import jax
        if steps < 1:
            raise ValueError("trace needs steps >= 1")
        self._sync(self.sub.run(feed_dict))  # compile+warm OUTSIDE the trace
        first = int(self.ex.step_counter)
        with jax.profiler.trace(str(log_dir)):
            for i in range(steps):
                with jax.profiler.StepTraceAnnotation(
                        "hetu_step", step_num=first + i):
                    out = self.sub.run(feed_dict)
            self._sync(out)
        return str(log_dir)


class CollectiveProfiler:
    """Collective latency/bandwidth over mesh axes (reference NCCLProfiler).

    Results feed the auto-parallel cost model: ``{'allreduce': {bytes: s},
    'sendrecv': {...}, 'alltoall': {...}}`` plus ``bandwidth()`` estimates.
    """

    def __init__(self, mesh=None, axis=None, repeats=5):
        import jax
        from .context import make_mesh
        if mesh is None:
            n = len(jax.devices())
            mesh = make_mesh({"dp": n})
        self.mesh = mesh
        self.axis = axis or list(mesh.shape)[0]
        self.repeats = repeats

    def _timed(self, build_fn, nbytes):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        n = self.mesh.shape[self.axis]
        elems = max(1, nbytes // 4)
        x = jnp.zeros((n, elems), jnp.float32)
        x = jax.device_put(x, NamedSharding(self.mesh, P(self.axis, None)))
        fn = build_fn(n)
        out = fn(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(self.repeats):
            out = fn(x)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / self.repeats

    def profile_allreduce(self, nbytes):
        import jax
        from jax.sharding import PartitionSpec as P
        def build(n):
            @jax.jit
            def f(x):
                return jax.shard_map(
                    lambda v: jax.lax.psum(v, self.axis),
                    mesh=self.mesh, in_specs=P(self.axis, None),
                    out_specs=P(self.axis, None))(x)
            return f
        return self._timed(build, nbytes)

    def profile_sendrecv(self, nbytes):
        import jax
        from jax.sharding import PartitionSpec as P
        def build(n):
            perm = [(i, (i + 1) % n) for i in range(n)]

            @jax.jit
            def f(x):
                return jax.shard_map(
                    lambda v: jax.lax.ppermute(v, self.axis, perm),
                    mesh=self.mesh, in_specs=P(self.axis, None),
                    out_specs=P(self.axis, None))(x)
            return f
        return self._timed(build, nbytes)

    def profile_alltoall(self, nbytes):
        import jax
        from jax.sharding import PartitionSpec as P
        n = self.mesh.shape[self.axis]
        if n == 1:
            return 0.0

        def build(n):
            @jax.jit
            def f(x):
                # per-shard (1, e): split the feature dim n ways, concat on
                # the leading dim — the canonical tiled all_to_all
                return jax.shard_map(
                    lambda v: jax.lax.all_to_all(v, self.axis, 1, 0,
                                                 tiled=True),
                    mesh=self.mesh, in_specs=P(self.axis, None),
                    out_specs=P(self.axis, None))(x)
            return f
        # the feature dim must divide by n: round elems to a multiple of n
        elems = max(n, (max(1, nbytes // 4) // n) * n)
        return self._timed(build, elems * 4)

    def bandwidth_table(self, sizes=(1 << 16, 1 << 20, 1 << 24)):
        """{collective: {nbytes: (seconds, GB/s)}} over the probe sizes."""
        table = {}
        for name, fn in (("allreduce", self.profile_allreduce),
                         ("sendrecv", self.profile_sendrecv),
                         ("alltoall", self.profile_alltoall)):
            table[name] = {}
            for s in sizes:
                dt = fn(s)
                gbps = (s / dt) / 1e9 if dt > 0 else 0.0
                table[name][s] = (dt, gbps)
        return table


__all__ = ["HetuProfiler", "CollectiveProfiler"]
