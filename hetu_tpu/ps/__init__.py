"""Parameter-server capability: host-resident embedding store + HET cache.

Native C++ core (``native/ps_store.cc``) re-designing the reference's
ps-lite server (ps-lite/include/ps/…) and hetu_cache client
(src/hetu_cache/…) for TPU hosts — see module docstrings for the mapping.
"""
from .store import EmbeddingStore, default_store
from .cstable import CacheSparseTable
from .dist_store import DistCacheTable, DistributedStore
from .refcache import PerKeyCacheTable
from .ops import PSEmbeddingLookupOp, ps_embedding_lookup_op

__all__ = ["EmbeddingStore", "default_store", "CacheSparseTable",
           "DistCacheTable", "DistributedStore", "PerKeyCacheTable",
           "PSEmbeddingLookupOp", "ps_embedding_lookup_op"]
