"""Compile-on-first-import of the native PS/embedding-cache library.

The reference ships prebuilt ``libps.so`` / ``hetu_cache`` modules via cmake
(CMakeLists.txt:19-31); here the single-file C++ core is compiled lazily with
g++ into the package directory and loaded with ctypes (the image has no
pybind11 — see ``src/python_binding.cc:8-151`` for the reference's C-ABI
precedent).
"""
import ctypes
import os
import subprocess

from ..obs.lock_witness import make_lock as _make_lock

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "native", "ps_store.cc")
_SO = os.path.join(_HERE, "native", "libhetu_ps.so")

_lock = _make_lock("ps.build._lock")
_lib = None


def _compile():
    """Compile to a temp name then atomically rename, under a cross-process
    file lock, so concurrent importers never dlopen a half-written .so."""
    import fcntl
    lock_path = _SO + ".lock"
    with open(lock_path, "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        try:
            if (os.path.exists(_SO)
                    and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
                return  # another process built it while we waited
            tmp = f"{_SO}.tmp.{os.getpid()}"
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                   "-pthread", _SRC, "-o", tmp]
            subprocess.run(cmd, check=True, capture_output=True)
            os.rename(tmp, _SO)
        finally:
            fcntl.flock(lk, fcntl.LOCK_UN)


def get_lib():
    """Load (building if stale) the native library; None if unavailable."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _compile()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError) as e:
            import warnings
            warnings.warn(f"hetu_tpu.ps: native core unavailable ({e}); "
                          "falling back to the slow numpy store")
            return None
        c = ctypes
        P, F, I, L, U = (c.c_void_p, c.c_float, c.c_int, c.c_int64, c.c_uint64)
        FP = c.POINTER(c.c_float)
        LP = c.POINTER(c.c_int64)
        sigs = {
            "hetu_ps_create": (P, []),
            "hetu_ps_destroy": (None, [P]),
            "hetu_ps_init_table": (L, [P, L, I, I, F, F, F, F, U, F]),
            "hetu_ps_set_data": (None, [P, L, FP]),
            "hetu_ps_get_data": (None, [P, L, FP]),
            "hetu_ps_rows": (L, [P, L]),
            "hetu_ps_width": (I, [P, L]),
            "hetu_ps_pull": (None, [P, L, LP, L, FP]),
            "hetu_ps_push": (None, [P, L, LP, L, FP, F]),
            "hetu_ps_push_pull": (None, [P, L, LP, L, FP, F, LP, L, FP]),
            "hetu_ps_dense_push": (None, [P, L, FP, F]),
            "hetu_ps_versions": (None, [P, L, LP, L, LP]),
            "hetu_ps_save": (I, [P, L, c.c_char_p]),
            "hetu_ps_load": (I, [P, L, c.c_char_p]),
            "hetu_ps_ssp_init": (None, [P, I]),
            "hetu_ps_clock": (None, [P, I]),
            "hetu_ps_clock_value": (L, [P, I]),
            "hetu_ps_ssp_sync": (I, [P, I, I, I]),
            "hetu_cache_create": (P, [P, L, L, I, L, L]),
            "hetu_cache_destroy": (None, [P]),
            "hetu_cache_set_bounds": (None, [P, L, L]),
            "hetu_cache_bypass": (None, [P, I]),
            "hetu_cache_size": (L, [P]),
            "hetu_cache_lookup": (None, [P, LP, L, FP]),
            "hetu_cache_update": (None, [P, LP, L, FP]),
            "hetu_cache_push_pull": (None, [P, LP, L, FP, LP, L, FP]),
            "hetu_cache_flush": (None, [P]),
            "hetu_cache_perf": (None, [P, LP]),
        }
        for name, (res, args) in sigs.items():
            fn = getattr(lib, name)
            fn.restype = res
            fn.argtypes = args
        _lib = lib
        return _lib
