"""CacheSparseTable — HET bounded-staleness embedding cache client.

API parity with the reference's ``python/hetu/cstable.py:19`` (which wraps
the pybind11 ``hetu_cache`` module, ``src/hetu_cache/include/cache.h:21``):
``embedding_lookup`` / ``embedding_update`` / ``embedding_push_pull`` return
futures (the reference's ``wait_t``); eviction policy ∈ {LRU, LFU, LFUOPT};
``pull_bound``/``push_bound`` bound read/write staleness in versions
(HET, VLDB'22).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .build import get_lib
from .store import EmbeddingStore, default_store

_POLICY = {"LRU": 0, "LFU": 1, "LFUOPT": 2}


class CacheSparseTable:
    def __init__(self, limit, length, width, node_id=0, policy="LRU",
                 bound=100, pull_bound=None, push_bound=None, store=None,
                 table=None, opt="sgd", lr=0.01, seed=0):
        """``limit``: max cached rows; ``length``×``width``: table shape;
        ``bound``: default staleness bound (pull & push), overridable
        separately (reference setPullBound/setPushBound)."""
        self.store = store or default_store()
        if table is None:
            table = self.store.init_table(length, width, opt=opt, lr=lr,
                                          seed=seed)
        self.table = table
        self.length, self.width = length, width
        self.node_id = node_id
        policy = policy.upper()
        if policy not in _POLICY:
            raise ValueError(f"unknown cache policy {policy!r}")
        self.policy = policy
        pull_bound = bound if pull_bound is None else pull_bound
        push_bound = bound if push_bound is None else push_bound
        self._lib = get_lib()
        self._h = None
        if self._lib and self.store._h:
            self._h = self._lib.hetu_cache_create(
                self.store._h, table, limit, _POLICY[policy],
                pull_bound, push_bound)
        self._pool = ThreadPoolExecutor(max_workers=1)  # ordered async ops

    # -- bounds ------------------------------------------------------------
    def set_pull_bound(self, bound):
        if self._h:
            self._lib.hetu_cache_set_bounds(self._h, bound, -1)

    def set_push_bound(self, bound):
        if self._h:
            self._lib.hetu_cache_set_bounds(self._h, -1, bound)

    def bypass(self, on=True):
        if self._h:
            self._lib.hetu_cache_bypass(self._h, int(on))

    # -- core (sync) -------------------------------------------------------
    def _check_keys(self, keys):
        if keys.size and (keys.min() < 0 or keys.max() >= self.length):
            raise IndexError(
                f"embedding key out of range: [{keys.min()}, {keys.max()}] "
                f"vs table length {self.length}")

    def _lookup_sync(self, keys, dest):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        self._check_keys(keys)
        if self._h:
            import ctypes
            self._lib.hetu_cache_lookup(
                self._h,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                dest.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            dest.reshape(keys.size, self.width)[:] = \
                self.store.pull(self.table, keys)
        return dest

    def _update_sync(self, keys, grads):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        self._check_keys(keys)
        grads = np.ascontiguousarray(grads, np.float32)
        if self._h:
            import ctypes
            self._lib.hetu_cache_update(
                self._h,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            self.store.push(self.table, keys, grads)

    # -- reference async API ----------------------------------------------
    def embedding_lookup(self, keys, dest=None):
        """Async lookup; returns a future resolving to ``dest``
        (keys.shape + (width,))."""
        keys = np.asarray(keys)
        if dest is None:
            dest = np.empty(keys.shape + (self.width,), np.float32)
        return self._pool.submit(self._lookup_sync, keys, dest)

    def embedding_update(self, keys, grads):
        return self._pool.submit(self._update_sync, keys, grads)

    def embedding_push_pull(self, push_keys, grads, pull_keys, dest=None):
        if dest is None:
            dest = np.empty(np.asarray(pull_keys).shape + (self.width,),
                            np.float32)

        def run():
            self._update_sync(push_keys, grads)
            return self._lookup_sync(np.asarray(pull_keys), dest)
        return self._pool.submit(run)

    # -- maintenance -------------------------------------------------------
    def flush(self):
        """Push every dirty cached row to the store (checkpoint barrier)."""
        self._pool.submit(lambda: None).result()  # drain queue
        if self._h:
            self._lib.hetu_cache_flush(self._h)

    def perf(self):
        if not self._h:
            return {}
        import ctypes
        out = np.zeros(8, np.int64)
        self._lib.hetu_cache_perf(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        keys = ["lookups", "hits", "evictions", "pushes", "fetches", "size",
                "write_lookups", "write_hits"]
        d = dict(zip(keys, out.tolist()))
        # read hit rate — the HET cache's citable number (reference cache.h
        # perf_ semantics: reads and writes count separately)
        d["hit_rate"] = (d["hits"] / d["lookups"]) if d["lookups"] else 0.0
        return d

    def __len__(self):
        return int(self._lib.hetu_cache_size(self._h)) if self._h else 0

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._pool.shutdown(wait=True)
                self._lib.hetu_cache_destroy(self._h)
        except Exception:
            pass
