"""CacheSparseTable — HET bounded-staleness embedding cache client.

API parity with the reference's ``python/hetu/cstable.py:19`` (which wraps
the pybind11 ``hetu_cache`` module, ``src/hetu_cache/include/cache.h:21``):
``embedding_lookup`` / ``embedding_update`` / ``embedding_push_pull`` return
futures (the reference's ``wait_t``); eviction policy ∈ {LRU, LFU, LFUOPT};
``pull_bound``/``push_bound`` bound read/write staleness in versions
(HET, VLDB'22).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .build import get_lib
from .store import default_store

_POLICY = {"LRU": 0, "LFU": 1, "LFUOPT": 2}


class CacheSparseTable:
    def __init__(self, limit, length, width, node_id=0, policy="LRU",
                 bound=100, pull_bound=None, push_bound=None, store=None,
                 table=None, opt="sgd", lr=0.01, seed=0):
        """``limit``: max cached rows; ``length``×``width``: table shape;
        ``bound``: default staleness bound (pull & push), overridable
        separately (reference setPullBound/setPushBound)."""
        self.store = store or default_store()
        if table is None:
            table = self.store.init_table(length, width, opt=opt, lr=lr,
                                          seed=seed)
        self.table = table
        self.length, self.width = length, width
        self.node_id = node_id
        policy = policy.upper()
        if policy not in _POLICY:
            raise ValueError(f"unknown cache policy {policy!r}")
        self.policy = policy
        pull_bound = bound if pull_bound is None else pull_bound
        push_bound = bound if push_bound is None else push_bound
        self._lib = get_lib()
        self._h = None
        if self._lib and self.store._h:
            self._h = self._lib.hetu_cache_create(
                self.store._h, table, limit, _POLICY[policy],
                pull_bound, push_bound)
        self._pool = ThreadPoolExecutor(max_workers=1)  # ordered async ops

    def _ensure_pool(self):
        """The async pool, revived if a previous ``close()`` shut it down.

        ``Executor.__del__`` closes the caches its graphs reference, but a
        cache can outlive that executor (shared across graphs, or the
        executor was rebound mid-experiment) — the next async op then
        re-spawns the worker instead of dying on a closed pool; an unused
        closed cache still leaks nothing."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=1)
        return self._pool

    # -- bounds ------------------------------------------------------------
    def set_pull_bound(self, bound):
        if self._h:
            self._lib.hetu_cache_set_bounds(self._h, bound, -1)

    def set_push_bound(self, bound):
        if self._h:
            self._lib.hetu_cache_set_bounds(self._h, -1, bound)

    def bypass(self, on=True):
        if self._h:
            self._lib.hetu_cache_bypass(self._h, int(on))

    # -- core (sync) -------------------------------------------------------
    def _check_keys(self, keys):
        if keys.size and (keys.min() < 0 or keys.max() >= self.length):
            raise IndexError(
                f"embedding key out of range: [{keys.min()}, {keys.max()}] "
                f"vs table length {self.length}")

    def _lookup_sync(self, keys, dest):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        self._check_keys(keys)
        if self._h:
            import ctypes
            self._lib.hetu_cache_lookup(
                self._h,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                dest.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            dest.reshape(keys.size, self.width)[:] = \
                self.store.pull(self.table, keys)
        return dest

    def _update_sync(self, keys, grads):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        self._check_keys(keys)
        grads = np.ascontiguousarray(grads, np.float32)
        if self._h:
            import ctypes
            self._lib.hetu_cache_update(
                self._h,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            self.store.push(self.table, keys, grads)

    # -- reference async API ----------------------------------------------
    def embedding_lookup(self, keys, dest=None):
        """Async lookup; returns a future resolving to ``dest``
        (keys.shape + (width,))."""
        keys = np.asarray(keys)
        if dest is None:
            dest = np.empty(keys.shape + (self.width,), np.float32)
        return self._ensure_pool().submit(self._lookup_sync, keys, dest)

    def embedding_update(self, keys, grads):
        return self._ensure_pool().submit(self._update_sync, keys, grads)

    def embedding_push_pull(self, push_keys, grads, pull_keys, dest=None):
        if dest is None:
            dest = np.empty(np.asarray(pull_keys).shape + (self.width,),
                            np.float32)

        def run():
            self._update_sync(push_keys, grads)
            return self._lookup_sync(np.asarray(pull_keys), dest)
        return self._ensure_pool().submit(run)

    # -- maintenance -------------------------------------------------------
    def flush(self):
        """Push every dirty cached row to the store (checkpoint barrier)."""
        pool = self._pool    # snapshot: close() may null it from a GC
        if pool is not None:  # thread between the check and the submit
            try:
                pool.submit(lambda: None).result()  # drain queue
            except RuntimeError:
                pass    # close() shut the snapshot down concurrently —
                        # a drained-then-destroyed pool has nothing queued
        if self._h:
            self._lib.hetu_cache_flush(self._h)

    def close(self):
        """Flush, then shut the per-table async pool down.  Idempotent.

        Without this every CacheSparseTable leaked its
        ``ThreadPoolExecutor`` (worker thread + queue) for the process
        lifetime; ``Executor.__del__``'s teardown calls it for every
        cache its graphs own, and ``__del__`` covers direct users.

        Teardown traps this must survive (both observed as interpreter
        hangs): (1) when ``__del__`` fires inside a GC pass, the pool
        OBJECT may already be collected — its weakref callback woke the
        worker, which exited — so a drain via ``submit().result()`` would
        queue a task no thread will ever run and block forever;
        ``shutdown(wait=True)`` drains pending work when the worker is
        alive and joins instantly when it's dead.  (2) GC can run ON the
        pool's own worker thread, where any blocking join deadlocks —
        detected and degraded to ``wait=False``."""
        import sys
        import threading
        pool = self._pool
        if pool is None:
            return
        self._pool = None
        if sys.is_finalizing():
            return      # runtime teardown: joining/flushing segfaults
        on_own_worker = threading.current_thread() in \
            getattr(pool, "_threads", ())
        try:
            pool.shutdown(wait=not on_own_worker)
        except Exception:
            pass        # interpreter already past futures teardown
        try:
            if self._h:
                self._lib.hetu_cache_flush(self._h)
        except Exception:
            pass        # native lib may already be unloaded at teardown

    def perf(self):
        if not self._h:
            return {}
        import ctypes
        out = np.zeros(8, np.int64)
        self._lib.hetu_cache_perf(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        keys = ["lookups", "hits", "evictions", "pushes", "fetches", "size",
                "write_lookups", "write_hits"]
        d = dict(zip(keys, out.tolist()))
        # read hit rate — the HET cache's citable number (reference cache.h
        # perf_ semantics: reads and writes count separately)
        d["hit_rate"] = (d["hits"] / d["lookups"]) if d["lookups"] else 0.0
        return d

    def __len__(self):
        return int(self._lib.hetu_cache_size(self._h)) if self._h else 0

    def __del__(self):
        try:
            self.close()
            if getattr(self, "_h", None):
                self._lib.hetu_cache_destroy(self._h)
                self._h = None
        except Exception:
            pass
