"""Multi-host sharded parameter server — TCP-routed key ownership.

Round-1 shipped a single-process host store; this module delivers the
reference's multi-server topology (``ps-lite/src/van.cc`` ZMQ transport,
worker routing ``include/ps/worker/PSAgent.h:50``, server shards
``PSFHandle.h``): every process owns the keys with ``key % world == rank``
(the promised ``hash(key) % nprocs`` ownership), runs a TCP server thread
answering pull/push/versions/SSP for its shard (backed by the native C++
:class:`~hetu_tpu.ps.store.EmbeddingStore`), and routes non-owned keys to
their owner over persistent sockets with a compact binary wire format
(length-prefixed frames; int64 keys + float32 rows — no pickle).

ASP (reference ``ParameterServerCommunicate.py:38`` async path):
``push_async`` enqueues onto a bounded background queue so device steps
overlap with PS traffic; ``flush`` drains.  SSP clocks live on rank 0
(the reference's scheduler role).

Deliberate non-goals (vs ps-lite's transport depth).  ps-lite ships
priority-scheduled message dispatch (``ps-lite/src/p3_van.h``) and an
RDMA/IBVerbs zero-copy van (``ibverbs_van.h``, ~1.2k LoC).  Neither is
reimplemented here, on purpose: on a TPU pod the dense-parameter path
rides XLA collectives over ICI (this store only carries sparse embedding
rows between host RAM and host RAM), the P3 priority trick exists to
overlap push/pull with GPU backprop at single-digit-ms step times —
covered here by ``push_async``'s bounded queue + the executor's
one-pusher gating — and RDMA presumes NIC hardware this runtime does not
manage.  What IS kept from ps-lite's transport: at-least-once retries
with (client, seq) dedup for pushes AND clock ticks (``resender.h``
semantics), socket timeouts + reconnect, and dead-peer diagnostics.
"""
from __future__ import annotations

import itertools
import os
import queue
import socket
import struct
import threading
import time

import numpy as np

from .store import EmbeddingStore
from .. import chaos as _chaos
from ..metrics import record_fault

OP_PULL, OP_PUSH, OP_VERSIONS, OP_CLOCK, OP_SSP_SYNC, OP_SSP_INIT, \
    OP_SHUTDOWN, OP_CLOCKS, OP_HEARTBEAT, OP_ALIVE = range(1, 11)

# op, table, nkeys, lr, payload_width, client rank, client sequence number.
# (client, seq) lets the server DEDUPLICATE retried pushes: the transport
# retries are at-least-once (the reference's ps-lite ``resender.h`` keeps
# the same ack+dedup discipline), and double-applying a gradient push would
# silently corrupt training.
_HDR = struct.Struct("<BiqdIqq")
#: retried pushes are remembered per client this many ops back
_DEDUP_WINDOW = 4096


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_frame(sock, *parts):
    body = b"".join(parts)
    sock.sendall(struct.pack("<q", len(body)) + body)


class FrameError(ConnectionError):
    """Corrupt frame header — framing on this stream is unrecoverable, so
    it subclasses ConnectionError: the server loop drops the connection
    and the client retries on a fresh one."""


#: hard cap on a decoded frame length; a corrupt/hostile length prefix must
#: raise a clean protocol error, not ``bytearray(n)`` blowing up (negative)
#: or a multi-GB allocation.  Configurable: ``HETU_MAX_FRAME_MB``.
MAX_FRAME_BYTES = int(float(os.environ.get("HETU_MAX_FRAME_MB",
                                           "1024")) * 1e6)


def _recv_frame(sock):
    (n,) = struct.unpack("<q", _recv_exact(sock, 8))
    if n < 0 or n > MAX_FRAME_BYTES:
        record_fault("ps_bad_frame")
        raise FrameError(
            f"frame length {n} outside [0, {MAX_FRAME_BYTES}] "
            f"(HETU_MAX_FRAME_MB) — corrupt or hostile peer")
    return _recv_exact(sock, n)


class StoreServer:
    """Serves one process's shard over TCP (the reference server role)."""

    def __init__(self, local: EmbeddingStore, world: int, rank: int,
                 host="127.0.0.1", port=0):
        self.local, self.world, self.rank = local, world, rank
        self._ssp_lock = threading.Condition()
        self._clocks = {}          # channel -> per-worker clock vector
        self._hb = {}              # rank -> (monotonic last-seen, step)
        self._hb_lock = threading.Lock()
        self._applied = {}         # client -> OrderedDict of recent push seqs
        self._applied_lock = threading.Lock()
        self._live_conns = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stop:      # raced a concurrent stop(): refuse service
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._live_conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                body = _recv_frame(conn)
                if self._stop:
                    # a stopped server must refuse ALL service, even on a
                    # connection that slipped past stop() (some platforms
                    # don't wake a blocked accept on close) — serving
                    # from a "dead" server would make kill-based fault
                    # tests pass vacuously
                    break
                try:
                    stop = self._handle(conn, body)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # surface handler errors to the client
                    _send_frame(conn, b"\x01",
                                f"{type(e).__name__}: {e}".encode())
                    continue
                if stop:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            self._live_conns.discard(conn)
            conn.close()

    def _seen(self, client, seq):
        """True iff this (client, seq) NON-IDEMPOTENT op (push, clock) was
        already applied — a transport retry resent a frame whose ack was
        lost.  Window-bounded (reference ``resender.h`` ack+dedup
        semantics).  Clients base seq on time_ns so a RESTARTED client's
        sequences are always fresh (old seqs in the window cannot swallow
        the new instance's ops)."""
        from collections import OrderedDict
        with self._applied_lock:
            seen = self._applied.setdefault(client, OrderedDict())
            if seq in seen:
                return True
            seen[seq] = True
            while len(seen) > _DEDUP_WINDOW:
                seen.popitem(last=False)
            return False

    def _clock_vec(self, channel):
        v = self._clocks.get(channel)
        if v is None:
            raise RuntimeError(
                f"SSP channel {channel} not initialised: call "
                f"ssp_init(n_workers, channel={channel}) first")
        return v

    def _handle(self, conn, body):
        op, table, nkeys, lr, width, client, seq = _HDR.unpack_from(body)
        off = _HDR.size
        keys = np.frombuffer(body, np.int64, nkeys, off)
        off += nkeys * 8
        if op == OP_PULL:
            out = self.local.pull(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_PUSH:
            if not self._seen(client, seq):
                grads = np.frombuffer(body, np.float32, nkeys * width,
                                      off).reshape(nkeys, width)
                self.local.push(table, keys // self.world, grads, lr)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_VERSIONS:
            v = self.local.versions(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(v, np.int64).tobytes())
        elif op == OP_SSP_INIT:
            n, channel = int(keys[0]), int(keys[1])
            with self._ssp_lock:
                # idempotent: every rank calls init; re-zeroing on the
                # second caller would erase live arrivals.  A different
                # size is an explicit reset (fresh run, same server).
                cur = self._clocks.get(channel)
                if cur is None or cur.size != n:
                    self._clocks[channel] = np.zeros(n, np.int64)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_CLOCK:
            # clock ticks are as non-idempotent as pushes: a retried tick
            # whose ack was lost must not double-increment (it would fake
            # an arrival and let stale peers past the SSP bound)
            channel = int(keys[1]) if nkeys > 1 else 0
            if not self._seen(client, seq):
                with self._ssp_lock:
                    self._clock_vec(channel)[int(keys[0])] += 1
                    self._ssp_lock.notify_all()
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SSP_SYNC:
            worker, staleness = int(keys[0]), int(keys[1])
            channel = int(keys[2]) if nkeys > 2 else 0
            # the server-side wait is ALWAYS bounded (570s < the client's
            # 600s no-timeout socket deadline) by a TOTAL monotonic
            # deadline — bounding each cond.wait alone would reset the
            # budget on every notify_all (any tick, any channel) and
            # leak this handler thread under steady clock traffic
            deadline = time.monotonic() + (lr if lr > 0 else 570.0)
            ok = True
            with self._ssp_lock:
                v = self._clock_vec(channel)
                while v[worker] - v.min() > staleness:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._ssp_lock.wait(left):
                        ok = False
                        break
                    v = self._clock_vec(channel)
            _send_frame(conn, b"\x00", b"\x01" if ok else b"\x00")
        elif op == OP_CLOCKS:
            channel = int(keys[0]) if nkeys else 0
            with self._ssp_lock:
                v = self._clock_vec(channel).copy()
            _send_frame(conn, b"\x00", v.tobytes())
        elif op == OP_HEARTBEAT:
            # liveness ping: rank + current step.  Idempotent (a retried
            # ping just refreshes the timestamp), so no dedup needed.
            with self._hb_lock:
                self._hb[int(keys[0])] = (time.monotonic(), int(keys[1]))
            _send_frame(conn, b"\x00\x01")
        elif op == OP_ALIVE:
            # keys=[n_workers], lr carries deadline_ms: int64 mask, 1 iff
            # the rank pinged within the deadline.  A rank that NEVER
            # pinged counts alive: liveness only declares death for ranks
            # it has seen alive (startup stagger — e.g. 30 s of backend
            # init before the first ping — must not read as death; a
            # rank that truly never starts is the launcher/supervisor's
            # failure domain, not the heartbeat's).
            n = int(keys[0])
            deadline_s = (lr if lr > 0 else 10_000.0) / 1e3
            now = time.monotonic()
            mask = np.zeros(n, np.int64)
            with self._hb_lock:
                for r in range(n):
                    rec = self._hb.get(r)
                    mask[r] = 1 if rec is None else \
                        int(now - rec[0] <= deadline_s)
            _send_frame(conn, b"\x00", mask.tobytes())
        elif op == OP_SHUTDOWN:
            _send_frame(conn, b"\x00\x01")
            return True
        else:
            raise ValueError(f"unknown opcode {op}")
        return False

    def stop(self):
        self._stop = True
        try:    # shutdown (not just close) wakes a blocked accept() on
            self._sock.shutdown(socket.SHUT_RDWR)   # platforms where
        except OSError:                             # close() alone doesn't
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # close live per-connection sockets too: a stopped server must look
        # DEAD to peers (fast ConnectionError), not wedged
        for conn in list(self._live_conns):
            try:
                conn.close()
            except OSError:
                pass


class DistributedStore:
    """Worker+server pair with ``key % world`` routing (EmbeddingStore API).

    ``endpoints``: list of (host, port) for every rank, index = rank; this
    process's entry may be None (it uses its own server's bound port).
    """

    def __init__(self, rank, world, endpoints=None, host="127.0.0.1",
                 port=0, async_queue=64, rpc_timeout=60.0, rpc_retries=3,
                 connect_timeout=10.0):
        self.rank, self.world = rank, world
        self.local = EmbeddingStore()
        self.server = StoreServer(self.local, world, rank, host, port)
        self.endpoints = list(endpoints) if endpoints else [None] * world
        self.endpoints[rank] = (host, self.server.port)
        self.rpc_timeout = rpc_timeout
        self.rpc_retries = max(1, rpc_retries)
        self.connect_timeout = connect_timeout
        # seq base = time_ns: strictly increasing across process restarts,
        # so a relaunched worker's sequences can never collide with its
        # predecessor's entries still in the server dedup window
        self._seq = itertools.count(time.time_ns())  # thread-safe in CPython
        self._conns = {}
        self._conn_locks = {}
        self._connect_lock = threading.Lock()  # guards the conn dicts
        self._pool = None                      # lazy RPC fan-out pool
        self._tables = {}
        self._queue = queue.Queue(maxsize=async_queue)
        self._async_thread = None
        self._hb_thread = None
        self._hb_stop = threading.Event()
        # HETU_CHAOS=seed:spec activates the chaos harness for every store
        # in the process; the server registers as a kill:ps target
        inj = _chaos.active() or _chaos.install_from_env()
        if inj is not None:
            inj.register_server(rank, self.server)

    # -- connections -------------------------------------------------------
    def _conn(self, peer):
        # per-peer locks so a slow/unreachable peer cannot stall RPCs to
        # healthy peers; the short global lock only guards the dicts
        with self._connect_lock:
            lock = self._conn_locks.setdefault(peer, threading.Lock())
        with lock:
            if peer not in self._conns:
                s = socket.create_connection(self.endpoints[peer],
                                             timeout=self.connect_timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[peer] = s
            return self._conns[peer], lock

    def _drop_conn(self, peer):
        with self._connect_lock:
            lock = self._conn_locks.setdefault(peer, threading.Lock())
        with lock:
            s = self._conns.pop(peer, None)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _rpc(self, peer, op, table, keys, payload=b"", lr=-1.0, width=0,
             op_timeout=None):
        """One request/response against ``peer``'s shard.

        Transport discipline (reference ``ps-lite/src/resender.h``): every
        socket op carries a timeout, a failed op drops the connection and
        retries on a fresh one with backoff (the same (client, seq) header
        lets the server dedup a retried PUSH whose ack was lost), and
        exhausted retries raise a *diagnosable* RuntimeError naming the
        peer — never a raw OSError or an unbounded blocking recv (the
        executor's SSP-watchdog discipline applied to the transport)."""
        keys = np.ascontiguousarray(keys, np.int64)
        hdr = _HDR.pack(op, table, keys.size, lr, width, self.rank,
                        next(self._seq))
        last_err = None
        for attempt in range(self.rpc_retries):
            if attempt:
                record_fault("ps_rpc_retry")
                time.sleep(min(1.0, 0.2 * attempt))
            try:
                # chaos harness: the active schedule may drop, delay,
                # duplicate, or wedge this frame (hetu_tpu.chaos); a clean
                # run pays one global read
                inj = _chaos.active()
                act = inj.on_send(peer, op) if inj is not None else None
                if act is not None and act[0] == "drop":
                    raise TimeoutError("chaos: dropped frame")
                sock, lock = self._conn(peer)
                with lock:
                    sock.settimeout(op_timeout if op_timeout is not None
                                    else self.rpc_timeout)
                    if act is not None and act[0] == "delay":
                        time.sleep(act[1] / 1e3)
                    elif act is not None and act[0] == "wedge":
                        # hold the socket past the op deadline's spirit:
                        # the client sees a timeout and retries fresh
                        time.sleep(act[1] / 1e3)
                        raise TimeoutError("chaos: wedged socket")
                    _send_frame(sock, hdr, keys.tobytes(), payload)
                    if act is not None and act[0] == "dup":
                        # at-least-once retry simulation: same (client,
                        # seq) frame twice — the server's dedup window
                        # must apply non-idempotent ops exactly once
                        _send_frame(sock, hdr, keys.tobytes(), payload)
                        _recv_frame(sock)       # discard the dup's ack
                    resp = _recv_frame(sock)
                break
            except (TimeoutError, ConnectionError, OSError) as e:
                last_err = e
                self._drop_conn(peer)
        else:
            record_fault("ps_peer_unreachable")
            host_, port_ = self.endpoints[peer] or ("?", "?")
            raise RuntimeError(
                f"PS peer {peer} at {host_}:{port_} unreachable after "
                f"{self.rpc_retries} attempts "
                f"({type(last_err).__name__}: {last_err}) — server process "
                f"dead or wedged")
        if not resp or resp[:1] == b"\x01":
            raise RuntimeError(
                f"PS rank {peer} error: {resp[1:].decode(errors='replace')}")
        return resp[1:]

    def _fanout(self, jobs):
        """Run per-peer jobs concurrently (one in-flight RPC per peer)."""
        if len(jobs) <= 1:
            for fn in jobs:
                fn()
            return
        from concurrent.futures import ThreadPoolExecutor
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=max(2, self.world))
        futs = [self._pool.submit(fn) for fn in jobs]
        for f in futs:
            f.result()

    # -- tables ------------------------------------------------------------
    def _local_rows(self, rows):
        return (rows - self.rank + self.world - 1) // self.world

    def init_table(self, rows, width, **kw):
        tid = self.local.init_table(self._local_rows(rows), width, **kw)
        self._tables[tid] = (rows, width)
        return tid

    def width(self, table):
        return self._tables[table][1]

    # -- sparse ops (EmbeddingStore API) -----------------------------------
    def pull(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        rows, width = self._tables[table]
        out = np.empty((flat.size, width), np.float32)
        owners = flat % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: out.__setitem__(
                    sel, self.local.pull(table, flat[sel] // self.world)))
            else:
                def job(r=r, sel=sel):
                    raw = self._rpc(r, OP_PULL, table, flat[sel])
                    out[sel] = np.frombuffer(raw, np.float32).reshape(
                        sel.size, width)
                jobs.append(job)
        self._fanout(jobs)
        return out.reshape(keys.shape + (width,))

    def push(self, table, keys, grads, lr=-1.0):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        rows, width = self._tables[table]
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        owners = keys % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: self.local.push(
                    table, keys[sel] // self.world, grads[sel], lr))
            else:
                jobs.append(lambda r=r, sel=sel: self._rpc(
                    r, OP_PUSH, table, keys[sel],
                    np.ascontiguousarray(grads[sel]).tobytes(), lr, width))
        self._fanout(jobs)

    def push_pull(self, table, push_keys, grads, pull_keys, lr=-1.0):
        self.push(table, push_keys, grads, lr)
        return self.pull(table, pull_keys)

    def versions(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        out = np.empty(keys.size, np.int64)
        owners = keys % self.world
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                out[sel] = self.local.versions(table, keys[sel] // self.world)
            else:
                raw = self._rpc(r, OP_VERSIONS, table, keys[sel])
                out[sel] = np.frombuffer(raw, np.int64)
        return out

    # -- ASP: bounded async push (reference asp prefetch path) -------------
    def _async_worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            table, keys, grads, lr = item
            self.push(table, keys, grads, lr)
            self._queue.task_done()

    def push_async(self, table, keys, grads, lr=-1.0):
        """Enqueue a push; blocks only when ``async_queue`` is full
        (bounded eventual consistency — ASP mode, ``bsp=-1``)."""
        if self._async_thread is None:
            self._async_thread = threading.Thread(target=self._async_worker,
                                                  daemon=True)
            self._async_thread.start()
        self._queue.put((table, np.array(keys, np.int64, copy=True),
                         np.array(grads, np.float32, copy=True), lr))

    def flush(self):
        """Barrier: wait until every queued async push has been applied."""
        if self._async_thread is not None:
            self._queue.join()

    # -- SSP via rank 0 (the reference scheduler role) ---------------------
    # ``channel`` separates independent clock consumers on the same server:
    # the executor's SSP step loop ticks channel 0, partial-reduce arrival
    # clocks live on their own channel — sharing one vector double-
    # incremented per step and broke preduce's 'arrival at step s ⇔
    # clock >= s+1' assumption (round-3 advisor finding).
    def ssp_init(self, n_workers, channel=0):
        """Idempotent per (channel, size): every rank may call it."""
        self._rpc(0, OP_SSP_INIT, 0,
                  np.asarray([n_workers, channel], np.int64))

    def clock(self, worker=None, channel=0):
        w = self.rank if worker is None else worker
        self._rpc(0, OP_CLOCK, 0, np.asarray([w, channel], np.int64))

    def clocks(self, channel=0):
        """Every worker's clock value (rank-0 authoritative copy) — the
        arrival feed for partial-reduce group formation."""
        raw = self._rpc(0, OP_CLOCKS, 0, np.asarray([channel], np.int64))
        return np.frombuffer(raw, np.int64).copy()

    # -- liveness: heartbeats on rank 0 (the scheduler role) ---------------
    def heartbeat(self, rank=None, step=0):
        """Ping rank 0's liveness table with (rank, step)."""
        w = self.rank if rank is None else rank
        self._rpc(0, OP_HEARTBEAT, 0,
                  np.asarray([w, step], np.int64))

    def alive_mask(self, deadline_ms, n_workers=None):
        """int64 mask over workers: 1 iff the rank heartbeated within
        ``deadline_ms`` — or never heartbeated at all (liveness only
        declares death for ranks it has seen alive; see the OP_ALIVE
        handler).  The liveness feed for partial-reduce dead-rank
        exclusion."""
        n = self.world if n_workers is None else n_workers
        raw = self._rpc(0, OP_ALIVE, 0, np.asarray([n], np.int64),
                        lr=float(deadline_ms))
        return np.frombuffer(raw, np.int64).copy()

    def start_heartbeat(self, interval_ms=None, step_fn=None):
        """Background liveness pings every ``interval_ms`` (env default
        ``HETU_HEARTBEAT_MS``=500) until ``close``.  ``step_fn`` supplies
        the step number reported with each ping (e.g. ``lambda:
        ex.step_counter``).  A failing ping is counted
        (``heartbeat_send_failed``) and retried next interval — a dead
        scheduler must not crash the worker from a daemon thread."""
        if self._hb_thread is not None:
            return
        iv = (float(os.environ.get("HETU_HEARTBEAT_MS", "500"))
              if interval_ms is None else float(interval_ms)) / 1e3

        def beat():
            while not self._hb_stop.wait(iv):
                try:
                    self.heartbeat(step=int(step_fn()) if step_fn else 0)
                except (RuntimeError, OSError, ConnectionError):
                    record_fault("heartbeat_send_failed")

        self._hb_thread = threading.Thread(
            target=beat, daemon=True, name=f"hetu-hb-{self.rank}")
        self._hb_thread.start()

    #: the server side blocks on a condition variable (OP_SSP_SYNC
    #: handler) — one RPC waits out the whole bound, no client polling
    ssp_blocking = True

    def ssp_sync(self, worker=None, staleness=0, timeout_ms=0, channel=0):
        w = self.rank if worker is None else worker
        # the server blocks until the staleness bound clears: the socket
        # deadline must outlive the requested wait (timeout_ms=0 means
        # "wait for stragglers" — bounded here at 600s rather than forever,
        # so a dead scheduler still surfaces as a diagnosable error)
        raw = self._rpc(0, OP_SSP_SYNC, 0,
                        np.asarray([w, staleness, channel], np.int64),
                        lr=timeout_ms / 1e3 if timeout_ms else -1.0,
                        op_timeout=(timeout_ms / 1e3 + 30.0) if timeout_ms
                        else 600.0)
        return raw == b"\x01"

    # -- shard persistence (reference per-server SaveParam) ----------------
    def save(self, table, path):
        self.local.save(table, f"{path}.shard{self.rank}")

    def load(self, table, path):
        self.local.load(table, f"{path}.shard{self.rank}")

    def close(self):
        self._hb_stop.set()
        self.flush()
        if self._async_thread is not None:
            self._queue.put(None)
        for peer in list(self._conns):
            try:
                self._rpc(peer, OP_SHUTDOWN, 0, np.zeros(0, np.int64))
            except (OSError, RuntimeError, ConnectionError):
                pass     # peer already gone; _rpc dropped the conn
            self._drop_conn(peer)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.server.stop()


class DistCacheTable:
    """HET bounded-staleness cache over a :class:`DistributedStore`
    (cross-host variant of the native ``CacheSparseTable``; reference
    ``src/hetu_cache/cache.h:21`` pull_bound_/push_bound_ semantics).

    - ``pull_bound``: a cached row may serve at most this many lookups
      before it must be re-pulled from its owner.
    - ``push_bound``: local gradient updates accumulate per row and are
      pushed to the owner once this many are pending (or on ``flush``).
    - LRU eviction at ``limit`` rows; evicting a dirty row pushes it.
    """

    def __init__(self, store: DistributedStore, table, limit=1 << 16,
                 pull_bound=100, push_bound=10, lr=-1.0):
        self.store, self.table = store, table
        self.width = store.width(table)
        self.limit = limit
        self.pull_bound, self.push_bound = pull_bound, push_bound
        self.lr = lr
        from collections import OrderedDict
        self._rows = OrderedDict()  # key -> np row, LRU order (O(1) evict)
        self._uses = {}     # key -> lookups since refresh
        self._grad = {}     # key -> (accumulated grad, count)
        self.stats = {"lookups": 0, "hits": 0, "evictions": 0, "pushes": 0,
                      "fetches": 0}

    def _evict_if_needed(self):
        while len(self._rows) > self.limit:
            victim, _ = self._rows.popitem(last=False)
            self._push_key(victim)
            self._uses.pop(victim, None)
            self.stats["evictions"] += 1

    def _push_key(self, key):
        g = self._grad.pop(key, None)
        if g is not None:
            self.store.push(self.table, np.asarray([key]), g[0][None, :],
                            self.lr)
            self.stats["pushes"] += 1

    def lookup(self, keys):
        keys = np.asarray(keys, np.int64).reshape(-1)
        out = np.empty((keys.size, self.width), np.float32)
        misses = []
        for i, k in enumerate(keys):
            k = int(k)
            self.stats["lookups"] += 1
            if k in self._rows and self._uses[k] < self.pull_bound:
                out[i] = self._rows[k]
                self._uses[k] += 1
                self._rows.move_to_end(k)
                self.stats["hits"] += 1
            else:
                misses.append((i, k))
        if misses:
            mk = np.asarray([k for _, k in misses], np.int64)
            # a stale row may carry pending local grads — push them first so
            # the refreshed value includes this worker's own updates
            for _, k in misses:
                self._push_key(k)
            rows = self.store.pull(self.table, mk)
            self.stats["fetches"] += len(misses)
            for (i, k), row in zip(misses, rows):
                out[i] = row
                self._rows[k] = row.copy()
                self._rows.move_to_end(k)
                self._uses[k] = 1
            self._evict_if_needed()
        return out

    def update(self, keys, grads):
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        for k, g in zip(keys, grads):
            k = int(k)
            acc, cnt = self._grad.get(k, (np.zeros(self.width, np.float32), 0))
            acc = acc + g
            cnt += 1
            if cnt >= self.push_bound:
                self.store.push(self.table, np.asarray([k]), acc[None, :],
                                self.lr)
                self.stats["pushes"] += 1
                self._grad.pop(k, None)
                # local cached copy is now stale relative to the server
                self._uses[k] = self.pull_bound
            else:
                self._grad[k] = (acc, cnt)

    def flush(self):
        for k in list(self._grad):
            self._push_key(k)
