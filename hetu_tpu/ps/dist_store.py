"""Multi-host sharded parameter server — TCP-routed key ownership.

Round-1 shipped a single-process host store; this module delivers the
reference's multi-server topology (``ps-lite/src/van.cc`` ZMQ transport,
worker routing ``include/ps/worker/PSAgent.h:50``, server shards
``PSFHandle.h``): every process owns the keys with ``key % world == rank``
(the promised ``hash(key) % nprocs`` ownership), runs a TCP server thread
answering pull/push/versions/SSP for its shard (backed by the native C++
:class:`~hetu_tpu.ps.store.EmbeddingStore`), and routes non-owned keys to
their owner over persistent sockets with a compact binary wire format
(length-prefixed frames; int64 keys + float32 rows — no pickle).

ASP (reference ``ParameterServerCommunicate.py:38`` async path):
``push_async`` enqueues onto a bounded background queue so device steps
overlap with PS traffic; ``flush`` drains.  SSP clocks live on rank 0
(the reference's scheduler role).

Deliberate non-goals (vs ps-lite's transport depth).  ps-lite ships
priority-scheduled message dispatch (``ps-lite/src/p3_van.h``) and an
RDMA/IBVerbs zero-copy van (``ibverbs_van.h``, ~1.2k LoC).  Neither is
reimplemented here, on purpose: on a TPU pod the dense-parameter path
rides XLA collectives over ICI (this store only carries sparse embedding
rows between host RAM and host RAM), the P3 priority trick exists to
overlap push/pull with GPU backprop at single-digit-ms step times —
covered here by ``push_async``'s bounded queue + the executor's
one-pusher gating — and RDMA presumes NIC hardware this runtime does not
manage.  What IS kept from ps-lite's transport: at-least-once retries
with (client, seq) dedup for pushes AND clock ticks (``resender.h``
semantics), socket timeouts + reconnect, and dead-peer diagnostics.
"""
from __future__ import annotations

import itertools
import os
import queue
import socket
import struct
import threading
import time

import numpy as np

from .store import EmbeddingStore
from .. import chaos as _chaos
from ..metrics import record_cache, record_fault

OP_PULL, OP_PUSH, OP_VERSIONS, OP_CLOCK, OP_SSP_SYNC, OP_SSP_INIT, \
    OP_SHUTDOWN, OP_CLOCKS, OP_HEARTBEAT, OP_ALIVE = range(1, 11)
#: fused push+pull (reference PsfType kSDPushPull): keys frame carries
#: ``[npush, push_keys..., pull_keys...]``, payload carries the grads —
#: one round trip per peer instead of serial push-then-pull
OP_PUSH_PULL = 11

# op, table, nkeys, lr, payload_width, client rank, client sequence number.
# (client, seq) lets the server DEDUPLICATE retried pushes: the transport
# retries are at-least-once (the reference's ps-lite ``resender.h`` keeps
# the same ack+dedup discipline), and double-applying a gradient push would
# silently corrupt training.
_HDR = struct.Struct("<BiqdIqq")
#: retried pushes are remembered per client this many ops back
_DEDUP_WINDOW = 4096


def _segment_sum(grads, inv, counts):
    """Per-unique-key float32 grad sums (the client-side half of wire
    dedup).  A one-hot CSR matmul when scipy is present — numpy's own
    scatter-reductions (``ufunc.at``, ``reduceat``) are scalar-dispatched
    and ~5x slower on the (batch, width) slabs this path moves; scipy
    ships with jax, so the fallback exists only for exotic builds.
    Summation association may differ from a per-occurrence loop by
    float32 rounding; every cache/transport DECISION is value-independent
    (keys and counters only), so semantics are unaffected."""
    if counts.size == inv.size:         # all keys distinct: reorder only
        return np.ascontiguousarray(grads[np.argsort(inv, kind="stable")])
    try:
        from scipy import sparse as _sp
        onehot = _sp.csr_matrix(
            (np.ones(inv.size, np.float32), inv,
             np.arange(inv.size + 1, dtype=np.int64)),
            shape=(inv.size, counts.size))
        return np.asarray(onehot.T @ grads, np.float32)
    except ImportError:
        order = np.argsort(inv, kind="stable")
        starts = np.zeros(counts.size, np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        return np.add.reduceat(grads[order], starts, axis=0)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_frame(sock, *parts):
    body = b"".join(parts)
    sock.sendall(struct.pack("<q", len(body)) + body)


class FrameError(ConnectionError):
    """Corrupt frame header — framing on this stream is unrecoverable, so
    it subclasses ConnectionError: the server loop drops the connection
    and the client retries on a fresh one."""


#: hard cap on a decoded frame length; a corrupt/hostile length prefix must
#: raise a clean protocol error, not ``bytearray(n)`` blowing up (negative)
#: or a multi-GB allocation.  Configurable: ``HETU_MAX_FRAME_MB``.
MAX_FRAME_BYTES = int(float(os.environ.get("HETU_MAX_FRAME_MB",
                                           "1024")) * 1e6)


def _recv_frame(sock):
    (n,) = struct.unpack("<q", _recv_exact(sock, 8))
    if n < 0 or n > MAX_FRAME_BYTES:
        record_fault("ps_bad_frame")
        raise FrameError(
            f"frame length {n} outside [0, {MAX_FRAME_BYTES}] "
            f"(HETU_MAX_FRAME_MB) — corrupt or hostile peer")
    return _recv_exact(sock, n)


class StoreServer:
    """Serves one process's shard over TCP (the reference server role)."""

    def __init__(self, local: EmbeddingStore, world: int, rank: int,
                 host="127.0.0.1", port=0):
        self.local, self.world, self.rank = local, world, rank
        self._ssp_lock = threading.Condition()
        self._clocks = {}          # channel -> per-worker clock vector
        self._hb = {}              # rank -> (monotonic last-seen, step)
        self._hb_lock = threading.Lock()
        self._applied = {}         # client -> OrderedDict of recent push seqs
        self._applied_lock = threading.Lock()
        self._live_conns = set()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stop:      # raced a concurrent stop(): refuse service
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._live_conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                body = _recv_frame(conn)
                if self._stop:
                    # a stopped server must refuse ALL service, even on a
                    # connection that slipped past stop() (some platforms
                    # don't wake a blocked accept on close) — serving
                    # from a "dead" server would make kill-based fault
                    # tests pass vacuously
                    break
                try:
                    stop = self._handle(conn, body)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # surface handler errors to the client
                    _send_frame(conn, b"\x01",
                                f"{type(e).__name__}: {e}".encode())
                    continue
                if stop:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            self._live_conns.discard(conn)
            conn.close()

    def _seen(self, client, seq):
        """True iff this (client, seq) NON-IDEMPOTENT op (push, clock) was
        already applied — a transport retry resent a frame whose ack was
        lost.  Window-bounded (reference ``resender.h`` ack+dedup
        semantics).  Clients base seq on time_ns so a RESTARTED client's
        sequences are always fresh (old seqs in the window cannot swallow
        the new instance's ops)."""
        from collections import OrderedDict
        with self._applied_lock:
            seen = self._applied.setdefault(client, OrderedDict())
            if seq in seen:
                return True
            seen[seq] = True
            while len(seen) > _DEDUP_WINDOW:
                seen.popitem(last=False)
            return False

    def _clock_vec(self, channel):
        v = self._clocks.get(channel)
        if v is None:
            raise RuntimeError(
                f"SSP channel {channel} not initialised: call "
                f"ssp_init(n_workers, channel={channel}) first")
        return v

    def _handle(self, conn, body):
        op, table, nkeys, lr, width, client, seq = _HDR.unpack_from(body)
        off = _HDR.size
        keys = np.frombuffer(body, np.int64, nkeys, off)
        off += nkeys * 8
        if op == OP_PULL:
            out = self.local.pull(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_PUSH:
            if not self._seen(client, seq):
                grads = np.frombuffer(body, np.float32, nkeys * width,
                                      off).reshape(nkeys, width)
                self.local.push(table, keys // self.world, grads, lr)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_PUSH_PULL:
            # fused SDPushPull: apply the push shard, answer the pull shard,
            # one ack.  The push half is as non-idempotent as OP_PUSH — a
            # retried frame skips it but still serves the (idempotent) pull.
            npush = int(keys[0])
            push_keys = keys[1:1 + npush]
            pull_keys = keys[1 + npush:]
            if npush and not self._seen(client, seq):
                grads = np.frombuffer(body, np.float32, npush * width,
                                      off).reshape(npush, width)
                self.local.push(table, push_keys // self.world, grads, lr)
            out = self.local.pull(table, pull_keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_VERSIONS:
            v = self.local.versions(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(v, np.int64).tobytes())
        elif op == OP_SSP_INIT:
            n, channel = int(keys[0]), int(keys[1])
            with self._ssp_lock:
                # idempotent: every rank calls init; re-zeroing on the
                # second caller would erase live arrivals.  A different
                # size is an explicit reset (fresh run, same server).
                cur = self._clocks.get(channel)
                if cur is None or cur.size != n:
                    self._clocks[channel] = np.zeros(n, np.int64)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_CLOCK:
            # clock ticks are as non-idempotent as pushes: a retried tick
            # whose ack was lost must not double-increment (it would fake
            # an arrival and let stale peers past the SSP bound)
            channel = int(keys[1]) if nkeys > 1 else 0
            if not self._seen(client, seq):
                with self._ssp_lock:
                    self._clock_vec(channel)[int(keys[0])] += 1
                    self._ssp_lock.notify_all()
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SSP_SYNC:
            worker, staleness = int(keys[0]), int(keys[1])
            channel = int(keys[2]) if nkeys > 2 else 0
            # the server-side wait is ALWAYS bounded (570s < the client's
            # 600s no-timeout socket deadline) by a TOTAL monotonic
            # deadline — bounding each cond.wait alone would reset the
            # budget on every notify_all (any tick, any channel) and
            # leak this handler thread under steady clock traffic
            deadline = time.monotonic() + (lr if lr > 0 else 570.0)
            ok = True
            with self._ssp_lock:
                v = self._clock_vec(channel)
                while v[worker] - v.min() > staleness:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._ssp_lock.wait(left):
                        ok = False
                        break
                    v = self._clock_vec(channel)
            _send_frame(conn, b"\x00", b"\x01" if ok else b"\x00")
        elif op == OP_CLOCKS:
            channel = int(keys[0]) if nkeys else 0
            with self._ssp_lock:
                v = self._clock_vec(channel).copy()
            _send_frame(conn, b"\x00", v.tobytes())
        elif op == OP_HEARTBEAT:
            # liveness ping: rank + current step.  Idempotent (a retried
            # ping just refreshes the timestamp), so no dedup needed.
            with self._hb_lock:
                self._hb[int(keys[0])] = (time.monotonic(), int(keys[1]))
            _send_frame(conn, b"\x00\x01")
        elif op == OP_ALIVE:
            # keys=[n_workers], lr carries deadline_ms: int64 mask, 1 iff
            # the rank pinged within the deadline.  A rank that NEVER
            # pinged counts alive: liveness only declares death for ranks
            # it has seen alive (startup stagger — e.g. 30 s of backend
            # init before the first ping — must not read as death; a
            # rank that truly never starts is the launcher/supervisor's
            # failure domain, not the heartbeat's).
            n = int(keys[0])
            deadline_s = (lr if lr > 0 else 10_000.0) / 1e3
            now = time.monotonic()
            mask = np.zeros(n, np.int64)
            with self._hb_lock:
                for r in range(n):
                    rec = self._hb.get(r)
                    mask[r] = 1 if rec is None else \
                        int(now - rec[0] <= deadline_s)
            _send_frame(conn, b"\x00", mask.tobytes())
        elif op == OP_SHUTDOWN:
            _send_frame(conn, b"\x00\x01")
            return True
        else:
            raise ValueError(f"unknown opcode {op}")
        return False

    def stop(self):
        self._stop = True
        try:    # shutdown (not just close) wakes a blocked accept() on
            self._sock.shutdown(socket.SHUT_RDWR)   # platforms where
        except OSError:                             # close() alone doesn't
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # close live per-connection sockets too: a stopped server must look
        # DEAD to peers (fast ConnectionError), not wedged
        for conn in list(self._live_conns):
            try:
                conn.close()
            except OSError:
                pass


class DistributedStore:
    """Worker+server pair with ``key % world`` routing (EmbeddingStore API).

    ``endpoints``: list of (host, port) for every rank, index = rank; this
    process's entry may be None (it uses its own server's bound port).
    """

    def __init__(self, rank, world, endpoints=None, host="127.0.0.1",
                 port=0, async_queue=64, rpc_timeout=60.0, rpc_retries=3,
                 connect_timeout=10.0):
        self.rank, self.world = rank, world
        self.local = EmbeddingStore()
        self.server = StoreServer(self.local, world, rank, host, port)
        self.endpoints = list(endpoints) if endpoints else [None] * world
        self.endpoints[rank] = (host, self.server.port)
        self.rpc_timeout = rpc_timeout
        self.rpc_retries = max(1, rpc_retries)
        self.connect_timeout = connect_timeout
        # seq base = time_ns: strictly increasing across process restarts,
        # so a relaunched worker's sequences can never collide with its
        # predecessor's entries still in the server dedup window
        self._seq = itertools.count(time.time_ns())  # thread-safe in CPython
        self._conns = {}
        self._conn_locks = {}
        self._connect_lock = threading.Lock()  # guards the conn dicts
        self._pool = None                      # lazy RPC fan-out pool
        self._tables = {}
        self._queue = queue.Queue(maxsize=async_queue)
        self._async_thread = None
        self._hb_thread = None
        self._hb_stop = threading.Event()
        # HETU_CHAOS=seed:spec activates the chaos harness for every store
        # in the process; the server registers as a kill:ps target
        inj = _chaos.active() or _chaos.install_from_env()
        if inj is not None:
            inj.register_server(rank, self.server)

    # -- connections -------------------------------------------------------
    def _conn(self, peer):
        # per-peer locks so a slow/unreachable peer cannot stall RPCs to
        # healthy peers; the short global lock only guards the dicts
        with self._connect_lock:
            lock = self._conn_locks.setdefault(peer, threading.Lock())
        with lock:
            if peer not in self._conns:
                s = socket.create_connection(self.endpoints[peer],
                                             timeout=self.connect_timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[peer] = s
            return self._conns[peer], lock

    def _drop_conn(self, peer):
        with self._connect_lock:
            lock = self._conn_locks.setdefault(peer, threading.Lock())
        with lock:
            s = self._conns.pop(peer, None)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _rpc(self, peer, op, table, keys, payload=b"", lr=-1.0, width=0,
             op_timeout=None):
        """One request/response against ``peer``'s shard.

        Transport discipline (reference ``ps-lite/src/resender.h``): every
        socket op carries a timeout, a failed op drops the connection and
        retries on a fresh one with backoff (the same (client, seq) header
        lets the server dedup a retried PUSH whose ack was lost), and
        exhausted retries raise a *diagnosable* RuntimeError naming the
        peer — never a raw OSError or an unbounded blocking recv (the
        executor's SSP-watchdog discipline applied to the transport)."""
        keys = np.ascontiguousarray(keys, np.int64)
        hdr = _HDR.pack(op, table, keys.size, lr, width, self.rank,
                        next(self._seq))
        last_err = None
        for attempt in range(self.rpc_retries):
            if attempt:
                record_fault("ps_rpc_retry")
                time.sleep(min(1.0, 0.2 * attempt))
            try:
                # chaos harness: the active schedule may drop, delay,
                # duplicate, or wedge this frame (hetu_tpu.chaos); a clean
                # run pays one global read
                inj = _chaos.active()
                act = inj.on_send(peer, op) if inj is not None else None
                if act is not None and act[0] == "drop":
                    raise TimeoutError("chaos: dropped frame")
                sock, lock = self._conn(peer)
                with lock:
                    sock.settimeout(op_timeout if op_timeout is not None
                                    else self.rpc_timeout)
                    if act is not None and act[0] == "delay":
                        time.sleep(act[1] / 1e3)
                    elif act is not None and act[0] == "wedge":
                        # hold the socket past the op deadline's spirit:
                        # the client sees a timeout and retries fresh
                        time.sleep(act[1] / 1e3)
                        raise TimeoutError("chaos: wedged socket")
                    _send_frame(sock, hdr, keys.tobytes(), payload)
                    if act is not None and act[0] == "dup":
                        # at-least-once retry simulation: same (client,
                        # seq) frame twice — the server's dedup window
                        # must apply non-idempotent ops exactly once
                        _send_frame(sock, hdr, keys.tobytes(), payload)
                        _recv_frame(sock)       # discard the dup's ack
                    resp = _recv_frame(sock)
                break
            except (TimeoutError, ConnectionError, OSError) as e:
                last_err = e
                self._drop_conn(peer)
        else:
            record_fault("ps_peer_unreachable")
            host_, port_ = self.endpoints[peer] or ("?", "?")
            raise RuntimeError(
                f"PS peer {peer} at {host_}:{port_} unreachable after "
                f"{self.rpc_retries} attempts "
                f"({type(last_err).__name__}: {last_err}) — server process "
                f"dead or wedged")
        if not resp or resp[:1] == b"\x01":
            raise RuntimeError(
                f"PS rank {peer} error: {resp[1:].decode(errors='replace')}")
        return resp[1:]

    def _fanout(self, jobs):
        """Run per-peer jobs concurrently (one in-flight RPC per peer)."""
        if len(jobs) <= 1:
            for fn in jobs:
                fn()
            return
        from concurrent.futures import ThreadPoolExecutor
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=max(2, self.world))
        futs = [self._pool.submit(fn) for fn in jobs]
        for f in futs:
            f.result()

    # -- tables ------------------------------------------------------------
    def _local_rows(self, rows):
        return (rows - self.rank + self.world - 1) // self.world

    def init_table(self, rows, width, **kw):
        tid = self.local.init_table(self._local_rows(rows), width, **kw)
        self._tables[tid] = (rows, width)
        return tid

    def width(self, table):
        return self._tables[table][1]

    # -- sparse ops (EmbeddingStore API) -----------------------------------
    # Wire-level dedup: a zipf-skewed CTR batch (2048x26 ids) is MOSTLY
    # duplicate keys — pull/push collapse to unique keys with ``np.unique``
    # BEFORE the shard fanout and scatter results back through the inverse
    # index, so the wire carries each row once.  Semantics are unchanged:
    # the server already accumulates duplicate keys within one push
    # (store.py _push_locked / the native core), so pre-summing duplicate
    # grads client-side yields the identical optimizer step and the same
    # per-key version bump.  The saved traffic is counted in
    # ``hetu_tpu.metrics`` (``ps_dedup_*``) — GC3's batching-over-many-
    # small-messages discipline, applied to the sparse path.

    @staticmethod
    def _sorted_unique(flat):
        """True iff already strictly ascending — the HET cache hands over
        pre-deduped sorted keys, so the wire path skips a re-dedup."""
        return flat.size <= 1 or bool(np.all(np.diff(flat) > 0))

    def _dedup_grads(self, keys, grads, width):
        """(unique_keys, per-unique summed grads); counts saved rows."""
        if self._sorted_unique(keys):
            return keys, grads
        uk, inv, counts = np.unique(keys, return_inverse=True,
                                    return_counts=True)
        if uk.size < keys.size:
            record_cache("ps_dedup_push_rows_saved", keys.size - uk.size)
            record_cache("ps_dedup_push_bytes_saved",
                         (keys.size - uk.size) * (width * 4 + 8))
        return uk, _segment_sum(grads, inv, counts)

    def pull(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        rows, width = self._tables[table]
        if self._sorted_unique(flat):
            uk, inv = flat, None
        else:
            uk, inv = np.unique(flat, return_inverse=True)
            if uk.size < flat.size:
                record_cache("ps_dedup_pull_rows_saved",
                             flat.size - uk.size)
                record_cache("ps_dedup_pull_bytes_saved",
                             (flat.size - uk.size) * (width * 4 + 8))
        out = np.empty((uk.size, width), np.float32)
        owners = uk % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: out.__setitem__(
                    sel, self.local.pull(table, uk[sel] // self.world)))
            else:
                def job(r=r, sel=sel):
                    raw = self._rpc(r, OP_PULL, table, uk[sel])
                    out[sel] = np.frombuffer(raw, np.float32).reshape(
                        sel.size, width)
                jobs.append(job)
        self._fanout(jobs)
        if inv is not None:
            out = out[inv]
        return out.reshape(keys.shape + (width,))

    def push(self, table, keys, grads, lr=-1.0):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        rows, width = self._tables[table]
        if not keys.size:
            return
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        uk, acc = self._dedup_grads(keys, grads, width)
        owners = uk % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: self.local.push(
                    table, uk[sel] // self.world, acc[sel], lr))
            else:
                jobs.append(lambda r=r, sel=sel: self._rpc(
                    r, OP_PUSH, table, uk[sel],
                    np.ascontiguousarray(acc[sel]).tobytes(), lr, width))
        self._fanout(jobs)

    def push_pull(self, table, push_keys, grads, pull_keys, lr=-1.0):
        """Fused SDPushPull: each peer gets ONE ``OP_PUSH_PULL`` round trip
        carrying its push shard + pull shard (server applies the push
        before answering the pull), instead of a serial push fanout
        followed by a pull fanout.  Rows are owner-partitioned, so a pull
        only ever depends on the pushes riding the same frame."""
        push_keys = np.ascontiguousarray(push_keys, np.int64).reshape(-1)
        pull_arr = np.ascontiguousarray(pull_keys, np.int64)
        pflat = pull_arr.reshape(-1)
        rows, width = self._tables[table]
        if not push_keys.size:
            return self.pull(table, pull_arr)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            push_keys.size, -1)
        upk, acc = self._dedup_grads(push_keys, grads, width)
        if self._sorted_unique(pflat):
            ulk, linv = pflat, None
        else:
            ulk, linv = np.unique(pflat, return_inverse=True)
            record_cache("ps_dedup_pull_rows_saved", pflat.size - ulk.size)
            record_cache("ps_dedup_pull_bytes_saved",
                         (pflat.size - ulk.size) * (width * 4 + 8))
        out = np.empty((ulk.size, width), np.float32)
        powners = upk % self.world
        lowners = ulk % self.world
        jobs = []
        for r in range(self.world):
            psel = np.nonzero(powners == r)[0]
            lsel = np.nonzero(lowners == r)[0]
            if not psel.size and not lsel.size:
                continue
            if r == self.rank:
                def local_job(psel=psel, lsel=lsel):
                    if psel.size:
                        self.local.push(table, upk[psel] // self.world,
                                        acc[psel], lr)
                    if lsel.size:
                        out[lsel] = self.local.pull(
                            table, ulk[lsel] // self.world)
                jobs.append(local_job)
            elif psel.size:
                def fused_job(r=r, psel=psel, lsel=lsel):
                    frame_keys = np.concatenate(
                        (np.asarray([psel.size], np.int64),
                         upk[psel], ulk[lsel]))
                    raw = self._rpc(
                        r, OP_PUSH_PULL, table, frame_keys,
                        np.ascontiguousarray(acc[psel]).tobytes(), lr,
                        width)
                    if lsel.size:
                        out[lsel] = np.frombuffer(raw, np.float32).reshape(
                            lsel.size, width)
                        # only a frame that genuinely carried BOTH halves
                        # counts as a saved round trip
                        record_cache("ps_push_pull_fused_rpcs", 1)
                jobs.append(fused_job)
            else:       # nothing to push at this peer: plain pull
                def pull_job(r=r, lsel=lsel):
                    raw = self._rpc(r, OP_PULL, table, ulk[lsel])
                    out[lsel] = np.frombuffer(raw, np.float32).reshape(
                        lsel.size, width)
                jobs.append(pull_job)
        self._fanout(jobs)
        if linv is not None:
            out = out[linv]
        return out.reshape(pull_arr.shape + (width,))

    def versions(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        uk, inv = np.unique(keys, return_inverse=True)
        out = np.empty(uk.size, np.int64)
        owners = uk % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: out.__setitem__(
                    sel, self.local.versions(table, uk[sel] // self.world)))
            else:
                def vjob(r=r, sel=sel):
                    raw = self._rpc(r, OP_VERSIONS, table, uk[sel])
                    out[sel] = np.frombuffer(raw, np.int64)
                jobs.append(vjob)
        self._fanout(jobs)
        return out[inv]

    # -- ASP: bounded async push (reference asp prefetch path) -------------
    def _async_worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            table, keys, grads, lr = item
            self.push(table, keys, grads, lr)
            self._queue.task_done()

    def push_async(self, table, keys, grads, lr=-1.0):
        """Enqueue a push; blocks only when ``async_queue`` is full
        (bounded eventual consistency — ASP mode, ``bsp=-1``)."""
        if self._async_thread is None:
            self._async_thread = threading.Thread(target=self._async_worker,
                                                  daemon=True)
            self._async_thread.start()
        self._queue.put((table, np.array(keys, np.int64, copy=True),
                         np.array(grads, np.float32, copy=True), lr))

    def flush(self):
        """Barrier: wait until every queued async push has been applied."""
        if self._async_thread is not None:
            self._queue.join()

    # -- SSP via rank 0 (the reference scheduler role) ---------------------
    # ``channel`` separates independent clock consumers on the same server:
    # the executor's SSP step loop ticks channel 0, partial-reduce arrival
    # clocks live on their own channel — sharing one vector double-
    # incremented per step and broke preduce's 'arrival at step s ⇔
    # clock >= s+1' assumption (round-3 advisor finding).
    def ssp_init(self, n_workers, channel=0):
        """Idempotent per (channel, size): every rank may call it."""
        self._rpc(0, OP_SSP_INIT, 0,
                  np.asarray([n_workers, channel], np.int64))

    def clock(self, worker=None, channel=0):
        w = self.rank if worker is None else worker
        self._rpc(0, OP_CLOCK, 0, np.asarray([w, channel], np.int64))

    def clocks(self, channel=0):
        """Every worker's clock value (rank-0 authoritative copy) — the
        arrival feed for partial-reduce group formation."""
        raw = self._rpc(0, OP_CLOCKS, 0, np.asarray([channel], np.int64))
        return np.frombuffer(raw, np.int64).copy()

    # -- liveness: heartbeats on rank 0 (the scheduler role) ---------------
    def heartbeat(self, rank=None, step=0):
        """Ping rank 0's liveness table with (rank, step)."""
        w = self.rank if rank is None else rank
        self._rpc(0, OP_HEARTBEAT, 0,
                  np.asarray([w, step], np.int64))

    def alive_mask(self, deadline_ms, n_workers=None):
        """int64 mask over workers: 1 iff the rank heartbeated within
        ``deadline_ms`` — or never heartbeated at all (liveness only
        declares death for ranks it has seen alive; see the OP_ALIVE
        handler).  The liveness feed for partial-reduce dead-rank
        exclusion."""
        n = self.world if n_workers is None else n_workers
        raw = self._rpc(0, OP_ALIVE, 0, np.asarray([n], np.int64),
                        lr=float(deadline_ms))
        return np.frombuffer(raw, np.int64).copy()

    def start_heartbeat(self, interval_ms=None, step_fn=None):
        """Background liveness pings every ``interval_ms`` (env default
        ``HETU_HEARTBEAT_MS``=500) until ``close``.  ``step_fn`` supplies
        the step number reported with each ping (e.g. ``lambda:
        ex.step_counter``).  A failing ping is counted
        (``heartbeat_send_failed``) and retried next interval — a dead
        scheduler must not crash the worker from a daemon thread."""
        if self._hb_thread is not None:
            return
        iv = (float(os.environ.get("HETU_HEARTBEAT_MS", "500"))
              if interval_ms is None else float(interval_ms)) / 1e3

        def beat():
            while not self._hb_stop.wait(iv):
                try:
                    self.heartbeat(step=int(step_fn()) if step_fn else 0)
                except (RuntimeError, OSError, ConnectionError):
                    record_fault("heartbeat_send_failed")

        self._hb_thread = threading.Thread(
            target=beat, daemon=True, name=f"hetu-hb-{self.rank}")
        self._hb_thread.start()

    #: the server side blocks on a condition variable (OP_SSP_SYNC
    #: handler) — one RPC waits out the whole bound, no client polling
    ssp_blocking = True

    def ssp_sync(self, worker=None, staleness=0, timeout_ms=0, channel=0):
        w = self.rank if worker is None else worker
        # the server blocks until the staleness bound clears: the socket
        # deadline must outlive the requested wait (timeout_ms=0 means
        # "wait for stragglers" — bounded here at 600s rather than forever,
        # so a dead scheduler still surfaces as a diagnosable error)
        raw = self._rpc(0, OP_SSP_SYNC, 0,
                        np.asarray([w, staleness, channel], np.int64),
                        lr=timeout_ms / 1e3 if timeout_ms else -1.0,
                        op_timeout=(timeout_ms / 1e3 + 30.0) if timeout_ms
                        else 600.0)
        return raw == b"\x01"

    # -- shard persistence (reference per-server SaveParam) ----------------
    def save(self, table, path):
        self.local.save(table, f"{path}.shard{self.rank}")

    def load(self, table, path):
        self.local.load(table, f"{path}.shard{self.rank}")

    def close(self):
        self._hb_stop.set()
        self.flush()
        if self._async_thread is not None:
            self._queue.put(None)
        for peer in list(self._conns):
            try:
                self._rpc(peer, OP_SHUTDOWN, 0, np.zeros(0, np.int64))
            except (OSError, RuntimeError, ConnectionError):
                pass     # peer already gone; _rpc dropped the conn
            self._drop_conn(peer)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.server.stop()


class DistCacheTable:
    """HET bounded-staleness embedding cache — fully vectorized, batch-
    granular (reference ``src/hetu_cache/cache.h:21`` pull_bound_/
    push_bound_ semantics; HET VLDB'22).  Works over any store exposing
    the EmbeddingStore sparse API (:class:`DistributedStore` across hosts,
    or a plain :class:`~hetu_tpu.ps.store.EmbeddingStore` locally).

    Storage is a contiguous ``(limit, width)`` float32 slab plus an
    open-addressed int64 key→slot hash table in numpy — no per-key Python
    objects anywhere.  ``lookup``/``update`` are vectorized hit/miss
    partitions; LRU/LFU eviction picks victims with one ``lexsort`` over
    per-slot clocks; gradients accumulate via ``np.add.at`` into a dirty
    slab; and EVERY pending push (miss-refresh, eviction, push-bound
    overflow, ``flush``) rides ONE batched ``store.push`` — grouped per
    owner rank by the store's shard fanout — instead of the pre-PR one
    single-row RPC per dirty key.  A miss-refresh that also has pushes
    pending fuses both into one ``store.push_pull`` round trip per peer.

    Contract (the per-key reference model in ``refcache.py`` implements
    the SAME rules — the parity suite holds the two bitwise equal):

    - Decisions are BATCH-granular over the call's sorted unique keys: a
      key is a HIT iff cached with ``uses < pull_bound``; all its
      occurrences serve the same row, and ``uses`` grows by the
      occurrence count.  A refresh (stale or absent) re-pulls the row and
      restarts ``uses`` at the occurrence count.
    - ``update`` accumulates per-key grads client-side (``gcnt`` grows by
      occurrence count); reaching ``push_bound`` pushes the accumulated
      grad and invalidates the local row (``uses = pull_bound``), as does
      ``flush``.  Updating an uncached key allocates a grad-only slot
      whose row never serves (born stale).
    - Eviction at ``limit``: victims are the smallest ``(last-use tick,
      key)`` [LRU] or ``(freq, tick, key)`` [LFU] among slots not touched
      by the current batch; dirty victims join the batched push.  If a
      single batch's unique keys exceed capacity, the sorted-first keys
      get slots and the remainder are served (and their grads pushed)
      uncached.
    """

    _EMPTY, _TOMB = -1, -2

    def __init__(self, store, table, limit=1 << 16,
                 pull_bound=100, push_bound=10, lr=-1.0, policy="lru"):
        self.store, self.table = store, table
        self.width = int(store.width(table))
        self.limit = int(limit)
        self.pull_bound, self.push_bound = int(pull_bound), int(push_bound)
        self.lr = lr
        policy = policy.lower()
        if policy not in ("lru", "lfu"):
            raise ValueError(f"unknown cache policy {policy!r}")
        self.policy = policy
        L, w = self.limit, self.width
        self._data = np.zeros((L, w), np.float32)   # cached rows
        self._grad = np.zeros((L, w), np.float32)   # pending grad slab
        self._slotkey = np.full(L, self._EMPTY, np.int64)  # slot -> key
        self._uses = np.zeros(L, np.int64)     # lookups since refresh
        self._gcnt = np.zeros(L, np.int64)     # pending update events
        self._ticks = np.zeros(L, np.int64)    # last-touch clock (LRU)
        self._freq = np.zeros(L, np.int64)     # touch count (LFU)
        cap = 1 << max(6, (4 * L - 1).bit_length())   # load factor <= 1/4
        self._hcap, self._hmask = cap, cap - 1
        self._hkey = np.full(cap, self._EMPTY, np.int64)
        self._hslot = np.zeros(cap, np.int64)
        self._htomb = 0
        # O(1) slot allocator: popping from the end hands out ascending
        # slot ids (slot identity is unobservable — victim order ties
        # break on KEY, never slot)
        self._freelist = np.arange(L - 1, -1, -1, dtype=np.int64)
        self._nfree = L
        self._tick = 0
        self._lock = threading.RLock()   # executor prefetch thread + main
        #: (flat, uk, inv, cnt, slots) of the latest lookup — the executor
        #: and the CTR step always update() the exact ids they just looked
        #: up, so the batch partition is computed once, not twice
        self._batch_memo = None
        self.stats = {"lookups": 0, "hits": 0, "evictions": 0, "pushes": 0,
                      "fetches": 0, "updates": 0, "push_rpcs": 0}

    # -- open-addressed int64 hash table (vectorized linear probing) -------
    def _hash(self, keys):
        h = keys.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        h ^= h >> np.uint64(29)
        return (h & np.uint64(self._hmask)).astype(np.int64)

    def _find(self, ukeys):
        """Slot for each (unique) key, -1 if absent — every probe round
        advances ALL still-unresolved keys one step at once."""
        out = np.full(ukeys.size, -1, np.int64)
        if not ukeys.size:
            return out
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            found = hk == ukeys[pend]
            if found.any():
                out[pend[found]] = self._hslot[h[found]]
            stop = found | (hk == self._EMPTY)   # TOMB keeps probing
            keep = ~stop
            if not keep.any():
                break
            pend = pend[keep]
            h = (h[keep] + 1) & self._hmask
        return out

    def _hinsert(self, ukeys, slots):
        """Insert absent unique keys; conflicting claims on one free cell
        are resolved per round (first claimant wins, rest re-probe)."""
        if not ukeys.size:
            return
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            usable = (hk == self._EMPTY) | (hk == self._TOMB)
            if usable.any():
                upos, first = np.unique(h[usable], return_index=True)
                winners = np.flatnonzero(usable)[first]
                wcells = h[winners]
                self._htomb -= int((self._hkey[wcells] == self._TOMB).sum())
                self._hkey[wcells] = ukeys[pend[winners]]
                self._hslot[wcells] = slots[pend[winners]]
                keep = np.ones(pend.size, bool)
                keep[winners] = False
                pend, h = pend[keep], h[keep]
            h = (h + 1) & self._hmask

    def _hdelete(self, ukeys):
        """Tombstone present unique keys (chains through them survive)."""
        if not ukeys.size:
            return
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            found = hk == ukeys[pend]
            if found.any():
                self._hkey[h[found]] = self._TOMB
                self._htomb += int(found.sum())
            keep = ~(found | (hk == self._EMPTY))
            if not keep.any():
                break
            pend, h = pend[keep], h[keep]
            h = (h + 1) & self._hmask

    def _maybe_rehash(self):
        if self._htomb <= self._hcap // 4:
            return
        self._hkey.fill(self._EMPTY)
        self._htomb = 0
        occ = np.flatnonzero(self._slotkey >= 0)
        self._hinsert(self._slotkey[occ], occ)

    # -- slot allocation + vectorized victim selection ---------------------
    def _pick_victims(self, occ, n_ev):
        """The ``n_ev`` worst occupied slots under the policy's total
        order — LRU ``(tick, key)``, LFU ``(freq, tick, key)`` — via
        argpartition on the primary clock with a deterministic lexsort
        refinement of the boundary ties (a full lexsort of 10^6 occupied
        slots per batch would dominate the whole lookup)."""
        if n_ev >= occ.size:
            return occ
        prim = self._ticks[occ] if self.policy == "lru" \
            else self._freq[occ]
        part = np.argpartition(prim, n_ev - 1)[:n_ev]
        thresh = prim[part].max()
        sure = part[prim[part] < thresh]
        ties = np.flatnonzero(prim == thresh)
        if self.policy == "lru":
            order = np.argsort(self._slotkey[occ[ties]], kind="stable")
        else:
            order = np.lexsort((self._slotkey[occ[ties]],
                                self._ticks[occ[ties]]))
        chosen = ties[order[:n_ev - sure.size]]
        return occ[np.concatenate((sure, chosen))]

    def _plan_slots(self, newkeys, protect_slots):
        """PLAN slots for absent unique (sorted) ``newkeys``: free slots
        first, then LRU/LFU victims among slots not in ``protect_slots``
        (the current batch's own slots) — overflow beyond capacity stays
        -1 (uncacheable).  Pure read: nothing is committed until
        :meth:`_commit_slots`, so the fallible store round trip can sit
        between plan and commit without ever leaving torn cache state.
        The O(limit) protect mask + occupancy scan is built only when
        eviction is actually needed."""
        slots = np.full(newkeys.size, -1, np.int64)
        take = min(newkeys.size, self._nfree)
        if take:
            slots[:take] = self._freelist[self._nfree - take:
                                          self._nfree][::-1]
        need = newkeys.size - take
        evslots = evkeys = np.empty(0, np.int64)
        if need > 0:
            protect = np.zeros(self.limit, bool)
            protect[protect_slots] = True
            occ = np.flatnonzero((self._slotkey >= 0) & ~protect)
            n_ev = min(need, occ.size)
            if n_ev > 0:
                evslots = self._pick_victims(occ, n_ev)
                evkeys = self._slotkey[evslots].copy()
                slots[take:take + n_ev] = evslots
        return slots, take, evslots, evkeys

    def _plan_dirty(self, slot_sel):
        """(dirty_slots, their keys, grad copies) among ``slot_sel`` —
        the push payload is copied out so the slab mutates only after the
        push round trip succeeds."""
        dirty = slot_sel[self._gcnt[slot_sel] > 0]
        if not dirty.size:
            return dirty, None, None
        return dirty, self._slotkey[dirty].copy(), self._grad[dirty].copy()

    def _commit_slots(self, newkeys, plan):
        """Apply a :meth:`_plan_slots` plan: pop the freelist, tombstone +
        reset victims, register the new keys.  Returns the registered
        (keys, slots)."""
        slots, take, evslots, evkeys = plan
        self._nfree -= take
        if evslots.size:
            self._hdelete(evkeys)
            self._grad[evslots] = 0.0
            self._gcnt[evslots] = 0
            self.stats["evictions"] += int(evslots.size)
            record_cache("emb_cache_evict_rows", int(evslots.size))
        reg = slots >= 0
        regk, regs = newkeys[reg], slots[reg]
        self._slotkey[regs] = regk
        self._hinsert(regk, regs)
        self._freq[regs] = 0
        return regk, regs

    def _flush_to_store(self, push_keys, push_grads, pull_keys=None):
        """ONE batched store round trip for everything pending: the push
        list (concatenated, already per-unique-key accumulated) and, when
        ``pull_keys`` is given, the refresh pull — fused into a single
        ``push_pull`` per peer when the store supports it.  Counters
        record only after the round trip succeeds."""
        rows = None
        if push_keys:
            pk = np.concatenate(push_keys)
            pg = np.concatenate(push_grads)
            order = np.argsort(pk, kind="stable")   # deterministic wire
            pk, pg = pk[order], pg[order]
            if pull_keys is not None and hasattr(self.store, "push_pull"):
                rows = self.store.push_pull(self.table, pk, pg, pull_keys,
                                            self.lr)
            else:
                self.store.push(self.table, pk, pg, self.lr)
            self.stats["pushes"] += int(pk.size)
            self.stats["push_rpcs"] += 1
            record_cache("emb_cache_push_rows", int(pk.size))
            record_cache("emb_cache_push_rpcs", 1)
        if rows is None and pull_keys is not None:
            rows = self.store.pull(self.table, pull_keys)
        return rows

    # -- core ops ----------------------------------------------------------
    def lookup(self, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        with self._lock:
            out = self._lookup_locked(keys.reshape(-1))
        return out.reshape(keys.shape + (self.width,))

    def _lookup_locked(self, flat):
        self._tick += 1
        self._batch_memo = None
        self.stats["lookups"] += int(flat.size)
        if not flat.size:
            return np.empty((0, self.width), np.float32)
        uk, inv, cnt = np.unique(flat, return_inverse=True,
                                 return_counts=True)
        slots = self._find(uk)
        present = slots >= 0
        hit = np.zeros(uk.size, bool)
        hit[present] = self._uses[slots[present]] < self.pull_bound
        rows_out = np.empty((uk.size, self.width), np.float32)
        refresh = ~hit
        if refresh.any():
            rkeys = uk[refresh]
            rslots = slots[refresh].copy()
            push_keys, push_grads = [], []
            # stale rows keep their slots; their pending grads must land
            # BEFORE the re-pull so the refreshed value includes them —
            # payloads are COPIES, the slab clears only on success
            stale = rslots >= 0
            dirty, dkeys, dgrads = self._plan_dirty(rslots[stale])
            if dirty.size:
                push_keys.append(dkeys)
                push_grads.append(dgrads)
            absent = ~stale
            plan = None
            if absent.any():
                plan = self._plan_slots(rkeys[absent], slots[present])
                ev_dirty, evk, evg = self._plan_dirty(plan[2])
                if ev_dirty.size:
                    push_keys.append(evk)
                    push_grads.append(evg)
                rslots[absent] = plan[0]
            # the ONLY fallible step: one fused round trip.  A transport
            # failure raises with the cache untouched — no key registered
            # for a row that was never filled, no pending grad lost
            rows = self._flush_to_store(push_keys, push_grads, rkeys)
            self.stats["fetches"] += int(rkeys.size)
            if dirty.size:
                self._grad[dirty] = 0.0
                self._gcnt[dirty] = 0
            if plan is not None:
                self._commit_slots(rkeys[absent], plan)
            cached = rslots >= 0
            if cached.all():            # common case: no overflow spill
                cs, rows_c, cnt_r = rslots, rows, cnt[refresh]
            else:
                cs, rows_c = rslots[cached], rows[cached]
                cnt_r = cnt[refresh][cached]
            self._data[cs] = rows_c
            self._uses[cs] = cnt_r
            self._ticks[cs] = self._tick
            self._freq[cs] += cnt_r
            rows_out[refresh] = rows
            self._maybe_rehash()
            slots = slots.copy()
            slots[refresh] = rslots
        # hit bookkeeping commits AFTER the fallible round trip: a raised
        # lookup must not burn pull_bound budget (or count hits) for rows
        # that were never served
        n_hit_rows = int(cnt[hit].sum())
        self.stats["hits"] += n_hit_rows
        record_cache("emb_cache_hit_rows", n_hit_rows)
        record_cache("emb_cache_miss_rows", int(flat.size) - n_hit_rows)
        if hit.any():
            hs = slots[hit]
            self._uses[hs] += cnt[hit]
            self._ticks[hs] = self._tick
            self._freq[hs] += cnt[hit]
            rows_out[hit] = self._data[hs]
        self._batch_memo = (flat, uk, inv, cnt, slots)
        return rows_out[inv]

    def update(self, keys, grads):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        if not keys.size:
            return
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size,
                                                                -1)
        with self._lock:
            self._update_locked(keys, grads)

    def _update_locked(self, flat, grads):
        self._tick += 1
        memo, self._batch_memo = self._batch_memo, None
        self.stats["updates"] += int(flat.size)
        if not flat.size:
            return
        if memo is not None and memo[0].size == flat.size \
                and np.array_equal(memo[0], flat):
            # the immediately-preceding lookup partitioned this exact
            # batch; nothing mutated in between (same lock)
            _, uk, inv, cnt, slots = memo
            slots = slots.copy()
        else:
            uk, inv, cnt = np.unique(flat, return_inverse=True,
                                     return_counts=True)
            slots = self._find(uk)
        acc = _segment_sum(grads, inv, cnt)
        present = slots >= 0
        push_keys, push_grads = [], []
        absent = ~present
        plan = None
        if absent.any():
            plan = self._plan_slots(uk[absent], slots[present])
            ev_dirty, evk, evg = self._plan_dirty(plan[2])
            if ev_dirty.size:
                push_keys.append(evk)
                push_grads.append(evg)
            slots[absent] = plan[0]
        cached = slots >= 0
        if cached.all():
            cs, acc_c, cnt_c = slots, acc, cnt
        else:
            cs, acc_c, cnt_c = slots[cached], acc[cached], cnt[cached]
            # capacity overflow: these keys' grads go straight out with
            # the same batched push (early push is within the bound)
            push_keys.append(uk[~cached])
            push_grads.append(acc[~cached])
        # push-bound overflow computed on the HYPOTHETICAL post-batch
        # counts; payloads are fresh sums, the slab commits only after
        # the push lands, so a failed round trip leaves the CACHE
        # unapplied and a caller retry is exactly-once against a
        # single-shard store.  (Across a multi-peer fanout the push is
        # at-least-once on a partial failure — per-peer acks land
        # independently, the reference ps-lite semantics.)  Slots
        # PLANNED for new keys still hold their victim's uncommitted
        # gcnt/grad — a fresh key starts from zero, not from those
        fresh = None
        if plan is not None:
            # over uk: absent keys that got a slot this batch
            fresh = (absent & (slots >= 0))[cached] if not cached.all() \
                else absent
        prior_gcnt = self._gcnt[cs] if fresh is None \
            else np.where(fresh, 0, self._gcnt[cs])
        new_gcnt = prior_gcnt + cnt_c
        exceed = new_gcnt >= self.push_bound
        if exceed.any():
            es = cs[exceed]
            pgrads = self._grad[es] + acc_c[exceed]
            if fresh is not None and fresh[exceed].any():
                pgrads[fresh[exceed]] = acc_c[exceed][fresh[exceed]]
            push_keys.append(uk[cached][exceed])
            push_grads.append(pgrads)
        # the ONLY fallible step: one batched push round trip
        self._flush_to_store(push_keys, push_grads)
        if plan is not None:
            regk, regs = self._commit_slots(uk[absent], plan)
            # grad-only slots: the row was never pulled, so it must never
            # serve — born stale
            self._data[regs] = 0.0
            self._uses[regs] = self.pull_bound
        self._grad[cs] += acc_c
        self._gcnt[cs] = new_gcnt
        self._ticks[cs] = self._tick
        self._freq[cs] += cnt_c
        if exceed.any():
            self._grad[es] = 0.0
            self._gcnt[es] = 0
            self._uses[es] = self.pull_bound   # server is ahead: stale
        self._maybe_rehash()

    def flush(self):
        """Push every pending accumulated grad (ONE batched push) and
        invalidate the pushed rows (checkpoint barrier)."""
        with self._lock:
            d = np.flatnonzero((self._slotkey >= 0) & (self._gcnt > 0))
            if d.size:
                d = d[np.argsort(self._slotkey[d], kind="stable")]
                self._flush_to_store([self._slotkey[d].copy()],
                                     [self._grad[d].copy()])
                self._grad[d] = 0.0
                self._gcnt[d] = 0
                self._uses[d] = self.pull_bound

    def close(self):
        """Flush pending grads; safe to call repeatedly / at teardown.

        During interpreter finalization the flush is SKIPPED: pushing
        through numpy/ctypes while the runtime is being torn down
        segfaults (observed via ``Executor.__del__`` at process exit),
        and pending grads are bounded-staleness state — anything that
        must be durable goes through an explicit ``flush``/checkpoint
        from live code (``Executor.save`` already calls ``ps_flush``)."""
        import sys
        if sys.is_finalizing():
            return
        try:
            self.flush()
        except Exception:
            pass    # store already closed at teardown

    def perf(self):
        """Counter snapshot + read hit rate (CacheSparseTable.perf parity:
        the HET cache's citable number)."""
        with self._lock:
            d = dict(self.stats)
            d["size"] = int((self._slotkey >= 0).sum())
        d["hit_rate"] = (d["hits"] / d["lookups"]) if d["lookups"] else 0.0
        return d

    def __len__(self):
        with self._lock:
            return int((self._slotkey >= 0).sum())
