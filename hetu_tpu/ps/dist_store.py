"""Multi-host sharded parameter server — TCP-routed key ownership.

Round-1 shipped a single-process host store; this module delivers the
reference's multi-server topology (``ps-lite/src/van.cc`` ZMQ transport,
worker routing ``include/ps/worker/PSAgent.h:50``, server shards
``PSFHandle.h``): every process owns the keys with ``key % world == rank``
(the promised ``hash(key) % nprocs`` ownership), runs a TCP server thread
answering pull/push/versions/SSP for its shard (backed by the native C++
:class:`~hetu_tpu.ps.store.EmbeddingStore`), and routes non-owned keys to
their owner over persistent sockets with a compact binary wire format
(length-prefixed frames; int64 keys + float32 rows — no pickle).

ASP (reference ``ParameterServerCommunicate.py:38`` async path):
``push_async`` enqueues onto a bounded background queue so device steps
overlap with PS traffic; ``flush`` drains.  SSP clocks live on rank 0
(the reference's scheduler role).
"""
from __future__ import annotations

import queue
import socket
import struct
import threading

import numpy as np

from .store import EmbeddingStore

OP_PULL, OP_PUSH, OP_VERSIONS, OP_CLOCK, OP_SSP_SYNC, OP_SSP_INIT, \
    OP_SHUTDOWN, OP_CLOCKS = range(1, 9)

_HDR = struct.Struct("<BiqdI")  # op, table, nkeys, lr, payload_width


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_frame(sock, *parts):
    body = b"".join(parts)
    sock.sendall(struct.pack("<q", len(body)) + body)


def _recv_frame(sock):
    (n,) = struct.unpack("<q", _recv_exact(sock, 8))
    return _recv_exact(sock, n)


class StoreServer:
    """Serves one process's shard over TCP (the reference server role)."""

    def __init__(self, local: EmbeddingStore, world: int, rank: int,
                 host="127.0.0.1", port=0):
        self.local, self.world, self.rank = local, world, rank
        self._ssp_lock = threading.Condition()
        self._clocks = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                body = _recv_frame(conn)
                try:
                    stop = self._handle(conn, body)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # surface handler errors to the client
                    _send_frame(conn, b"\x01",
                                f"{type(e).__name__}: {e}".encode())
                    continue
                if stop:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _handle(self, conn, body):
        op, table, nkeys, lr, width = _HDR.unpack_from(body)
        off = _HDR.size
        keys = np.frombuffer(body, np.int64, nkeys, off)
        off += nkeys * 8
        if op == OP_PULL:
            out = self.local.pull(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_PUSH:
            grads = np.frombuffer(body, np.float32, nkeys * width,
                                  off).reshape(nkeys, width)
            self.local.push(table, keys // self.world, grads, lr)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_VERSIONS:
            v = self.local.versions(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(v, np.int64).tobytes())
        elif op == OP_SSP_INIT:
            with self._ssp_lock:
                self._clocks = np.zeros(int(keys[0]), np.int64)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_CLOCK:
            with self._ssp_lock:
                if self._clocks is None:
                    raise RuntimeError(
                        "SSP not initialised: call ssp_init(n_workers) first")
                self._clocks[int(keys[0])] += 1
                self._ssp_lock.notify_all()
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SSP_SYNC:
            worker, staleness = int(keys[0]), int(keys[1])
            timeout = lr if lr > 0 else None
            ok = True
            with self._ssp_lock:
                if self._clocks is None:
                    raise RuntimeError(
                        "SSP not initialised: call ssp_init(n_workers) first")
                while self._clocks[worker] - self._clocks.min() > staleness:
                    if not self._ssp_lock.wait(timeout):
                        ok = False
                        break
            _send_frame(conn, b"\x00", b"\x01" if ok else b"\x00")
        elif op == OP_CLOCKS:
            with self._ssp_lock:
                if self._clocks is None:
                    raise RuntimeError(
                        "SSP not initialised: call ssp_init(n_workers) first")
                v = self._clocks.copy()
            _send_frame(conn, b"\x00", v.tobytes())
        elif op == OP_SHUTDOWN:
            _send_frame(conn, b"\x00\x01")
            return True
        else:
            raise ValueError(f"unknown opcode {op}")
        return False

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


class DistributedStore:
    """Worker+server pair with ``key % world`` routing (EmbeddingStore API).

    ``endpoints``: list of (host, port) for every rank, index = rank; this
    process's entry may be None (it uses its own server's bound port).
    """

    def __init__(self, rank, world, endpoints=None, host="127.0.0.1",
                 port=0, async_queue=64):
        self.rank, self.world = rank, world
        self.local = EmbeddingStore()
        self.server = StoreServer(self.local, world, rank, host, port)
        self.endpoints = list(endpoints) if endpoints else [None] * world
        self.endpoints[rank] = (host, self.server.port)
        self._conns = {}
        self._conn_locks = {}
        self._connect_lock = threading.Lock()  # guards the conn dicts
        self._pool = None                      # lazy RPC fan-out pool
        self._tables = {}
        self._queue = queue.Queue(maxsize=async_queue)
        self._async_thread = None

    # -- connections -------------------------------------------------------
    def _conn(self, peer):
        # per-peer locks so a slow/unreachable peer cannot stall RPCs to
        # healthy peers; the short global lock only guards the dicts
        with self._connect_lock:
            lock = self._conn_locks.setdefault(peer, threading.Lock())
        with lock:
            if peer not in self._conns:
                s = socket.create_connection(self.endpoints[peer], timeout=30)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[peer] = s
            return self._conns[peer], lock

    def _rpc(self, peer, op, table, keys, payload=b"", lr=-1.0, width=0):
        sock, lock = self._conn(peer)
        keys = np.ascontiguousarray(keys, np.int64)
        with lock:
            _send_frame(sock, _HDR.pack(op, table, keys.size, lr, width),
                        keys.tobytes(), payload)
            resp = _recv_frame(sock)
        if not resp or resp[:1] == b"\x01":
            raise RuntimeError(
                f"PS rank {peer} error: {resp[1:].decode(errors='replace')}")
        return resp[1:]

    def _fanout(self, jobs):
        """Run per-peer jobs concurrently (one in-flight RPC per peer)."""
        if len(jobs) <= 1:
            for fn in jobs:
                fn()
            return
        from concurrent.futures import ThreadPoolExecutor
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=max(2, self.world))
        futs = [self._pool.submit(fn) for fn in jobs]
        for f in futs:
            f.result()

    # -- tables ------------------------------------------------------------
    def _local_rows(self, rows):
        return (rows - self.rank + self.world - 1) // self.world

    def init_table(self, rows, width, **kw):
        tid = self.local.init_table(self._local_rows(rows), width, **kw)
        self._tables[tid] = (rows, width)
        return tid

    def width(self, table):
        return self._tables[table][1]

    # -- sparse ops (EmbeddingStore API) -----------------------------------
    def pull(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        rows, width = self._tables[table]
        out = np.empty((flat.size, width), np.float32)
        owners = flat % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: out.__setitem__(
                    sel, self.local.pull(table, flat[sel] // self.world)))
            else:
                def job(r=r, sel=sel):
                    raw = self._rpc(r, OP_PULL, table, flat[sel])
                    out[sel] = np.frombuffer(raw, np.float32).reshape(
                        sel.size, width)
                jobs.append(job)
        self._fanout(jobs)
        return out.reshape(keys.shape + (width,))

    def push(self, table, keys, grads, lr=-1.0):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        rows, width = self._tables[table]
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        owners = keys % self.world
        jobs = []
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                jobs.append(lambda sel=sel: self.local.push(
                    table, keys[sel] // self.world, grads[sel], lr))
            else:
                jobs.append(lambda r=r, sel=sel: self._rpc(
                    r, OP_PUSH, table, keys[sel],
                    np.ascontiguousarray(grads[sel]).tobytes(), lr, width))
        self._fanout(jobs)

    def push_pull(self, table, push_keys, grads, pull_keys, lr=-1.0):
        self.push(table, push_keys, grads, lr)
        return self.pull(table, pull_keys)

    def versions(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        out = np.empty(keys.size, np.int64)
        owners = keys % self.world
        for r in range(self.world):
            sel = np.nonzero(owners == r)[0]
            if not sel.size:
                continue
            if r == self.rank:
                out[sel] = self.local.versions(table, keys[sel] // self.world)
            else:
                raw = self._rpc(r, OP_VERSIONS, table, keys[sel])
                out[sel] = np.frombuffer(raw, np.int64)
        return out

    # -- ASP: bounded async push (reference asp prefetch path) -------------
    def _async_worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            table, keys, grads, lr = item
            self.push(table, keys, grads, lr)
            self._queue.task_done()

    def push_async(self, table, keys, grads, lr=-1.0):
        """Enqueue a push; blocks only when ``async_queue`` is full
        (bounded eventual consistency — ASP mode, ``bsp=-1``)."""
        if self._async_thread is None:
            self._async_thread = threading.Thread(target=self._async_worker,
                                                  daemon=True)
            self._async_thread.start()
        self._queue.put((table, np.array(keys, np.int64, copy=True),
                         np.array(grads, np.float32, copy=True), lr))

    def flush(self):
        """Barrier: wait until every queued async push has been applied."""
        if self._async_thread is not None:
            self._queue.join()

    # -- SSP via rank 0 (the reference scheduler role) ---------------------
    def ssp_init(self, n_workers):
        self._rpc(0, OP_SSP_INIT, 0, np.asarray([n_workers], np.int64))

    def clock(self, worker=None):
        w = self.rank if worker is None else worker
        self._rpc(0, OP_CLOCK, 0, np.asarray([w], np.int64))

    def clocks(self):
        """Every worker's clock value (rank-0 authoritative copy) — the
        arrival feed for partial-reduce group formation."""
        raw = self._rpc(0, OP_CLOCKS, 0, np.zeros(0, np.int64))
        return np.frombuffer(raw, np.int64).copy()

    def ssp_sync(self, worker=None, staleness=0, timeout_ms=0):
        w = self.rank if worker is None else worker
        raw = self._rpc(0, OP_SSP_SYNC, 0,
                        np.asarray([w, staleness], np.int64),
                        lr=timeout_ms / 1e3 if timeout_ms else -1.0)
        return raw == b"\x01"

    # -- shard persistence (reference per-server SaveParam) ----------------
    def save(self, table, path):
        self.local.save(table, f"{path}.shard{self.rank}")

    def load(self, table, path):
        self.local.load(table, f"{path}.shard{self.rank}")

    def close(self):
        self.flush()
        if self._async_thread is not None:
            self._queue.put(None)
        for peer in list(self._conns):
            try:
                self._rpc(peer, OP_SHUTDOWN, 0, np.zeros(0, np.int64))
            except (OSError, RuntimeError, ConnectionError):
                pass
            try:
                self._conns[peer].close()
            except OSError:
                pass
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.server.stop()


class DistCacheTable:
    """HET bounded-staleness cache over a :class:`DistributedStore`
    (cross-host variant of the native ``CacheSparseTable``; reference
    ``src/hetu_cache/cache.h:21`` pull_bound_/push_bound_ semantics).

    - ``pull_bound``: a cached row may serve at most this many lookups
      before it must be re-pulled from its owner.
    - ``push_bound``: local gradient updates accumulate per row and are
      pushed to the owner once this many are pending (or on ``flush``).
    - LRU eviction at ``limit`` rows; evicting a dirty row pushes it.
    """

    def __init__(self, store: DistributedStore, table, limit=1 << 16,
                 pull_bound=100, push_bound=10, lr=-1.0):
        self.store, self.table = store, table
        self.width = store.width(table)
        self.limit = limit
        self.pull_bound, self.push_bound = pull_bound, push_bound
        self.lr = lr
        from collections import OrderedDict
        self._rows = OrderedDict()  # key -> np row, LRU order (O(1) evict)
        self._uses = {}     # key -> lookups since refresh
        self._grad = {}     # key -> (accumulated grad, count)
        self.stats = {"lookups": 0, "hits": 0, "evictions": 0, "pushes": 0,
                      "fetches": 0}

    def _evict_if_needed(self):
        while len(self._rows) > self.limit:
            victim, _ = self._rows.popitem(last=False)
            self._push_key(victim)
            self._uses.pop(victim, None)
            self.stats["evictions"] += 1

    def _push_key(self, key):
        g = self._grad.pop(key, None)
        if g is not None:
            self.store.push(self.table, np.asarray([key]), g[0][None, :],
                            self.lr)
            self.stats["pushes"] += 1

    def lookup(self, keys):
        keys = np.asarray(keys, np.int64).reshape(-1)
        out = np.empty((keys.size, self.width), np.float32)
        misses = []
        for i, k in enumerate(keys):
            k = int(k)
            self.stats["lookups"] += 1
            if k in self._rows and self._uses[k] < self.pull_bound:
                out[i] = self._rows[k]
                self._uses[k] += 1
                self._rows.move_to_end(k)
                self.stats["hits"] += 1
            else:
                misses.append((i, k))
        if misses:
            mk = np.asarray([k for _, k in misses], np.int64)
            # a stale row may carry pending local grads — push them first so
            # the refreshed value includes this worker's own updates
            for _, k in misses:
                self._push_key(k)
            rows = self.store.pull(self.table, mk)
            self.stats["fetches"] += len(misses)
            for (i, k), row in zip(misses, rows):
                out[i] = row
                self._rows[k] = row.copy()
                self._rows.move_to_end(k)
                self._uses[k] = 1
            self._evict_if_needed()
        return out

    def update(self, keys, grads):
        keys = np.asarray(keys, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(keys.size, -1)
        for k, g in zip(keys, grads):
            k = int(k)
            acc, cnt = self._grad.get(k, (np.zeros(self.width, np.float32), 0))
            acc = acc + g
            cnt += 1
            if cnt >= self.push_bound:
                self.store.push(self.table, np.asarray([k]), acc[None, :],
                                self.lr)
                self.stats["pushes"] += 1
                self._grad.pop(k, None)
                # local cached copy is now stale relative to the server
                self._uses[k] = self.pull_bound
            else:
                self._grad[k] = (acc, cnt)

    def flush(self):
        for k in list(self._grad):
            self._push_key(k)
