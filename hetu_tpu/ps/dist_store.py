"""Multi-host sharded parameter server — TCP-routed key ownership.

Round-1 shipped a single-process host store; this module delivers the
reference's multi-server topology (``ps-lite/src/van.cc`` ZMQ transport,
worker routing ``include/ps/worker/PSAgent.h:50``, server shards
``PSFHandle.h``): every process owns the keys with ``key % world == rank``
(the promised ``hash(key) % nprocs`` ownership), runs a TCP server thread
answering pull/push/versions/SSP for its shard (backed by the native C++
:class:`~hetu_tpu.ps.store.EmbeddingStore`), and routes non-owned keys to
their owner over persistent sockets with a compact binary wire format
(length-prefixed frames; int64 keys + float32 rows — no pickle).

ASP (reference ``ParameterServerCommunicate.py:38`` async path):
``push_async`` enqueues onto a bounded background queue so device steps
overlap with PS traffic; ``flush`` drains.  SSP clocks live on rank 0
(the reference's scheduler role).

Deliberate non-goals (vs ps-lite's transport depth).  ps-lite ships
priority-scheduled message dispatch (``ps-lite/src/p3_van.h``) and an
RDMA/IBVerbs zero-copy van (``ibverbs_van.h``, ~1.2k LoC).  Neither is
reimplemented here, on purpose: on a TPU pod the dense-parameter path
rides XLA collectives over ICI (this store only carries sparse embedding
rows between host RAM and host RAM), the P3 priority trick exists to
overlap push/pull with GPU backprop at single-digit-ms step times —
covered here by ``push_async``'s bounded queue + the executor's
one-pusher gating — and RDMA presumes NIC hardware this runtime does not
manage.  What IS kept from ps-lite's transport: at-least-once retries
with (client, seq) dedup for pushes AND clock ticks (``resender.h``
semantics), socket timeouts + reconnect, and dead-peer diagnostics.

Live shard replication (``replication=2``, ps-lite's sketched server-side
replication done properly): shard ``s`` keeps a bitwise-identical backup
on rank ``(s+1) % world`` via seq-ordered op-log forwarding — the serving
server mirrors every state-mutating frame (``OP_PUSH``, the push half of
``OP_PUSH_PULL``, ``OP_SET_DATA``, heartbeat writes for shard 0) to the
backup over ``OP_REPLICATE`` *before* acking the client, under one
replication lock so the backup applies ops in primary apply order.  The
forwarded frame carries the ORIGINAL (client, seq) header, so the
backup's dedup window absorbs the promotion-window retry: a push the
primary ack'd-then-died-on, retried against the promoted backup, applies
exactly once.  Client-side, ``_rpc`` exhaustion against a shard's
serving rank no longer raises: the shard router promotes the backup
(``OP_PROMOTE``, idempotent), re-routes the in-flight fanout, and counts
``ps_failover*`` events — a killed parameter server costs one RPC
timeout, zero restarts, zero lost steps.  ``re_replicate`` restores
redundancy onto a relaunched holder (``OP_INIT`` replica tables, then an
``OP_SYNC`` chunked snapshot reusing the v3 streamed checkpoint format,
then op-log catch-up) so a second failure is survivable.

Failure model: fail-stop AND network partitions, fenced by **epochs**.
Every shard carries a monotonic fencing epoch, stamped on every
replication-relevant frame (``OP_PUSH``, ``OP_PUSH_PULL``,
``OP_SET_DATA``, ``OP_REPLICATE``, ``OP_PROMOTE``, ``OP_SYNC``/
``OP_SYNC_PUT``/``OP_INIT``) via the wire header.  Promotion bumps the
shard's epoch (``ps_epoch_bumps``), so after a partition strands a
still-alive ex-primary, the two lineages are ORDERED: any frame the
stale lineage sends into the new one — an op-log forward, a snapshot,
a write relayed for a stale client — is refused with an
:class:`EpochFenced` error (``ps_epoch_refused``) instead of applied,
and the refusal teaches the sender the newer epoch.  A healed stale
ex-primary therefore DEMOTES itself on first contact with the new
lineage (``ps_demotions``): it stops serving, drops promotability, and
waits for epoch-checked re-replication instead of acking clients —
split brain converges to exactly one serving lineage, and no write
acked by the surviving lineage is lost.  Reads stay UNFENCED on
purpose: a partitioned cell keeps serving (possibly stale) local reads
— the HET bounded-staleness contract — while writes are what fencing
makes safe.  The chaos DSL reproduces the failure deterministically
(``partition:rank<a>|rank<b>@step<n>[:heal<m>]``), and
``tools/ps_fsck.py --verify --retries N`` proves post-heal convergence
(bitwise digests + exactly one serving epoch per shard).
"""
from __future__ import annotations

import gc
import itertools
import os
import queue
import random
import socket
import struct
import threading
import time

import numpy as np

from .store import EmbeddingStore, _OPT_IDS, _OPT_NAMES, _V3_CHUNK
from .. import chaos as _chaos
from .. import race as _race
from ..analysis.protocol import PROTO as _PROTO
from ..metrics import record_cache, record_fault, record_rpc
from ..obs.lock_witness import make_condition, make_lock, make_rlock
from ..obs.trace import TRACER as _TR

# Opcodes register through hetu_tpu.ps.opcodes: the registry asserts wire-
# value uniqueness at import time (runtime twin of the tools/hetu_lint.py
# protocol check) and names frames in errors/chaos logs via op_name().
from .opcodes import defop as _defop, frame_repr, op_name

# A cyclic-GC pass can run an ``Executor.__del__`` → ``close()`` chain
# while the interrupted frame sits inside a native store call and sibling
# objects are destructed in arbitrary order — teardown reached from a GC
# finalizer must not touch the native store (see DistCacheTable.close).
# The flag is a plain module global: GC callbacks and the finalizers they
# trigger run on the collecting thread, and a concurrent close() on
# another thread spuriously skipping a flush only costs bounded staleness.
_GC_ACTIVE = False


def _gc_phase(phase, info):
    global _GC_ACTIVE
    _GC_ACTIVE = phase == "start"


gc.callbacks.append(_gc_phase)


def _in_gc_pass():
    """True while a cyclic-GC collection is running on this process."""
    return _GC_ACTIVE


OP_PULL = _defop("OP_PULL", 1)
OP_PUSH = _defop("OP_PUSH", 2)
OP_VERSIONS = _defop("OP_VERSIONS", 3)
OP_CLOCK = _defop("OP_CLOCK", 4)
OP_SSP_SYNC = _defop("OP_SSP_SYNC", 5)
OP_SSP_INIT = _defop("OP_SSP_INIT", 6)
OP_SHUTDOWN = _defop("OP_SHUTDOWN", 7)
OP_CLOCKS = _defop("OP_CLOCKS", 8)
OP_HEARTBEAT = _defop("OP_HEARTBEAT", 9)
OP_ALIVE = _defop("OP_ALIVE", 10)
#: fused push+pull (reference PsfType kSDPushPull): keys frame carries
#: ``[npush, push_keys..., pull_keys...]``, payload carries the grads —
#: one round trip per peer instead of serial push-then-pull
OP_PUSH_PULL = _defop("OP_PUSH_PULL", 11)
#: replication plane (see module docstring): mirror a mutating frame to a
#: backup; promote a backup to serving; create a replica table; set a
#: shard's full slab; snapshot-transfer for re-replication; state digest
OP_REPLICATE = _defop("OP_REPLICATE", 12)
OP_PROMOTE = _defop("OP_PROMOTE", 13)
OP_INIT = _defop("OP_INIT", 14)
OP_SET_DATA = _defop("OP_SET_DATA", 15)
OP_SYNC = _defop("OP_SYNC", 16)
OP_SYNC_PUT = _defop("OP_SYNC_PUT", 17)
OP_CHECKSUM = _defop("OP_CHECKSUM", 18)
#: shard lineage introspection: (fencing epoch, serving?) of one shard's
#: copy on the answering server — how ps_fsck asserts a single surviving
#: lineage and how liveness probes prove a "dead" rank is merely cut off
OP_EPOCH = _defop("OP_EPOCH", 19)

# op, table, nkeys, lr, payload_width, client rank, client sequence
# number, shard (-1 = the receiving server's own primary shard), and the
# sender's fencing EPOCH for that shard (see the module docstring).
# (client, seq) lets the server DEDUPLICATE retried pushes: the transport
# retries are at-least-once (the reference's ps-lite ``resender.h`` keeps
# the same ack+dedup discipline), and double-applying a gradient push would
# silently corrupt training.  The shard field routes a frame to the right
# replica after a failover moved serving away from the home rank; the
# epoch field is what lets a server refuse frames from a stale lineage
# (and lets a stale server discover it was deposed).
_HDR = struct.Struct("<BiqdIqqqq")
#: retried pushes are remembered per client this many ops back
_DEDUP_WINDOW = 4096


def _next_backoff(base, prev, cap, rng):
    """Decorrelated-jitter retry delay (AWS architecture-blog formula):
    ``min(cap, uniform(base, 3*prev))``.  Unlike the old linear ramp, no
    two workers sleep the same schedule — a fleet retrying a just-killed
    primary spreads out instead of stampeding the promoted backup in
    lockstep.  Split out so the schedule is unit-testable."""
    return min(cap, rng.uniform(base, 3.0 * max(base, prev)))


def _segment_sum(grads, inv, counts):
    """Per-unique-key float32 grad sums (the client-side half of wire
    dedup).  A one-hot CSR matmul when scipy is present — numpy's own
    scatter-reductions (``ufunc.at``) are scalar-dispatched and ~5x
    slower on the (batch, width) slabs this path moves; scipy ships
    with jax, so the fallback exists only for exotic builds and is
    COUNTED (``emb_grad_host_fallback`` in the cache family) so a run
    that silently lost the fast path is visible in its counters.
    Device-resident tables skip this host pass entirely: their grads
    arrive pre-summed by the Pallas scatter-add kernel
    (``ops/pallas/emb_cache.py``) through ``apply_update_summed``.
    Summation association may differ from a per-occurrence loop by
    float32 rounding; every cache/transport DECISION is value-independent
    (keys and counters only), so semantics are unaffected."""
    if counts.size == inv.size:         # all keys distinct: reorder only
        return np.ascontiguousarray(grads[np.argsort(inv, kind="stable")])
    try:
        from scipy import sparse as _sp
        onehot = _sp.csr_matrix(
            (np.ones(inv.size, np.float32), inv,
             np.arange(inv.size + 1, dtype=np.int64)),
            shape=(inv.size, counts.size))
        return np.asarray(onehot.T @ grads, np.float32)
    except ImportError:
        # DELIBERATELY np.add.at (ISSUE 11 satellite): simplest correct
        # scatter-reduce, slow per the note above — which is exactly why
        # it is counted; a build that trips this counter should install
        # scipy, not live on the fallback
        record_cache("emb_grad_host_fallback", 1)
        out = np.zeros((counts.size, grads.shape[1]), np.float32)
        np.add.at(out, inv, grads)
        return out


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _send_frame(sock, *parts):
    body = b"".join(parts)
    sock.sendall(struct.pack("<q", len(body)) + body)


class FrameError(ConnectionError):
    """Corrupt frame header — framing on this stream is unrecoverable, so
    it subclasses ConnectionError: the server loop drops the connection
    and the client retries on a fresh one."""


class EpochFenced(RuntimeError):
    """A replication-relevant frame was refused by the fencing epoch.

    ``current`` is the refusing side's epoch for the shard and
    ``serving`` whether the refusing side still serves it — together
    they tell the client how to converge: a serving refuser means "you
    are behind, adopt my epoch and retry here"; a non-serving refuser
    means "I was deposed (or just demoted myself), adopt the epoch and
    re-route to the shard's other holder".  The message carries both in
    a parseable form because the refusal usually crosses the wire as a
    server-error string."""

    def __init__(self, shard, current, serving):
        self.shard, self.current, self.serving = \
            int(shard), int(current), bool(serving)
        super().__init__(
            f"shard {shard} epoch_fence cur={int(current)} "
            f"serving={int(bool(serving))} — frame from a different "
            f"lineage refused")


def _fence_info(err):
    """(current_epoch, refuser_still_serving) parsed from an epoch-fence
    refusal — local :class:`EpochFenced` or its over-the-wire string
    form — or None for any other error."""
    if isinstance(err, EpochFenced):
        return err.current, err.serving
    import re
    m = re.search(r"epoch_fence cur=(\d+) serving=([01])", str(err))
    return (int(m.group(1)), bool(int(m.group(2)))) if m else None


#: hard cap on a decoded frame length; a corrupt/hostile length prefix must
#: raise a clean protocol error, not ``bytearray(n)`` blowing up (negative)
#: or a multi-GB allocation.  Configurable: ``HETU_MAX_FRAME_MB``.
MAX_FRAME_BYTES = int(float(os.environ.get("HETU_MAX_FRAME_MB",
                                           "1024")) * 1e6)


def _recv_frame(sock):
    (n,) = struct.unpack("<q", _recv_exact(sock, 8))
    if n < 0 or n > MAX_FRAME_BYTES:
        record_fault("ps_bad_frame")
        raise FrameError(
            f"frame length {n} outside [0, {MAX_FRAME_BYTES}] "
            f"(HETU_MAX_FRAME_MB) — corrupt or hostile peer")
    return _recv_exact(sock, n)


class StoreServer:
    """Serves one process's shard over TCP (the reference server role).

    With ``replication=2`` this server additionally HOLDS (but does not
    serve) a bitwise replica of shard ``(rank-1) % world``, kept in sync
    by the op-log frames its primary forwards (``OP_REPLICATE``), and its
    own primary shard's mutations are mirrored to rank ``(rank+1) %
    world`` before each ack.  ``OP_PROMOTE`` flips a held replica to
    serving after the primary dies.  Forwarding rides the owning
    :class:`DistributedStore`'s client transport via :attr:`rpc_fn`.
    """

    def __init__(self, local: EmbeddingStore, world: int, rank: int,
                 host="127.0.0.1", port=0, replication=1, standby=False):
        self.local, self.world, self.rank = local, world, rank
        self.replication = int(replication)
        self.standby = bool(standby)
        self._ssp_lock = make_condition("StoreServer._ssp_lock")
        self._clocks = {}          # channel -> per-worker clock vector
        self._hb = {}              # rank -> (monotonic last-seen, step)
        self._hb_lock = make_lock("StoreServer._hb_lock")
        self._applied = {}         # client -> OrderedDict of recent push seqs
        self._applied_lock = make_lock("StoreServer._applied_lock")
        self._live_conns = set()
        # -- replication state (all guarded by _repl_lock where it matters)
        #: shard -> store holding that shard's rows on this server
        self._stores = {rank: local}
        self._ntables = {rank: 0}  # shard -> tables created (idempotent init)
        #: shards this server ANSWERS for.  A STANDBY (a relaunched
        #: replacement for a dead rank) starts serving NOTHING: its home
        #: shard's promoted ex-backup is the live truth, and claiming to
        #: serve an empty copy would let a role-resolved chaos kill (or a
        #: stale client) pick the wrong server.  It serves only after
        #: re-replication + an explicit OP_PROMOTE.
        standby = bool(standby and self.replication >= 2)
        self._serving = set() if standby else {rank}
        #: shards whose local copy may be PROMOTED into serving.  Table
        #: count alone cannot distinguish synced-from-primary from
        #: freshly-seed-initialized: a standby whose own training script
        #: calls init_table has the right table COUNT but step-0 data —
        #: promoting that would silently reset the shard.  A normal
        #: bring-up is promotable from the start (deterministic seeded
        #: init + the op-log keeps the backup bitwise-identical); a
        #: standby earns promotability only when an OP_SYNC snapshot
        #: completes (_sync_put loads the last table).
        self._promotable = set() if standby \
            else {rank, (rank - 1) % world} if self.replicable else {rank}
        #: shard -> fencing epoch of the lineage our copy belongs to.
        #: Bumped by promotion, adopted from newer frames (OP_INIT /
        #: OP_SYNC_PUT / OP_REPLICATE), compared on every replication-
        #: relevant frame (module docstring).  A fresh server starts at
        #: 0 and LEARNS the live epoch from re-replication — a standby
        #: can never leapfrog the serving lineage.
        self._epochs = {rank: 0}
        #: LEAF lock for the epoch map — deliberately NOT ``_repl_lock``:
        #: a primary holds ``_repl_lock`` ACROSS its forward RPC, so the
        #: receive side of a forward (OP_REPLICATE's epoch gate) must
        #: never block on the receiver's ``_repl_lock`` or three
        #: primaries forwarding around the ring deadlock until their
        #: socket timeouts fire.  ``_epoch_lock`` is never held across
        #: any RPC (or across ``_repl_lock``).
        self._epoch_lock = make_lock("StoreServer._epoch_lock")
        self._fwd_ok = {}          # shard -> live forwarding enabled
        #: shard -> monotonic time of the last broken-forward lineage
        #: probe (see _probe_lineage): rate-limits the reachability
        #: check a degraded primary runs before acking further writes
        self._fence_probe = {}
        self._oplog = {}           # shard -> buffered frames during OP_SYNC
        self._sync_parts = {}      # (shard, table) -> received snapshot chunks
        #: ordered apply+forward: the backup must see ops in primary apply
        #: order, so {apply locally; mirror} is one critical section
        self._repl_lock = make_rlock("StoreServer._repl_lock")
        #: set by the owning DistributedStore — forwards/syncs ride the
        #: client transport: rpc_fn(peer, op, table, keys, payload=...)
        self.rpc_fn = None
        if self.replicable:
            backup_of = (rank - 1) % world
            self._stores[backup_of] = EmbeddingStore()
            self._ntables[backup_of] = 0
            self._epochs[backup_of] = 0
            self._fwd_ok[rank] = True
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # -- replication topology ----------------------------------------------
    @property
    def replicable(self):
        return self.replication >= 2 and self.world >= 2

    def serves(self, shard):
        """True iff this server currently ANSWERS for ``shard``."""
        return shard in self._serving

    def holds(self, shard):
        """True iff this server keeps a copy of ``shard`` (serving or
        standby backup) — the chaos kill-backup target predicate."""
        return shard in self._stores

    def epoch(self, shard):
        """This server's fencing epoch for ``shard`` (0 if unheld)."""
        return self._epochs.get(shard, 0)

    def _adopt_epoch(self, shard, epoch):
        """Advance ``shard``'s epoch to at least ``epoch`` — a locked
        max-merge, never a plain assignment: two handler threads racing
        adoptions (e.g. a stalled stale snapshot chunk vs a newer
        lineage's re-replication) must not let the LOWER epoch win, or
        the losing lineage's remaining frames would pass the fence."""
        with self._epoch_lock:
            adopted = epoch > self._epochs.get(shard, 0)
            if adopted:
                self._epochs[shard] = epoch
        if adopted and _PROTO.on:
            _PROTO.emit("ps", "adopt", rank=self.rank, shard=shard,
                        new=epoch)

    def _fence_or_adopt(self, shard, epoch, refuse_equal_if_serving=False):
        """The replica-plane epoch gate (OP_REPLICATE / OP_INIT /
        OP_SYNC_PUT): refuse frames from an OLDER lineage (and, for
        op-log forwards, an equal-epoch frame aimed at a copy we SERVE
        — two same-epoch primaries of one shard cannot exist); adopt a
        NEWER epoch, demoting first when we still thought we served the
        shard (the healed stale ex-primary's learning moment).  The
        compare runs under the leaf ``_epoch_lock`` (see its comment:
        this path sits on the receive side of forwards and must never
        block on ``_repl_lock``); adoption/demotion are monotone
        max-merges, so acting on the snapshot after release is safe."""
        with self._epoch_lock:
            cur = self._epochs.get(shard, 0)
            if epoch < cur or (refuse_equal_if_serving and epoch == cur
                               and shard in self._serving):
                record_fault("ps_epoch_refused")
                if _PROTO.on:
                    _PROTO.emit("ps", "fence_refused", gate="repl",
                                rank=self.rank, shard=shard, cur=cur,
                                got=epoch)
                raise EpochFenced(shard, cur,
                                  serving=shard in self._serving)
        if epoch > cur:
            if shard in self._serving:
                self._demote(shard, epoch)
            else:
                self._adopt_epoch(shard, epoch)

    def _demote(self, shard, new_epoch):
        """Stop serving ``shard``: a newer lineage exists (we just saw
        epoch ``new_epoch`` > ours).  The local copy stays on disk but
        is no longer promotable — it may hold writes the surviving
        lineage never saw, so promoting it would resurrect the split
        brain — and forwarding stops (our op-log is the STALE one).
        Idempotent; callers hold no particular lock (``_repl_lock`` is
        re-entrant for the under-forward caller)."""
        self._adopt_epoch(shard, new_epoch)
        with self._repl_lock:
            if shard not in self._serving:
                return
            self._serving.discard(shard)
            self._promotable.discard(shard)
            self._fwd_ok[shard] = False
            record_fault("ps_demotions")
            if _PROTO.on:
                _PROTO.emit("ps", "demote", rank=self.rank, shard=shard,
                            epoch=self._epochs.get(shard, 0))

    def _fence(self, shard, frame_epoch):
        """Fencing gate for a replication-relevant frame against a shard
        this server SERVES.  Equal epochs pass.  A NEWER frame epoch
        means we missed a promotion — demote ourselves and refuse (the
        caller must not be acked by a deposed lineage).  An OLDER frame
        epoch is a stale sender — refuse and teach it our epoch.  Must
        run BEFORE the (client, seq) dedup registration: a refused frame
        retried at the correct epoch must still apply."""
        with self._epoch_lock:    # leaf lock: see its init comment
            cur = self._epochs.get(shard, 0)
        if frame_epoch == cur:
            return
        record_fault("ps_epoch_refused")
        if _PROTO.on:
            _PROTO.emit("ps", "fence_refused", gate="serve",
                        rank=self.rank, shard=shard, cur=cur,
                        got=frame_epoch)
        if frame_epoch > cur:
            self._demote(shard, frame_epoch)
            raise EpochFenced(shard, frame_epoch, serving=False)
        raise EpochFenced(shard, cur, serving=shard in self._serving)

    def register_table(self, shard):
        """Owner bookkeeping for a table created directly on ``local``."""
        with self._repl_lock:
            self._ntables[shard] = self._ntables.get(shard, 0) + 1

    def _fwd_target(self, shard):
        """The OTHER holder of ``shard`` in the k=2 ring: its deterministic
        backup rank when we are the home primary, the home rank when we
        are the promoted backup."""
        return (shard + 1) % self.world if self.rank == shard else shard

    def _store_serving(self, shard):
        """(store, shard) serving ``shard`` (-1 = our home shard), or a
        client-visible error — a stale route hitting a non-serving holder
        must get a LOUD 'not served' answer the router can fail over on,
        never silently read a possibly-stale replica."""
        if shard < 0:
            shard = self.rank
        if shard not in self._serving:
            raise RuntimeError(
                f"shard {shard} not served by rank {self.rank} "
                f"(serving {sorted(self._serving)})")
        return self._stores[shard], shard

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            if self._stop:      # raced a concurrent stop(): refuse service
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._live_conns.add(conn)
            # named: handler threads carry the replication forward (the
            # op-log mirror to the backup), so they appear as a
            # "ps-serve-r<rank>" track in exported traces
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True,
                             name=f"ps-serve-r{self.rank}").start()

    def _serve(self, conn):
        try:
            while True:
                body = _recv_frame(conn)
                if self._stop:
                    # a stopped server must refuse ALL service, even on a
                    # connection that slipped past stop() (some platforms
                    # don't wake a blocked accept on close) — serving
                    # from a "dead" server would make kill-based fault
                    # tests pass vacuously
                    break
                try:
                    if _TR.on:
                        # server apply path: one span per handled frame
                        # on this rank's ps-serve track (the replication
                        # forward nests inside it)
                        t_h = time.perf_counter_ns()
                        stop = self._handle(conn, body)
                        _TR.complete("ps.apply", t_h,
                                     time.perf_counter_ns(), cat="ps",
                                     args={"bytes": len(body)})
                    else:
                        stop = self._handle(conn, body)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:  # surface handler errors to the client
                    _send_frame(conn, b"\x01",
                                f"{type(e).__name__}: {e}".encode())
                    continue
                if stop:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            self._live_conns.discard(conn)
            conn.close()

    def _seen(self, client, seq):
        """True iff this (client, seq) NON-IDEMPOTENT op (push, clock) was
        already applied — a transport retry resent a frame whose ack was
        lost.  Window-bounded (reference ``resender.h`` ack+dedup
        semantics).  Clients base seq on time_ns so a RESTARTED client's
        sequences are always fresh (old seqs in the window cannot swallow
        the new instance's ops)."""
        from collections import OrderedDict
        with self._applied_lock:
            seen = self._applied.setdefault(client, OrderedDict())
            if seq in seen:
                return True
            seen[seq] = True
            while len(seen) > _DEDUP_WINDOW:
                seen.popitem(last=False)
            return False

    def _clock_vec(self, channel):
        v = self._clocks.get(channel)
        if v is None:
            raise RuntimeError(
                f"SSP channel {channel} not initialised: call "
                f"ssp_init(n_workers, channel={channel}) first")
        return v

    # -- op-log forwarding (the replication write path) --------------------
    def _forward(self, shard, body):
        """Mirror one already-applied mutating frame to ``shard``'s other
        holder.  MUST be called under ``_repl_lock`` (same critical
        section as the local apply), so the backup receives the op-log in
        primary apply order over one ordered connection.  During an
        ``OP_SYNC`` snapshot transfer the frame is buffered instead and
        drained after the snapshot lands (op-log catch-up).  A transport
        failure degrades to unreplicated serving (availability over
        redundancy) until ``re_replicate`` restores the backup — but an
        EPOCH-FENCE refusal means the peer belongs to a NEWER lineage
        (we are a healed stale ex-primary): then this server demotes
        itself and re-raises, so the handler refuses the client instead
        of acking a write onto the losing side of a split brain."""
        log = self._oplog.get(shard)
        if log is not None:
            log.append(bytes(body))
            return
        if not self._fwd_ok.get(shard):
            return
        t_fwd = time.perf_counter_ns() if _TR.on else 0
        try:
            if self.rpc_fn is None:
                raise RuntimeError("replication transport not attached")
            # the mirror must land inside the apply critical section,
            # BEFORE the ack: the backup sees ops in primary apply order
            # and an ack'd write is always replicated (_repl_lock's whole
            # reason to exist; _epoch_lock is the leaf that keeps the
            # receive side from blocking on us)
            # lint: held-rpc-ok ordered apply+mirror-before-ack protocol
            self.rpc_fn(self._fwd_target(shard), OP_REPLICATE, 0,
                        np.asarray([shard], np.int64), payload=bytes(body),
                        epoch=self._epochs.get(shard, 0))
            if _TR.on:
                # the replication-forwarder leg of the apply critical
                # section, on the serve thread's track
                _TR.complete("repl.forward", t_fwd,
                             time.perf_counter_ns(), cat="ps",
                             args={"shard": shard})
        except Exception as e:
            fence = _fence_info(e)
            if fence is not None:
                self._demote(shard, fence[0])
                raise EpochFenced(shard, fence[0], serving=False) from e
            self._fwd_ok[shard] = False
            record_fault("repl_forward_failed")
            import warnings
            warnings.warn(
                f"rank {self.rank}: op-log forward for shard {shard} to "
                f"rank {self._fwd_target(shard)} failed "
                f"({type(e).__name__}: {e}) — shard now serves "
                f"UNREPLICATED until re_replicate()", RuntimeWarning)

    def _probe_lineage(self, shard):
        """Rate-limited (``HETU_PS_FENCE_PROBE_S``, default 5s) epoch
        probe of ``shard``'s other holder while our forwarding to it is
        broken: if it answers with a NEWER epoch, we were deposed while
        cut off — demote and refuse the in-flight write instead of
        acking it onto the losing lineage.  An unreachable peer keeps
        today's degraded-but-available serving (without a quorum a lone
        primary cannot tell partition from backup death — CAP; the
        probe bounds how long a HEALED cut stays split-brained)."""
        interval = float(os.environ.get("HETU_PS_FENCE_PROBE_S", "5"))
        now = time.monotonic()
        if now - self._fence_probe.get(shard, -1e9) < interval:
            return
        self._fence_probe[shard] = now
        try:
            raw = self.rpc_fn(self._fwd_target(shard), OP_EPOCH, 0,
                              np.asarray([shard], np.int64),
                              op_timeout=2.0, record=False, retries=1)
            peer_epoch = struct.unpack("<qq", raw)[0]
        except Exception:
            return      # still unreachable/odd: availability wins
        if peer_epoch > self._epochs.get(shard, 0):
            self._demote(shard, peer_epoch)
            raise EpochFenced(shard, peer_epoch, serving=False)

    def _maybe_probe_degraded(self, shard):
        """When ``shard`` serves with its forwarding broken (and no sync
        in flight), run the rate-limited deposed-check BEFORE the apply
        and OUTSIDE ``_repl_lock`` — a probe RPC under the server-wide
        lock would stall every shard's write plane for the probe
        timeout, and refusing before the apply also spares the stale
        copy the refused mutation."""
        if not self._fwd_ok.get(shard) and self._oplog.get(shard) is None:
            self._probe_lineage(shard)

    def _apply_push(self, shard, store, table, keys, grads, lr, body):
        """Serving-side push: apply + mirror atomically (see _forward)."""
        if not self.replicable:
            store.push(table, keys // self.world, grads, lr)
            return
        self._maybe_probe_degraded(shard)
        with self._repl_lock:
            # lint: held-rpc-ok apply+mirror is ONE critical section
            store.push(table, keys // self.world, grads, lr)
            self._forward(shard, body)

    def _apply_set_data(self, shard, store, table, arr, body):
        if not self.replicable:
            store.set_data(table, arr)
            return
        self._maybe_probe_degraded(shard)
        with self._repl_lock:
            store.set_data(table, arr)
            self._forward(shard, body)

    def _apply_replicated(self, shard, inner):
        """Replay one forwarded frame against the HELD (non-serving)
        replica of ``shard``.  Ordering comes from the sender (one
        connection, forwards serialized under its _repl_lock), so no lock
        is needed here beyond the table's own; dedup registers the
        ORIGINAL (client, seq) so the promotion-window retry of an
        ack'd-then-died push is recognised as already applied."""
        # the inner frame's own epoch is ignored: the OUTER OP_REPLICATE
        # frame was already fenced against the forwarding primary's epoch
        iop, itable, inkeys, ilr, iwidth, iclient, iseq, _, _ = \
            _HDR.unpack_from(inner)
        ioff = _HDR.size
        ikeys = np.frombuffer(inner, np.int64, inkeys, ioff)
        ioff += inkeys * 8
        if iop == OP_HEARTBEAT:
            # mirrored liveness write (shard-0 replication): restamp with
            # OUR monotonic clock — timestamps don't travel across hosts
            with self._hb_lock:
                self._hb[int(ikeys[0])] = (time.monotonic(), int(ikeys[1]))
            return
        if iop == OP_SSP_INIT:
            # mirrored scheduler state (shard-0 replication): the SSP
            # barrier must survive rank-0 death like the liveness table
            n, channel = int(ikeys[0]), int(ikeys[1])
            with self._ssp_lock:
                cur = self._clocks.get(channel)
                if cur is None or cur.size != n:
                    self._clocks[channel] = np.zeros(n, np.int64)
            return
        if iop == OP_CLOCK:
            channel = int(ikeys[1]) if inkeys > 1 else 0
            worker = int(ikeys[0])
            if not self._seen(iclient, iseq):
                with self._ssp_lock:
                    v = self._clocks.get(channel)
                    if v is None or v.size <= worker:
                        # a re-attached standby can see ticks before any
                        # client re-runs ssp_init — grow instead of
                        # breaking the whole forward stream
                        nv = np.zeros(max(self.world, worker + 1),
                                      np.int64)
                        if v is not None:
                            nv[:v.size] = v
                        v = self._clocks[channel] = nv
                    v[worker] += 1
                    self._ssp_lock.notify_all()
            return
        store = self._stores.get(shard)
        if store is None:
            raise RuntimeError(
                f"rank {self.rank} holds no replica of shard {shard}")
        if iop == OP_PUSH:
            if not self._seen(iclient, iseq):
                grads = np.frombuffer(inner, np.float32, inkeys * iwidth,
                                      ioff).reshape(inkeys, iwidth)
                store.push(itable, ikeys // self.world, grads, ilr)
                if _PROTO.on:
                    _PROTO.emit("ps", "apply_replica", rank=self.rank,
                                shard=shard, client=iclient, seq=iseq)
        elif iop == OP_PUSH_PULL:
            npush = int(ikeys[0])
            if npush and not self._seen(iclient, iseq):
                grads = np.frombuffer(inner, np.float32, npush * iwidth,
                                      ioff).reshape(npush, iwidth)
                store.push(itable, ikeys[1:1 + npush] // self.world,
                           grads, ilr)
                if _PROTO.on:
                    _PROTO.emit("ps", "apply_replica", rank=self.rank,
                                shard=shard, client=iclient, seq=iseq)
        elif iop == OP_SET_DATA:
            n = (len(inner) - ioff) // 4
            store.set_data(itable, np.frombuffer(
                inner, np.float32, n, ioff).reshape(-1, iwidth))
        else:
            raise RuntimeError(
                f"{frame_repr(iop, itable, inkeys, client=iclient, seq=iseq)}"
                f" is not replicable")

    def _init_replica_table(self, shard, table, local_rows, width, opt_id,
                            seed, lr, beta1, beta2, eps, init_scale,
                            epoch=0):
        """Create table ``table`` in the held copy of ``shard`` with the
        SAME init parameters as the primary (deterministic seeded init ⇒
        bitwise-identical starting state).  Idempotent per table id —
        retried/raced OP_INIT frames are absorbed.

        The frame's ``epoch`` is the re-replication entry point of the
        fencing protocol: a NEWER epoch on a shard we still serve is how
        a healed stale ex-primary learns it was deposed (demote, accept
        the replica role); an OLDER epoch is a stale client trying to
        re-replicate the wrong lineage (refused)."""
        store = self._stores.get(shard)
        if store is None:
            raise RuntimeError(
                f"rank {self.rank} is not a replica holder for shard "
                f"{shard} (replication={self.replication})")
        self._fence_or_adopt(shard, epoch)
        with self._repl_lock:
            have = self._ntables.get(shard, 0)
            if table < have:
                return               # idempotent re-init
            if table > have:
                raise RuntimeError(
                    f"out-of-order replica init: table {table} before "
                    f"{have} on shard {shard}")
            tid = store.init_table(
                local_rows, width, opt=_OPT_NAMES[opt_id], lr=lr,
                beta1=beta1, beta2=beta2, eps=eps, seed=seed,
                init_scale=init_scale)
            assert tid == table, (tid, table)
            self._ntables[shard] = table + 1

    def _promote(self, shard, want_tables, want_epoch=0):
        """Serve ``shard`` from our held replica (idempotent); returns
        the shard's resulting fencing epoch.  Refuses when we don't hold
        the shard, hold fewer tables than the client expects, or the
        copy was never synced (a standby's self-created tables have the
        right COUNT but seed-initialized data — promoting that would
        silently reset the shard to step 0 instead of raising a loud
        both-copies-gone outage).

        A REAL promotion bumps the epoch past both our replica's last
        known epoch and the promoting client's (``want_epoch`` = client
        epoch + 1), so the new lineage strictly dominates the old one:
        the deposed primary's frames are refusable, and every client
        that promotes concurrently converges on the same epoch (the
        idempotent path returns the current epoch without bumping)."""
        with self._repl_lock:
            cur = self._epochs.get(shard, 0)
            if shard in self._serving:
                if want_epoch > cur:       # concurrent promoter raced a
                    cur = want_epoch       # newer lineage onto us: adopt
                    self._adopt_epoch(shard, cur)
                return cur
            if not self.replicable:
                raise RuntimeError(
                    f"rank {self.rank} runs unreplicated "
                    f"(replication={self.replication}) — cannot promote "
                    f"shard {shard}")
            store = self._stores.get(shard)
            if store is None or self._ntables.get(shard, 0) < want_tables:
                raise RuntimeError(
                    f"rank {self.rank} replica of shard {shard} has "
                    f"{self._ntables.get(shard, 0)}/{want_tables} tables "
                    f"— not promotable")
            if shard not in self._promotable and want_tables > 0:
                raise RuntimeError(
                    f"rank {self.rank} copy of shard {shard} was never "
                    f"synced from the serving replica — not promotable")
            new_epoch = max(cur + 1, want_epoch)
            self._adopt_epoch(shard, new_epoch)
            self._serving.add(shard)
            # the old primary is presumed dead (or fenced off): no
            # forwarding until re_replicate() attaches a fresh backup
            self._fwd_ok[shard] = False
            record_fault("ps_promoted")
            record_fault("ps_epoch_bumps")
            if _PROTO.on:
                _PROTO.emit("ps", "promote", rank=self.rank, shard=shard,
                            old=cur, new=new_epoch, want=want_epoch)
            return new_epoch

    def _sync_to(self, shard, target):
        """Re-replication source half: snapshot every table of ``shard``
        (the store's own streamed save format — v3 chunked for the numpy
        fallback), push it to ``target`` in bounded ``OP_SYNC_PUT``
        frames, then drain the op-log buffered during the transfer and
        resume live forwarding.  Mutations are blocked only for the
        snapshot-to-disk and the drain, not the transfer; the transfer
        streams chunk-by-chunk off the temp files so peak RSS stays one
        chunk, never a table copy (the v3 format's whole point)."""
        import tempfile
        if shard not in self._serving:
            raise RuntimeError(
                f"rank {self.rank} does not serve shard {shard} — "
                f"only the serving replica can source a sync")
        if not self.replicable:
            raise RuntimeError("replication disabled on this server")
        if target != self._fwd_target(shard):
            raise RuntimeError(
                f"shard {shard}: rank {target} is not its replica slot "
                f"(expected {self._fwd_target(shard)})")
        store = self._stores[shard]
        ntabs = self._ntables.get(shard, 0)
        paths = []
        with self._repl_lock:
            if self._fwd_ok.get(shard):
                return               # redundancy already live: no-op
            if self._oplog.get(shard) is not None:
                raise RuntimeError(
                    f"shard {shard}: sync already in progress")
            self._fwd_ok[shard] = False
            self._oplog[shard] = []
            for tid in range(ntabs):
                fd, path = tempfile.mkstemp(prefix="hetu_ps_sync_")
                os.close(fd)
                paths.append(path)
                store.save(tid, path)
        try:
            chunk = min(_V3_CHUNK, max(1 << 20, MAX_FRAME_BYTES // 2))
            epoch = self._epochs.get(shard, 0)
            for tid, path in enumerate(paths):
                size = os.path.getsize(path)
                nch = max(1, -(-size // chunk))
                with open(path, "rb") as f:
                    for ci in range(nch):
                        self.rpc_fn(
                            target, OP_SYNC_PUT, tid,
                            np.asarray([shard, ci, nch, size, ntabs],
                                       np.int64),
                            payload=f.read(chunk), epoch=epoch)
            with self._repl_lock:
                for frame in self._oplog.pop(shard, []):
                    # the op-log drain and the fwd_ok flip must be atomic
                    # against concurrent applies, or a racing write could
                    # land between catch-up and live forwarding
                    # lint: held-rpc-ok op-log catch-up precedes live fwd
                    self.rpc_fn(target, OP_REPLICATE, 0,
                                np.asarray([shard], np.int64),
                                payload=frame, epoch=epoch)
                self._fwd_ok[shard] = True
            record_fault("ps_re_replicated")
        except Exception as e:
            with self._repl_lock:
                self._oplog.pop(shard, None)
                self._fwd_ok[shard] = False
            fence = _fence_info(e)
            if fence is not None:
                # the target refused OUR snapshot: it belongs to a newer
                # lineage, so WE are the stale ex-primary trying to
                # overwrite the survivor — learn the epoch and demote
                # instead of retrying this doomed sync every tick
                self._demote(shard, fence[0])
                raise EpochFenced(shard, fence[0], serving=False) from e
            record_fault("ps_re_replicate_failed")
            raise
        finally:
            for path in paths:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def _sync_put(self, shard, table, ci, nch, total, ntabs, payload,
                  epoch=0):
        """Re-replication sink half: append snapshot chunks straight to a
        temp file (bounded RSS) and load the completed table via the
        store's own load path.  Once every one of the shard's ``ntabs``
        tables has landed, the copy becomes PROMOTABLE.  Chunks arrive in
        order (one connection); a retried chunk is idempotent.  The
        snapshot carries the source lineage's epoch: an OLDER epoch is a
        stale source trying to overwrite us with the losing lineage
        (refused); a newer one is adopted — and demotes us first if we
        still thought we served the shard."""
        import tempfile
        store = self._stores.get(shard)
        if store is None:
            raise RuntimeError(
                f"rank {self.rank} holds no replica of shard {shard}")
        self._fence_or_adopt(shard, epoch)
        if shard in self._serving and shard != self.rank:
            raise RuntimeError(
                f"rank {self.rank} already SERVES shard {shard} — "
                f"refusing a snapshot that would overwrite live state")
        part = self._sync_parts.get((shard, table))
        if part is None:
            fd, path = tempfile.mkstemp(prefix="hetu_ps_sync_")
            os.close(fd)
            part = self._sync_parts[(shard, table)] = {
                "path": path, "next": 0}
        if ci < part["next"]:
            return                   # retried chunk
        if ci != part["next"]:
            raise RuntimeError(
                f"sync chunk gap: got {ci}, expected {part['next']}")
        with open(part["path"], "ab") as f:
            f.write(payload)
        part["next"] = ci + 1
        if part["next"] < nch:
            return
        del self._sync_parts[(shard, table)]
        try:
            if os.path.getsize(part["path"]) != total:
                raise RuntimeError(
                    f"sync snapshot truncated: "
                    f"{os.path.getsize(part['path'])}/{total} bytes")
            store.load(table, part["path"])
        finally:
            try:
                os.unlink(part["path"])
            except OSError:
                pass
        with self._repl_lock:
            done = self._sync_parts.setdefault(("loaded", shard), set())
            done.add(table)
            if len(done) >= ntabs:
                del self._sync_parts[("loaded", shard)]
                self._promotable.add(shard)
                if _PROTO.on:
                    _PROTO.emit("ps", "sync_done", rank=self.rank,
                                shard=shard,
                                epoch=self._epochs.get(shard, 0))

    def _handle(self, conn, body):
        op, table, nkeys, lr, width, client, seq, shard, epoch = \
            _HDR.unpack_from(body)
        off = _HDR.size
        keys = np.frombuffer(body, np.int64, nkeys, off)
        off += nkeys * 8
        if op == OP_PULL:
            # reads are deliberately UNFENCED: a partitioned cell keeps
            # serving (bounded-staleness) local reads — fencing guards
            # the write plane, where split-brain divergence is made
            store, shard = self._store_serving(shard)
            out = store.pull(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_PUSH:
            store, shard = self._store_serving(shard)
            # fence BEFORE the dedup registration: a refused frame
            # retried at the correct epoch must not read as a duplicate
            self._fence(shard, epoch)
            if not self._seen(client, seq):
                grads = np.frombuffer(body, np.float32, nkeys * width,
                                      off).reshape(nkeys, width)
                self._apply_push(shard, store, table, keys, grads, lr, body)
                if _PROTO.on:
                    _PROTO.emit("ps", "apply", rank=self.rank, shard=shard,
                                client=client, seq=seq, epoch=epoch)
            elif _PROTO.on:
                _PROTO.emit("ps", "dedup_hit", rank=self.rank, shard=shard,
                            client=client, seq=seq)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_PUSH_PULL:
            # fused SDPushPull: apply the push shard, answer the pull shard,
            # one ack.  The push half is as non-idempotent as OP_PUSH — a
            # retried frame skips it but still serves the (idempotent) pull.
            store, shard = self._store_serving(shard)
            self._fence(shard, epoch)
            npush = int(keys[0])
            push_keys = keys[1:1 + npush]
            pull_keys = keys[1 + npush:]
            if npush and not self._seen(client, seq):
                grads = np.frombuffer(body, np.float32, npush * width,
                                      off).reshape(npush, width)
                self._apply_push(shard, store, table, push_keys, grads, lr,
                                 body)
                if _PROTO.on:
                    _PROTO.emit("ps", "apply", rank=self.rank, shard=shard,
                                client=client, seq=seq, epoch=epoch)
            out = store.pull(table, pull_keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(out, np.float32).tobytes())
        elif op == OP_VERSIONS:
            store, shard = self._store_serving(shard)
            v = store.versions(table, keys // self.world)
            _send_frame(conn, b"\x00",
                        np.ascontiguousarray(v, np.int64).tobytes())
        elif op == OP_SET_DATA:
            store, shard = self._store_serving(shard)
            self._fence(shard, epoch)
            n = (len(body) - off) // 4
            arr = np.frombuffer(body, np.float32, n, off).reshape(-1, width)
            self._apply_set_data(shard, store, table, arr, body)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_REPLICATE:
            # op-log from a STALE lineage (a healed ex-primary that
            # never heard it was deposed) is refused — which is what
            # turns its next client ack into a self-demotion
            s = int(keys[0])
            self._fence_or_adopt(s, epoch, refuse_equal_if_serving=True)
            self._apply_replicated(s, body[off:])
            _send_frame(conn, b"\x00\x01")
        elif op == OP_PROMOTE:
            ep = self._promote(int(keys[0]), int(keys[1]),
                               int(keys[2]) if nkeys > 2 else 0)
            _send_frame(conn, b"\x00", struct.pack("<q", ep))
        elif op == OP_INIT:
            # keys=[local_rows, width, opt_id, seed]; payload packs the
            # float init params (NaN init_scale = store default)
            p = struct.unpack_from("<5d", body, off)
            self._init_replica_table(
                shard, table, int(keys[0]), int(keys[1]), int(keys[2]),
                int(keys[3]), p[0], p[1], p[2], p[3],
                None if p[4] != p[4] else p[4], epoch=epoch)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SYNC:
            self._fence(int(keys[0]), epoch)
            self._sync_to(int(keys[0]), int(keys[1]))
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SYNC_PUT:
            self._sync_put(int(keys[0]), table, int(keys[1]), int(keys[2]),
                           int(keys[3]), int(keys[4]), body[off:],
                           epoch=epoch)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_EPOCH:
            # lineage introspection (fsck, liveness probes): the fencing
            # epoch of one shard's copy here + whether we serve it.
            # Answered for ANY shard (0 if unheld) — the probe must work
            # against a standby or a demoted holder too.
            s = self.rank if not nkeys else int(keys[0])
            _send_frame(conn, b"\x00",
                        struct.pack("<qq", self._epochs.get(s, 0),
                                    int(s in self._serving)))
        elif op == OP_CHECKSUM:
            # full-state digest of ANY held copy (serving or standby) —
            # tools/ps_fsck.py compares primary vs backup for divergence
            s = self.rank if shard < 0 else shard
            store = self._stores.get(s)
            if store is None:
                raise RuntimeError(
                    f"rank {self.rank} holds no copy of shard {s}")
            _send_frame(conn, b"\x00", store.state_digest(table).encode())
        elif op == OP_SSP_INIT:
            n, channel = int(keys[0]), int(keys[1])
            with self._ssp_lock:
                # idempotent: every rank calls init; re-zeroing on the
                # second caller would erase live arrivals.  A different
                # size is an explicit reset (fresh run, same server).
                cur = self._clocks.get(channel)
                if cur is None or cur.size != n:
                    self._clocks[channel] = np.zeros(n, np.int64)
            if self.replicable and 0 in self._serving:
                with self._repl_lock:
                    self._forward(0, body)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_CLOCK:
            # clock ticks are as non-idempotent as pushes: a retried tick
            # whose ack was lost must not double-increment (it would fake
            # an arrival and let stale peers past the SSP bound).  Like
            # heartbeats, the scheduler's clock vectors ride shard 0's
            # replication so the SSP barrier survives rank-0 death.
            channel = int(keys[1]) if nkeys > 1 else 0
            if not self._seen(client, seq):
                with self._ssp_lock:
                    self._clock_vec(channel)[int(keys[0])] += 1
                    self._ssp_lock.notify_all()
                if self.replicable and 0 in self._serving:
                    with self._repl_lock:
                        self._forward(0, body)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_SSP_SYNC:
            worker, staleness = int(keys[0]), int(keys[1])
            channel = int(keys[2]) if nkeys > 2 else 0
            # the server-side wait is ALWAYS bounded (570s < the client's
            # 600s no-timeout socket deadline) by a TOTAL monotonic
            # deadline — bounding each cond.wait alone would reset the
            # budget on every notify_all (any tick, any channel) and
            # leak this handler thread under steady clock traffic
            deadline = time.monotonic() + (lr if lr > 0 else 570.0)
            ok = True
            with self._ssp_lock:
                v = self._clock_vec(channel)
                while v[worker] - v.min() > staleness:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._ssp_lock.wait(left):
                        ok = False
                        break
                    v = self._clock_vec(channel)
            _send_frame(conn, b"\x00", b"\x01" if ok else b"\x00")
        elif op == OP_CLOCKS:
            channel = int(keys[0]) if nkeys else 0
            with self._ssp_lock:
                v = self._clock_vec(channel).copy()
            _send_frame(conn, b"\x00", v.tobytes())
        elif op == OP_HEARTBEAT:
            # liveness ping: rank + current step.  Idempotent (a retried
            # ping just refreshes the timestamp), so no dedup needed.
            with self._hb_lock:
                self._hb[int(keys[0])] = (time.monotonic(), int(keys[1]))
            # the failure DETECTOR must itself survive failure: liveness
            # state rides shard 0's replication ring, so rank 0's backup
            # holds a live alive_mask when rank 0 dies (the backup
            # restamps with its own monotonic clock on apply)
            if self.replicable and 0 in self._serving:
                with self._repl_lock:
                    self._forward(0, body)
            _send_frame(conn, b"\x00\x01")
        elif op == OP_ALIVE:
            # keys=[n_workers], lr carries deadline_ms: int64 mask, 1 iff
            # the rank pinged within the deadline.  A rank that NEVER
            # pinged counts alive: liveness only declares death for ranks
            # it has seen alive (startup stagger — e.g. 30 s of backend
            # init before the first ping — must not read as death; a
            # rank that truly never starts is the launcher/supervisor's
            # failure domain, not the heartbeat's).
            n = int(keys[0])
            # keys=[n, 1] requests STRICT mode: never-pinged counts dead
            # (the failover cross-check wants positive evidence of life,
            # not the benefit of the doubt the exclusion path grants)
            strict = nkeys > 1 and bool(keys[1])
            deadline_s = (lr if lr > 0 else 10_000.0) / 1e3
            now = time.monotonic()
            mask = np.zeros(n, np.int64)
            with self._hb_lock:
                for r in range(n):
                    rec = self._hb.get(r)
                    mask[r] = (0 if strict else 1) if rec is None else \
                        int(now - rec[0] <= deadline_s)
            _send_frame(conn, b"\x00", mask.tobytes())
        elif op == OP_SHUTDOWN:
            _send_frame(conn, b"\x00\x01")
            return True
        else:
            raise ValueError(
                f"unknown opcode in frame "
                f"{frame_repr(op, table, nkeys, shard, client, seq)}")
        return False

    def stop(self):
        self._stop = True
        try:    # shutdown (not just close) wakes a blocked accept() on
            self._sock.shutdown(socket.SHUT_RDWR)   # platforms where
        except OSError:                             # close() alone doesn't
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # close live per-connection sockets too: a stopped server must look
        # DEAD to peers (fast ConnectionError), not wedged
        for conn in list(self._live_conns):
            try:
                conn.close()
            except OSError:
                pass


class DistributedStore:
    """Worker+server pair with ``key % world`` routing (EmbeddingStore API).

    ``endpoints``: list of (host, port) for every rank, index = rank; this
    process's entry may be None (it uses its own server's bound port).
    """

    def __init__(self, rank, world, endpoints=None, host="127.0.0.1",
                 port=0, async_queue=64, rpc_timeout=60.0, rpc_retries=3,
                 connect_timeout=10.0, replication=None, standby=None):
        self.rank, self.world = rank, world
        # standby (env HETU_PS_STANDBY, set by the launcher's solo-respawn
        # path): this process replaces a dead rank — its server holds its
        # shards but serves nothing until re-replication re-attaches it
        if standby is None:
            standby = os.environ.get("HETU_PS_STANDBY", "") == "1"
        # replication=k (env default HETU_PS_REPLICATION): 1 = today's
        # single-copy topology, 2 = every shard keeps a live backup on the
        # next rank (see the module docstring).  world=1 has nowhere to
        # put a backup, so it silently degrades to 1.
        if replication is None:
            replication = int(os.environ.get("HETU_PS_REPLICATION", "1"))
        replication = int(replication)
        if not 1 <= replication <= 2:
            raise ValueError(
                f"replication={replication} unsupported: 1 (off) or 2 "
                f"(primary + one ring backup)")
        self.replication = replication if world >= 2 else 1
        self.local = EmbeddingStore()
        self.server = StoreServer(self.local, world, rank, host, port,
                                  replication=self.replication,
                                  standby=standby)
        self.endpoints = list(endpoints) if endpoints else [None] * world
        self.endpoints[rank] = (host, self.server.port)
        self.rpc_timeout = rpc_timeout
        self.rpc_retries = max(1, rpc_retries)
        self.connect_timeout = connect_timeout
        # retry backoff: exponential with decorrelated jitter (see
        # _next_backoff) so a worker fleet never stampedes a promoted
        # backup in lockstep; base is env-tunable
        self._backoff_base = float(
            os.environ.get("HETU_RPC_BACKOFF_MS", "50")) / 1e3
        self._backoff_cap = 1.0
        self._backoff_rng = random.Random()
        # seq base = time_ns: strictly increasing across process restarts,
        # so a relaunched worker's sequences can never collide with its
        # predecessor's entries still in the server dedup window
        self._seq = itertools.count(time.time_ns())  # thread-safe in CPython
        self._conns = {}
        self._conn_locks = {}
        self._connect_lock = make_lock("DistributedStore._connect_lock")  # guards the conn dicts
        self._pool = None                      # lazy RPC fan-out pool
        self._tables = {}
        self._table_init_kw = {}   # tid -> init kwargs (replica re-init)
        #: shard -> rank currently serving it; failover flips an entry to
        #: the shard's other replica holder.  Every client converges
        #: independently (promote is idempotent).
        self._route = list(range(world))
        #: shard -> the fencing epoch this client believes is current.
        #: Advanced by OP_PROMOTE acks and by epoch-fence refusals — a
        #: refused write teaches the client the surviving lineage before
        #: the retry (module docstring).
        self._epoch = [0] * world
        #: leaf lock for the fence-adoption state (_epoch/_route/_flip
        #: _epoch): _note_fence runs on whichever thread saw the refusal
        #: — fanout pool workers, the heartbeat pinger, the async push
        #: worker — and an unlocked check-then-act let two racing
        #: refusals regress the epoch or double-flip the route BACK onto
        #: the deposed rank (ISSUE 14 shared-state finding).  Never held
        #: across an RPC.
        self._fence_lock = make_lock("DistributedStore._fence_lock")
        self._flip_epoch = {}      # shard -> epoch at which route flipped
        self._failed_over = set()  # shards running without redundancy
        self._queue = queue.Queue(maxsize=async_queue)
        self._async_thread = None
        self._hb_thread = None
        self._hb_stop = threading.Event()
        # the server's op-log forwards / sync transfers ride this client's
        # transport (persistent sockets, timeouts, retries)
        self.server.rpc_fn = self._rpc
        # HETU_CHAOS=seed:spec activates the chaos harness for every store
        # in the process; the server registers as a kill:ps target
        inj = _chaos.active() or _chaos.install_from_env()
        if inj is not None:
            inj.register_server(rank, self.server)

    # -- connections -------------------------------------------------------
    def _conn(self, peer):
        # per-peer locks so a slow/unreachable peer cannot stall RPCs to
        # healthy peers; the short global lock only guards the dicts
        with self._connect_lock:
            lock = self._conn_locks.setdefault(
                peer, make_lock("DistributedStore._conn_locks[*]"))
        with lock:
            if peer not in self._conns:
                s = socket.create_connection(self.endpoints[peer],
                                             timeout=self.connect_timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._conns[peer] = s
            return self._conns[peer], lock

    def _drop_conn(self, peer):
        with self._connect_lock:
            lock = self._conn_locks.setdefault(
                peer, make_lock("DistributedStore._conn_locks[*]"))
        with lock:
            s = self._conns.pop(peer, None)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _rpc(self, peer, op, table, keys, payload=b"", lr=-1.0, width=0,
             op_timeout=None, shard=-1, seq=None, record=True,
             retries=None, epoch=0):
        """One request/response against ``peer``'s shard.

        Transport discipline (reference ``ps-lite/src/resender.h``): every
        socket op carries a timeout, a failed op drops the connection and
        retries on a fresh one with decorrelated-jitter backoff (the same
        (client, seq) header lets the server dedup a retried PUSH whose
        ack was lost), and exhausted retries raise a *diagnosable*
        RuntimeError naming the peer — never a raw OSError or an
        unbounded blocking recv (the executor's SSP-watchdog discipline
        applied to the transport).  ``seq`` may be pinned by the caller so
        a failover retry of the SAME logical op against the promoted
        backup is recognised by its dedup window (see _rpc_shard)."""
        keys = np.ascontiguousarray(keys, np.int64)
        hdr = _HDR.pack(op, table, keys.size, lr, width, self.rank,
                        next(self._seq) if seq is None else seq, shard,
                        epoch)
        # per-opcode latency histogram + payload-bytes counter (the
        # telemetry registry) — a socket round trip dwarfs two clock
        # reads, so the measurement is unconditional; counter-silent
        # probes (record=False) stay invisible here too
        t_rpc = time.perf_counter_ns()
        nbytes = keys.nbytes + len(payload)
        last_err = None
        delay = 0.0
        for attempt in range(self.rpc_retries if retries is None
                             else max(1, retries)):
            if attempt:
                if record:
                    record_fault("ps_rpc_retry")
                delay = _next_backoff(self._backoff_base, delay,
                                      self._backoff_cap, self._backoff_rng)
                time.sleep(delay)
            try:
                # chaos harness: the active schedule may drop, delay,
                # duplicate, or wedge this frame (hetu_tpu.chaos); a clean
                # run pays one global read
                inj = _chaos.active()
                act = inj.on_send(peer, op, src=self.rank) \
                    if inj is not None else None
                if act is not None and act[0] == "drop":
                    raise TimeoutError(
                        f"chaos: dropped {op_name(op)} frame")
                sock, lock = self._conn(peer)
                with lock:
                    sock.settimeout(op_timeout if op_timeout is not None
                                    else self.rpc_timeout)
                    if act is not None and act[0] == "delay":
                        time.sleep(act[1] / 1e3)
                    elif act is not None and act[0] == "wedge":
                        # hold the socket past the op deadline's spirit:
                        # the client sees a timeout and retries fresh
                        time.sleep(act[1] / 1e3)
                        raise TimeoutError(
                            f"chaos: wedged socket on {op_name(op)}")
                    _send_frame(sock, hdr, keys.tobytes(), payload)
                    if act is not None and act[0] == "dup":
                        # at-least-once retry simulation: same (client,
                        # seq) frame twice — the server's dedup window
                        # must apply non-idempotent ops exactly once
                        _send_frame(sock, hdr, keys.tobytes(), payload)
                        _recv_frame(sock)       # discard the dup's ack
                    resp = _recv_frame(sock)
                break
            except (TimeoutError, ConnectionError, OSError) as e:
                last_err = e
                self._drop_conn(peer)
        else:
            if record:
                record_fault("ps_peer_unreachable")
            host_, port_ = self.endpoints[peer] or ("?", "?")
            raise RuntimeError(
                f"PS peer {peer} at {host_}:{port_} unreachable after "
                f"{self.rpc_retries} attempts sending "
                f"{frame_repr(op, table, keys.size, shard)} "
                f"({type(last_err).__name__}: {last_err}) — server process "
                f"dead or wedged")
        if not resp or resp[:1] == b"\x01":
            raise RuntimeError(
                f"PS rank {peer} error on {op_name(op)}: "
                f"{resp[1:].decode(errors='replace')}")
        if record:
            name = op_name(op)
            record_rpc(name, (time.perf_counter_ns() - t_rpc) / 1e3,
                       nbytes)
            if _TR.on:
                _TR.complete("rpc:" + name, t_rpc,
                             time.perf_counter_ns(), cat="ps",
                             args={"peer": peer, "bytes": nbytes,
                                   "shard": shard})
        return resp[1:]

    # -- shard routing + client-side failover ------------------------------
    @staticmethod
    def _failover_worthy(err):
        """Exhausted transport (peer dead/wedged) or a stale route hitting
        a non-serving holder; application errors must still raise."""
        msg = str(err)
        return "unreachable" in msg or "not served" in msg

    def _note_fence(self, shard, err):
        """Adopt the surviving lineage an epoch-fence refusal names:
        advance this client's epoch for ``shard`` (a locked max-merge —
        the server-side ``_adopt_epoch`` discipline) and — when the
        refuser no longer serves (it was deposed or just demoted
        itself) — flip the route to the shard's other holder and mark
        the shard for re-replication (the demoted copy is stale by
        construction).  The flip is recorded PER EPOCH: refusals land on
        whichever thread sent the frame (fanout pool, heartbeat pinger,
        async worker), and two racing refusals from one fence event must
        flip the route ONCE — an unguarded toggle sent the second flip
        straight back to the deposed rank (ISSUE 14 regression test)."""
        cur, serving = _fence_info(err)
        with self._fence_lock:
            known = self._epoch[shard]
            if cur > known:
                self._epoch[shard] = known = cur
            # flip only on information at least as new as ours (a STALE
            # refusal must not steer the route away from the lineage we
            # already follow), and at most once per epoch
            if not serving and cur == known \
                    and self._flip_epoch.get(shard) != cur:
                self._flip_epoch[shard] = cur
                dead = self._route[shard]
                self._route[shard] = (shard + 1) % self.world \
                    if dead == shard else shard
                self._failed_over.add(shard)
                if _PROTO.on:
                    _PROTO.emit("ps", "route_flip", rank=self.rank,
                                shard=shard, epoch=cur,
                                to=self._route[shard])

    def _rpc_shard(self, shard, op, table, keys, payload=b"", lr=-1.0,
                   width=0, op_timeout=None):
        """Shard-addressed RPC: routes to the rank currently serving
        ``shard`` and, with ``replication>=2``, turns an unreachable
        primary into a transparent failover — promote the backup, flip
        the route, retry THE SAME frame (pinned seq → the backup's dedup
        window keeps an ack'd-then-died push exactly-once).  An epoch-
        fence refusal is handled the same one-retry way: learn the
        surviving epoch from the refusal, re-route if the refuser was
        deposed, resend the SAME frame stamped with the new epoch."""
        seq = next(self._seq)
        try:
            return self._rpc(self._route[shard], op, table, keys, payload,
                             lr, width, op_timeout, shard=shard, seq=seq,
                             epoch=self._epoch[shard])
        except RuntimeError as e:
            if _fence_info(e) is not None:
                # learn the surviving epoch/route, then fall through to
                # the SAME send-with-failover discipline below — a fence
                # refusal must not cost the retry its transparent-
                # failover safety net (the corrected target can die too)
                self._note_fence(shard, e)
            elif self.replication < 2 or not self._failover_worthy(e):
                raise
            else:
                self._failover(shard, err=e)
        try:
            return self._rpc(self._route[shard], op, table, keys, payload,
                             lr, width, op_timeout, shard=shard, seq=seq,
                             epoch=self._epoch[shard])
        except RuntimeError as e:
            if self.replication < 2 or not self._failover_worthy(e):
                raise
            alt = self._failover(shard, err=e)
            return self._rpc(alt, op, table, keys, payload, lr, width,
                             op_timeout, shard=shard, seq=seq,
                             epoch=self._epoch[shard])

    def _failover(self, shard, err=None):
        """Promote ``shard``'s other replica holder and re-route.  Raises
        (chaining the transport error) when the backup is unreachable or
        not promotable — both copies gone is a real outage."""
        dead = self._route[shard]
        alt = (shard + 1) % self.world if dead == shard else shard
        record_fault("ps_failover")
        # best-effort liveness cross-check: telemetry only — the exhausted
        # retry budget IS the detector, but a mask that still believes the
        # peer alive flags a possible partition in the failover artifact.
        # One cheap, counter-silent attempt with a short deadline: in a
        # double failure (rank 0 dead too) this probe must not stack the
        # full retry budget on top of the recovery path.
        if shard != 0:
            try:
                hb_ms = float(os.environ.get("HETU_HEARTBEAT_MS", "500"))
                raw = self._rpc(self._route[0], OP_ALIVE, 0,
                                np.asarray([self.world, 1], np.int64),
                                lr=3.0 * hb_ms,
                                op_timeout=min(2.0, self.rpc_timeout),
                                record=False, retries=1)
                if np.frombuffer(raw, np.int64)[dead]:
                    record_fault("ps_failover_primary_reported_alive")
            except (RuntimeError, OSError, ConnectionError):
                pass
        try:
            # want_epoch = our epoch + 1: the promotion must strictly
            # dominate the lineage we are abandoning, so the deposed
            # primary's frames become refusable (fencing)
            raw = self._rpc(alt, OP_PROMOTE, 0,
                            np.asarray([shard, len(self._tables),
                                        self._epoch[shard] + 1], np.int64))
        except (RuntimeError, OSError, ConnectionError) as e2:
            record_fault("ps_failover_failed")
            raise RuntimeError(
                f"shard {shard}: serving rank {dead} unreachable AND "
                f"backup rank {alt} not promotable ({e2})") from err
        with self._fence_lock:
            if len(raw) >= 8:    # the ack names the resulting epoch
                self._epoch[shard] = max(self._epoch[shard],
                                         int(np.frombuffer(raw, np.int64,
                                                           1)[0]))
            self._route[shard] = alt
            # the promotion IS this epoch's route change: a fence
            # refusal racing in from the deposed primary must not
            # toggle the route away from the just-promoted holder
            self._flip_epoch[shard] = self._epoch[shard]
            self._failed_over.add(shard)
        record_fault("ps_failover_promoted")
        if _PROTO.on:
            _PROTO.emit("ps", "client_failover", rank=self.rank,
                        shard=shard, to=alt, epoch=self._epoch[shard])
        return alt

    def _fanout(self, jobs):
        """Run per-peer jobs concurrently (one in-flight RPC per peer)."""
        if len(jobs) <= 1:
            for fn in jobs:
                fn()
            return
        from concurrent.futures import ThreadPoolExecutor
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=max(2, self.world))
        futs = [self._pool.submit(fn) for fn in jobs]
        for f in futs:
            f.result()

    # -- tables ------------------------------------------------------------
    def _shard_rows(self, rows, shard):
        return (rows - shard + self.world - 1) // self.world

    def _local_rows(self, rows):
        return self._shard_rows(rows, self.rank)

    def init_table(self, rows, width, **kw):
        tid = self.local.init_table(self._local_rows(rows), width, **kw)
        self.server.register_table(self.rank)
        self._tables[tid] = (rows, width)
        self._table_init_kw[tid] = dict(kw)
        if self.replication >= 2:
            # mirror-init our shard's backup with the SAME parameters:
            # seeded init is deterministic, so both copies start bitwise
            # identical and the forwarded op-log keeps them that way
            self._replica_init(tid, self.rank,
                               (self.rank + 1) % self.world, patient=True)
        return tid

    def _replica_init(self, tid, shard, target, patient=False):
        """OP_INIT ``shard``'s table ``tid`` on ``target`` (idempotent).

        ``patient``: table creation at cluster bring-up races the
        backup's server bind (processes start in arbitrary order), so the
        init path keeps knocking for a bounded startup grace instead of
        failing on the first connection refusal.  Re-replication probes
        stay impatient — a dead standby should defer fast."""
        rows, width = self._tables[tid]
        kw = self._table_init_kw.get(tid, {})
        scale = kw.get("init_scale")
        keys = np.asarray([self._shard_rows(rows, shard), width,
                           _OPT_IDS[kw.get("opt", "sgd")],
                           int(kw.get("seed", 0))], np.int64)
        payload = struct.pack(
            "<5d", float(kw.get("lr", 0.01)), float(kw.get("beta1", 0.9)),
            float(kw.get("beta2", 0.999)), float(kw.get("eps", 1e-7)),
            float("nan") if scale is None else float(scale))
        deadline = time.monotonic() + max(3 * self.connect_timeout, 15.0)
        while True:
            try:
                return self._rpc(target, OP_INIT, tid, keys, payload,
                                 shard=shard, record=not patient,
                                 epoch=self._epoch[shard])
            except RuntimeError as e:
                fence = _fence_info(e)
                if fence is not None:
                    # the target already belongs to a NEWER lineage (e.g.
                    # a standby's bring-up mirror-init raced an earlier
                    # promotion): the replica table exists there — adopt
                    # the epoch and treat the init as done
                    with self._fence_lock:
                        if fence[0] > self._epoch[shard]:
                            self._epoch[shard] = fence[0]
                    return None
                if not patient or time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    def width(self, table):
        return self._tables[table][1]

    def set_data(self, table, arr):
        """Scatter a full ``(rows, width)`` array across every shard — and
        through each shard's replication path, so a replicated cluster
        seeded this way starts with bitwise-identical primary/backup
        copies (``s.local.set_data`` would seed only the local primary)."""
        rows, width = self._tables[table]
        arr = np.ascontiguousarray(arr, np.float32)
        if arr.shape != (rows, width):
            raise ValueError(f"set_data shape {arr.shape} != "
                             f"({rows}, {width})")
        jobs = []
        for s in range(self.world):
            part = np.ascontiguousarray(arr[s::self.world])
            if self._route[s] == self.rank and self.server.serves(s):
                jobs.append(lambda s=s, part=part:
                            self._local_set_data(s, table, part))
            else:
                jobs.append(lambda s=s, part=part: self._rpc_shard(
                    s, OP_SET_DATA, table, np.zeros(0, np.int64),
                    part.tobytes(), width=width))
        self._fanout(jobs)

    # -- serving-local apply (replication-ordered) -------------------------
    # Ops against a shard WE serve skip the wire but must still ride the
    # op-log: the server's apply+forward critical section is the single
    # ordering point for a shard's mutations, whether they arrived over
    # TCP or from this process's own client.
    def _local_store(self, shard):
        return self.server._stores[shard]

    def _local_push(self, shard, table, keys, grads, lr):
        keys = np.ascontiguousarray(keys, np.int64)
        grads = np.ascontiguousarray(grads, np.float32)
        body = None
        if self.server.replicable:
            body = _HDR.pack(OP_PUSH, table, keys.size, lr, grads.shape[1],
                             self.rank, next(self._seq), shard,
                             self._epoch[shard]) \
                + keys.tobytes() + grads.tobytes()
        try:
            self.server._apply_push(shard, self._local_store(shard), table,
                                    keys, grads, lr, body)
        except EpochFenced as e:
            # our own server just learned it is a deposed lineage (its
            # op-log forward was epoch-refused) and demoted itself.  The
            # local apply landed only on the now-demoted, never-again-
            # promotable copy — resend the op to the surviving lineage,
            # which never saw it (exactly-once there).
            self._note_fence(shard, e)
            self._rpc_shard(shard, OP_PUSH, table, keys,
                            np.ascontiguousarray(grads).tobytes(), lr,
                            grads.shape[1])

    def _local_set_data(self, shard, table, part):
        body = None
        if self.server.replicable:
            body = _HDR.pack(OP_SET_DATA, table, 0, -1.0, part.shape[1],
                             self.rank, next(self._seq), shard,
                             self._epoch[shard]) \
                + part.tobytes()
        try:
            self.server._apply_set_data(shard, self._local_store(shard),
                                        table, part, body)
        except EpochFenced as e:
            self._note_fence(shard, e)       # see _local_push
            self._rpc_shard(shard, OP_SET_DATA, table,
                            np.zeros(0, np.int64), part.tobytes(),
                            width=part.shape[1])

    # -- sparse ops (EmbeddingStore API) -----------------------------------
    # Wire-level dedup: a zipf-skewed CTR batch (2048x26 ids) is MOSTLY
    # duplicate keys — pull/push collapse to unique keys with ``np.unique``
    # BEFORE the shard fanout and scatter results back through the inverse
    # index, so the wire carries each row once.  Semantics are unchanged:
    # the server already accumulates duplicate keys within one push
    # (store.py _push_locked / the native core), so pre-summing duplicate
    # grads client-side yields the identical optimizer step and the same
    # per-key version bump.  The saved traffic is counted in
    # ``hetu_tpu.metrics`` (``ps_dedup_*``) — GC3's batching-over-many-
    # small-messages discipline, applied to the sparse path.

    @staticmethod
    def _sorted_unique(flat):
        """True iff already strictly ascending — the HET cache hands over
        pre-deduped sorted keys, so the wire path skips a re-dedup."""
        return flat.size <= 1 or bool(np.all(np.diff(flat) > 0))

    def _dedup_grads(self, keys, grads, width):
        """(unique_keys, per-unique summed grads); counts saved rows."""
        if self._sorted_unique(keys):
            return keys, grads
        uk, inv, counts = np.unique(keys, return_inverse=True,
                                    return_counts=True)
        if uk.size < keys.size:
            record_cache("ps_dedup_push_rows_saved", keys.size - uk.size)
            record_cache("ps_dedup_push_bytes_saved",
                         (keys.size - uk.size) * (width * 4 + 8))
        return uk, _segment_sum(grads, inv, counts)

    def pull(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        rows, width = self._tables[table]
        if self._sorted_unique(flat):
            uk, inv = flat, None
        else:
            uk, inv = np.unique(flat, return_inverse=True)
            if uk.size < flat.size:
                record_cache("ps_dedup_pull_rows_saved",
                             flat.size - uk.size)
                record_cache("ps_dedup_pull_bytes_saved",
                             (flat.size - uk.size) * (width * 4 + 8))
        out = np.empty((uk.size, width), np.float32)
        owners = uk % self.world
        jobs = []
        for s in range(self.world):
            sel = np.nonzero(owners == s)[0]
            if not sel.size:
                continue
            if self._route[s] == self.rank and self.server.serves(s):
                jobs.append(lambda s=s, sel=sel: out.__setitem__(
                    sel, self._local_store(s).pull(
                        table, uk[sel] // self.world)))
            else:
                def job(s=s, sel=sel):
                    raw = self._rpc_shard(s, OP_PULL, table, uk[sel])
                    out[sel] = np.frombuffer(raw, np.float32).reshape(
                        sel.size, width)
                jobs.append(job)
        self._fanout(jobs)
        if inv is not None:
            out = out[inv]
        return out.reshape(keys.shape + (width,))

    def push(self, table, keys, grads, lr=-1.0):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        rows, width = self._tables[table]
        if not keys.size:
            return
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        uk, acc = self._dedup_grads(keys, grads, width)
        owners = uk % self.world
        jobs = []
        for s in range(self.world):
            sel = np.nonzero(owners == s)[0]
            if not sel.size:
                continue
            if self._route[s] == self.rank and self.server.serves(s):
                jobs.append(lambda s=s, sel=sel: self._local_push(
                    s, table, uk[sel], acc[sel], lr))
            else:
                jobs.append(lambda s=s, sel=sel: self._rpc_shard(
                    s, OP_PUSH, table, uk[sel],
                    np.ascontiguousarray(acc[sel]).tobytes(), lr, width))
        self._fanout(jobs)

    def push_pull(self, table, push_keys, grads, pull_keys, lr=-1.0):
        """Fused SDPushPull: each peer gets ONE ``OP_PUSH_PULL`` round trip
        carrying its push shard + pull shard (server applies the push
        before answering the pull), instead of a serial push fanout
        followed by a pull fanout.  Rows are owner-partitioned, so a pull
        only ever depends on the pushes riding the same frame."""
        push_keys = np.ascontiguousarray(push_keys, np.int64).reshape(-1)
        pull_arr = np.ascontiguousarray(pull_keys, np.int64)
        pflat = pull_arr.reshape(-1)
        rows, width = self._tables[table]
        if not push_keys.size:
            return self.pull(table, pull_arr)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            push_keys.size, -1)
        upk, acc = self._dedup_grads(push_keys, grads, width)
        if self._sorted_unique(pflat):
            ulk, linv = pflat, None
        else:
            ulk, linv = np.unique(pflat, return_inverse=True)
            record_cache("ps_dedup_pull_rows_saved", pflat.size - ulk.size)
            record_cache("ps_dedup_pull_bytes_saved",
                         (pflat.size - ulk.size) * (width * 4 + 8))
        out = np.empty((ulk.size, width), np.float32)
        powners = upk % self.world
        lowners = ulk % self.world
        jobs = []
        for s in range(self.world):
            psel = np.nonzero(powners == s)[0]
            lsel = np.nonzero(lowners == s)[0]
            if not psel.size and not lsel.size:
                continue
            if self._route[s] == self.rank and self.server.serves(s):
                def local_job(s=s, psel=psel, lsel=lsel):
                    if psel.size:
                        self._local_push(s, table, upk[psel], acc[psel], lr)
                    if not lsel.size:
                        return
                    if self.server.serves(s):
                        out[lsel] = self._local_store(s).pull(
                            table, ulk[lsel] // self.world)
                    else:
                        # the push's epoch fence just demoted our own
                        # server: the pull must follow the re-route too
                        raw = self._rpc_shard(s, OP_PULL, table, ulk[lsel])
                        out[lsel] = np.frombuffer(raw, np.float32).reshape(
                            lsel.size, width)
                jobs.append(local_job)
            elif psel.size:
                def fused_job(s=s, psel=psel, lsel=lsel):
                    frame_keys = np.concatenate(
                        (np.asarray([psel.size], np.int64),
                         upk[psel], ulk[lsel]))
                    raw = self._rpc_shard(
                        s, OP_PUSH_PULL, table, frame_keys,
                        np.ascontiguousarray(acc[psel]).tobytes(), lr,
                        width)
                    if lsel.size:
                        out[lsel] = np.frombuffer(raw, np.float32).reshape(
                            lsel.size, width)
                        # only a frame that genuinely carried BOTH halves
                        # counts as a saved round trip
                        record_cache("ps_push_pull_fused_rpcs", 1)
                jobs.append(fused_job)
            else:       # nothing to push at this peer: plain pull
                def pull_job(s=s, lsel=lsel):
                    raw = self._rpc_shard(s, OP_PULL, table, ulk[lsel])
                    out[lsel] = np.frombuffer(raw, np.float32).reshape(
                        lsel.size, width)
                jobs.append(pull_job)
        self._fanout(jobs)
        if linv is not None:
            out = out[linv]
        return out.reshape(pull_arr.shape + (width,))

    def versions(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        uk, inv = np.unique(keys, return_inverse=True)
        out = np.empty(uk.size, np.int64)
        owners = uk % self.world
        jobs = []
        for s in range(self.world):
            sel = np.nonzero(owners == s)[0]
            if not sel.size:
                continue
            if self._route[s] == self.rank and self.server.serves(s):
                jobs.append(lambda s=s, sel=sel: out.__setitem__(
                    sel, self._local_store(s).versions(
                        table, uk[sel] // self.world)))
            else:
                def vjob(s=s, sel=sel):
                    raw = self._rpc_shard(s, OP_VERSIONS, table, uk[sel])
                    out[sel] = np.frombuffer(raw, np.int64)
                jobs.append(vjob)
        self._fanout(jobs)
        return out[inv]

    # -- ASP: bounded async push (reference asp prefetch path) -------------
    def _async_worker(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            table, keys, grads, lr = item
            self.push(table, keys, grads, lr)
            self._queue.task_done()

    def push_async(self, table, keys, grads, lr=-1.0):
        """Enqueue a push; blocks only when ``async_queue`` is full
        (bounded eventual consistency — ASP mode, ``bsp=-1``)."""
        if self._async_thread is None:
            self._async_thread = threading.Thread(target=self._async_worker,
                                                  daemon=True)
            self._async_thread.start()
        self._queue.put((table, np.array(keys, np.int64, copy=True),
                         np.array(grads, np.float32, copy=True), lr))

    def flush(self):
        """Barrier: wait until every queued async push has been applied."""
        if self._async_thread is not None:
            self._queue.join()

    # -- SSP via rank 0 (the reference scheduler role) ---------------------
    # ``channel`` separates independent clock consumers on the same server:
    # the executor's SSP step loop ticks channel 0, partial-reduce arrival
    # clocks live on their own channel — sharing one vector double-
    # incremented per step and broke preduce's 'arrival at step s ⇔
    # clock >= s+1' assumption (round-3 advisor finding).
    # SSP scheduler state is SHARD-0 traffic like the heartbeats: with
    # replication>=2 every clock tick / channel init is mirrored to shard
    # 0's backup (dedup'd under the same (client, seq)), so the barrier
    # itself fails over with the rest of the shard.
    def ssp_init(self, n_workers, channel=0):
        """Idempotent per (channel, size): every rank may call it."""
        self._rpc_shard(0, OP_SSP_INIT, 0,
                        np.asarray([n_workers, channel], np.int64))

    def clock(self, worker=None, channel=0):
        w = self.rank if worker is None else worker
        self._rpc_shard(0, OP_CLOCK, 0, np.asarray([w, channel], np.int64))

    def clocks(self, channel=0):
        """Every worker's clock value (rank-0 authoritative copy) — the
        arrival feed for partial-reduce group formation."""
        raw = self._rpc_shard(0, OP_CLOCKS, 0,
                              np.asarray([channel], np.int64))
        return np.frombuffer(raw, np.int64).copy()

    # -- liveness: heartbeats on rank 0 (the scheduler role) ---------------
    # Routed as SHARD-0 traffic: with replication>=2 the rank-0 server
    # mirrors every heartbeat write to shard 0's backup, so the failure
    # detector itself fails over — alive_mask survives rank-0 death.
    def heartbeat(self, rank=None, step=0):
        """Ping the liveness table (rank 0, or its promoted backup)."""
        w = self.rank if rank is None else rank
        self._rpc_shard(0, OP_HEARTBEAT, 0,
                        np.asarray([w, step], np.int64))

    def alive_mask(self, deadline_ms, n_workers=None):
        """int64 mask over workers: 1 iff the rank heartbeated within
        ``deadline_ms`` — or never heartbeated at all (liveness only
        declares death for ranks it has seen alive; see the OP_ALIVE
        handler).  The liveness feed for partial-reduce dead-rank
        exclusion."""
        n = self.world if n_workers is None else n_workers
        raw = self._rpc_shard(0, OP_ALIVE, 0, np.asarray([n], np.int64),
                              lr=float(deadline_ms))
        return np.frombuffer(raw, np.int64).copy()

    def start_heartbeat(self, interval_ms=None, step_fn=None):
        """Background liveness pings every ``interval_ms`` (env default
        ``HETU_HEARTBEAT_MS``=500) until ``close``.  ``step_fn`` supplies
        the step number reported with each ping (e.g. ``lambda:
        ex.step_counter``).  A failing ping is counted
        (``heartbeat_send_failed``) and retried next interval — a dead
        scheduler must not crash the worker from a daemon thread."""
        if self._hb_thread is not None:
            return
        iv = (float(os.environ.get("HETU_HEARTBEAT_MS", "500"))
              if interval_ms is None else float(interval_ms)) / 1e3

        def beat():
            while not self._hb_stop.wait(iv):
                try:
                    self.heartbeat(step=int(step_fn()) if step_fn else 0)
                except (RuntimeError, OSError, ConnectionError):
                    record_fault("heartbeat_send_failed")

        self._hb_thread = threading.Thread(
            target=beat, daemon=True, name=f"hetu-hb-{self.rank}")
        self._hb_thread.start()

    #: the server side blocks on a condition variable (OP_SSP_SYNC
    #: handler) — one RPC waits out the whole bound, no client polling
    ssp_blocking = True

    def ssp_sync(self, worker=None, staleness=0, timeout_ms=0, channel=0):
        w = self.rank if worker is None else worker
        # the server blocks until the staleness bound clears: the socket
        # deadline must outlive the requested wait (timeout_ms=0 means
        # "wait for stragglers" — bounded here at 600s rather than forever,
        # so a dead scheduler still surfaces as a diagnosable error)
        raw = self._rpc_shard(0, OP_SSP_SYNC, 0,
                              np.asarray([w, staleness, channel], np.int64),
                              lr=timeout_ms / 1e3 if timeout_ms else -1.0,
                              op_timeout=(timeout_ms / 1e3 + 30.0)
                              if timeout_ms else 600.0)
        return raw == b"\x01"

    # -- re-replication (redundancy repair after a failover) ---------------
    def re_replicate(self, shard=None):
        """Restore ``replication=2`` redundancy for ``shard`` (default:
        every shard this client failed over): re-create the replica
        tables on the shard's vacant holder (``OP_INIT``, idempotent),
        then have the serving replica stream a chunked snapshot and drain
        its op-log catch-up (``OP_SYNC``/``OP_SYNC_PUT``).  After this, a
        SECOND failure of the shard is survivable — the router promotes
        the freshly attached copy."""
        if self.replication < 2:
            raise RuntimeError("re_replicate needs replication >= 2")
        shards = sorted(self._failed_over) if shard is None else [shard]
        for s in shards:
            serving = self._route[s]
            target = s if serving != s else (s + 1) % self.world
            for tid in sorted(self._tables):
                self._replica_init(tid, s, target)
            if serving == self.rank:
                self.server._sync_to(s, target)
            else:
                self._rpc(serving, OP_SYNC, 0,
                          np.asarray([s, target], np.int64),
                          op_timeout=max(self.rpc_timeout, 600.0),
                          epoch=self._epoch[s])
            self._failed_over.discard(s)

    def re_replicate_async(self, shard=None):
        """Background :meth:`re_replicate`; failures surface as the
        ``ps_re_replicate_failed`` counter + a warning, not a crash."""
        def run():
            try:
                self.re_replicate(shard)
            except (RuntimeError, OSError, ConnectionError) as e:
                import warnings
                warnings.warn(f"background re-replication failed: {e}",
                              RuntimeWarning)
        t = threading.Thread(target=run, daemon=True,
                             name=f"hetu-resync-{self.rank}")
        t.start()
        return t

    def maybe_re_replicate(self):
        """Opportunistic redundancy repair (the executor's step-hook
        driver, ``HETU_PS_REREPLICATE_EVERY``): for each shard running
        without a backup — one this client failed over, or one OUR server
        serves whose op-log forwarding broke (the backup died) — try one
        re-replication; a still-dead target defers quietly to the next
        tick.  Returns True iff any shard was repaired."""
        if self.replication < 2:
            return False
        pending = set(self._failed_over)
        srv = self.server
        if srv.replicable:
            for s in list(srv._serving):
                if not srv._fwd_ok.get(s) and srv._oplog.get(s) is None:
                    pending.add(s)
        if not pending:
            return False
        repaired = False
        for s in sorted(pending):
            try:
                self.re_replicate(s)
                repaired = True
            except (RuntimeError, OSError, ConnectionError):
                record_fault("ps_re_replicate_deferred")
        return repaired

    def table_checksum(self, table, shard, rank=None):
        """Full-state digest of ``shard``'s copy of ``table`` held on
        ``rank`` (default: the serving rank) — the live divergence
        detector behind ``tools/ps_fsck.py --verify``."""
        peer = self._route[shard] if rank is None else rank
        if peer == self.rank:
            return self.server._stores[shard].state_digest(table)
        raw = self._rpc(peer, OP_CHECKSUM, table, np.zeros(0, np.int64),
                        shard=shard)
        return raw.decode()

    def shard_epoch(self, shard, rank=None):
        """``(epoch, serving)`` of ``shard``'s copy on ``rank`` (default:
        the rank this client routes the shard to) — the lineage probe
        behind ``ps_fsck --json`` epochs and the single-surviving-
        lineage assertion."""
        peer = self._route[shard] if rank is None else rank
        if peer == self.rank:
            return (self.server.epoch(shard), self.server.serves(shard))
        raw = self._rpc(peer, OP_EPOCH, 0, np.asarray([shard], np.int64))
        ep, serving = struct.unpack("<qq", raw)
        return int(ep), bool(serving)

    def liveness_report(self, deadline_ms, n_workers=None):
        """Classify non-heartbeating ranks as DEAD vs UNREACHABLE.

        ``alive_mask`` (the rank-0 heartbeat table) conflates "the rank
        died" with "the rank cannot reach rank 0" — under an asymmetric
        partition those demand opposite reactions (a partitioned rank
        must be fenced, not respawned over).  For every rank the mask
        declares dead, this sends ONE cheap direct probe (``OP_EPOCH``,
        short deadline, counter-silent transport): a rank that answers
        is recorded as ``unreachable`` (+ the ``ps_unreachable`` fault
        counter — partition evidence), one that doesn't as ``dead``.
        The verdict is from THIS client's vantage point: a rank this
        client also cannot reach stays ``dead`` even if it lives on the
        far side of a cut."""
        n = self.world if n_workers is None else int(n_workers)
        mask = self.alive_mask(deadline_ms, n)
        report = {"alive": [], "dead": [], "unreachable": []}
        for r in range(min(n, self.world)):
            if mask[r]:
                report["alive"].append(r)
                continue
            try:
                self._rpc(r, OP_EPOCH, 0, np.asarray([r], np.int64),
                          op_timeout=min(2.0, self.rpc_timeout),
                          record=False, retries=1)
            except (RuntimeError, OSError, ConnectionError):
                report["dead"].append(r)
            else:
                report["unreachable"].append(r)
                record_fault("ps_unreachable")
        return report

    # -- shard persistence (reference per-server SaveParam) ----------------
    # Shard files are named by SHARD, not by rank, and cover every shard
    # this server currently SERVES: after a failover the promoted server
    # checkpoints the shard it adopted (otherwise post-failover
    # auto-saves would silently omit the adopted shard's live state),
    # and a not-yet-synced standby serves nothing — so its executor's
    # auto-save can never overwrite a shard file with seed-init data.
    def save(self, table, path):
        for shard in sorted(self.server._serving):
            self.server._stores[shard].save(table, f"{path}.shard{shard}")

    def load(self, table, path):
        for shard in sorted(self.server._serving):
            self.server._stores[shard].load(table, f"{path}.shard{shard}")

    def close(self):
        self._hb_stop.set()
        self.flush()
        if self._async_thread is not None:
            self._queue.put(None)
        for peer in list(self._conns):
            try:
                # best-effort goodbye: an already-dead peer during an
                # ordered teardown is not a FAULT — don't record one
                self._rpc(peer, OP_SHUTDOWN, 0, np.zeros(0, np.int64),
                          op_timeout=min(5.0, self.rpc_timeout),
                          record=False, retries=1)
            except (OSError, RuntimeError, ConnectionError):
                pass     # peer already gone; _rpc dropped the conn
            self._drop_conn(peer)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.server.stop()


class _DevLookup:
    """Pending device-mode lookup (``DistCacheTable.begin_lookup``): the
    host-side plan frozen before the one fallible store round trip.
    :meth:`roundtrip` touches ONLY the store (no cache state, no lock),
    so it is safe on any thread — the executor runs it on the
    feed-pipeline thread to overlap the miss pull with the dense
    forward; stats/counters land at commit on the owning thread."""

    __slots__ = ("cache", "shape", "flat", "uk", "inv", "cnt", "slots",
                 "hit", "refresh", "rkeys", "rslots", "dirty", "plan",
                 "absent", "pk", "pg", "positions", "fill_targets",
                 "done", "flow_id")

    def __init__(self, cache, shape, flat):
        self.cache, self.shape, self.flat = cache, shape, flat
        self.uk = self.inv = self.cnt = self.slots = None
        self.hit = self.refresh = None
        self.rkeys = self.rslots = None
        self.dirty = self.plan = self.absent = None
        self.pk = self.pg = None
        self.positions = self.fill_targets = None
        self.done = False
        self.flow_id = None     # trace arrow: miss pull -> consuming step

    def roundtrip(self):
        """The one fallible step: pending pushes + the batched MISS pull,
        fused into one ``push_pull`` per peer when the store supports it
        (``_flush_to_store`` wire behaviour, counters deferred to
        commit).  Returns the pulled rows aligned to ``rkeys`` (None
        when the batch had no misses)."""
        c = self.cache
        rows = None
        if self.pk is not None:
            if self.rkeys is not None and hasattr(c.store, "push_pull"):
                rows = c.store.push_pull(c.table, self.pk, self.pg,
                                         self.rkeys, c.lr)
            else:
                c.store.push(c.table, self.pk, self.pg, c.lr)
        if rows is None and self.rkeys is not None:
            rows = c.store.pull(c.table, self.rkeys)
        return rows


class DistCacheTable:
    """HET bounded-staleness embedding cache — fully vectorized, batch-
    granular (reference ``src/hetu_cache/cache.h:21`` pull_bound_/
    push_bound_ semantics; HET VLDB'22).  Works over any store exposing
    the EmbeddingStore sparse API (:class:`DistributedStore` across hosts,
    or a plain :class:`~hetu_tpu.ps.store.EmbeddingStore` locally).

    Storage is a contiguous ``(limit, width)`` float32 slab plus an
    open-addressed int64 key→slot hash table in numpy — no per-key Python
    objects anywhere.  ``lookup``/``update`` are vectorized hit/miss
    partitions; LRU/LFU eviction picks victims with one ``lexsort`` over
    per-slot clocks; gradients accumulate via ``np.add.at`` into a dirty
    slab; and EVERY pending push (miss-refresh, eviction, push-bound
    overflow, ``flush``) rides ONE batched ``store.push`` — grouped per
    owner rank by the store's shard fanout — instead of the pre-PR one
    single-row RPC per dirty key.  A miss-refresh that also has pushes
    pending fuses both into one ``store.push_pull`` round trip per peer.

    Contract (the per-key reference model in ``refcache.py`` implements
    the SAME rules — the parity suite holds the two bitwise equal):

    - Decisions are BATCH-granular over the call's sorted unique keys: a
      key is a HIT iff cached with ``uses < pull_bound``; all its
      occurrences serve the same row, and ``uses`` grows by the
      occurrence count.  A refresh (stale or absent) re-pulls the row and
      restarts ``uses`` at the occurrence count.
    - ``update`` accumulates per-key grads client-side (``gcnt`` grows by
      occurrence count); reaching ``push_bound`` pushes the accumulated
      grad and invalidates the local row (``uses = pull_bound``), as does
      ``flush``.  Updating an uncached key allocates a grad-only slot
      whose row never serves (born stale).
    - Eviction at ``limit``: victims are the smallest ``(last-use tick,
      key)`` [LRU] or ``(freq, tick, key)`` [LFU] among slots not touched
      by the current batch; dirty victims join the batched push.  If a
      single batch's unique keys exceed capacity, the sorted-first keys
      get slots and the remainder are served (and their grads pushed)
      uncached.

    **Device-resident mode** (``device=True`` — ISSUE 11): the slot
    table, hash table, eviction clocks and the transactional commit
    protocol stay host-side and UNCHANGED (every decision above is
    byte-identical to host mode), but the row slab gains a
    device-resident mirror of shape ``(limit + device_scratch + 1,
    width)`` and the hot path stops moving hit rows across the host
    boundary: a lookup is split into :meth:`begin_lookup` (plan, under
    the lock) → a store round trip for the pushes + MISS pull only
    (:meth:`_DevLookup.roundtrip`, lock-free — the executor runs it on
    the feed-pipeline thread so it overlaps the dense forward) →
    :meth:`finish_lookup` (commit).  Hit rows are gathered ON DEVICE by
    slot index (``ops/pallas/emb_cache.py`` Pallas kernel, with counted
    ``jnp.take`` fallback off-TPU); only miss rows are H2D-transferred,
    landing in their committed slots via :func:`fill_rows`.  Batch
    unique keys that exceed capacity are served through ``device_scratch``
    scratch rows past the slab (positions ``[limit, limit+scratch)``;
    never registered, overwritten freely — the "served uncached"
    contract above), and one dump row at ``limit + scratch`` absorbs
    fill padding.  The training grad path arrives pre-summed per unique
    key from the device scatter-add kernel through
    :meth:`apply_update_summed`, replacing the host scipy-CSR segment
    sum.  The lock is HELD from ``begin_lookup`` to
    ``finish_lookup``/:meth:`abort_lookup` (the host-mode ``lookup``
    holds it for the same window), so a transport failure still leaves
    the cache untouched.  In device mode the host ``_data`` slab is NOT
    mirrored (the device slab is the one serving copy — a host mirror
    would double the per-step row traffic for a buffer nothing reads);
    served values stay bitwise equal to host mode because both modes
    fill from the same pull bytes and copy them verbatim.  Restrictions:
    mutually exclusive with ``read_only``; the executor wiring supports
    BSP single-process training (ASP/SSP/multi-process raise).

    **Read-only serving mode** (``read_only=True`` — what
    :class:`hetu_tpu.serving.InferenceExecutor` mounts): a pure lookup
    serves any cached row WITHOUT burning ``pull_bound`` budget, touching
    the dirty-grad slab, or counting toward ``push_bound`` — the
    training-mode ``uses`` clock exists to bound staleness *between this
    client's own writes*, and a serving replica never writes.  ``update``
    is rejected outright.  Staleness is VERSION-based instead: each fill
    records the row's server version (one extra batched ``versions``
    fanout on the miss path only), and :meth:`refresh_stale` — invoked
    explicitly, or every ``refresh_every`` lookups (asynchronously, on a
    background thread, so no serving batch pays the sweep in its own
    latency; :meth:`refresh_join` drains it) — re-pulls exactly the
    cached rows whose server version advanced (a trainer elsewhere kept
    writing), in one batched owner-grouped round trip.  Eviction recency
    (ticks/freq) still advances on read-only lookups: LRU/LFU victim
    choice needs it.
    """

    _EMPTY, _TOMB = -1, -2

    def __init__(self, store, table, limit=1 << 16,
                 pull_bound=100, push_bound=10, lr=-1.0, policy="lru",
                 read_only=False, refresh_every=0, device=False,
                 device_scratch=None, device_interpret=None):
        self.store, self.table = store, table
        self.width = int(store.width(table))
        self.limit = int(limit)
        self.pull_bound, self.push_bound = int(pull_bound), int(push_bound)
        self.lr = lr
        self.read_only = bool(read_only)
        #: device-resident slab mode (see class docstring)
        self.device = bool(device)
        if self.device and self.read_only:
            raise NotImplementedError(
                "DistCacheTable(device=True, read_only=True): the "
                "serving path keeps its host slab (version-refresh "
                "rides it) — device-resident serving is future work")
        #: scratch rows past the slab for capacity-overflow batches
        #: (keys served uncached still need a device row to gather)
        self._dev_scratch = int(device_scratch) if device_scratch \
            is not None else max(256, self.limit // 4)
        #: fill-padding target: one garbage row that is never gathered
        self._dev_dump = self.limit + self._dev_scratch
        #: Pallas dispatch knob forwarded to ops/pallas/emb_cache.py
        #: (None = auto: kernel on TPU, counted fallback elsewhere)
        self.device_interpret = device_interpret
        self._dev_slab = None   # lazily-built (limit+scratch+1, width)
        #: read-only mode: run a version-based refresh sweep every N
        #: lookup calls (0 = only when refresh_stale() is called)
        self.refresh_every = int(refresh_every)
        self._lookups_since_refresh = 0
        self._refresh_thread = None   # in-flight async sweep (at most one)
        policy = policy.lower()
        if policy not in ("lru", "lfu"):
            raise ValueError(f"unknown cache policy {policy!r}")
        self.policy = policy
        L, w = self.limit, self.width
        # device mode never reads the host row mirror (the device slab
        # is the one serving copy) — don't commit limit*width host bytes
        # to a buffer nothing reads
        self._data = np.zeros((0 if self.device else L, w), np.float32)
        self._grad = np.zeros((L, w), np.float32)   # pending grad slab
        self._slotkey = np.full(L, self._EMPTY, np.int64)  # slot -> key
        self._uses = np.zeros(L, np.int64)     # lookups since refresh
        self._gcnt = np.zeros(L, np.int64)     # pending update events
        self._ticks = np.zeros(L, np.int64)    # last-touch clock (LRU)
        self._freq = np.zeros(L, np.int64)     # touch count (LFU)
        #: server version at fill time, maintained in read-only mode
        #: only (training-mode staleness rides pull_bound instead)
        self._vers = np.zeros(L, np.int64)
        cap = 1 << max(6, (4 * L - 1).bit_length())   # load factor <= 1/4
        self._hcap, self._hmask = cap, cap - 1
        self._hkey = np.full(cap, self._EMPTY, np.int64)
        self._hslot = np.zeros(cap, np.int64)
        self._htomb = 0
        # O(1) slot allocator: popping from the end hands out ascending
        # slot ids (slot identity is unobservable — victim order ties
        # break on KEY, never slot)
        self._freelist = np.arange(L - 1, -1, -1, dtype=np.int64)
        self._nfree = L
        self._tick = 0
        self._lock = make_rlock("DistCacheTable._lock")   # prefetch + main
        #: (flat, uk, inv, cnt, slots) of the latest lookup — the executor
        #: and the CTR step always update() the exact ids they just looked
        #: up, so the batch partition is computed once, not twice
        self._batch_memo = None
        self.stats = {"lookups": 0, "hits": 0, "evictions": 0, "pushes": 0,
                      "fetches": 0, "updates": 0, "push_rpcs": 0}

    # -- open-addressed int64 hash table (vectorized linear probing) -------
    def _hash(self, keys):
        h = keys.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        h ^= h >> np.uint64(29)
        return (h & np.uint64(self._hmask)).astype(np.int64)

    def _find(self, ukeys):
        """Slot for each (unique) key, -1 if absent — every probe round
        advances ALL still-unresolved keys one step at once."""
        out = np.full(ukeys.size, -1, np.int64)
        if not ukeys.size:
            return out
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            found = hk == ukeys[pend]
            if found.any():
                out[pend[found]] = self._hslot[h[found]]
            stop = found | (hk == self._EMPTY)   # TOMB keeps probing
            keep = ~stop
            if not keep.any():
                break
            pend = pend[keep]
            h = (h[keep] + 1) & self._hmask
        return out

    def _hinsert(self, ukeys, slots):
        """Insert absent unique keys; conflicting claims on one free cell
        are resolved per round (first claimant wins, rest re-probe)."""
        if not ukeys.size:
            return
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            usable = (hk == self._EMPTY) | (hk == self._TOMB)
            if usable.any():
                upos, first = np.unique(h[usable], return_index=True)
                winners = np.flatnonzero(usable)[first]
                wcells = h[winners]
                self._htomb -= int((self._hkey[wcells] == self._TOMB).sum())
                self._hkey[wcells] = ukeys[pend[winners]]
                self._hslot[wcells] = slots[pend[winners]]
                keep = np.ones(pend.size, bool)
                keep[winners] = False
                pend, h = pend[keep], h[keep]
            h = (h + 1) & self._hmask

    def _hdelete(self, ukeys):
        """Tombstone present unique keys (chains through them survive)."""
        if not ukeys.size:
            return
        pend = np.arange(ukeys.size)
        h = self._hash(ukeys)
        while pend.size:
            hk = self._hkey[h]
            found = hk == ukeys[pend]
            if found.any():
                self._hkey[h[found]] = self._TOMB
                self._htomb += int(found.sum())
            keep = ~(found | (hk == self._EMPTY))
            if not keep.any():
                break
            pend, h = pend[keep], h[keep]
            h = (h + 1) & self._hmask

    def _maybe_rehash(self):
        if self._htomb <= self._hcap // 4:
            return
        self._hkey.fill(self._EMPTY)
        self._htomb = 0
        occ = np.flatnonzero(self._slotkey >= 0)
        self._hinsert(self._slotkey[occ], occ)

    # -- slot allocation + vectorized victim selection ---------------------
    def _pick_victims(self, occ, n_ev):
        """The ``n_ev`` worst occupied slots under the policy's total
        order — LRU ``(tick, key)``, LFU ``(freq, tick, key)`` — via
        argpartition on the primary clock with a deterministic lexsort
        refinement of the boundary ties (a full lexsort of 10^6 occupied
        slots per batch would dominate the whole lookup)."""
        if n_ev >= occ.size:
            return occ
        prim = self._ticks[occ] if self.policy == "lru" \
            else self._freq[occ]
        part = np.argpartition(prim, n_ev - 1)[:n_ev]
        thresh = prim[part].max()
        sure = part[prim[part] < thresh]
        ties = np.flatnonzero(prim == thresh)
        if self.policy == "lru":
            order = np.argsort(self._slotkey[occ[ties]], kind="stable")
        else:
            order = np.lexsort((self._slotkey[occ[ties]],
                                self._ticks[occ[ties]]))
        chosen = ties[order[:n_ev - sure.size]]
        return occ[np.concatenate((sure, chosen))]

    def _plan_slots(self, newkeys, protect_slots):
        """PLAN slots for absent unique (sorted) ``newkeys``: free slots
        first, then LRU/LFU victims among slots not in ``protect_slots``
        (the current batch's own slots) — overflow beyond capacity stays
        -1 (uncacheable).  Pure read: nothing is committed until
        :meth:`_commit_slots`, so the fallible store round trip can sit
        between plan and commit without ever leaving torn cache state.
        The O(limit) protect mask + occupancy scan is built only when
        eviction is actually needed."""
        slots = np.full(newkeys.size, -1, np.int64)
        take = min(newkeys.size, self._nfree)
        if take:
            slots[:take] = self._freelist[self._nfree - take:
                                          self._nfree][::-1]
        need = newkeys.size - take
        evslots = evkeys = np.empty(0, np.int64)
        if need > 0:
            protect = np.zeros(self.limit, bool)
            protect[protect_slots] = True
            occ = np.flatnonzero((self._slotkey >= 0) & ~protect)
            n_ev = min(need, occ.size)
            if n_ev > 0:
                evslots = self._pick_victims(occ, n_ev)
                evkeys = self._slotkey[evslots].copy()
                slots[take:take + n_ev] = evslots
        return slots, take, evslots, evkeys

    def _plan_dirty(self, slot_sel):
        """(dirty_slots, their keys, grad copies) among ``slot_sel`` —
        the push payload is copied out so the slab mutates only after the
        push round trip succeeds."""
        dirty = slot_sel[self._gcnt[slot_sel] > 0]
        if not dirty.size:
            return dirty, None, None
        return dirty, self._slotkey[dirty].copy(), self._grad[dirty].copy()

    def _commit_slots(self, newkeys, plan):
        """Apply a :meth:`_plan_slots` plan: pop the freelist, tombstone +
        reset victims, register the new keys.  Returns the registered
        (keys, slots)."""
        slots, take, evslots, evkeys = plan
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("cache.evict_commit")
        self._nfree -= take
        if evslots.size:
            self._hdelete(evkeys)
            self._grad[evslots] = 0.0
            self._gcnt[evslots] = 0
            self.stats["evictions"] += int(evslots.size)
            record_cache("emb_cache_evict_rows", int(evslots.size))
        reg = slots >= 0
        regk, regs = newkeys[reg], slots[reg]
        self._slotkey[regs] = regk
        self._hinsert(regk, regs)
        self._freq[regs] = 0
        return regk, regs

    def _flush_to_store(self, push_keys, push_grads, pull_keys=None):
        """ONE batched store round trip for everything pending: the push
        list (concatenated, already per-unique-key accumulated) and, when
        ``pull_keys`` is given, the refresh pull — fused into a single
        ``push_pull`` per peer when the store supports it.  Counters
        record only after the round trip succeeds."""
        rows = None
        if push_keys:
            pk = np.concatenate(push_keys)
            pg = np.concatenate(push_grads)
            order = np.argsort(pk, kind="stable")   # deterministic wire
            pk, pg = pk[order], pg[order]
            if pull_keys is not None and hasattr(self.store, "push_pull"):
                # lint: held-rpc-ok transactional commit protocol (plan under lock, ONE fallible round trip, then commit)
                rows = self.store.push_pull(self.table, pk, pg, pull_keys,
                                            self.lr)
            else:
                # lint: held-rpc-ok same transactional commit round trip (push half)
                self.store.push(self.table, pk, pg, self.lr)
            self.stats["pushes"] += int(pk.size)
            self.stats["push_rpcs"] += 1
            record_cache("emb_cache_push_rows", int(pk.size))
            record_cache("emb_cache_push_rpcs", 1)
        if rows is None and pull_keys is not None:
            # lint: held-rpc-ok the refresh pull is the same one fallible round trip
            rows = self.store.pull(self.table, pull_keys)
        return rows

    # -- core ops ----------------------------------------------------------
    def lookup(self, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        if self.device:
            return self._lookup_device(keys)
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("cache.lookup")
        sweep = False
        with self._lock:
            if self.read_only:
                out = self._lookup_readonly_locked(keys.reshape(-1))
                if self.refresh_every > 0:
                    self._lookups_since_refresh += 1
                    if self._lookups_since_refresh >= self.refresh_every:
                        self._lookups_since_refresh = 0
                        sweep = True
            else:
                out = self._lookup_locked(keys.reshape(-1))
        if sweep:
            self._refresh_async()
        return out.reshape(keys.shape + (self.width,))

    def _lookup_readonly_locked(self, flat):
        """Pure read-only lookup: a cached row is a hit regardless of
        ``pull_bound`` (``uses`` budget is never consumed — that clock
        bounds staleness between this client's own pushes, and a
        read-only client never pushes), no dirty-slab planning anywhere
        (the grad slab is untouched by invariant: ``update`` is
        rejected), and each fill records the row's server version for
        :meth:`refresh_stale`.  Eviction recency still advances."""
        self._tick += 1
        self.stats["lookups"] += int(flat.size)
        if not flat.size:
            return np.empty((0, self.width), np.float32)
        uk, inv, cnt = np.unique(flat, return_inverse=True,
                                 return_counts=True)
        slots = self._find(uk)
        present = slots >= 0
        rows_out = np.empty((uk.size, self.width), np.float32)
        miss = ~present
        if miss.any():
            mkeys = uk[miss]
            plan = self._plan_slots(mkeys, slots[present])
            # the ONLY fallible step: one batched owner-grouped pull (+
            # one versions fanout over the same keys).  A transport
            # failure raises with the cache untouched — failover inside
            # the store's pull is invisible here.  Versions are read
            # BEFORE the rows: a write landing between the two RPCs then
            # leaves a version OLDER than the data (refresh_stale re-pulls
            # once, harmlessly), whereas the reverse order would record a
            # version NEWER than the data and hide the stale row from
            # refresh_stale forever
            # lint: held-rpc-ok transactional miss fill, versions first
            vers = self.store.versions(self.table, mkeys) \
                if hasattr(self.store, "versions") else None
            if _race.ACTIVE is not None:   # ISSUE 14: the racing-writer
                _race.point("cache.miss_fill")   # window (vers -> rows)
            # lint: held-rpc-ok same transactional miss-fill window
            rows = self.store.pull(self.table, mkeys)
            self.stats["fetches"] += int(mkeys.size)
            self._commit_slots(mkeys, plan)
            mslots = plan[0]
            cached = mslots >= 0
            cs = mslots[cached]
            self._data[cs] = rows[cached]
            self._uses[cs] = 0
            self._ticks[cs] = self._tick
            self._freq[cs] += cnt[miss][cached]
            self._vers[cs] = 0 if vers is None else vers[cached]
            rows_out[miss] = rows
            self._maybe_rehash()
            slots = slots.copy()
            slots[miss] = mslots
        n_hit_rows = int(cnt[present].sum())
        self.stats["hits"] += n_hit_rows
        record_cache("emb_cache_hit_rows", n_hit_rows)
        record_cache("emb_cache_miss_rows", int(flat.size) - n_hit_rows)
        if present.any():
            hs = slots[present]
            # recency/frequency clocks advance (eviction needs them);
            # the pull_bound budget (_uses) does NOT
            self._ticks[hs] = self._tick
            self._freq[hs] += cnt[present]
            rows_out[present] = self._data[hs]
        return rows_out[inv]

    def refresh_stale(self):
        """Version-based staleness refresh (read-only serving): ONE
        batched ``versions`` fanout over every cached key, then ONE
        batched pull of exactly the rows whose server version advanced
        since fill (a trainer elsewhere kept writing them).  Both store
        round trips run OUTSIDE the cache lock so concurrent lookups
        keep serving mid-sweep; the commit re-validates that each slot
        still holds its key (eviction races skip) and only moves
        versions FORWARD (a racing miss fill that pulled fresher data
        wins).  Returns the number of refreshed rows."""
        if not hasattr(self.store, "versions"):
            return 0
        with self._lock:
            occ = np.flatnonzero(self._slotkey >= 0)
            if not occ.size:
                return 0
            keys = self._slotkey[occ]
            order = np.argsort(keys, kind="stable")   # deterministic wire
            keys = keys[order]
            have = self._vers[occ[order]].copy()
        vers = np.asarray(self.store.versions(self.table, keys), np.int64)
        stale = vers > have
        if not stale.any():
            return 0
        sk = keys[stale]
        rows = np.asarray(self.store.pull(self.table, sk), np.float32)
        sv = vers[stale]
        if _race.ACTIVE is not None:   # ISSUE 14 preemption point
            _race.point("cache.refresh_commit")
        refreshed = 0
        with self._lock:
            slots = self._find(sk)
            live = slots >= 0
            if live.any():
                s = slots[live]
                newer = sv[live] > self._vers[s]
                s = s[newer]
                self._data[s] = rows[live][newer]
                self._vers[s] = sv[live][newer]
                refreshed = int(s.size)
        if refreshed:
            record_cache("emb_cache_refresh_rows", refreshed)
        return refreshed

    def _refresh_async(self):
        """Run :meth:`refresh_stale` on a background daemon thread (at
        most one in flight): the serving batch whose lookup trips the
        ``refresh_every`` counter must not pay the sweep's store round
        trips in its own tail latency."""
        with self._lock:
            if self._refresh_thread is not None \
                    and self._refresh_thread.is_alive():
                return
            t = threading.Thread(target=self._refresh_quiet, daemon=True,
                                 name="hetu-emb-refresh")
            # started INSIDE the lock: a concurrent refresh_join must
            # never observe (and try to join) a not-yet-started thread,
            # and a concurrent _refresh_async must never read the
            # unstarted thread as not-alive and spawn a second sweep
            t.start()
            self._refresh_thread = t

    def _refresh_quiet(self):
        t0 = time.perf_counter_ns() if _TR.on else 0
        try:
            n = self.refresh_stale()
            if _TR.on:
                # the read-only staleness sweep, on its own
                # "hetu-emb-refresh" track
                _TR.complete("emb.refresh", t0, time.perf_counter_ns(),
                             cat="serve", args={"rows": n})
        except Exception:
            pass    # best-effort: the next counter trip retries

    def refresh_join(self, timeout=None):
        """Wait for an in-flight async staleness sweep (deterministic
        tests, drain-before-shutdown).  Returns True when no sweep is
        running afterwards."""
        with self._lock:
            t = self._refresh_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    # -- device-resident mode (ISSUE 11; see class docstring) --------------
    def _ensure_dev_slab(self):
        """The device row slab: ``limit`` cache slots + ``device_scratch``
        overflow rows + one dump row for fill padding.  Built lazily so
        a host-mode table never touches jax."""
        if self._dev_slab is None:
            import jax.numpy as jnp
            self._dev_slab = jnp.zeros(
                (self.limit + self._dev_scratch + 1, self.width),
                jnp.float32)
        return self._dev_slab

    def begin_lookup(self, keys):
        """Device-mode lookup, phase 1 of 3: take the cache lock and PLAN
        — hit/refresh partition, victim/slot plan, push payload copies,
        device positions — exactly the pre-RPC half of the host-mode
        ``_lookup_locked``.  Returns a :class:`_DevLookup` handle whose
        :meth:`_DevLookup.roundtrip` runs the one fallible store round
        trip LOCK-FREE (any thread — the executor uses the feed-pipeline
        thread so the miss pull overlaps the dense forward), after which
        :meth:`finish_lookup` commits, or :meth:`abort_lookup` releases
        with the cache untouched (transactional contract: a transport
        failure registers no never-filled slot and loses no pending
        grad).  The lock is HELD until finish/abort — the same window
        the host-mode ``lookup`` holds it for."""
        if not self.device:
            raise RuntimeError("begin_lookup requires device=True")
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        self._lock.acquire()
        try:
            h = _DevLookup(self, keys.shape, flat)
            self._tick += 1
            self._batch_memo = None
            self.stats["lookups"] += int(flat.size)
            if not flat.size:
                return h
            uk, inv, cnt = np.unique(flat, return_inverse=True,
                                     return_counts=True)
            slots = self._find(uk)
            present = slots >= 0
            hit = np.zeros(uk.size, bool)
            hit[present] = self._uses[slots[present]] < self.pull_bound
            refresh = ~hit
            h.uk, h.inv, h.cnt = uk, inv, cnt
            h.slots, h.hit, h.refresh = slots, hit, refresh
            push_keys, push_grads = [], []
            if refresh.any():
                rkeys = uk[refresh]
                rslots = slots[refresh].copy()
                stale = rslots >= 0
                dirty, dkeys, dgrads = self._plan_dirty(rslots[stale])
                if dirty.size:
                    push_keys.append(dkeys)
                    push_grads.append(dgrads)
                absent = ~stale
                plan = None
                if absent.any():
                    plan = self._plan_slots(rkeys[absent], slots[present])
                    ev_dirty, evk, evg = self._plan_dirty(plan[2])
                    if ev_dirty.size:
                        push_keys.append(evk)
                        push_grads.append(evg)
                    rslots[absent] = plan[0]
                h.rkeys, h.rslots = rkeys, rslots
                h.dirty, h.plan, h.absent = dirty, plan, absent
            if push_keys:
                pk = np.concatenate(push_keys)
                pg = np.concatenate(push_grads)
                order = np.argsort(pk, kind="stable")  # deterministic wire
                h.pk, h.pg = pk[order], pg[order]
            # device positions per unique key: committed/planned slot,
            # or a scratch row for capacity-overflow keys (served — and
            # grad-pushed — uncached, never registered)
            pos = slots.copy()
            if h.rslots is not None:
                pos[refresh] = h.rslots
            over = pos < 0
            n_over = int(over.sum())
            if n_over > self._dev_scratch:
                raise RuntimeError(
                    f"device-mode batch overflow: {n_over} uncacheable "
                    f"unique keys exceed device_scratch="
                    f"{self._dev_scratch} — raise device_scratch (or "
                    f"limit), or use the host cache for this workload")
            pos[over] = self.limit + np.arange(n_over)
            h.positions = pos
            if h.rkeys is not None:
                h.fill_targets = pos[refresh].astype(np.int32)
            return h
        except BaseException:
            self._lock.release()
            raise

    def finish_lookup(self, h, rows):
        """Device-mode lookup, phase 3: COMMIT the plan with the pulled
        miss ``rows`` (aligned to ``h.rkeys``) — the post-RPC half of
        the host-mode ``_lookup_locked`` (slot registration, hit/
        eviction bookkeeping, counters) plus the eager in-place device
        fill (:meth:`_apply_dev_fill`) — and release the lock.
        Standalone callers and the executor share this one commit
        path; the consuming gather (in the step, or eagerly in
        ``lookup``) happens after it."""
        try:
            if h.flat.size == 0:
                return
            uk, cnt, hit, refresh = h.uk, h.cnt, h.hit, h.refresh
            if h.pk is not None:
                self.stats["pushes"] += int(h.pk.size)
                self.stats["push_rpcs"] += 1
                record_cache("emb_cache_push_rows", int(h.pk.size))
                record_cache("emb_cache_push_rpcs", 1)
            slots = h.slots
            if h.rkeys is not None:
                rslots = h.rslots
                self.stats["fetches"] += int(h.rkeys.size)
                if h.dirty.size:
                    self._grad[h.dirty] = 0.0
                    self._gcnt[h.dirty] = 0
                if h.plan is not None:
                    self._commit_slots(h.rkeys[h.absent], h.plan)
                cached = rslots >= 0
                if cached.all():
                    cs, cnt_r = rslots, cnt[refresh]
                else:
                    cs = rslots[cached]
                    cnt_r = cnt[refresh][cached]
                # NB: no ``_data[cs] = rows`` here — in device mode the
                # filled slab IS the serving copy; mirroring every miss
                # row into the host slab would double the per-step row
                # traffic for a buffer nothing reads
                self._uses[cs] = cnt_r
                self._ticks[cs] = self._tick
                self._freq[cs] += cnt_r
                self._maybe_rehash()
                slots = slots.copy()
                slots[refresh] = rslots
            n_hit_rows = int(cnt[hit].sum())
            self.stats["hits"] += n_hit_rows
            record_cache("emb_cache_hit_rows", n_hit_rows)
            record_cache("emb_cache_miss_rows",
                         int(h.flat.size) - n_hit_rows)
            if hit.any():
                hs = slots[hit]
                self._uses[hs] += cnt[hit]
                self._ticks[hs] = self._tick
                self._freq[hs] += cnt[hit]
            self._batch_memo = (h.flat, uk, h.inv, cnt, slots)
            if h.rkeys is not None:
                try:
                    self._apply_dev_fill(rows, h.fill_targets)
                except BaseException:
                    # the host commit above is already irreversible (and
                    # correct — the pushes landed); a failed FILL must
                    # not leave registered slots whose slab rows were
                    # never written, so poison them stale: they re-pull
                    # on the next lookup instead of serving garbage
                    if cs.size:
                        self._uses[cs] = self.pull_bound
                    raise
        finally:
            h.done = True
            self._lock.release()

    def _apply_dev_fill(self, rows, targets):
        """Land pulled rows in the device slab IN PLACE: the fill
        arrays are padded to a pow2 bucket (padding targets the dump
        row) so miss-count jitter cycles a bounded set of tiny compiled
        fill programs, and the slab rides through a jit donated on TPU
        so no per-step ``(limit + scratch, width)`` copy exists there
        (CPU cannot honor donation and copies either way).  The
        training step's own program never sees the fill — its input
        shapes stay fixed."""
        import jax
        from ..ops.pallas import emb_cache as _emb
        m = int(rows.shape[0])
        bucket = _emb.fill_bucket(m)
        # np.empty: padding rows are garbage by design — their targets
        # all point at the dump row, which is never gathered
        fr = np.empty((bucket, self.width), np.float32)
        ft = np.full((bucket,), self._dev_dump, np.int32)
        fr[:m] = rows
        ft[:m] = targets
        self._dev_slab = _emb.fill_rows_inplace(
            self._ensure_dev_slab(), jax.device_put(fr),
            jax.device_put(ft))

    def abort_lookup(self, h):
        """Release a :meth:`begin_lookup` handle after a failed round
        trip: the plan is discarded, nothing host- or device-side was
        mutated by it (the tick/lookup stats advanced, as they do on a
        failed host-mode lookup)."""
        if not h.done:
            h.done = True
            self._lock.release()

    def _lookup_device(self, keys):
        """Standalone device-mode lookup (parity tests, the profiler,
        non-executor callers — e.g. ``PSEmbeddingLookupOp.pull_rows``
        on a prefetch thread): the same begin → round trip → commit
        protocol the executor drives, with the gather run eagerly
        through the dispatcher.  The RLock is re-entered around
        commit+gather so the whole serve is ATOMIC like the host-mode
        ``lookup`` — without it, a concurrent lookup could evict one of
        this batch's slots and fill another key's row into it between
        the commit and the gather.  Returns host rows like host mode."""
        h = self.begin_lookup(keys)
        try:
            rows = h.roundtrip()
        except BaseException:
            self.abort_lookup(h)
            raise
        # RLock depth 2 (begin holds depth 1): finish_lookup's release
        # drops to depth 1, keeping other threads out until the gather
        # below has served this batch's rows
        self._lock.acquire()
        try:
            self.finish_lookup(h, rows)
            if not h.flat.size:
                return np.empty(keys.shape + (self.width,), np.float32)
            import jax.numpy as jnp
            from ..ops.pallas import emb_cache as _emb
            out = _emb.emb_gather(self._ensure_dev_slab(),
                                  jnp.asarray(h.positions[h.inv]
                                              .astype(np.int32)),
                                  interpret=self.device_interpret)
            return np.asarray(out).reshape(keys.shape + (self.width,))
        finally:
            self._lock.release()

    def _lookup_locked(self, flat):
        self._tick += 1
        self._batch_memo = None
        self.stats["lookups"] += int(flat.size)
        if not flat.size:
            return np.empty((0, self.width), np.float32)
        uk, inv, cnt = np.unique(flat, return_inverse=True,
                                 return_counts=True)
        slots = self._find(uk)
        present = slots >= 0
        hit = np.zeros(uk.size, bool)
        hit[present] = self._uses[slots[present]] < self.pull_bound
        rows_out = np.empty((uk.size, self.width), np.float32)
        refresh = ~hit
        if refresh.any():
            rkeys = uk[refresh]
            rslots = slots[refresh].copy()
            push_keys, push_grads = [], []
            # stale rows keep their slots; their pending grads must land
            # BEFORE the re-pull so the refreshed value includes them —
            # payloads are COPIES, the slab clears only on success
            stale = rslots >= 0
            dirty, dkeys, dgrads = self._plan_dirty(rslots[stale])
            if dirty.size:
                push_keys.append(dkeys)
                push_grads.append(dgrads)
            absent = ~stale
            plan = None
            if absent.any():
                plan = self._plan_slots(rkeys[absent], slots[present])
                ev_dirty, evk, evg = self._plan_dirty(plan[2])
                if ev_dirty.size:
                    push_keys.append(evk)
                    push_grads.append(evg)
                rslots[absent] = plan[0]
            # the ONLY fallible step: one fused round trip.  A transport
            # failure raises with the cache untouched — no key registered
            # for a row that was never filled, no pending grad lost
            rows = self._flush_to_store(push_keys, push_grads, rkeys)
            self.stats["fetches"] += int(rkeys.size)
            if dirty.size:
                self._grad[dirty] = 0.0
                self._gcnt[dirty] = 0
            if plan is not None:
                self._commit_slots(rkeys[absent], plan)
            cached = rslots >= 0
            if cached.all():            # common case: no overflow spill
                cs, rows_c, cnt_r = rslots, rows, cnt[refresh]
            else:
                cs, rows_c = rslots[cached], rows[cached]
                cnt_r = cnt[refresh][cached]
            self._data[cs] = rows_c
            self._uses[cs] = cnt_r
            self._ticks[cs] = self._tick
            self._freq[cs] += cnt_r
            rows_out[refresh] = rows
            self._maybe_rehash()
            slots = slots.copy()
            slots[refresh] = rslots
        # hit bookkeeping commits AFTER the fallible round trip: a raised
        # lookup must not burn pull_bound budget (or count hits) for rows
        # that were never served
        n_hit_rows = int(cnt[hit].sum())
        self.stats["hits"] += n_hit_rows
        record_cache("emb_cache_hit_rows", n_hit_rows)
        record_cache("emb_cache_miss_rows", int(flat.size) - n_hit_rows)
        if hit.any():
            hs = slots[hit]
            self._uses[hs] += cnt[hit]
            self._ticks[hs] = self._tick
            self._freq[hs] += cnt[hit]
            rows_out[hit] = self._data[hs]
        self._batch_memo = (flat, uk, inv, cnt, slots)
        return rows_out[inv]

    def update(self, keys, grads):
        if self.read_only:
            raise RuntimeError(
                "DistCacheTable(read_only=True) rejects update(): a "
                "serving replica must never push gradients — train "
                "through a read-write cache and serve through this one")
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        if not keys.size:
            return
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size,
                                                                -1)
        if self.device:
            # standalone device-mode update: the per-unique-key segment
            # sum runs through the device scatter-add dispatcher (the
            # executor hands in pre-summed grads via apply_update_summed
            # instead — same kernel, summed inside the jitted step)
            import jax.numpy as jnp
            from ..ops.pallas import emb_cache as _emb
            uk, inv, cnt = np.unique(keys, return_inverse=True,
                                     return_counts=True)
            acc = np.asarray(_emb.emb_scatter_add(
                jnp.asarray(grads), jnp.asarray(inv.astype(np.int32)),
                interpret=self.device_interpret))[:uk.size]
            self.apply_update_summed(uk, acc, cnt)
            return
        with self._lock:
            self._update_locked(keys, grads)

    def apply_update_summed(self, uk, acc, cnt):
        """Device-path update entry: ``acc`` already holds the
        per-unique-key grad sums (the device scatter-add kernel replaced
        the host scipy-CSR pass), ``uk`` the batch's sorted unique keys
        and ``cnt`` their occurrence counts — everything the bounded-
        staleness bookkeeping (``gcnt``/``push_bound``/eviction clocks)
        needs, with identical integer decisions to the host-mode
        ``update`` on the same batch."""
        uk = np.ascontiguousarray(uk, np.int64).reshape(-1)
        acc = np.ascontiguousarray(acc, np.float32).reshape(uk.size, -1)
        cnt = np.ascontiguousarray(cnt, np.int64).reshape(-1)
        with self._lock:
            self._tick += 1
            self._batch_memo = None
            self.stats["updates"] += int(cnt.sum())
            if not uk.size:
                return
            self._apply_update(uk, cnt, self._find(uk), acc)

    def _update_locked(self, flat, grads):
        self._tick += 1
        memo, self._batch_memo = self._batch_memo, None
        self.stats["updates"] += int(flat.size)
        if not flat.size:
            return
        if memo is not None and memo[0].size == flat.size \
                and np.array_equal(memo[0], flat):
            # the immediately-preceding lookup partitioned this exact
            # batch; nothing mutated in between (same lock)
            _, uk, inv, cnt, slots = memo
            slots = slots.copy()
        else:
            uk, inv, cnt = np.unique(flat, return_inverse=True,
                                     return_counts=True)
            slots = self._find(uk)
        acc = _segment_sum(grads, inv, cnt)
        self._apply_update(uk, cnt, slots, acc)

    def _apply_update(self, uk, cnt, slots, acc):
        """Post-segment-sum half of ``update`` (shared by the host path
        and the device path's pre-summed entry): slot planning for
        absent keys, push-bound accounting, the one batched push round
        trip, and the transactional commit."""
        present = slots >= 0
        push_keys, push_grads = [], []
        absent = ~present
        plan = None
        if absent.any():
            plan = self._plan_slots(uk[absent], slots[present])
            ev_dirty, evk, evg = self._plan_dirty(plan[2])
            if ev_dirty.size:
                push_keys.append(evk)
                push_grads.append(evg)
            slots[absent] = plan[0]
        cached = slots >= 0
        if cached.all():
            cs, acc_c, cnt_c = slots, acc, cnt
        else:
            cs, acc_c, cnt_c = slots[cached], acc[cached], cnt[cached]
            # capacity overflow: these keys' grads go straight out with
            # the same batched push (early push is within the bound)
            push_keys.append(uk[~cached])
            push_grads.append(acc[~cached])
        # push-bound overflow computed on the HYPOTHETICAL post-batch
        # counts; payloads are fresh sums, the slab commits only after
        # the push lands, so a failed round trip leaves the CACHE
        # unapplied and a caller retry is exactly-once against a
        # single-shard store.  (Across a multi-peer fanout the push is
        # at-least-once on a partial failure — per-peer acks land
        # independently, the reference ps-lite semantics.)  Slots
        # PLANNED for new keys still hold their victim's uncommitted
        # gcnt/grad — a fresh key starts from zero, not from those
        fresh = None
        if plan is not None:
            # over uk: absent keys that got a slot this batch
            fresh = (absent & (slots >= 0))[cached] if not cached.all() \
                else absent
        prior_gcnt = self._gcnt[cs] if fresh is None \
            else np.where(fresh, 0, self._gcnt[cs])
        new_gcnt = prior_gcnt + cnt_c
        exceed = new_gcnt >= self.push_bound
        if exceed.any():
            es = cs[exceed]
            pgrads = self._grad[es] + acc_c[exceed]
            if fresh is not None and fresh[exceed].any():
                pgrads[fresh[exceed]] = acc_c[exceed][fresh[exceed]]
            push_keys.append(uk[cached][exceed])
            push_grads.append(pgrads)
        # the ONLY fallible step: one batched push round trip
        self._flush_to_store(push_keys, push_grads)
        if plan is not None:
            regk, regs = self._commit_slots(uk[absent], plan)
            # grad-only slots: the row was never pulled, so it must never
            # serve — born stale (device mode has no host row mirror to
            # zero; uses=pull_bound alone keeps the slot unservable)
            if not self.device:
                self._data[regs] = 0.0
            self._uses[regs] = self.pull_bound
        self._grad[cs] += acc_c
        self._gcnt[cs] = new_gcnt
        self._ticks[cs] = self._tick
        self._freq[cs] += cnt_c
        if exceed.any():
            self._grad[es] = 0.0
            self._gcnt[es] = 0
            self._uses[es] = self.pull_bound   # server is ahead: stale
        self._maybe_rehash()

    def flush(self):
        """Push every pending accumulated grad (ONE batched push) and
        invalidate the pushed rows (checkpoint barrier)."""
        with self._lock:
            d = np.flatnonzero((self._slotkey >= 0) & (self._gcnt > 0))
            if d.size:
                d = d[np.argsort(self._slotkey[d], kind="stable")]
                self._flush_to_store([self._slotkey[d].copy()],
                                     [self._grad[d].copy()])
                self._grad[d] = 0.0
                self._gcnt[d] = 0
                self._uses[d] = self.pull_bound

    def close(self):
        """Flush pending grads; safe to call repeatedly / at teardown.

        During interpreter finalization OR a garbage-collection pass the
        flush is SKIPPED: pushing through numpy/ctypes while the runtime
        is being torn down segfaults (observed via ``Executor.__del__``
        at process exit), and a GC-triggered ``__del__`` can reach this
        close while the interrupted main-thread frame sits INSIDE a
        native push on a store whose peers are being destructed in the
        same pass in arbitrary order (observed as a segfault in
        ``PSAgent.rows`` mid-collection) — finalizer context must never
        touch the native store.  Pending grads are bounded-staleness
        state; anything that must be durable goes through an explicit
        ``flush``/checkpoint from live code (``Executor.save`` already
        calls ``ps_flush``)."""
        import sys
        if sys.is_finalizing() or _in_gc_pass():
            return
        try:
            self.flush()
        except Exception:
            pass    # store already closed at teardown

    def perf(self):
        """Counter snapshot + read hit rate (CacheSparseTable.perf parity:
        the HET cache's citable number)."""
        with self._lock:
            d = dict(self.stats)
            d["size"] = int((self._slotkey >= 0).sum())
        d["hit_rate"] = (d["hits"] / d["lookups"]) if d["lookups"] else 0.0
        return d

    def __len__(self):
        with self._lock:
            return int((self._slotkey >= 0).sum())
