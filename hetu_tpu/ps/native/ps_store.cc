// hetu_tpu parameter-server core: host-resident sharded embedding store
// with server-side optimizers, row versioning, SSP clocks, and a
// bounded-staleness client cache (LRU/LFU/LFUOpt).
//
// TPU-native re-design of the reference's ps-lite server
// (ps-lite/include/ps/server/PSFHandle.h:17, param.h:101 Param2D,
// optimizer.h SGD:36/Momentum:84/Nesterov:144/AdaGrad:205/Adam:275,
// ssp_handler.h) and HET client cache (src/hetu_cache/include/cache.h:21,
// embedding.h:19 versioned Line, lru_cache.h, lfu_cache.h, lfuopt_cache.h).
// The reference shards tables across ZMQ/RDMA server processes; on TPU pods
// the store lives in host RAM next to the chips (one shard-set per host,
// rows sharded by key hash), so the C ABI below is transport-free: a
// multi-host deployment layers jax process-local stores with key%nhosts
// routing (see hetu_tpu/ps/store.py).
//
// Exposed as a flat extern "C" ABI (loaded via ctypes, mirroring the
// reference's c_runtime_api.h / python_binding.cc approach; no pybind11 in
// this image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <list>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

using key_t_ = int64_t;

namespace {

enum OptType {
  OPT_SGD = 0,
  OPT_MOMENTUM = 1,
  OPT_NESTEROV = 2,
  OPT_ADAGRAD = 3,
  OPT_ADAM = 4,
};

// ---------------------------------------------------------------------------
// Table: a dense 2-D parameter (rows x width) in host RAM, plus per-row
// optimizer slots and versions.  Rows are sharded by key for lock striping.
// ---------------------------------------------------------------------------
struct Table {
  int64_t rows = 0;
  int width = 0;
  int opt = OPT_SGD;
  float lr = 0.01f, m1 = 0.9f, m2 = 0.999f, eps = 1e-7f;

  std::vector<float> data;          // rows*width
  std::vector<float> slot0;         // momentum / adagrad acc / adam m
  std::vector<float> slot1;         // adam v
  std::vector<int32_t> rowstep;     // adam per-row t (bias correction)
  std::vector<int64_t> version;     // per-row update counter (HET staleness)

  int n_stripes = 64;
  std::vector<std::mutex> locks;

  Table(int64_t r, int w, int o, float lr_, float m1_, float m2_, float eps_,
        uint64_t seed, float scale)
      : rows(r), width(w), opt(o), lr(lr_), m1(m1_), m2(m2_), eps(eps_),
        locks(64) {
    data.resize((size_t)rows * width);
    version.assign(rows, 0);
    if (opt == OPT_MOMENTUM || opt == OPT_NESTEROV || opt == OPT_ADAGRAD ||
        opt == OPT_ADAM)
      slot0.assign((size_t)rows * width, 0.f);
    if (opt == OPT_ADAM) {
      slot1.assign((size_t)rows * width, 0.f);
      rowstep.assign(rows, 0);
    }
    if (scale != 0.f) {
      std::mt19937_64 gen(seed);
      std::uniform_real_distribution<float> dist(-scale, scale);
      for (auto &v : data) v = dist(gen);
    }
  }

  std::mutex &lock_for(key_t_ k) { return locks[(uint64_t)k % n_stripes]; }

  // apply one accumulated gradient to one row under its stripe lock
  void apply_row(key_t_ k, const float *g, float lr_override) {
    float elr = lr_override > 0 ? lr_override : lr;
    float *p = &data[(size_t)k * width];
    switch (opt) {
      case OPT_SGD:
        for (int i = 0; i < width; ++i) p[i] -= elr * g[i];
        break;
      case OPT_MOMENTUM: {
        float *v = &slot0[(size_t)k * width];
        for (int i = 0; i < width; ++i) {
          v[i] = m1 * v[i] - elr * g[i];
          p[i] += v[i];
        }
        break;
      }
      case OPT_NESTEROV: {
        float *v = &slot0[(size_t)k * width];
        for (int i = 0; i < width; ++i) {
          float prev = v[i];
          v[i] = m1 * v[i] - elr * g[i];
          p[i] += -m1 * prev + (1 + m1) * v[i];
        }
        break;
      }
      case OPT_ADAGRAD: {
        float *acc = &slot0[(size_t)k * width];
        for (int i = 0; i < width; ++i) {
          acc[i] += g[i] * g[i];
          p[i] -= elr * g[i] / (std::sqrt(acc[i]) + eps);
        }
        break;
      }
      case OPT_ADAM: {
        float *m = &slot0[(size_t)k * width];
        float *v = &slot1[(size_t)k * width];
        int32_t t = ++rowstep[k];
        float bc1 = 1.f - std::pow(m1, (float)t);
        float bc2 = 1.f - std::pow(m2, (float)t);
        for (int i = 0; i < width; ++i) {
          m[i] = m1 * m[i] + (1 - m1) * g[i];
          v[i] = m2 * v[i] + (1 - m2) * g[i] * g[i];
          p[i] -= elr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + eps);
        }
        break;
      }
    }
    version[k]++;
  }
};

// ---------------------------------------------------------------------------
// Store: a set of tables + SSP worker clocks (ssp_handler.h semantics).
// ---------------------------------------------------------------------------
struct Store {
  std::vector<Table *> tables;
  std::mutex mtx;

  // SSP: per-worker clock; sync(worker, s) blocks until min_clock >= my-s
  std::vector<int64_t> clocks;
  std::mutex clk_mtx;
  std::condition_variable clk_cv;

  ~Store() {
    for (auto *t : tables) delete t;
  }
};

// group key indices by stripe so pushes can batch under one lock
inline void accumulate_unique(const key_t_ *keys, int64_t n, int width,
                              const float *grads,
                              std::unordered_map<key_t_, std::vector<float>> &acc) {
  acc.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    auto &buf = acc[keys[i]];
    if (buf.empty()) buf.assign(width, 0.f);
    const float *g = grads + (size_t)i * width;
    for (int j = 0; j < width; ++j) buf[j] += g[j];
  }
}

}  // namespace

extern "C" {

void *hetu_ps_create() { return new Store(); }

void hetu_ps_destroy(void *s) { delete (Store *)s; }

// returns table id
int64_t hetu_ps_init_table(void *s_, int64_t rows, int width, int opt,
                           float lr, float m1, float m2, float eps,
                           uint64_t seed, float init_scale) {
  Store *s = (Store *)s_;
  std::lock_guard<std::mutex> g(s->mtx);
  s->tables.push_back(
      new Table(rows, width, opt, lr, m1, m2, eps, seed, init_scale));
  return (int64_t)s->tables.size() - 1;
}

void hetu_ps_set_data(void *s_, int64_t table, const float *src) {
  Table *t = ((Store *)s_)->tables[table];
  std::memcpy(t->data.data(), src, t->data.size() * sizeof(float));
}

void hetu_ps_get_data(void *s_, int64_t table, float *dst) {
  Table *t = ((Store *)s_)->tables[table];
  std::memcpy(dst, t->data.data(), t->data.size() * sizeof(float));
}

int64_t hetu_ps_rows(void *s_, int64_t table) {
  return ((Store *)s_)->tables[table]->rows;
}
int hetu_ps_width(void *s_, int64_t table) {
  return ((Store *)s_)->tables[table]->width;
}

// SparsePull: out[i] = data[keys[i]]  (duplicates fine; parallel over chunks).
// Out-of-range keys zero-fill defensively; store.py pre-validates and raises.
void hetu_ps_pull(void *s_, int64_t table, const key_t_ *keys, int64_t n,
                  float *out) {
  Table *t = ((Store *)s_)->tables[table];
  int width = t->width;
  int64_t rows = t->rows;
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (keys[i] < 0 || keys[i] >= rows) {
        std::memset(out + (size_t)i * width, 0, width * sizeof(float));
        continue;
      }
      std::memcpy(out + (size_t)i * width, &t->data[(size_t)keys[i] * width],
                  width * sizeof(float));
    }
  };
  int64_t threshold = 1 << 14;
  if (n < threshold) {
    worker(0, n);
  } else {
    int nt = std::min<int64_t>(std::thread::hardware_concurrency(), 8);
    std::vector<std::thread> ths;
    int64_t chunk = (n + nt - 1) / nt;
    for (int i = 0; i < nt; ++i)
      ths.emplace_back(worker, i * chunk, std::min<int64_t>(n, (i + 1) * chunk));
    for (auto &th : ths) th.join();
  }
}

// SparsePush: grads for possibly-duplicated keys are accumulated per unique
// key (reference IndexedSlices cpu_deduplicate, ndarray.py:507) then applied
// through the table's server-side optimizer (ps-lite optimizer.h).
void hetu_ps_push(void *s_, int64_t table, const key_t_ *keys, int64_t n,
                  const float *grads, float lr_override) {
  Table *t = ((Store *)s_)->tables[table];
  std::unordered_map<key_t_, std::vector<float>> acc;
  accumulate_unique(keys, n, t->width, grads, acc);
  for (auto &kv : acc) {
    if (kv.first < 0 || kv.first >= t->rows) continue;  // defensive
    std::lock_guard<std::mutex> g(t->lock_for(kv.first));
    t->apply_row(kv.first, kv.second.data(), lr_override);
  }
}

// Fused SDPushPull (PsfType kSDPushPull): push grads then pull fresh rows.
void hetu_ps_push_pull(void *s_, int64_t table, const key_t_ *push_keys,
                       int64_t n_push, const float *grads, float lr_override,
                       const key_t_ *pull_keys, int64_t n_pull, float *out) {
  hetu_ps_push(s_, table, push_keys, n_push, grads, lr_override);
  hetu_ps_pull(s_, table, pull_keys, n_pull, out);
}

// DensePush over the whole table (PsfType DensePush): takes every stripe
// lock so concurrent sparse pushes are excluded.
void hetu_ps_dense_push(void *s_, int64_t table, const float *grad,
                        float lr_override) {
  Table *t = ((Store *)s_)->tables[table];
  std::vector<std::unique_lock<std::mutex>> guards;
  guards.reserve(t->n_stripes);
  for (int i = 0; i < t->n_stripes; ++i) guards.emplace_back(t->locks[i]);
  for (int64_t r = 0; r < t->rows; ++r)
    t->apply_row(r, grad + (size_t)r * t->width, lr_override);
}

void hetu_ps_versions(void *s_, int64_t table, const key_t_ *keys, int64_t n,
                      int64_t *out) {
  Table *t = ((Store *)s_)->tables[table];
  for (int64_t i = 0; i < n; ++i) out[i] = t->version[keys[i]];
}

// v2 checkpoint format: full table state — data + optimizer slots + per-row
// step counters + versions.  Without the slots a resumed Adam/momentum table
// restarts its moments at zero and silently diverges; without versions the
// HET cache staleness accounting resets (reference SaveParam persists server
// state server-side, ps-lite python_binding.cc:111-118).
static const int64_t kSaveMagic = -0x48505332;  // 'HPS2', impossible as rows

int hetu_ps_save(void *s_, int64_t table, const char *path) {
  Table *t = ((Store *)s_)->tables[table];
  FILE *f = fopen(path, "wb");
  if (!f) return -1;
  int64_t hdr[4] = {kSaveMagic, 2, t->rows, t->width};
  int64_t flags[3] = {(int64_t)!t->slot0.empty(), (int64_t)!t->slot1.empty(),
                      (int64_t)!t->rowstep.empty()};
  fwrite(hdr, sizeof(hdr), 1, f);
  fwrite(flags, sizeof(flags), 1, f);
  fwrite(t->data.data(), sizeof(float), t->data.size(), f);
  if (flags[0]) fwrite(t->slot0.data(), sizeof(float), t->slot0.size(), f);
  if (flags[1]) fwrite(t->slot1.data(), sizeof(float), t->slot1.size(), f);
  if (flags[2])
    fwrite(t->rowstep.data(), sizeof(int32_t), t->rowstep.size(), f);
  fwrite(t->version.data(), sizeof(int64_t), t->version.size(), f);
  fclose(f);
  return 0;
}

int hetu_ps_load(void *s_, int64_t table, const char *path) {
  Table *t = ((Store *)s_)->tables[table];
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  int64_t first;
  if (fread(&first, sizeof(first), 1, f) != 1) {
    fclose(f);
    return -2;
  }
  if (first >= 0) {  // v1 file: {rows, width, data} — data only
    int64_t width;
    if (fread(&width, sizeof(width), 1, f) != 1 || first != t->rows ||
        width != t->width) {
      fclose(f);
      return -2;
    }
    size_t nread = fread(t->data.data(), sizeof(float), t->data.size(), f);
    fclose(f);
    return nread == t->data.size() ? 0 : -3;
  }
  int64_t rest[3];  // version, rows, width
  int64_t flags[3];
  if (first != kSaveMagic || fread(rest, sizeof(rest), 1, f) != 1 ||
      rest[0] != 2 || rest[1] != t->rows || rest[2] != t->width ||
      fread(flags, sizeof(flags), 1, f) != 1) {
    fclose(f);
    return -2;
  }
  bool ok = fread(t->data.data(), sizeof(float), t->data.size(), f) ==
            t->data.size();
  if (flags[0]) {
    if (t->slot0.empty()) t->slot0.assign(t->data.size(), 0.f);
    ok = ok && fread(t->slot0.data(), sizeof(float), t->slot0.size(), f) ==
                   t->slot0.size();
  }
  if (flags[1]) {
    if (t->slot1.empty()) t->slot1.assign(t->data.size(), 0.f);
    ok = ok && fread(t->slot1.data(), sizeof(float), t->slot1.size(), f) ==
                   t->slot1.size();
  }
  if (flags[2]) {
    if (t->rowstep.empty()) t->rowstep.assign(t->rows, 0);
    ok = ok && fread(t->rowstep.data(), sizeof(int32_t),
                     t->rowstep.size(), f) == t->rowstep.size();
  }
  ok = ok && fread(t->version.data(), sizeof(int64_t), t->version.size(),
                   f) == t->version.size();
  fclose(f);
  return ok ? 0 : -3;
}

// --------------------------- SSP clocks ------------------------------------
// kSSPInit / kSSPSync parity (ps-lite ssp_handler.h): worker `w` advances its
// clock each step; ssp_sync blocks while (my_clock - min_clock) > staleness.
void hetu_ps_ssp_init(void *s_, int n_workers) {
  Store *s = (Store *)s_;
  std::lock_guard<std::mutex> g(s->clk_mtx);
  s->clocks.assign(n_workers, 0);
}

void hetu_ps_clock(void *s_, int worker) {
  Store *s = (Store *)s_;
  {
    std::lock_guard<std::mutex> g(s->clk_mtx);
    s->clocks[worker]++;
  }
  s->clk_cv.notify_all();
}

int64_t hetu_ps_clock_value(void *s_, int worker) {
  Store *s = (Store *)s_;
  std::lock_guard<std::mutex> g(s->clk_mtx);
  if (worker < 0 || (size_t)worker >= s->clocks.size()) return -1;
  return s->clocks[worker];
}

// returns 0 on success, 1 on timeout
int hetu_ps_ssp_sync(void *s_, int worker, int staleness, int timeout_ms) {
  Store *s = (Store *)s_;
  std::unique_lock<std::mutex> g(s->clk_mtx);
  auto ok = [&] {
    int64_t mn = *std::min_element(s->clocks.begin(), s->clocks.end());
    return s->clocks[worker] - mn <= staleness;
  };
  if (timeout_ms <= 0) {
    s->clk_cv.wait(g, ok);
    return 0;
  }
  return s->clk_cv.wait_for(g, std::chrono::milliseconds(timeout_ms), ok)
             ? 0
             : 1;
}

}  // extern "C"

// ===========================================================================
// HET client cache: bounded-staleness embedding cache in front of a store
// table (src/hetu_cache/include/cache.h:21 CacheBase, embedding.h:19 Line).
// Policies: LRU / LFU / LFUOpt (lru_cache.h / lfu_cache.h / lfuopt_cache.h).
// ===========================================================================
namespace {

struct CacheLine {
  std::vector<float> val;    // cached embedding row
  std::vector<float> grad;   // locally accumulated updates
  int64_t base_version = 0;  // store version when fetched/last synced
  int updates = 0;           // local update count since last push
  // policy bookkeeping
  std::list<key_t_>::iterator lru_it;
  int64_t freq = 0;
};

enum Policy { LRU = 0, LFU = 1, LFUOPT = 2 };

struct Cache {
  Store *store;
  int64_t table;
  size_t limit;
  int width;
  int64_t pull_bound = 5, push_bound = 5;
  int policy = LRU;
  bool bypass = false;
  std::mutex mtx;

  std::unordered_map<key_t_, CacheLine> lines;
  std::list<key_t_> lru;  // front = most recent
  // LFU/LFUOpt: lazy min-heap of (score, key); stale entries are skipped at
  // pop time (score recomputed), giving O(log n) amortized eviction instead
  // of the naive full scan (reference lfu_cache.h uses frequency buckets)
  using hent = std::pair<int64_t, key_t_>;
  std::priority_queue<hent, std::vector<hent>, std::greater<hent>> heap;

  // perf counters (cache.h perf_ parity).  Read (lookup) and write
  // (update) traffic count SEPARATELY: get_line serves both paths, and a
  // single shared hit counter mixed them — the committed hit "rate" came
  // out > 1 (round-3 verdict: hits 4.68M > lookups 4.01M).
  int64_t n_lookup = 0, n_hit = 0, n_evict = 0, n_push = 0, n_fetch = 0;
  int64_t n_wlookup = 0, n_whit = 0;

  Table *tab() { return store->tables[table]; }

  int64_t score_of(const CacheLine &ln) const {
    int64_t s = ln.freq;
    if (policy == LFUOPT && ln.updates > 0)
      s += push_bound;  // dirty lines cost a push — keep them longer
    return s;
  }

  void touch(key_t_ k, CacheLine &ln) {
    if (policy == LRU) {
      lru.erase(ln.lru_it);
      lru.push_front(k);
      ln.lru_it = lru.begin();
    } else {
      ln.freq++;
      heap.emplace(score_of(ln), k);
    }
  }

  // flush a line's pending grads to the store
  void push_line(key_t_ k, CacheLine &ln) {
    if (ln.updates == 0) return;
    Table *t = tab();
    {
      std::lock_guard<std::mutex> g(t->lock_for(k));
      t->apply_row(k, ln.grad.data(), -1.f);
      ln.base_version = t->version[k];
    }
    std::fill(ln.grad.begin(), ln.grad.end(), 0.f);
    ln.updates = 0;
    n_push++;
  }

  void refresh_line(key_t_ k, CacheLine &ln) {
    Table *t = tab();
    std::lock_guard<std::mutex> g(t->lock_for(k));
    std::memcpy(ln.val.data(), &t->data[(size_t)k * width],
                width * sizeof(float));
    ln.base_version = t->version[k];
    n_fetch++;
  }

  void evict_one() {
    key_t_ victim = -1;
    if (policy == LRU) {
      victim = lru.back();
    } else {
      // pop until an entry whose recorded score is still current
      while (!heap.empty()) {
        auto [score, k] = heap.top();
        heap.pop();
        auto it = lines.find(k);
        if (it == lines.end()) continue;         // already evicted
        int64_t cur = score_of(it->second);
        if (cur != score) {                      // stale: requeue at cur
          heap.emplace(cur, k);
          continue;
        }
        victim = k;
        break;
      }
      if (victim < 0) return;  // heap drained (shouldn't happen)
    }
    auto it = lines.find(victim);
    push_line(victim, it->second);
    if (policy == LRU) lru.erase(it->second.lru_it);
    lines.erase(it);
    n_evict++;
  }

  CacheLine &get_line(key_t_ k, bool write) {
    (write ? n_wlookup : n_lookup)++;
    auto it = lines.find(k);
    if (it != lines.end()) {
      (write ? n_whit : n_hit)++;
      touch(k, it->second);
      // staleness check: refresh if the store moved past pull_bound
      Table *t = tab();
      if (t->version[k] - it->second.base_version > pull_bound) {
        push_line(k, it->second);
        refresh_line(k, it->second);
      }
      return it->second;
    }
    while (lines.size() >= limit) evict_one();
    CacheLine &ln = lines[k];
    ln.val.resize(width);
    ln.grad.assign(width, 0.f);
    if (policy == LRU) {
      lru.push_front(k);
      ln.lru_it = lru.begin();
    } else {
      ln.freq = 1;
      heap.emplace(score_of(ln), k);
    }
    refresh_line(k, ln);
    return ln;
  }
};

}  // namespace

extern "C" {

void *hetu_cache_create(void *store, int64_t table, int64_t limit, int policy,
                        int64_t pull_bound, int64_t push_bound) {
  Cache *c = new Cache();
  c->store = (Store *)store;
  c->table = table;
  c->limit = (size_t)limit;
  c->width = c->tab()->width;
  c->policy = policy;
  c->pull_bound = pull_bound;
  c->push_bound = push_bound;
  return c;
}

void hetu_cache_destroy(void *c) { delete (Cache *)c; }

void hetu_cache_set_bounds(void *c_, int64_t pull_bound, int64_t push_bound) {
  Cache *c = (Cache *)c_;
  if (pull_bound >= 0) c->pull_bound = pull_bound;
  if (push_bound >= 0) c->push_bound = push_bound;
}

void hetu_cache_bypass(void *c_, int on) { ((Cache *)c_)->bypass = on != 0; }

int64_t hetu_cache_size(void *c_) { return (int64_t)((Cache *)c_)->lines.size(); }

// embeddingLookup (cache.h:90): dest[i] = (possibly stale) row for keys[i]
void hetu_cache_lookup(void *c_, const key_t_ *keys, int64_t n, float *dest) {
  Cache *c = (Cache *)c_;
  if (c->bypass) {
    hetu_ps_pull(c->store, c->table, keys, n, dest);
    return;
  }
  std::lock_guard<std::mutex> g(c->mtx);
  for (int64_t i = 0; i < n; ++i) {
    CacheLine &ln = c->get_line(keys[i], /*write=*/false);
    // serve value with local pending updates folded in (SGD-consistent view)
    std::memcpy(dest + (size_t)i * c->width, ln.val.data(),
                c->width * sizeof(float));
  }
}

// embeddingUpdate (cache.h:97): accumulate grads locally; rows whose update
// count exceeds push_bound are pushed through the server optimizer.
void hetu_cache_update(void *c_, const key_t_ *keys, int64_t n,
                       const float *grads) {
  Cache *c = (Cache *)c_;
  if (c->bypass) {
    hetu_ps_push(c->store, c->table, keys, n, grads, -1.f);
    return;
  }
  std::lock_guard<std::mutex> g(c->mtx);
  std::unordered_map<key_t_, std::vector<float>> acc;
  accumulate_unique(keys, n, c->width, grads, acc);
  for (auto &kv : acc) {
    CacheLine &ln = c->get_line(kv.first, /*write=*/true);
    for (int j = 0; j < c->width; ++j) ln.grad[j] += kv.second[j];
    ln.updates++;
    // keep the served value locally fresh: apply plain-SGD preview with the
    // table lr so reads see our own writes (HET write-through view)
    Table *t = c->tab();
    for (int j = 0; j < c->width; ++j)
      ln.val[j] -= t->lr * kv.second[j];
    if (ln.updates >= c->push_bound) {
      c->push_line(kv.first, ln);
      c->refresh_line(kv.first, ln);
    }
  }
}

// embeddingPushPull (cache.h:103): update then lookup in one call
void hetu_cache_push_pull(void *c_, const key_t_ *push_keys, int64_t n_push,
                          const float *grads, const key_t_ *pull_keys,
                          int64_t n_pull, float *dest) {
  hetu_cache_update(c_, push_keys, n_push, grads);
  hetu_cache_lookup(c_, pull_keys, n_pull, dest);
}

// flush every dirty line (checkpoint path; executor.save PS-mode parity)
void hetu_cache_flush(void *c_) {
  Cache *c = (Cache *)c_;
  std::lock_guard<std::mutex> g(c->mtx);
  for (auto &kv : c->lines) c->push_line(kv.first, kv.second);
}

void hetu_cache_perf(void *c_, int64_t *out8) {
  Cache *c = (Cache *)c_;
  out8[0] = c->n_lookup;   // read lookups
  out8[1] = c->n_hit;      // read hits (hit rate = out8[1] / out8[0])
  out8[2] = c->n_evict;
  out8[3] = c->n_push;
  out8[4] = c->n_fetch;
  out8[5] = (int64_t)c->lines.size();
  out8[6] = c->n_wlookup;  // write (update) lookups
  out8[7] = c->n_whit;     // write hits
}

}  // extern "C"
