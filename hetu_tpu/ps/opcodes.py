"""PS wire-protocol opcode registry.

Runtime twin of the ``tools/hetu_lint.py`` opcode-integrity check: every
``OP_*`` constant in :mod:`hetu_tpu.ps.dist_store` registers here, and the
registry ASSERTS value uniqueness at import time — two opcodes silently
sharing a wire value (the classic copy-paste drift when a new frame type is
added on one side of the protocol) fails the import, not a training run.

It also gives frames a human-readable identity: :func:`op_name` maps a wire
value back to its symbolic name, and :func:`frame_repr` renders a decoded
header for error messages and chaos logs — ``OP_PUSH(table=3, nkeys=128,
shard=1)`` instead of ``op 2``.
"""
from __future__ import annotations

#: wire value -> symbolic name (populated by :func:`defop`)
OPCODES = {}
_BY_NAME = {}


def defop(name, value):
    """Register opcode ``name`` with wire ``value``; returns ``value``.

    Raises at import time on a duplicate value or a renamed duplicate —
    the runtime enforcement of the protocol's uniqueness invariant (the
    AST self-lint enforces the same thing without importing).
    """
    value = int(value)
    prev = OPCODES.get(value)
    if prev is not None and prev != name:
        raise AssertionError(
            f"PS opcode value collision: {name} and {prev} both claim "
            f"wire value {value}")
    prev_val = _BY_NAME.get(name)
    if prev_val is not None and prev_val != value:
        raise AssertionError(
            f"PS opcode {name} registered twice with different values "
            f"({prev_val} and {value})")
    OPCODES[value] = name
    _BY_NAME[name] = value
    return value


def op_name(value):
    """Symbolic name of a wire opcode value (unknown values keep the
    number, flagged)."""
    try:
        return OPCODES.get(int(value), f"OP_UNKNOWN({int(value)})")
    except (TypeError, ValueError):
        return f"OP_UNKNOWN({value!r})"


def frame_repr(op, table=None, nkeys=None, shard=None, client=None,
               seq=None):
    """Readable one-line description of a decoded frame header."""
    parts = []
    if table is not None:
        parts.append(f"table={table}")
    if nkeys is not None:
        parts.append(f"nkeys={nkeys}")
    if shard is not None and shard != -1:
        parts.append(f"shard={shard}")
    if client is not None:
        parts.append(f"client={client}")
    if seq is not None:
        parts.append(f"seq={seq}")
    return f"{op_name(op)}({', '.join(parts)})"
