"""PS-backed embedding lookup as a graph op.

Reference behavior (``gpu_ops/EmbeddingLookUp.py:10``): with a CPU/PS
context, the lookup's compute is replaced by a PS SparsePull of the batch's
rows (forward_hook:56-76), and the backward pushes IndexedSlices grads via
``ParameterServerCommunicateOp`` (backward_hook:77; SURVEY.md §3.3).

TPU-native: the table lives in the host store (:mod:`hetu_tpu.ps.store`) —
only the batch's rows enter the jitted XLA program, as a *leaf input* whose
gradient jax computes like any parameter.  The executor pulls rows (through
the HET cache when given a :class:`CacheSparseTable`) right before the step
and pushes the dense row-gradient straight after, so the device never holds
the full table — that is the trillion-parameter capability path
(reference README.md:19).

Fault transparency: this op carries NO failover logic on purpose.  With a
replicated :class:`~hetu_tpu.ps.dist_store.DistributedStore`
(``replication=2``) a killed shard primary is absorbed one layer down —
the store's shard router promotes the backup and re-routes inside the
same ``pull``/``push`` call, so the graph op, the HET cache's
transactional paths (plan → one fallible round trip → commit), and the
executor's step loop all run unchanged through a PS failure.
"""
from __future__ import annotations

import numpy as np

from ..graph.node import PlaceholderOp
from .cstable import CacheSparseTable
from .dist_store import DistCacheTable
from .store import default_store


class PSEmbeddingLookupOp(PlaceholderOp):
    """Leaf node whose per-step value = pulled embedding rows for the batch."""

    op_type = "PSEmbeddingLookup"
    is_ps = True

    def __init__(self, table, ids_node, width=None, name=None):
        super().__init__(name=name or "ps_embedding", shape=None)
        self.inputs = []           # leaf: ids resolved host-side per step
        self.ids_node = ids_node
        self._last_ids = None
        if isinstance(table, (CacheSparseTable, DistCacheTable)):
            self.cache = table
            self.store, self.table = table.store, table.table
            self.width = table.width
        elif isinstance(table, tuple):
            self.cache = None
            self.store, self.table = table
            self.width = width
        else:  # bare table id on the default store
            self.cache = None
            self.store, self.table = default_store(), int(table)
            self.width = width

    @property
    def device_mode(self):
        """True when the backing cache keeps a device-resident slab
        (``DistCacheTable(device=True)``): the executor then lowers this
        lookup to a slot-indexed on-device gather, overlaps the PS miss
        pull with the dense forward on the feed-pipeline thread, and
        feeds the grad back through the device scatter-add kernel —
        ``pull``/``push`` below are the HOST-mode protocol and are not
        used on the device path (``pull_rows`` still works: standalone
        callers and the profiler get rows through the same device
        commit protocol)."""
        return isinstance(self.cache, DistCacheTable) \
            and getattr(self.cache, "device", False)

    # host-side pull/push used by the executor around the jitted step
    def pull_rows(self, ids):
        """Stateless row pull — safe on a background prefetch thread (does
        NOT touch ``_last_ids``, which the in-flight step's push needs).
        Cache-backed lookups mutate only cache bookkeeping, which is
        internally locked; a prefetch-thread lookup observes the same
        bounded staleness the cache already grants."""
        ids = np.asarray(ids, np.int64)
        if isinstance(self.cache, DistCacheTable):
            return self.cache.lookup(ids)
        if self.cache is not None:
            dest = np.empty(ids.shape + (self.cache.width,), np.float32)
            return self.cache._lookup_sync(ids, dest)
        return self.store.pull(self.table, ids)

    def pull(self, ids):
        ids = np.asarray(ids, np.int64)
        self._last_ids = ids
        return self.pull_rows(ids)

    def push_to(self, ids, grads):
        """Push grads onto explicit ids — safe for deferred (async) pushes,
        which must not read ``_last_ids`` at execution time (the next step
        may have overwritten it by then)."""
        if ids is None:
            return
        if isinstance(self.cache, DistCacheTable):
            self.cache.update(ids, grads)
        elif self.cache is not None:
            self.cache._update_sync(ids, grads)
        else:
            self.store.push(self.table, ids, grads)

    def push(self, grads):
        self.push_to(self._last_ids, grads)


def ps_embedding_lookup_op(table, ids_node, width=None, name=None):
    """``ht.ps_embedding_lookup_op(table, ids)`` — embedding rows for the ids
    batch, stored host-side (PS capability parity; see class docstring)."""
    return PSEmbeddingLookupOp(table, ids_node, width=width, name=name)
