"""Per-key HET cache reference model — the semantic oracle and the
pre-PR baseline.

:class:`PerKeyCacheTable` implements the EXACT bounded-staleness contract
of the vectorized :class:`~hetu_tpu.ps.dist_store.DistCacheTable`
(batch-granular hit/refresh decisions over sorted unique keys, eviction by
smallest ``(tick, key)`` / ``(freq, tick, key)``, push-bound accumulation,
grad-only slots, capacity-overflow spill) — but with the pre-PR
implementation style: Python dict churn per key and ONE single-row
``store.push`` RPC per dirty key (miss-refresh, eviction, push-bound
overflow, flush alike).  Two jobs:

1. **Parity oracle** — the tests replay identical traces through both
   implementations over identically-seeded stores and require bitwise
   equality (outputs, final server table, versions, stats).
2. **Bench baseline** — ``bench.py --config emb`` measures the vectorized
   cache's rows/s against this model on the same zipf trace; the pre-PR
   ``DistCacheTable`` had this cost shape (per-key dict ops + per-key
   RPCs), so the ratio is the honest speedup claim.
"""
from __future__ import annotations

import numpy as np


class PerKeyCacheTable:
    def __init__(self, store, table, limit=1 << 16, pull_bound=100,
                 push_bound=10, lr=-1.0, policy="lru"):
        self.store, self.table = store, table
        self.width = int(store.width(table))
        self.limit = int(limit)
        self.pull_bound, self.push_bound = int(pull_bound), int(push_bound)
        self.lr = lr
        policy = policy.lower()
        if policy not in ("lru", "lfu"):
            raise ValueError(f"unknown cache policy {policy!r}")
        self.policy = policy
        self._rows = {}     # key -> row (None = grad-only, never serves)
        self._uses = {}     # key -> lookups since refresh
        self._grad = {}     # key -> accumulated grad
        self._gcnt = {}     # key -> pending update events
        self._tick_of = {}  # key -> last-touch clock
        self._freq = {}     # key -> touch count since insert
        self._tick = 0
        self.stats = {"lookups": 0, "hits": 0, "evictions": 0, "pushes": 0,
                      "fetches": 0, "updates": 0, "push_rpcs": 0}

    # -- per-key push: the pre-PR one-RPC-per-dirty-key pattern ------------
    def _push_key(self, k):
        g = self._grad.pop(k, None)
        self._gcnt.pop(k, None)
        if g is not None:
            self.store.push(self.table, np.asarray([k], np.int64),
                            g[None, :], self.lr)
            self.stats["pushes"] += 1
            self.stats["push_rpcs"] += 1

    def _victims(self, need, protect):
        """Evictable keys, worst-first by the policy order, excluding the
        current batch's keys."""
        cands = [k for k in self._rows if k not in protect]
        if self.policy == "lru":
            cands.sort(key=lambda k: (self._tick_of[k], k))
        else:
            cands.sort(key=lambda k: (self._freq[k], self._tick_of[k], k))
        return cands[:min(need, len(cands))]

    def _evict(self, victims):
        for k in victims:
            self._push_key(k)
            for d in (self._rows, self._uses, self._tick_of, self._freq):
                d.pop(k, None)
            self.stats["evictions"] += 1

    def lookup(self, keys):
        keys = np.ascontiguousarray(keys, np.int64)
        flat = keys.reshape(-1)
        self._tick += 1
        self.stats["lookups"] += int(flat.size)
        if not flat.size:
            return np.empty(keys.shape + (self.width,), np.float32)
        uk, cnt = np.unique(flat, return_counts=True)
        served = {}
        hit_keys = set()
        refresh = []
        # batch-granular DECISIONS over the sorted unique keys (the
        # shared contract)…
        for k, c in zip(uk.tolist(), cnt.tolist()):
            if (k in self._rows and self._rows[k] is not None
                    and self._uses[k] < self.pull_bound):
                served[k] = self._rows[k]
                hit_keys.add(k)
                self._tick_of[k] = self._tick
            else:
                refresh.append((k, c))
        if refresh:
            batch_keys = set(uk.tolist())
            # pending grads of stale rows land before the re-pull
            for k, _ in refresh:
                if k in self._rows:
                    self._push_key(k)
            new = [k for k, _ in refresh if k not in self._rows]
            avail = self.limit - len(self._rows)
            if len(new) > avail:
                self._evict(self._victims(len(new) - avail, batch_keys))
            cacheable = set(new[:self.limit - len(self._rows)])
            rk = np.asarray([k for k, _ in refresh], np.int64)
            rows = self.store.pull(self.table, rk)
            self.stats["fetches"] += len(refresh)
            for (k, c), row in zip(refresh, rows):
                served[k] = row
                if k in self._rows or k in cacheable:
                    if k in cacheable:       # fresh insert: freq restarts
                        self._freq[k] = 0
                    self._rows[k] = row.copy()
                    self._uses[k] = c
                    self._tick_of[k] = self._tick
                    self._freq[k] += c
        # …then per-OCCURRENCE serving with per-occurrence bookkeeping —
        # the pre-PR lookup's exact cost shape (dict get + uses/freq/stat
        # increments for every one of the batch's ids)
        out = np.empty((flat.size, self.width), np.float32)
        for i, k in enumerate(flat.tolist()):
            out[i] = served[k]
            if k in hit_keys:
                self._uses[k] += 1
                self._freq[k] += 1
                self.stats["hits"] += 1
        return out.reshape(keys.shape + (self.width,))

    def update(self, keys, grads):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        if not keys.size:
            return
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            keys.size, -1)
        self._tick += 1
        self.stats["updates"] += int(keys.size)
        uk, cnt = np.unique(keys, return_counts=True)
        # per-OCCURRENCE accumulation, one fresh array per add — the
        # pre-PR update()'s exact cost shape (and bitwise-identical
        # occurrence-order float32 sums)
        sums = {}
        for k, g in zip(keys.tolist(), grads):
            a = sums.get(k)
            sums[k] = g.copy() if a is None else a + g
        acc = np.stack([sums[k] for k in uk.tolist()])
        batch_keys = set(uk.tolist())
        new = [k for k in uk.tolist() if k not in self._rows]
        avail = self.limit - len(self._rows)
        if len(new) > avail:
            self._evict(self._victims(len(new) - avail, batch_keys))
        cacheable = set(new[:self.limit - len(self._rows)])
        for k, c, g in zip(uk.tolist(), cnt.tolist(), acc):
            if k not in self._rows:
                if k not in cacheable:
                    # capacity overflow: straight out, uncached
                    self.store.push(self.table, np.asarray([k], np.int64),
                                    g[None, :], self.lr)
                    self.stats["pushes"] += 1
                    self.stats["push_rpcs"] += 1
                    continue
                self._rows[k] = None       # grad-only slot: born stale
                self._uses[k] = self.pull_bound
                self._freq[k] = 0
            self._grad[k] = self._grad.get(
                k, np.zeros(self.width, np.float32)) + g
            self._gcnt[k] = self._gcnt.get(k, 0) + c
            self._tick_of[k] = self._tick
            self._freq[k] += c
            if self._gcnt[k] >= self.push_bound:
                self._push_key(k)
                self._uses[k] = self.pull_bound   # server is ahead: stale

    def flush(self):
        for k in sorted(self._grad):
            self._push_key(k)
            self._uses[k] = self.pull_bound

    def perf(self):
        d = dict(self.stats)
        d["size"] = len(self._rows)
        d["hit_rate"] = (d["hits"] / d["lookups"]) if d["lookups"] else 0.0
        return d

    def __len__(self):
        return len(self._rows)
