"""Host-resident sharded embedding store (parameter-server capability).

TPU-native replacement for the reference's ps-lite server stack
(``ps-lite/include/ps/worker/PSAgent.h:50`` vecPushSparse/vecSDPushPull,
server ``PSFHandle.h:17``, server-side optimizers ``optimizer.h``): the
"server" is host RAM next to the TPU chips.  On a multi-host pod each
process owns the key range ``hash(key) % nprocs == process_index`` so pulls
and pushes stay host-local for the rows a host's data shard touches; the
HET-style client cache (:class:`hetu_tpu.ps.cstable.CacheSparseTable`)
absorbs cross-host skew with bounded staleness.

A pure-numpy fallback covers environments without a C++ toolchain.
"""
from __future__ import annotations

import json
import os
import struct

import numpy as np

from .build import get_lib

_OPT_IDS = {"sgd": 0, "momentum": 1, "nesterov": 2, "adagrad": 3, "adam": 4}
_OPT_NAMES = {v: k for k, v in _OPT_IDS.items()}

#: v3 numpy-table checkpoint: magic + JSON header + raw array bytes,
#: streamed in bounded chunks (a 10^7x64 table must checkpoint without a
#: full in-memory copy — ``np.savez`` materialises each array's bytes)
_V3_MAGIC = b"HETUPS3\n"
_V3_CHUNK = 1 << 26          # 64 MB per write/readinto slice


def _write_chunked(f, arr):
    """Stream a C-contiguous array to ``f`` without copying it whole."""
    mv = memoryview(arr).cast("B")
    for off in range(0, len(mv), _V3_CHUNK):
        f.write(mv[off:off + _V3_CHUNK])


def _read_chunked(f, arr):
    """Stream bytes from ``f`` straight into ``arr``'s buffer."""
    mv = memoryview(arr).cast("B")
    off = 0
    while off < len(mv):
        n = f.readinto(mv[off:off + _V3_CHUNK])
        if not n:
            raise IOError(f"truncated v3 table checkpoint at byte {off}")
        off += n


class _NumpyTable:
    """Fallback with identical semantics to the native Table (SGD/… updates,
    per-row versions).  Used only when g++ is unavailable."""

    def __init__(self, rows, width, opt, lr, m1, m2, eps, seed, scale):
        from ..obs.lock_witness import make_lock
        rng = np.random.RandomState(seed & 0xFFFFFFFF)
        self.data = (rng.uniform(-scale, scale, (rows, width))
                     if scale else np.zeros((rows, width))).astype(np.float32)
        self.version = np.zeros(rows, np.int64)
        self.opt, self.lr, self.m1, self.m2, self.eps = opt, lr, m1, m2, eps
        self.s0 = np.zeros_like(self.data) if opt in (1, 2, 3, 4) else None
        self.s1 = np.zeros_like(self.data) if opt == 4 else None
        self.t = np.zeros(rows, np.int32) if opt == 4 else None
        # concurrent remote pushes arrive from StoreServer handler threads;
        # the native table stripe-locks, this fallback must lock too
        self._lock = make_lock("_NumpyTable._lock")

    def pull(self, keys):
        with self._lock:
            return self.data[keys].copy()

    def push(self, keys, grads, lr=-1.0):
        with self._lock:
            return self._push_locked(keys, grads, lr)

    def _push_locked(self, keys, grads, lr=-1.0):
        elr = self.lr if lr <= 0 else lr
        uk, inv = np.unique(keys, return_inverse=True)
        acc = np.zeros((len(uk), self.data.shape[1]), np.float32)
        np.add.at(acc, inv, grads.reshape(len(keys), -1))
        if self.opt == 0:
            self.data[uk] -= elr * acc
        elif self.opt in (1, 2):
            prev = self.s0[uk]
            v = self.m1 * prev - elr * acc
            self.s0[uk] = v
            self.data[uk] += (-self.m1 * prev + (1 + self.m1) * v) \
                if self.opt == 2 else v
        elif self.opt == 3:
            self.s0[uk] += acc * acc
            self.data[uk] -= elr * acc / (np.sqrt(self.s0[uk]) + self.eps)
        else:
            self.t[uk] += 1
            t = self.t[uk][:, None].astype(np.float32)
            m = self.m1 * self.s0[uk] + (1 - self.m1) * acc
            v = self.m2 * self.s1[uk] + (1 - self.m2) * acc * acc
            self.s0[uk], self.s1[uk] = m, v
            self.data[uk] -= elr * (m / (1 - self.m1 ** t)) / (
                np.sqrt(v / (1 - self.m2 ** t)) + self.eps)
        self.version[uk] += 1


class EmbeddingStore:
    """A set of host-RAM parameter tables with server-side optimizers.

    API parity with the worker surface of the reference PS
    (ParameterInit / SparsePull / SparsePush / SDPushPull / Save / Load,
    ``PSAgent.h:124-447``) plus SSP clock sync (``ssp_handler.h``).
    """

    def __init__(self):
        self._lib = get_lib()
        self._h = self._lib.hetu_ps_create() if self._lib else None
        self._np_tables = []

    # -- table management --------------------------------------------------
    def init_table(self, rows, width, opt="sgd", lr=0.01, beta1=0.9,
                   beta2=0.999, eps=1e-7, seed=0, init_scale=None):
        if init_scale is None:
            init_scale = float(np.sqrt(1.0 / width))  # reference default-ish
        o = _OPT_IDS[opt]
        if self._lib:
            return int(self._lib.hetu_ps_init_table(
                self._h, rows, width, o, lr, beta1, beta2, eps, seed,
                init_scale))
        self._np_tables.append(
            _NumpyTable(rows, width, o, lr, beta1, beta2, eps, seed,
                        init_scale))
        return len(self._np_tables) - 1

    def set_data(self, table, arr):
        arr = np.ascontiguousarray(arr, np.float32)
        if self._lib:
            import ctypes
            self._lib.hetu_ps_set_data(
                self._h, table,
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        else:
            self._np_tables[table].data[:] = arr

    def get_data(self, table):
        if self._lib:
            import ctypes
            rows = self._lib.hetu_ps_rows(self._h, table)
            width = self._lib.hetu_ps_width(self._h, table)
            out = np.empty((rows, width), np.float32)
            self._lib.hetu_ps_get_data(
                self._h, table,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out
        return self._np_tables[table].data.copy()

    def rows(self, table):
        """Row count of ``table`` (reference PSAgent table metadata)."""
        if self._lib:
            return int(self._lib.hetu_ps_rows(self._h, table))
        return int(self._np_tables[table].data.shape[0])

    def width(self, table):
        """Embedding width of ``table`` — gives the cache clients one
        accessor that works for both this store and DistributedStore."""
        if self._lib:
            return int(self._lib.hetu_ps_width(self._h, table))
        return int(self._np_tables[table].data.shape[1])

    def _check_keys(self, table, keys):
        if keys.size == 0:
            return
        lo, hi = int(keys.min()), int(keys.max())
        rows = self.rows(table)
        if lo < 0 or hi >= rows:
            raise IndexError(
                f"embedding key out of range: [{lo}, {hi}] vs table rows "
                f"{rows}")

    # -- sparse ops --------------------------------------------------------
    # -- load recording (reference startRecord/getLoads,
    #    ps-lite python_binding.cc:121-127, executor.py:356-359) ------------
    def start_record(self):
        self._loads = {}

    def get_loads(self):
        """{(table, 'pull'|'push'): {key: count}} since start_record."""
        return getattr(self, "_loads", {})

    def _record(self, table, kind, keys):
        loads = getattr(self, "_loads", None)
        if loads is None:
            return
        bucket = loads.setdefault((table, kind), {})
        for k, n in zip(*np.unique(keys, return_counts=True)):
            bucket[int(k)] = bucket.get(int(k), 0) + int(n)

    def pull(self, table, keys):
        """SparsePull: rows for ``keys`` (any shape) → keys.shape + (width,)."""
        keys = np.ascontiguousarray(keys, np.int64)
        self._check_keys(table, keys)
        self._record(table, "pull", keys.reshape(-1))
        if self._lib:
            import ctypes
            width = self._lib.hetu_ps_width(self._h, table)
            flat = keys.reshape(-1)
            out = np.empty((flat.size, width), np.float32)
            self._lib.hetu_ps_pull(
                self._h, table,
                flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                flat.size,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            return out.reshape(keys.shape + (width,))
        out = self._np_tables[table].pull(keys.reshape(-1))
        return out.reshape(keys.shape + out.shape[-1:])

    def push(self, table, keys, grads, lr=-1.0):
        """SparsePush: apply per-key accumulated grads via server optimizer."""
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        self._check_keys(table, keys)
        self._record(table, "push", keys)
        grads = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        if self._lib:
            import ctypes
            self._lib.hetu_ps_push(
                self._h, table,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                float(lr))
        else:
            self._np_tables[table].push(keys, grads, lr)

    def push_pull(self, table, push_keys, grads, pull_keys, lr=-1.0):
        """Fused SDPushPull (PsfType kSDPushPull)."""
        self.push(table, push_keys, grads, lr)
        return self.pull(table, pull_keys)

    def dense_push(self, table, grad, lr=-1.0):
        """DensePush: whole-table gradient through the server optimizer
        (PsfType DensePush); excludes concurrent sparse pushes."""
        grad = np.ascontiguousarray(grad, np.float32)
        if self._lib:
            import ctypes
            self._lib.hetu_ps_dense_push(
                self._h, table,
                grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                float(lr))
        else:
            t = self._np_tables[table]
            t.push(np.arange(t.data.shape[0]), grad, lr)

    def versions(self, table, keys):
        keys = np.ascontiguousarray(keys, np.int64).reshape(-1)
        if self._lib:
            import ctypes
            out = np.empty(keys.size, np.int64)
            self._lib.hetu_ps_versions(
                self._h, table,
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                keys.size,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            return out
        return self._np_tables[table].version[keys].copy()

    # -- persistence (SaveParam/LoadParam parity) --------------------------
    def save(self, table, path):
        """Full table state: data + optimizer slots + versions (a resumed
        Adam table with zeroed moments silently diverges).

        Numpy fallback writes the streamed v3 format: arrays go to disk in
        bounded 64 MB slices straight off their buffers, so checkpointing
        a multi-GB table needs no full in-memory copy (``np.savez``
        materialised each array's bytes — 2.5 GB of transient RSS for the
        10^7x64 table).  The native core already streams via fwrite."""
        if self._lib:
            rc = self._lib.hetu_ps_save(self._h, table, path.encode())
            if rc:
                raise IOError(f"ps save failed rc={rc}")
        else:
            t = self._np_tables[table]
            blobs = [("data", t.data), ("version", t.version)]
            for name in ("s0", "s1", "t"):
                if getattr(t, name) is not None:
                    blobs.append((name, getattr(t, name)))
            header = json.dumps({"arrays": [
                {"name": n, "dtype": str(a.dtype), "shape": list(a.shape)}
                for n, a in blobs]}).encode()
            with open(path, "wb") as f:
                f.write(_V3_MAGIC)
                f.write(struct.pack("<q", len(header)))
                f.write(header)
                for _, a in blobs:
                    _write_chunked(f, a)

    def load(self, table, path):
        if self._lib:
            rc = self._lib.hetu_ps_load(self._h, table, path.encode())
            if rc:
                raise IOError(f"ps load failed rc={rc}")
            return
        t = self._np_tables[table]
        with open(path, "rb") as f:
            head = f.read(8)
            if head == _V3_MAGIC:  # v3: streamed chunked format
                (hlen,) = struct.unpack("<q", f.read(8))
                meta = json.loads(f.read(hlen).decode())
                for spec in meta["arrays"]:
                    target = {"data": t.data, "version": t.version,
                              "s0": t.s0, "s1": t.s1, "t": t.t}.get(
                                  spec["name"])
                    nbytes = (int(np.prod(spec["shape"]))
                              * np.dtype(spec["dtype"]).itemsize)
                    if target is None:
                        f.seek(nbytes, 1)   # slot this table doesn't keep
                        continue
                    if (list(target.shape) != list(spec["shape"])
                            or str(target.dtype) != spec["dtype"]):
                        raise IOError(
                            f"v3 checkpoint array {spec['name']} is "
                            f"{spec['shape']}:{spec['dtype']}, table wants "
                            f"{list(target.shape)}:{target.dtype}")
                    _read_chunked(f, target)
                return
        if head[:2] == b"PK":      # npz archive: v2 full state
            blobs = np.load(path)
            t.data[:] = blobs["data"]
            t.version[:] = blobs["version"]
            for name in ("s0", "s1", "t"):
                if name in blobs and getattr(t, name) is not None:
                    getattr(t, name)[:] = blobs[name]
        else:                      # v1 file: bare .npy of the data
            t.data[:] = np.load(path)

    def state_digest(self, table, chunk=_V3_CHUNK):
        """sha256 hex digest over the table's FULL state — data slab,
        optimizer moments, per-row versions — streamed in bounded slices
        (never a whole-table copy).  Two replicas that applied the same
        op-log agree bitwise iff their digests agree, so this is the
        replica-divergence detector behind ``OP_CHECKSUM`` and
        ``tools/ps_fsck.py``.  Native tables digest their streamed save
        file (same full-state coverage); compare like flavours only."""
        import hashlib
        h = hashlib.sha256()
        if self._lib:
            import tempfile
            fd, path = tempfile.mkstemp(prefix="hetu_ps_digest_")
            os.close(fd)
            try:
                self.save(table, path)
                with open(path, "rb") as f:
                    while True:
                        b = f.read(chunk)
                        if not b:
                            break
                        h.update(b)
            finally:
                os.unlink(path)
            return h.hexdigest()
        t = self._np_tables[table]
        with t._lock:   # a mid-push digest would tear data vs moments
            for name in ("data", "version", "s0", "s1", "t"):
                a = getattr(t, name)
                if a is None:
                    continue
                mv = memoryview(np.ascontiguousarray(a)).cast("B")
                for off in range(0, len(mv), chunk):
                    h.update(mv[off:off + chunk])
        return h.hexdigest()

    # -- SSP (bounded staleness barrier) ----------------------------------
    #: set by ssp_init — the native clock/ssp_sync entry points index the
    #: clock vector unchecked, so callers must not touch them before init
    ssp_ready = False

    def ssp_init(self, n_workers):
        if self._lib:
            self._lib.hetu_ps_ssp_init(self._h, n_workers)
        else:
            from ..obs.lock_witness import make_condition
            self._clocks = np.zeros(n_workers, np.int64)
            self._clock_cv = make_condition("EmbeddingStore._clock_cv")
        self.ssp_ready = True

    def clock(self, worker):
        if self._lib:
            self._lib.hetu_ps_clock(self._h, worker)
        else:
            with self._clock_cv:
                self._clocks[worker] += 1
                self._clock_cv.notify_all()

    def clock_value(self, worker):
        """This worker's current SSP clock (testing/monitoring)."""
        if self._lib:
            return int(self._lib.hetu_ps_clock_value(self._h, worker))
        with self._clock_cv:
            return int(self._clocks[worker])

    #: every store flavour blocks now: native condvar (ps_store.cc),
    #: distributed server-side condition (dist_store), and the numpy
    #: fallback's threading.Condition below — callers never host-poll
    ssp_blocking = True

    def ssp_sync(self, worker, staleness, timeout_ms=0):
        """Block until this worker is within ``staleness`` clocks of the
        slowest worker.  Returns False on timeout; ``timeout_ms <= 0``
        waits forever (native-parity semantics — executor callers always
        pass a finite watchdog budget)."""
        if self._lib:
            return self._lib.hetu_ps_ssp_sync(
                self._h, worker, staleness, timeout_ms) == 0

        def ok():
            return bool(self._clocks[worker] - self._clocks.min()
                        <= staleness)

        with self._clock_cv:
            # one condition-variable wait, notified by every clock() tick
            # — replaces the executor-side 5 ms polling loop the old
            # report-only fallback forced (matching the native and
            # distributed stores)
            return self._clock_cv.wait_for(
                ok, None if timeout_ms <= 0 else timeout_ms / 1e3)

    def __del__(self):
        if getattr(self, "_lib", None) and getattr(self, "_h", None):
            try:
                self._lib.hetu_ps_destroy(self._h)
            except Exception:
                pass


_default_store = None


def default_store():
    """Process-wide store (the reference's implicit `ps.get_comm()`)."""
    global _default_store
    if _default_store is None:
        _default_store = EmbeddingStore()
    return _default_store
