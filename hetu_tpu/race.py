"""Deterministic race harness — seeded barrier scheduling of named
preemption points (ISSUE 14 tentpole, part 3).

Every race this repo's review logs caught (the router's
``set_result``/cancel window, the read-only cache's version-vs-rows
ordering, the commit-vs-evict window) was found by LUCK: a reviewer
imagining an interleaving the test suite had no way to force.  This
module makes interleavings first-class, chaos-DSL style::

    HETU_RACE="race:cache.miss_fill|test.write:seed1"

A :class:`RaceSchedule` names two SITES and a seed.  Product code (and
tests) mark sites with :func:`point` — zero-width — or bracket a region
with :func:`region`.  When a schedule is installed, the two sites
RENDEZVOUS: the seed picks a WINNER per pair, the loser thread blocks
at its site until the winner's region has completed, so the two
operations execute in a forced, reproducible order — same seed ⇒ same
interleaving (the determinism test's exact claim), different seeds
cover both orders.  ``pairs<k>`` repeats the rendezvous k times (a new
seed draw each pair); ``timeout<ms>`` bounds the wait so a schedule
whose peer site never executes degrades to ONE counted timeout
(``concurrency_race_timeouts``) after which the schedule free-runs —
never a deadlocked suite, never a per-encounter stall on a hot path.

Instrumented sites (the historical hot pairs; tests may mark their own
with any name):

========================  ==================================================
``cache.lookup``          host-mode ``DistCacheTable.lookup`` entry (before
                          the cache lock) — vs evict-commit
``cache.evict_commit``    ``DistCacheTable._commit_slots`` (victim
                          tombstoning + registration)
``cache.miss_fill``       read-only miss path, BETWEEN the versions read
                          and the row pull (the racing-writer window)
``cache.refresh_commit``  ``refresh_stale``, after the RPCs, before the
                          re-validating commit takes the lock
``router.resolve``        ``ServingRouter._run_batch``, before per-request
                          future resolution — vs a caller's ``cancel()``
``router.close``          ``ServingRouter.close``, before rejecting the
                          still-queued requests
``decode.step``           ``DecodeRouter._loop``, the join/step boundary
``decode.close``          ``DecodeRouter.close``, before failing the
                          still-queued streams
``recovery.detach``       ``DecodeRouter.detach_inflight``, after the
                          seated mirror is taken, before the journal
                          snapshots (ISSUE 19) — vs close/adopt
``recovery.adopt``        ``DecodeRouter.adopt``, before the rescued
                          requests enter the survivor's queue — vs the
                          survivor's own close
``exec.resize_world``     ``Executor.resize_world`` entry — vs an
                          in-flight async step
``exec.drain_async``      ``Executor._drain_async`` entry (the resize
                          quiesce leg)
``elastic.resize``        ``ElasticController._resize`` (the detect→resize
                          dance, outside the executor)
========================  ==================================================

Cost discipline (PR 10): the hot-path check is ONE module-global read —
:data:`ACTIVE` is ``None`` unless a schedule is installed, and
:func:`point`/:func:`region` sites on dispatch paths guard on it
inline.  The harness is a TESTING tool: schedules are installed by
tests (or ``HETU_RACE``), never in production runs.

Forced preemptions actually fired count ``concurrency_preemptions``;
rendezvous that timed out count ``concurrency_race_timeouts`` (both in
the ``concurrency_*`` family, ``HetuProfiler.concurrency_counters()``).
"""
from __future__ import annotations

import os
import random
import threading
import time

from .metrics import record_concurrency


class RaceSpecError(ValueError):
    """Malformed ``HETU_RACE`` spec (loud: a typo'd schedule forcing
    nothing would make a race test pass vacuously)."""


_GRAMMAR = "race:<site_a>|<site_b>:seed<n>[:pairs<k>][:timeout<ms>]"


def parse_spec(spec):
    """``"race:a|b:seed3[:pairs2][:timeout500]"`` →
    ``(site_a, site_b, seed, pairs, timeout_ms)``."""
    parts = spec.strip().split(":")
    if len(parts) < 3 or parts[0] != "race":
        raise RaceSpecError(f"bad race spec {spec!r}: expected {_GRAMMAR}")
    sides = parts[1].split("|")
    if len(sides) != 2 or not sides[0] or not sides[1]:
        raise RaceSpecError(
            f"bad race sites {parts[1]!r} in {spec!r}: expected "
            f"{_GRAMMAR}")
    if sides[0] == sides[1]:
        raise RaceSpecError(
            f"race sites must differ in {spec!r} — ordering a site "
            f"against itself forces nothing")
    seed = pairs = None
    timeout_ms = 2000.0
    for p in parts[2:]:
        if p.startswith("seed"):
            seed = int(p[4:])
        elif p.startswith("pairs"):
            pairs = int(p[5:])
        elif p.startswith("timeout"):
            timeout_ms = float(p[7:])
        else:
            raise RaceSpecError(
                f"unknown race clause {p!r} in {spec!r}: expected "
                f"{_GRAMMAR}")
    if seed is None:
        raise RaceSpecError(f"race spec {spec!r} missing ':seed<n>'")
    return sides[0], sides[1], seed, (pairs or 1), timeout_ms


class RaceSchedule:
    """One forced-interleaving schedule over two named sites.

    Semantics per pair: the seed draws a WINNER site, and the two sites
    RENDEZVOUS — the winner blocks at its site until the loser has
    ARRIVED at its own (so the forcing cannot be skipped by thread-
    start timing), then the winner's region runs to completion while
    the loser stays held, then both proceed.  "A's region completes
    before B's begins" is therefore a deterministic function of
    ``(sites, seed, pair index)`` whenever both sites execute; a peer
    that never arrives times out through (counted).  A re-entry of a
    site while its pair is already satisfied passes through unforced
    (schedules force the FIRST k encounters, not every one).

    ``log`` records ``(event, site)`` tuples (``enter`` / ``exit`` /
    ``forced`` / ``timeout``) for post-mortem inspection.  NOTE the
    deterministic contract is ``order`` (the drawn winners) and the
    region-COMPLETION order — which is what the determinism tests
    assert; the two ``enter`` entries of a pair land in OS-scheduling
    arrival order, so raw logs from two same-seed runs may differ in
    that interleaving-irrelevant respect.
    """

    def __init__(self, site_a, site_b, seed, pairs=1, timeout_ms=2000.0):
        self.sites = (str(site_a), str(site_b))
        self.seed = int(seed)
        self.pairs = max(1, int(pairs))
        self.timeout_ms = float(timeout_ms)
        rng = random.Random(self.seed)
        #: winner site per pair — the whole interleaving decision,
        #: drawn up front so it is a pure function of (sites, seed)
        self.order = [self.sites[rng.randrange(2)]
                      for _ in range(self.pairs)]
        self._cv = threading.Condition()
        self._pair = 0
        self._winner_done = False
        self._loser_arrived = False
        #: set on the FIRST rendezvous timeout of the current pair: the
        #: pair degrades to free-running (every later encounter passes
        #: straight through) instead of re-paying the timeout per
        #: encounter — a schedule naming a site that never executes
        #: costs ONE counted timeout, not one per hot-path hit
        self._timed_out = False
        #: per-thread pair index stamped at enter: an exit whose pair
        #: already closed (a stray extra thread at a hot site) is
        #: IGNORED instead of corrupting the next pair's state
        self._tl = threading.local()
        self.log = []

    @classmethod
    def from_spec(cls, spec):
        a, b, seed, pairs, timeout_ms = parse_spec(spec)
        return cls(a, b, seed, pairs, timeout_ms)

    @classmethod
    def from_env(cls, env_var="HETU_RACE"):
        spec = os.environ.get(env_var, "").strip()
        return cls.from_spec(spec) if spec else None

    # -- site hooks --------------------------------------------------------
    def enter(self, site):
        if site not in self.sites:
            return
        with self._cv:
            if self._pair >= self.pairs or self._timed_out:
                self._tl.entered = None
                return      # schedule exhausted, or pair degraded free
            my_pair = self._pair
            winner = self.order[my_pair]
            self._tl.entered = my_pair
            self.log.append(("enter", site))
            deadline = time.monotonic() + self.timeout_ms / 1e3
            if site == winner:
                # rendezvous: the winner HOLDS until the loser is at its
                # site — without this, a late-starting loser thread
                # would let the winner's whole region run first and the
                # forcing silently not happen (review finding: 3/9 runs
                # flaked on a loaded box).  A pair advancing under us
                # (another thread satisfied it) releases the wait too.
                while not self._loser_arrived and not self._winner_done \
                        and not self._timed_out and self._pair == my_pair:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        self._time_out(site)
                        return
                    self._cv.wait(left)
                return
            # loser: announce arrival, then hold until the winner's
            # region completed (or the pair closes under us)
            self._loser_arrived = True
            self._cv.notify_all()
            waited = False
            while not self._winner_done and not self._timed_out \
                    and self._pair == my_pair:
                left = deadline - time.monotonic()
                if left <= 0:
                    self._time_out(site)
                    return
                waited = True
                self._cv.wait(left)
            if waited and (self._winner_done or self._pair != my_pair):
                self.log.append(("forced", site))
                record_concurrency("concurrency_preemptions")

    def _time_out(self, site):
        """First rendezvous timeout of the pair (caller holds the cv):
        degrade the pair to free-running — ONE counted timeout, every
        later encounter of either site passes straight through."""
        self._timed_out = True
        self._loser_arrived = False
        self.log.append(("timeout", site))
        record_concurrency("concurrency_race_timeouts")
        self._cv.notify_all()

    def exit(self, site):
        if site not in self.sites:
            return
        with self._cv:
            if self._pair >= self.pairs or self._timed_out:
                return      # a timed-out schedule stays free-running —
                            # a late peer must not resurrect half a pair
            entered = getattr(self._tl, "entered", None)
            self._tl.entered = None
            if entered != self._pair:
                return      # this thread's pair already closed (a stray
                            # extra thread at a hot site): its exit must
                            # not corrupt the NEXT pair's state or stall
                            # that pair's real loser
            winner = self.order[self._pair]
            self.log.append(("exit", site))
            if site == winner:
                self._winner_done = True
                self._cv.notify_all()
            elif self._winner_done:
                # enter() only releases a loser once the winner's region
                # completed (timeout and pair-advance early-return
                # above), so the winner is necessarily done here
                self._advance()

    def _advance(self):
        """Both regions of the current pair completed (caller holds the
        cv): arm the next pair and wake any straddling waiter so it
        re-checks its pair index instead of sleeping to a timeout."""
        self._pair += 1
        self._winner_done = False
        self._loser_arrived = False
        self._cv.notify_all()

    @property
    def complete(self):
        """True once every scheduled pair has rendezvoused — or the
        schedule degraded after its one counted timeout (a timed-out
        schedule forces nothing further, so it IS finished)."""
        with self._cv:
            return self._pair >= self.pairs or self._timed_out


# ------------------------------------------------------------ active schedule

#: the installed schedule, or None — hot-path sites read this ONE global
ACTIVE = None
_install_lock = threading.Lock()


def active():
    """The process-wide schedule, or None (one global read)."""
    return ACTIVE


def install(schedule):
    """Make ``schedule`` the process-wide forcing schedule; returns the
    previous one so tests can restore it."""
    global ACTIVE
    with _install_lock:
        prev, ACTIVE = ACTIVE, schedule
    return prev


def install_from_env(env_var="HETU_RACE"):
    """Install a schedule from ``HETU_RACE`` if set; returns it (or
    None)."""
    sched = RaceSchedule.from_env(env_var)
    if sched is not None:
        install(sched)
    return sched


def uninstall():
    """Remove the process-wide schedule (test teardown)."""
    return install(None)


class _Region:
    """Context manager bracketing a named region (``with
    race.region("cache.evict_commit"): ...``)."""

    __slots__ = ("site", "_sched")

    def __init__(self, site):
        self.site = site
        self._sched = None

    def __enter__(self):
        s = ACTIVE
        if s is not None:
            self._sched = s
            s.enter(self.site)
        return self

    def __exit__(self, *exc):
        if self._sched is not None:
            self._sched.exit(self.site)
            self._sched = None
        return False


def region(site):
    """Bracket a region: the loser site's region cannot START until the
    winner site's region has COMPLETED."""
    return _Region(site)


def point(site):
    """A zero-width site: enter+exit immediately (orders the POINT
    against the peer's region).  No-op (one global read) when no
    schedule is installed."""
    s = ACTIVE
    if s is not None:
        s.enter(site)
        s.exit(site)


# HETU_RACE=... alone activates the harness for every instrumented site
# in the process (the chaos-module convention — install_from_env is a
# no-op without the env var, so normal runs pay one getenv at import)
if os.environ.get("HETU_RACE", "").strip():
    install_from_env()


__all__ = ["RaceSchedule", "RaceSpecError", "parse_spec", "active",
           "install", "install_from_env", "uninstall", "region", "point",
           "ACTIVE"]
