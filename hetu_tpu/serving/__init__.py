"""Online inference serving (ISSUE 7 tentpole).

The HET design this repo reproduces is a serving-era system; this package
is the serving half the training executor never had:

* :class:`InferenceExecutor` — compile-once serving over frozen weights:
  one pre-compiled executable per flash-legal batch bucket, read-only
  weight loading (live Executor / dict / checkpoint), donated request
  feeds, and static rejection of train-only subgraphs
  (``train-only-op-in-serving``).
* :class:`ServingRouter` — bounded request queue feeding an adaptive
  micro-batcher: pack waiting requests to the smallest legal bucket under
  a head-of-line deadline, one jitted call, scatter the rows back;
  queue-full is an explicit :class:`ServeRejected`, not unbounded growth.
* Read-mostly embedding serving rides
  ``DistCacheTable(read_only=True)`` + PR 4's replicated store: a killed
  shard primary fails over inside the batch's pull with zero restarts.
* :class:`DecodeEngine` / :class:`DecodeRouter` (ISSUE 16) —
  continuous-batching autoregressive decode over device-resident
  incremental KV caches: per-token join/leave with slot recycling,
  bucketed batch/length growth compiling once per
  ``(batch_bucket, len_bucket)`` pair, per-token futures on
  :class:`DecodeStream`, optional tp-sharded steps via a bound
  ``ParallelPlan`` — results bitwise-independent of batch composition.
* Chunked prefill + :class:`PrefixKVStore` (ISSUE 18) — prompt
  ingestion in ``ceil(P/chunk)`` mixed-batch steps through a q_len=C
  graph entry (one compile per ``(batch, chunk, len)`` bucket triple,
  pure-prefill steps skip the logits D2H), and shared-prefix KV
  snapshots seating repeat prompts with their cache rows pre-filled —
  prefill skipped outright, token streams bitwise-equal to the
  token-by-token path in every mode.
* :class:`CellMap` / :class:`CellHead` — geo-replicated serving cells:
  disjoint rank sets each serving local traffic off the read-only
  cache, surviving a cross-cell network partition (reads keep flowing,
  writes are epoch-fenced) and converging via epoch-checked
  re-replication at heal.
* :class:`FrontDoor` / :class:`SLOAutoscaler` (ISSUE 17) — the fleet
  tier: N router replicas behind one door with load-aware dispatch,
  class-based admission control (``interactive | batch | best_effort``
  shed lowest-first as structured :class:`ServeRejected` reasons),
  per-class deadlines rejected at the door, heartbeat
  ejection/rescue/re-admission, p99-SLO autoscaling on the elastic
  plane's flap-damping machinery, and graceful drain that hands queued
  work to survivors.
* Exactly-once stream recovery (ISSUE 19) — in-flight decode
  generations SURVIVE replica death: the stream's host-side
  emitted-token journal is detached with the queue when the sweep
  ejects a dead/wedged replica, replayed through chunked prefill on
  the least-loaded survivor (:class:`PrefixKVStore` consulted first)
  under a bumped replay epoch that fences the dead replica's late
  emissions — already-resolved ``token(i)`` futures never re-fire and
  the recovered stream is bitwise-equal to an unkilled run.
  Resurrection is gated (retry budget, deadline estimator, survivor
  existence); a doomed stream fails fast with
  ``ServeRejected('recovery_exhausted')`` carrying
  ``DecodeStream.partial()``.

Proven end-to-end by ``bench.py --config serve`` (zipf request stream,
p50/p99/QPS, chaos primary-kill mid-load with bitwise response parity)
and ``bench.py --config partition`` (cross-cell partition + heal with
zero local rejections and post-heal fsck convergence).
"""
from .cells import CellHead, CellMap
from .decode import DecodeEngine, DecodeRouter, DecodeStream
from .executor import InferenceExecutor, default_buckets
from .fleet import CLASSES, FrontDoor, SLOAutoscaler
from .prefix_cache import PrefixKVStore
from .router import ServingRouter, ServeRejected

__all__ = ["InferenceExecutor", "ServingRouter", "ServeRejected",
           "default_buckets", "CellMap", "CellHead",
           "DecodeEngine", "DecodeRouter", "DecodeStream",
           "PrefixKVStore", "FrontDoor", "SLOAutoscaler", "CLASSES"]
