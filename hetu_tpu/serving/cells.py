"""Geo-replicated serving cells (ISSUE 8 tentpole stratum 3).

A *cell* is a rank set that shares a failure domain — a pod, a zone, a
region.  Real fleets lose the LINK between cells far more often than
they lose a cell: the deployment this module models keeps every cell
answering its own traffic through a cross-cell partition and converges
the write plane when the link heals, riding three existing layers:

* **reads** — each cell serves :class:`~hetu_tpu.serving.InferenceExecutor`
  traffic through its own :class:`~hetu_tpu.serving.ServingRouter` off a
  read-only ``DistCacheTable`` (PR 7): warm rows are answered with zero
  cross-cell frames, so a partition costs cache-miss refreshes, never
  local availability.  Reads are deliberately unfenced (bounded
  staleness is the HET contract).
* **writes** — the fencing epochs of :mod:`hetu_tpu.ps.dist_store`: a
  cell that promotes a local backup during the partition creates a
  strictly newer lineage, so when the link heals the stranded ex-primary
  is refused (``ps_epoch_refused``), demotes itself (``ps_demotions``),
  and re-replicates — split brain converges to one serving lineage.
* **chaos** — :meth:`CellMap.partition_spec` emits the
  ``partition:rankA+...|rankB+...@step<n>[:heal<m>]`` chaos-DSL form for
  a cross-cell cut, so the whole scenario replays deterministically from
  one seed (``bench.py --config partition``).

The classes here are thin, deliberately: cells are *names over ranks*
plus the serving plumbing each cell repeats — the stores, graphs and
chaos schedule stay with the caller.
"""
from __future__ import annotations

import numpy as np

from .router import ServeRejected


class CellMap:
    """Disjoint, exhaustively tagged rank sets: ``{"west": [0, 1],
    "east": [2, 3]}``.  Validation is loud — an untagged or doubly
    tagged rank would silently mis-route a scenario's traffic.

    A cell value may also be the dict form ``{"ranks": [...],
    "replicas": N}`` (ISSUE 17): ``replicas`` sizes the cell's serving
    replica set — the ``n_replicas`` a :class:`~hetu_tpu.serving.fleet.
    FrontDoor` fronting the cell starts with (:meth:`replicas`
    reads it back, default 1).  Rank semantics are unchanged."""

    def __init__(self, cells):
        self.cells = {}
        self._replicas = {}
        for name, spec in dict(cells).items():
            name = str(name)
            if isinstance(spec, dict):
                ranks = spec["ranks"]
                n_rep = int(spec.get("replicas", 1))
                if n_rep < 1:
                    raise ValueError(
                        f"cell {name!r} asks for {n_rep} replicas — a "
                        f"cell serves with at least one")
                extra = set(spec) - {"ranks", "replicas"}
                if extra:
                    raise ValueError(
                        f"cell {name!r} spec has unknown keys "
                        f"{sorted(extra)} (known: ranks, replicas)")
                self._replicas[name] = n_rep
            else:
                ranks = spec
                self._replicas[name] = 1
            self.cells[name] = sorted(int(r) for r in ranks)
        self._cell_of = {}
        for name, ranks in self.cells.items():
            if not ranks:
                raise ValueError(f"cell {name!r} tags no ranks")
            for r in ranks:
                if r in self._cell_of:
                    raise ValueError(
                        f"rank {r} tagged in both {self._cell_of[r]!r} "
                        f"and {name!r} — cells must be disjoint")
                self._cell_of[r] = name
        self.world = len(self._cell_of)
        if sorted(self._cell_of) != list(range(self.world)):
            raise ValueError(
                f"cells must tag ranks 0..{self.world - 1} exactly once "
                f"(got {sorted(self._cell_of)})")

    def cell_of(self, rank):
        """The cell name tagging ``rank``."""
        return self._cell_of[int(rank)]

    def ranks(self, cell):
        """The ranks tagged into ``cell``."""
        return list(self.cells[cell])

    def replicas(self, cell):
        """The cell's serving replica-set size (dict-form cell specs;
        1 for plain rank-list cells)."""
        if cell not in self.cells:
            raise KeyError(cell)
        return self._replicas.get(cell, 1)

    def is_local(self, cell, rank):
        return self._cell_of.get(int(rank)) == cell

    def partition_spec(self, cell_a, cell_b, step, heal=None):
        """The chaos-DSL fault for a cross-cell partition:
        ``partition:rank<a>+...|rank<b>+...@step<n>[:heal<m>]`` — feed it
        to :class:`~hetu_tpu.chaos.ChaosInjector` (comma-joined with any
        other faults) and the cut reproduces from the schedule seed."""
        a = "+".join(f"rank{r}" for r in self.cells[cell_a])
        b = "+".join(f"rank{r}" for r in self.cells[cell_b])
        spec = f"partition:{a}|{b}@step{int(step)}"
        return spec if heal is None else f"{spec}:heal{int(heal)}"


class CellHead:
    """One cell's serving head: the cell-local store client, its
    read-only embedding cache, and the router fronting the cell's
    :class:`InferenceExecutor` — a :class:`ServingRouter`, or a
    :class:`~hetu_tpu.serving.fleet.FrontDoor` over a replica set
    (duck-typed: anything with ``submit``/``close``).

    Keeps PER-CELL counters (admitted / answered / rejections / errors)
    so a scenario can assert "the local cell kept serving: rejections=0"
    without untangling the process-global serving counters shared by
    every cell in an in-process test."""

    def __init__(self, name, store, router, cache=None):
        self.name = str(name)
        self.store = store
        self.router = router
        self.cache = cache
        self.stats = {"admitted": 0, "answered": 0, "rejections": 0,
                      "errors": 0}

    def warm(self, keys):
        """Pre-fill the read-only cache with ``keys`` (one batched
        owner-grouped pull) — a cell warmed over its working set serves
        it through a partition with zero cross-cell frames."""
        if self.cache is not None and np.asarray(keys).size:
            self.cache.lookup(np.asarray(keys, np.int64))

    def serve_wave(self, feeds, timeout=60.0):
        """Submit every feed dict in ``feeds`` to this cell's router and
        wait for the answers.  Returns ``(responses, wave_stats)`` where
        ``responses[i]`` is the request's fetch row list or None (its
        slot in a rejected/errored wave), and ``wave_stats`` counts this
        wave's admitted/answered/rejections/errors (also accumulated
        into :attr:`stats`)."""
        wave = {"admitted": 0, "answered": 0, "rejections": 0,
                "errors": 0}
        futs = []
        for fd in feeds:
            try:
                futs.append(self.router.submit(fd))
                wave["admitted"] += 1
            except ServeRejected:
                futs.append(None)
                wave["rejections"] += 1
        responses = [None] * len(feeds)
        for i, fut in enumerate(futs):
            if fut is None:
                continue
            try:
                responses[i] = fut.result(timeout=timeout)
                wave["answered"] += 1
            except Exception:   # noqa: BLE001 — per-request fate only
                wave["errors"] += 1
        for k, v in wave.items():
            self.stats[k] += v
        return responses, wave

    def catch_up(self):
        """Post-heal convergence driver: repair any shard this cell's
        client failed over (epoch-checked re-replication — the stranded
        ex-primary demotes and re-syncs) and re-pull whatever cached
        rows the surviving lineage advanced meanwhile.  Returns
        ``{"repaired": bool, "refreshed_rows": int}``."""
        repaired = self.store.maybe_re_replicate() \
            if getattr(self.store, "replication", 1) >= 2 else False
        refreshed = 0
        if self.cache is not None:
            try:
                refreshed = self.cache.refresh_stale()
            except (RuntimeError, OSError, ConnectionError):
                pass    # best-effort mid-partition: cached rows keep
                        # serving; the next catch_up retries the sweep
        return {"repaired": bool(repaired),
                "refreshed_rows": int(refreshed)}

    def close(self):
        self.router.close()


__all__ = ["CellMap", "CellHead"]
